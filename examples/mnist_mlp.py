"""BASELINE config #1: MLP trained via @alpa_tpu.parallelize.

Runs on any device set; use the virtual CPU mesh for a pod stand-in:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/mnist_mlp.py --platform cpu
"""
import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from flax.training import train_state

import alpa_tpu


class MLP(nn.Module):

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(512)(x)
        x = nn.relu(x)
        return nn.Dense(10)(x)


def synthetic_mnist(batch_size, rng):
    x = rng.randn(batch_size, 784).astype(np.float32)
    y = rng.randint(0, 10, (batch_size,))
    return {"x": x, "y": y}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--platform", default=None)
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch-size", type=int, default=1024)
    args = parser.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    alpa_tpu.init(cluster="local")
    print(f"devices: {jax.devices()}")

    model = MLP()
    rng = jax.random.PRNGKey(0)
    batch = synthetic_mnist(args.batch_size, np.random.RandomState(0))
    params = model.init(rng, jnp.asarray(batch["x"]))
    state = train_state.TrainState.create(apply_fn=model.apply,
                                          params=params,
                                          tx=optax.adam(1e-3))

    @alpa_tpu.parallelize(method=alpa_tpu.DataParallel())
    def train_step(state, batch):

        def loss_fn(p):
            logits = state.apply_fn(p, batch["x"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"]).mean()

        loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    for i in range(args.steps):
        state, loss = train_step(state, batch)
        if i % 10 == 0:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    print(f"final loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
