"""BASELINE config #5 shape: serving a decoder LM over HTTP.

  python examples/serve_gpt.py --port 8000
  curl -X POST localhost:8000/completions \
      -d '{"model": "gpt", "prompt_ids": [1,2,3], "max_new_tokens": 16}'
"""
import argparse
import time

import jax

from alpa_tpu.model.gpt_model import GPTConfig
from alpa_tpu.serve import get_model, run_controller


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--platform", default=None)
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--hidden", type=int, default=256)
    parser.add_argument("--layers", type=int, default=4)
    args = parser.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    config = GPTConfig(hidden_size=args.hidden, num_layers=args.layers,
                       num_heads=8, seq_len=512, vocab_size=32000)
    server = run_controller(port=args.port)
    server.controller.register_model("gpt", get_model(config))
    print(f"serving on http://127.0.0.1:{server.port}  "
          f"(models: {server.controller.list_models()})")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.shutdown()


if __name__ == "__main__":
    main()
