"""BASELINE config #5 shape: serving a decoder LM over HTTP.

  python examples/serve_gpt.py --port 8000 [--family gpt|bloom|codegen]

  # batched completion
  curl -X POST localhost:8000/completions \
      -d '{"model": "lm", "prompt_ids": [1,2,3], "max_new_tokens": 16}'
  # token streaming (server-sent events, rides continuous batching)
  curl -N -X POST localhost:8000/completions \
      -d '{"model": "lm", "prompt_ids": [1,2,3], "max_new_tokens": 16,
           "stream": true}'
"""
import argparse
import time

import jax


def build_generator(family, hidden, layers):
    from alpa_tpu.serve import get_model
    from alpa_tpu.serve.generation import Generator
    if family == "bloom":
        from alpa_tpu.model.bloom_model import BloomConfig, BloomModel
        cfg = BloomConfig(hidden_size=hidden, num_layers=layers,
                          num_heads=8, seq_len=512, vocab_size=32000)
        model = BloomModel(cfg)
    elif family == "codegen":
        from alpa_tpu.model.codegen_model import (CodeGenConfig,
                                                  CodeGenModel)
        cfg = CodeGenConfig(hidden_size=hidden, num_layers=layers,
                            num_heads=8,
                            rotary_dim=min(16, hidden // 8) // 2 * 2,
                            seq_len=512, vocab_size=32000)
        model = CodeGenModel(cfg)
    else:
        from alpa_tpu.model.gpt_model import GPTConfig
        return get_model(GPTConfig(hidden_size=hidden, num_layers=layers,
                                   num_heads=8, seq_len=512,
                                   vocab_size=32000))
    params = model.init(jax.random.PRNGKey(0),
                        jax.numpy.ones((1, 8), jax.numpy.int32))
    return Generator(model, params, cfg, batch_size=1)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--platform", default=None)
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--hidden", type=int, default=256)
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument("--family", default="gpt",
                        choices=["gpt", "bloom", "codegen"])
    args = parser.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from alpa_tpu.serve import run_controller

    server = run_controller(port=args.port)
    server.controller.register_model(
        "lm", build_generator(args.family, args.hidden, args.layers))
    print(f"serving on http://127.0.0.1:{server.port}  "
          f"(models: {server.controller.list_models()})")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.shutdown()


if __name__ == "__main__":
    main()
