"""Serving with admission policies, prefix caching, and speculative
decoding — the round-trip of the serving stack's scheduling features
(ref examples/llm_serving/service/scheduler.py; docs/serving.md).

  python examples/serving_policies.py --platform cpu

Registers a tiny LM with a weighted-fair scheduler (paid queue 4x the
free queue) and a cached system prompt, drives mixed streamed traffic
on both queues, then shows sampled speculative decoding with a draft
model.
"""
import argparse
import http.client
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None, choices=[None, "cpu"],
                    nargs="?")
    args = ap.parse_args()
    if args.platform == "cpu":
        from alpa_tpu.platform import pin_cpu_platform
        pin_cpu_platform(8)

    from alpa_tpu.model.gpt_model import GPTConfig, init_gpt_real
    from alpa_tpu.serve import (Controller, ControllerServer, Generator,
                                WeightedFairQueue)
    from alpa_tpu.serve.generation import GenerationConfig

    cfg = GPTConfig(hidden_size=64, num_layers=2, num_heads=4,
                    seq_len=128, vocab_size=256)
    model, params = init_gpt_real(cfg, 1)
    gen = Generator(model, params, cfg, prompt_buckets=[16],
                    prefill_chunk=16)

    system_prompt = np.arange(1, 9, dtype=np.int32)  # shared prefix
    controller = Controller()
    controller.register_model(
        "lm", gen, prefix_ids=system_prompt,
        scheduler_factory=lambda: WeightedFairQueue({"paid": 4.0,
                                                     "free": 1.0}))
    server = ControllerServer(controller, "127.0.0.1", 0)
    server.start()
    print(f"serving on :{server.port} (prefix {len(system_prompt)} "
          "tokens cached; paid queue weighted 4x)")

    def stream_one(queue, prompt, out):
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=120)
        body = {"model": "lm", "prompt_ids": prompt, "stream": True,
                "max_new_tokens": 6, "queue": queue}
        t0 = time.perf_counter()
        conn.request("POST", "/completions", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        toks, ttft = [], None
        while True:
            line = resp.fp.readline()
            if not line:
                break
            if line.startswith(b"data: "):
                evt = json.loads(line[6:])
                if "token" in evt:
                    if ttft is None:
                        ttft = time.perf_counter() - t0
                    toks.append(evt["token"])
                else:
                    break
        conn.close()
        out.append((queue, round(ttft or 0.0, 3), toks))

    results = []
    threads = [threading.Thread(
        target=stream_one,
        args=("paid" if i % 2 == 0 else "free", [10 + i, 20 + i],
              results)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for queue, ttft, toks in results:
        print(f"  [{queue:4s}] ttft {ttft:6.3f}s tokens {toks}")

    # sampled speculative decoding: draft proposes, target verifies by
    # rejection sampling — output exactly target-distributed
    dcfg = GPTConfig(hidden_size=32, num_layers=1, num_heads=2,
                     seq_len=128, vocab_size=256)
    dmodel, dparams = init_gpt_real(dcfg, 1)
    draft = Generator(dmodel, dparams, dcfg, prompt_buckets=[16])
    out, stats = gen.generate_speculative(
        draft, np.array([5, 6, 7], np.int32),
        GenerationConfig(max_new_tokens=12, do_sample=True,
                         temperature=1.1, top_k=8),
        num_draft=4, seed=0)
    print(f"speculative (sampled): {out.tolist()}  "
          f"accepted {stats['accepted']}/{stats['proposed']} "
          f"in {stats['rounds']} rounds")
    server.shutdown()


if __name__ == "__main__":
    main()
