"""BASELINE config #2: GPT-2-class intra-op auto-sharding on one host.

  python examples/gpt2_training.py                 # real chip(s)
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/gpt2_training.py --platform cpu --model tiny
"""
import argparse
import time

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

import alpa_tpu
from alpa_tpu.model.gpt_model import GPTConfig, GPTModel
from alpa_tpu.model.model_util import cross_entropy_loss
from alpa_tpu.util import compute_gpt_tflops

MODELS = {
    "tiny": GPTConfig(hidden_size=128, num_layers=4, num_heads=8,
                      seq_len=128, vocab_size=1024),
    "125M": GPTConfig(hidden_size=768, num_layers=12, num_heads=12,
                      seq_len=1024, vocab_size=51200,
                      dtype=jnp.bfloat16, attention_impl="flash"),
}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--platform", default=None)
    parser.add_argument("--model", default="125M", choices=MODELS)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--num-micro-batches", type=int, default=1)
    parser.add_argument("--steps", type=int, default=20)
    args = parser.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    alpa_tpu.init(cluster="local")
    config = MODELS[args.model]
    model = GPTModel(config)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (args.batch_size, config.seq_len), 0,
                             config.vocab_size)
    labels = jax.random.randint(jax.random.PRNGKey(1),
                                (args.batch_size, config.seq_len), 0,
                                config.vocab_size)
    params = model.init(rng, ids)
    state = train_state.TrainState.create(apply_fn=model.apply,
                                          params=params,
                                          tx=optax.adamw(1e-4))

    method = alpa_tpu.ShardParallel(
        num_micro_batches=(args.num_micro_batches
                           if args.num_micro_batches > 1 else None))

    @alpa_tpu.parallelize(method=method, donate_argnums=(0,))
    def train_step(state, batch):

        def loss_fn(p):
            logits = state.apply_fn(p, batch["ids"])
            return cross_entropy_loss(logits.astype(jnp.float32),
                                      batch["labels"])

        loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    batch = {"ids": ids, "labels": labels}
    for _ in range(3):
        state, loss = train_step(state, batch)
        float(loss)
    tic = time.perf_counter()
    for i in range(args.steps):
        state, loss = train_step(state, batch)
    final = float(loss)
    dt = (time.perf_counter() - tic) / args.steps
    tflops = compute_gpt_tflops(args.batch_size, config.seq_len,
                                config.num_layers, config.hidden_size,
                                config.vocab_size, len(jax.devices()), dt)
    print(f"loss {final:.4f}  {dt*1e3:.1f} ms/step  "
          f"{tflops:.1f} TFLOPS/device")


if __name__ == "__main__":
    main()
