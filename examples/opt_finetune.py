"""BASELINE config #3 shape: decoder-LM finetuning with inter+intra-op
(pipeshard) parallelism.

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/opt_finetune.py --platform cpu
"""
import argparse

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

import alpa_tpu
from alpa_tpu.model.gpt_model import GPTConfig, GPTModel
from alpa_tpu.model.model_util import cross_entropy_loss


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--platform", default=None)
    parser.add_argument("--num-stages", type=int, default=2)
    parser.add_argument("--num-micro-batches", type=int, default=4)
    parser.add_argument("--steps", type=int, default=5)
    parser.add_argument("--auto-stages", action="store_true",
                        help="use the OSDI'22-style auto stage search")
    args = parser.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    alpa_tpu.init(cluster="local")

    config = GPTConfig(hidden_size=128, num_layers=8, num_heads=8,
                       seq_len=128, vocab_size=2048,
                       pipeline_boundary_every=2)
    model = GPTModel(config)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (16, config.seq_len), 0,
                             config.vocab_size)
    labels = jax.random.randint(jax.random.PRNGKey(1),
                                (16, config.seq_len), 0, config.vocab_size)
    params = model.init(rng, ids)
    state = train_state.TrainState.create(apply_fn=model.apply,
                                          params=params,
                                          tx=optax.adamw(1e-4))

    stage_option = (alpa_tpu.AutoStageOption() if args.auto_stages else
                    alpa_tpu.UniformStageOption(args.num_stages))
    method = alpa_tpu.PipeshardParallel(
        num_micro_batches=args.num_micro_batches,
        layer_option=alpa_tpu.ManualLayerOption(),
        stage_option=stage_option,
        pipeline_schedule="1f1b")

    @alpa_tpu.parallelize(method=method)
    def train_step(state, batch):

        def loss_fn(p):
            logits = state.apply_fn(p, batch["ids"])
            return cross_entropy_loss(logits.astype(jnp.float32),
                                      batch["labels"])

        loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    batch = {"ids": ids, "labels": labels}
    for i in range(args.steps):
        state, loss = train_step(state, batch)
        print(f"step {i}  loss {float(loss):.4f}")
    ex = train_step.get_last_executable()
    print(ex.get_resharding_report())
    print("schedule:")
    print(ex.get_schedule_text())


if __name__ == "__main__":
    main()
