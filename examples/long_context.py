"""Long-context training with ring attention (sequence parallelism).

The sequence dim is sharded over an 'sp' mesh axis: each device holds
S / ring_size tokens of every layer's activations, and ring attention
(alpa_tpu.ops.ring_attention) rotates k/v around the ring while online-
softmax statistics combine exactly — context length scales with the
ring, not with one device's memory.  A capability axis the GPU
reference does not have (its longest context is one GPU's memory).

  python examples/long_context.py --seq 4096 --ring 4   # CPU mesh
  python examples/long_context.py --platform tpu ...    # real chips

Trains a compact GPT-style stack and reports loss + per-device sequence
shard.
"""
import argparse
import time

import jax


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--ring", type=int, default=4)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--lr", type=float, default=3e-3)
    args = ap.parse_args()
    if args.platform == "cpu":
        from alpa_tpu.platform import pin_cpu_platform
        pin_cpu_platform(args.dp * args.ring)

    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from alpa_tpu.ops.ring_attention import make_ring_attention_fn

    n_dev = args.dp * args.ring
    devices = np.array(jax.devices()[:n_dev]).reshape(args.dp, args.ring)
    mesh = Mesh(devices, ("dp", "sp"))
    ring_attn = make_ring_attention_fn(mesh, "sp")

    H, NH, S, V = args.hidden, 4, args.seq, 512
    B, L = args.dp, args.layers
    hd = H // NH
    rng = np.random.RandomState(0)

    params = {
        "wte": jnp.asarray(rng.randn(V, H) * 0.02, jnp.float32),
        "blocks": [{
            "qkv": jnp.asarray(rng.randn(H, 3 * H) * 0.02),
            "out": jnp.asarray(rng.randn(H, H) * 0.02),
            "fc_in": jnp.asarray(rng.randn(H, 4 * H) * 0.02),
            "fc_out": jnp.asarray(rng.randn(4 * H, H) * 0.02),
        } for _ in range(L)],
    }

    def block_fn(p, x):
        b, s, h = x.shape
        q, k, v = jnp.split(x @ p["qkv"], 3, axis=-1)
        o = ring_attn(q.reshape(b, s, NH, hd), k.reshape(b, s, NH, hd),
                      v.reshape(b, s, NH, hd), causal=True)
        x = x + o.reshape(b, s, h) @ p["out"]
        return x + jax.nn.relu(x @ p["fc_in"]) @ p["fc_out"]

    def loss_fn(params, ids, labels):
        x = params["wte"][ids]
        # activations sharded (dp, sp): each device holds S/ring tokens
        x = jax.lax.with_sharding_constraint(x, P("dp", "sp", None))
        for p in params["blocks"]:
            x = block_fn(p, x)
        logits = x @ params["wte"].T
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels).mean()

    tx = optax.adam(args.lr)
    opt_state = tx.init(params)

    def train_step(params, opt_state, ids, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids, labels)
        upd, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, upd), opt_state, loss

    ids = jax.device_put(
        jnp.asarray(rng.randint(0, V, (B, S)), jnp.int32),
        NamedSharding(mesh, P("dp", "sp")))
    labels = jax.device_put(
        jnp.asarray(rng.randint(0, V, (B, S)), jnp.int32),
        NamedSharding(mesh, P("dp", "sp")))

    with jax.set_mesh(mesh):
        step = jax.jit(train_step)
        losses = []
        tic = time.perf_counter()
        for _ in range(args.steps):
            params, opt_state, loss = step(params, opt_state, ids, labels)
            losses.append(float(loss))
    wall = time.perf_counter() - tic
    assert losses[-1] < losses[0], losses
    print(f"mesh (dp={args.dp}, sp={args.ring})  seq {S} "
          f"({S // args.ring} tokens/device)  "
          f"loss {losses[0]:.4f} -> {losses[-1]:.4f}  "
          f"{wall / args.steps:.2f}s/step")


if __name__ == "__main__":
    main()
