"""Pipeline/gradient marker primitive.

Analog of ref ``alpa/pipeline_parallel/primitive_def.py``: a jax primitive
``pipeline_p`` that is semantically the identity, used to tag

* layer boundaries (``mark_pipeline_boundary``, ref :18) — hints consumed by
  layer construction;
* full layer extents (mark_type="start"/"end") wrapping every layer
  input/output, inserted by layer construction so slicing survives jaxpr
  transforms;
* the gradient boundary (``mark_gradient``, ref :24) separating
  compute_grad from apply_grad for gradient accumulation.

Unlike the reference there is **no XLA CustomCall lowering**
(ref primitive_def.py:68-121): all slicing happens at jaxpr level before
lowering (SURVEY.md §7 design translations), so the mlir lowering is a
no-op identity.  JVP/transpose rules keep markers alive through autodiff
(ref :154): transposing a "start" marker yields an "end" marker of the
backward layer and vice versa.
"""
import itertools
from typing import Sequence

import jax
from jax.extend.core import Primitive
from jax.interpreters import ad, batching, mlir
from jax.tree_util import tree_flatten, tree_unflatten

pipeline_p = Primitive("pipeline")
pipeline_p.multiple_results = True


def _pipeline_impl(*args, **_params):
    return args


def _pipeline_abstract_eval(*avals, **_params):
    return avals


pipeline_p.def_impl(_pipeline_impl)
pipeline_p.def_abstract_eval(_pipeline_abstract_eval)


def _pipeline_jvp(primals, tangents, **params):
    primal_outs = pipeline_p.bind(*primals, **params)
    nz_idx = [
        i for i, t in enumerate(tangents) if not isinstance(t, ad.Zero)
    ]
    tangent_outs = list(tangents)
    if nz_idx:
        marked = pipeline_p.bind(
            *[tangents[i] for i in nz_idx],
            name=params["name"] + "_jvp",
            mark_type=params["mark_type"])
        for i, t in zip(nz_idx, marked):
            tangent_outs[i] = t
    return primal_outs, tangent_outs


ad.primitive_jvps[pipeline_p] = _pipeline_jvp

_FLIP = {"start": "end", "end": "start", "grad": "grad", "boundary": "boundary",
         "jvp": "jvp"}


def _pipeline_transpose(cts, *args, name, mark_type):
    nz_idx = [i for i, ct in enumerate(cts) if not isinstance(ct, ad.Zero)]
    out = list(cts)
    if nz_idx:
        marked = pipeline_p.bind(
            *[cts[i] for i in nz_idx],
            name=name + "_backward",
            mark_type=_FLIP[mark_type])
        for i, ct in zip(nz_idx, marked):
            out[i] = ct
    return out


ad.primitive_transposes[pipeline_p] = _pipeline_transpose


def _pipeline_batching(args, dims, **params):
    return pipeline_p.bind(*args, **params), dims


batching.primitive_batchers[pipeline_p] = _pipeline_batching

# Identity lowering: markers vanish at HLO level.
mlir.register_lowering(pipeline_p, lambda ctx, *args, **_params: args)

_boundary_counter = itertools.count()


def mark_pipeline_boundary():
    """User-facing layer-boundary hint (ref primitive_def.py:18).

    Call between layers inside a function parallelized with
    ``ManualLayerOption``-style layer construction.
    """
    pipeline_p.bind(name=str(next(_boundary_counter)), mark_type="boundary")


def mark_pipeline_values(values, name: str, mark_type: str):
    """Wrap a pytree of values in a pipeline marker."""
    flat, tree = tree_flatten(values)
    if not flat:
        return values
    marked = pipeline_p.bind(*flat, name=name, mark_type=mark_type)
    return tree_unflatten(tree, marked)


def mark_gradient(grads):
    """Tag gradient values as the compute/apply split point
    (ref primitive_def.py:24)."""
    return mark_pipeline_values(grads, "grad", "grad")


def is_pipeline_eqn(eqn) -> bool:
    return eqn.primitive is pipeline_p


def is_marker(eqn, mark_type: str) -> bool:
    return eqn.primitive is pipeline_p and eqn.params["mark_type"] == mark_type
