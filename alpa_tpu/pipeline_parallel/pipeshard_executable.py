"""Pipeshard driver executable: compile stages onto submeshes and interpret
the static instruction stream.

Analog of ref ``alpa/pipeline_parallel/pipeshard_executable.py`` (SURVEY.md
§2.4): the reference pushes per-worker instruction lists to Ray actors and
instantiates NCCL groups; here a single controller dispatches async jax
executions onto per-stage meshes, and cross-mesh resharding is
``jax.device_put`` (ICI/DCN transfers by the jax runtime).  Dispatch is
asynchronous, so consecutive RUNs on different meshes overlap on device —
the single Python loop plays the role of the reference's per-host
interpreter loops (``execute_on_worker``, ref pipeshard_executable.py:489).
"""
import contextlib
import itertools
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax._src.core import jaxpr_as_fun
from jax.extend.core import Literal, Var

from alpa_tpu import fault
from alpa_tpu.global_env import global_config
from alpa_tpu.mesh_executable import alloc_zero_buffers
from alpa_tpu.pipeline_parallel.runtime_emitter import (
    PipelineInstType, PipelineInstruction, PipeshardConfig,
    PlacementSpecEntry, emit_free_instructions, partition_streams)
from alpa_tpu.pipeline_parallel.schedules import create_pipeline_schedule
from alpa_tpu.shard_parallel.auto_sharding import MESH_AXIS_NAMES
from alpa_tpu.telemetry import flight as _flight
from alpa_tpu.telemetry import metrics as _tmetrics
from alpa_tpu.telemetry import trace as _ttrace
from alpa_tpu.util import OrderedSet

logger = logging.getLogger(__name__)

# driver-side dispatch latency of one pipeshard step (the whole
# instruction replay, not device wall clock) — replaces the deprecated
# timers("pipeshard-dispatch") bridge
_DISPATCH_SECONDS = _tmetrics.get_registry().histogram(
    "alpa_pipeshard_dispatch_seconds",
    "launch_on_driver dispatch latency per pipeshard step")


class StageExecutable:
    """One compiled stage bound to one mesh.

    Two-phase: ``plan()`` runs the intra-op planner and exposes
    ``in_shardings``; the driver may then *unify* shardings of values
    shared across same-mesh stages (see ``_unify_same_mesh_shardings``)
    before ``compile()`` locks them in — eliminating runtime relayouts
    between stages on one mesh.
    """

    def __init__(self, name, comp, mesh_id, physical_mesh, as_option,
                 logical_shape, donate_idx, as_overrides=None,
                 in_paths=None):
        self.name = name
        self.comp = comp
        self.mesh_id = mesh_id
        self.invars = list(comp.invars)
        self.outvars = list(comp.outvars)
        self.donate_idx = tuple(donate_idx)
        self._physical_mesh = physical_mesh
        self._as_option = as_option
        self._logical_shape = logical_shape
        self._as_overrides = as_overrides
        # pytree paths of stage invars that are global inputs ("" for
        # stage-internal values) — lets the per-stage planner classify
        # optimizer-state / param leaves for weight-update sharding
        self._in_paths = list(in_paths) if in_paths is not None else None
        self._fun = None
        self.compiled = None
        self.plan()

    def plan(self):
        closed = self.comp.closed_jaxpr()
        fun = jaxpr_as_fun(closed)
        avals = [v.aval for v in self.comp.invars]
        physical_mesh = self._physical_mesh
        as_option = self._as_option

        if physical_mesh.num_devices > 1 and as_option.enable_auto_sharding:
            from alpa_tpu.shard_parallel.solver import plan_auto_sharding
            opt = as_option.copy()
            if self._logical_shape is not None:
                opt.logical_mesh_shape = tuple(self._logical_shape)
            # per-stage AutoShardingOption overrides
            # (ref submesh_autosharding_option_dicts)
            for k, v in (self._as_overrides or {}).items():
                if not hasattr(opt, k):
                    raise ValueError(
                        f"unknown AutoShardingOption field {k!r} in "
                        "submesh_autosharding_option_dicts")
                setattr(opt, k, v)
            in_paths = (self._in_paths if self._in_paths is not None
                        else [""] * len(avals))
            jax_mesh, in_shardings, cfn, _shape = plan_auto_sharding(
                fun, avals, in_paths, [], physical_mesh, opt)
            if cfn is not None:
                fun = cfn  # realize the ILP plan inside the stage too
        else:
            from jax.sharding import NamedSharding, PartitionSpec
            lm = physical_mesh.get_logical_mesh(
                (physical_mesh.num_devices, 1))
            jax_mesh = lm.get_jax_mesh(MESH_AXIS_NAMES)
            from alpa_tpu.shard_parallel.auto_sharding import (
                plan_rule_based, resolved_zero_stage)
            if (physical_mesh.num_devices > 1 and
                    self._in_paths is not None and
                    resolved_zero_stage(as_option) in (2, 3)):
                # manual (rule-based) stages still honor forced
                # weight-update sharding over the stage's dp group
                in_shardings = plan_rule_based(
                    jax_mesh, avals, self._in_paths, [], as_option)
            else:
                in_shardings = [
                    NamedSharding(jax_mesh, PartitionSpec()) for _ in avals
                ]
        self._fun = fun
        self._avals = avals
        self.jax_mesh = jax_mesh
        self.in_shardings = list(in_shardings)
        # consumer-pinned output shardings (filled by unification)
        self.pinned_out: Dict[Var, Any] = {}

    def donated_out_shardings(self) -> Dict[Var, Any]:
        """Outvars whose sharding is locked by donation: summed gradient
        accumulators alias their (donated) acc invar's buffer, so their
        output sharding must equal that input sharding.  Single source of
        truth for both unification seeding and compile()."""
        donate_var = {self.comp.invars[i]: i for i in self.donate_idx}
        acc_out_for = getattr(self.comp, "_acc_out_map", {})
        return {
            ov: self.in_shardings[donate_var[acc_out_for[ov]]]
            for ov in self.comp.outvars
            if ov in acc_out_for and acc_out_for[ov] in donate_var
        }

    def compile(self):
        comp = self.comp
        # donated (accumulator) outputs must keep the input sharding
        locked = self.donated_out_shardings()
        out_shardings = []
        for ov in comp.outvars:
            if ov in locked:
                out_shardings.append(locked[ov])
            elif ov in self.pinned_out:
                out_shardings.append(self.pinned_out[ov])
            else:
                out_shardings.append(None)
        in_shardings = self.in_shardings

        jitted = jax.jit(self._fun,
                         in_shardings=tuple(in_shardings),
                         out_shardings=out_shardings,
                         donate_argnums=self.donate_idx)
        with _ttrace.span("xla-compile", "compile",
                          {"stage": self.name} if _ttrace.enabled()
                          else None):
            lowered = jitted.lower(*self._avals)
            self.compiled = lowered.compile()
        self.out_shardings = list(self.compiled.output_shardings)

    def sharding_for(self, var) -> Any:
        return self.in_shardings[self.invars.index(var)]

    def __call__(self, args):
        return self.compiled(*args)


def _unify_same_mesh_shardings(execs: List["StageExecutable"],
                               var_alias: Optional[Dict[Var, Var]] = None):
    """Align shardings of values shared between stages on one mesh:

    * multiple consumers of the same var on a mesh adopt the first
      consumer's planned sharding,
    * producers pin their output sharding of a var to its same-mesh
      consumer's input sharding,

    so no runtime relayout (same-mesh device_put) is needed between
    stages.  Call after every stage's plan() and before any compile().
    """
    # (mesh_id, var) -> chosen sharding (first consumer wins).
    # ``var_alias`` canonicalizes distinct Vars naming the same runtime
    # value (gradient-marker `post` vars alias the accumulator's summed
    # outvar), so apply stages adopt the accumulator shardings.
    var_alias = var_alias or {}

    def canon(v):
        return var_alias.get(v, v)

    chosen: Dict[Tuple[int, Var], Any] = {}
    # accumulator sum outputs are donation-locked to the acc input's
    # sharding — seed those first so consumers (apply stages) adopt them
    for ex in execs:
        for ov, s in ex.donated_out_shardings().items():
            chosen[(ex.mesh_id, canon(ov))] = s
    for ex in execs:
        for pos, v in enumerate(ex.invars):
            key = (ex.mesh_id, canon(v))
            if key in chosen:
                ex.in_shardings[pos] = chosen[key]
            else:
                chosen[key] = ex.in_shardings[pos]
    for ex in execs:
        for v in ex.outvars:
            s = chosen.get((ex.mesh_id, canon(v)))
            if s is not None and v not in ex.donated_out_shardings():
                ex.pinned_out[v] = s


class PipeshardDriverExecutable:
    """(ref pipeshard_executable.py:41)"""

    def __init__(self, *, virtual_mesh, fwd_stages, bwd_stages, apply_comps,
                 submeshes, logical_shapes, as_dicts, as_option,
                 schedule_name, num_micro_batches, global_invars,
                 global_outvars, batch_invars, donated_invars, grad_pairs,
                 acc_info, in_avals, micro_avals, consts_map,
                 apply_var_mesh, invar_paths=None):
        self.num_micro_batches = num_micro_batches
        self.global_invars = global_invars
        self.global_outvars = global_outvars
        self.batch_invars = batch_invars
        self.donated_invars = donated_invars
        self.in_avals = in_avals
        self.out_tree = None  # set by caller
        self.schedule_name = schedule_name
        self.grad_pairs = grad_pairs
        self.acc_info = acc_info
        self.consts_map = consts_map
        # global invar Var -> caller pytree path (keystr); lets per-stage
        # planners and the plan verifier classify optimizer-state leaves
        self.invar_paths: Dict[Var, str] = dict(invar_paths or {})

        num_stages = len(fwd_stages)
        self.num_meshes = num_stages
        self.mesh_group = virtual_mesh.get_physical_mesh_group(submeshes)

        # ---- per-stage gradient-accumulation metadata ----
        # acc invar -> (sum outvar); attach map for sharding pinning
        self.acc_pairs: Dict[Var, Var] = {}
        sum_to_acc = {}
        for pre, (acc, summed, ci) in acc_info.items():
            self.acc_pairs[acc] = summed
            sum_to_acc[summed] = acc
        all_comps = list(fwd_stages) + list(bwd_stages)
        for comp in all_comps:
            comp._acc_out_map = {
                ov: sum_to_acc[ov] for ov in comp.outvars if ov in sum_to_acc
            }

        # ---- compile stages ----
        def stage_paths(comp):
            """Caller pytree path per stage invar ("" for stage-internal
            values) — feeds weight-update sharding classification."""
            if not self.invar_paths:
                return None
            return [self.invar_paths.get(v, "") for v in comp.invars]

        self.stage_execs: List[StageExecutable] = []
        self._stage_of_comp = {}
        tic = time.time()
        for s, comp in enumerate(fwd_stages):
            donate = [
                i for i, v in enumerate(comp.invars) if v in self.acc_pairs
            ]
            self.stage_execs.append(
                StageExecutable(comp.name, comp, s, self.mesh_group[s],
                                as_option, logical_shapes[s], donate,
                                as_dicts[s] if as_dicts else None,
                                in_paths=stage_paths(comp)))
        for s, comp in enumerate(bwd_stages):
            donate = [
                i for i, v in enumerate(comp.invars) if v in self.acc_pairs
            ]
            self.stage_execs.append(
                StageExecutable(comp.name, comp, s, self.mesh_group[s],
                                as_option, logical_shapes[s], donate,
                                as_dicts[s] if as_dicts else None,
                                in_paths=stage_paths(comp)))
        self.num_fwd_stages = len(fwd_stages)
        self.has_bwd = len(bwd_stages) > 0
        # Donate state inputs (params/opt state) to the apply executables
        # that consume them exactly once — realizes the caller's
        # donate_argnums contract so old and new state never coexist.
        donated_global = {
            v for v, d in zip(global_invars, donated_invars) if d
        }
        use_count: Dict[Var, int] = {}
        for comp in apply_comps:
            for v in comp.invars:
                use_count[v] = use_count.get(v, 0) + 1
        self.apply_execs: List[Optional[StageExecutable]] = []
        for m, comp in enumerate(apply_comps):
            if comp.eqns or comp.outvars:
                donate = [
                    i for i, v in enumerate(comp.invars)
                    if v in donated_global and use_count.get(v) == 1
                ]
                self.apply_execs.append(
                    StageExecutable(comp.name, comp, m, self.mesh_group[m],
                                    as_option, logical_shapes[m], donate,
                                    in_paths=stage_paths(comp)))
            else:
                self.apply_execs.append(None)
        # unify shardings of values shared across same-mesh stages, then
        # compile everything with the agreed layouts
        all_execs = self.stage_execs + [
            e for e in self.apply_execs if e is not None
        ]
        post_to_sum = {
            post: acc_info[pre][1]
            for pre, post in grad_pairs if pre in acc_info
        }
        _unify_same_mesh_shardings(all_execs, post_to_sum)
        for e in all_execs:
            e.compile()
        if global_config.print_compilation_time:
            logger.warning("stage compilation took %.2f s",
                           time.time() - tic)

        # ---- build the schedule + instruction stream ----
        self.schedule = create_pipeline_schedule(
            schedule_name,
            num_stages=2 * num_stages if self.has_bwd else num_stages,
            num_meshes=num_stages,
            num_batch=num_micro_batches)
        self._emit()

    # ------------------------------------------------------------------
    # emission
    # ------------------------------------------------------------------
    def _stage_exec_for(self, stage_idx: int) -> StageExecutable:
        S = self.num_fwd_stages
        if stage_idx < S:
            return self.stage_execs[stage_idx]
        # backward stage: bwd of mesh (2S-1-stage_idx)
        mesh = 2 * S - 1 - stage_idx
        return self.stage_execs[S + mesh]

    def _apply_topo_order(self) -> List[int]:
        """Topological order of apply computations by cross-comp data deps.
        Cycles (mutual dependence, e.g. bidirectional norm clipping) raise —
        the compile driver re-partitions onto a single mesh in that case."""
        n = len(self.apply_execs)
        outs_of = {}
        for m, e in enumerate(self.apply_execs):
            if e is not None:
                for v in e.outvars:
                    outs_of[v] = m
        deps = {m: set() for m in range(n)}
        for m, e in enumerate(self.apply_execs):
            if e is None:
                continue
            for v in e.invars:
                src = outs_of.get(v)
                if src is not None and src != m:
                    deps[m].add(src)
        order, done = [], set()

        def visit(m, stack):
            if m in done:
                return
            if m in stack:
                raise ValueError(
                    "Cyclic cross-mesh dependency in apply_grad partition")
            stack.add(m)
            for d in deps[m]:
                visit(d, stack)
            stack.discard(m)
            done.add(m)
            order.append(m)

        for m in range(n):
            visit(m, set())
        return order

    def _emit(self):
        self._resharding_bytes = 0.0
        self._executed_resharding_bytes = 0.0
        self._executed_intra_mesh_bytes = 0.0
        # max per-link (per-device egress/ingress) bytes over all planned
        # cross-mesh transfers — the ISSUE 4 planner objective
        self._max_link_bytes = 0.0
        ginvar_idx = {v: i for i, v in enumerate(self.global_invars)}
        batch_var = {
            v for v, b in zip(self.global_invars, self.batch_invars) if b
        }
        instructions: List[PipelineInstruction] = []
        # key -> set of meshes currently holding the value
        location: Dict[Tuple[Var, int], OrderedSet] = {}
        # (var, inst, mesh) -> sharding the value currently has there
        sharding_at: Dict[Tuple[Var, int, int], Any] = {}

        def _compatible(s1, s2, ndim):
            if s1 is None or s2 is None:
                return True
            try:
                return s1.is_equivalent_to(s2, ndim)
            except Exception:  # pylint: disable=broad-except
                return s1 == s2

        # global invar placement (filled on demand)
        self.input_place: Dict[Var, List[Tuple[int, Any]]] = {}
        self.const_place: Dict[Var, List[Tuple[int, Any]]] = {}
        self.acc_allocs: List[Tuple[Var, int, Any, Any]] = []

        post_alias = {}
        for pre, post in self.grad_pairs:
            if pre in self.acc_info:
                _, summed, _ = self.acc_info[pre]
                post_alias[post] = summed

        def key_of(v, mb, exec_, first_mb):
            """Resolve the env key an invar reads from."""
            if v in self.acc_pairs:  # accumulator input
                if mb == first_mb:
                    return (v, -1)
                return (self.acc_pairs[v], -1)
            if v in post_alias:
                return (post_alias[v], -1)
            if v in ginvar_idx:
                return (v, mb) if v in batch_var else (v, -1)
            if v in self.consts_map:
                return (v, -1)
            return (v, mb)

        def ensure_on_mesh(key, mesh_id, dst_sharding, exec_name):
            v = key[0]
            ndim = len(getattr(v.aval, "shape", ()))
            if key not in location:
                # input / const / accumulator placed at launch
                if v in self.acc_pairs:
                    location[key] = OrderedSet([mesh_id])
                    sharding_at[(v, key[1], mesh_id)] = dst_sharding
                    return
                place_list = (self.input_place if v in ginvar_idx else
                              self.const_place).setdefault(v, [])
                if mesh_id not in [m for m, _ in place_list]:
                    place_list.append((mesh_id, dst_sharding))
                    sharding_at[(v, key[1], mesh_id)] = dst_sharding
                location[key] = OrderedSet([m for m, _ in place_list])
            if mesh_id not in location[key]:
                # ReplicatedDistributedArray analog (ref device_mesh.py:1697):
                # a non-batch global input or const consumed by stages on
                # several meshes (e.g. a tied embedding table used by both
                # the first and last stage) is placed on EACH mesh directly
                # from the host at launch — one logical tensor, multiple
                # residencies — instead of a serialized cross-mesh hop.
                replicable = (v in self.consts_map or
                              (v in ginvar_idx and v not in batch_var))
                if replicable and v not in self.acc_pairs:
                    place_list = (self.input_place if v in ginvar_idx else
                                  self.const_place).setdefault(v, [])
                    if mesh_id not in [m for m, _ in place_list]:
                        place_list.append((mesh_id, dst_sharding))
                    location[key].add(mesh_id)
                    sharding_at[(v, key[1], mesh_id)] = dst_sharding
                    return
                src = next(iter(location[key]))
                inst = PipelineInstruction(PipelineInstType.RESHARD,
                                           var_key=key, src_mesh=src,
                                           dst_mesh=mesh_id,
                                           dst_sharding=dst_sharding,
                                           info=exec_name)
                # plan the cross-mesh transfer (tile coverage + local
                # allgather rewrite) for accounting/reporting
                src_sh = sharding_at.get((v, key[1], src))
                if src_sh is not None and hasattr(v.aval, "shape"):
                    try:
                        from alpa_tpu.pipeline_parallel. \
                            cross_mesh_resharding import (ReshardingTask,
                                                          plan_resharding)
                        inst.src_sharding = src_sh
                        inst.plan = plan_resharding(
                            tuple(v.aval.shape), v.aval.dtype.itemsize,
                            src_sh, dst_sharding)
                        self._resharding_bytes += inst.plan.transfer_bytes
                        self._max_link_bytes = max(
                            self._max_link_bytes, inst.plan.max_link_bytes,
                            inst.plan.max_link_bytes_broadcast)
                        # pre-built, reusable executor: planned execution
                        # modes replay this task every step instead of
                        # re-resolving it on the hot path
                        inst.task = ReshardingTask(inst.plan, dst_sharding)
                    except Exception as e:  # pylint: disable=broad-except
                        # the planned execution mode silently degrades to
                        # device_put for this transfer — keep it visible
                        logger.warning(
                            "resharding plan for %s (%s -> mesh %d) "
                            "failed: %s", v, exec_name, mesh_id, e)
                        inst.plan = None
                instructions.append(inst)
                location[key].add(mesh_id)
                sharding_at[(v, key[1], mesh_id)] = dst_sharding
                return
            # present on this mesh: reconcile layout if needed
            cur = sharding_at.get((v, key[1], mesh_id))
            if not _compatible(cur, dst_sharding, ndim):
                instructions.append(
                    PipelineInstruction(PipelineInstType.RESHARD,
                                        var_key=key, src_mesh=mesh_id,
                                        dst_mesh=mesh_id,
                                        dst_sharding=dst_sharding,
                                        info=f"relayout:{exec_name}"))
                sharding_at[(v, key[1], mesh_id)] = dst_sharding

        first_mb_of_stage = {}

        def emit_run(exec_: StageExecutable, mb: int, mesh_id: int):
            first_mb = first_mb_of_stage.setdefault(id(exec_), mb)
            in_keys = []
            for pos, v in enumerate(exec_.invars):
                k = key_of(v, mb, exec_, first_mb)
                if v in self.acc_pairs and k == (v, -1):
                    # zero-allocated accumulator
                    if not any(a[0] is v for a in self.acc_allocs):
                        self.acc_allocs.append(
                            (v, mesh_id, v.aval, exec_.in_shardings[pos]))
                    location[(v, -1)] = OrderedSet([mesh_id])
                    sharding_at[(v, -1, mesh_id)] = exec_.in_shardings[pos]
                ensure_on_mesh(k, mesh_id, exec_.in_shardings[pos],
                               exec_.name)
                in_keys.append(k)
            out_keys = []
            for pos, ov in enumerate(exec_.outvars):
                k = (ov, -1) if ov in getattr(exec_.comp, "_acc_out_map",
                                              {}) else (ov, mb)
                out_keys.append(k)
                location[k] = OrderedSet([mesh_id])
                sharding_at[(k[0], k[1], mesh_id)] = exec_.out_shardings[pos]
            instructions.append(
                PipelineInstruction(PipelineInstType.RUN,
                                    stage_id=self.stage_execs.index(exec_)
                                    if exec_ in self.stage_execs else -1,
                                    micro_batch=mb,
                                    input_keys=in_keys,
                                    output_keys=out_keys,
                                    dst_mesh=mesh_id,
                                    info=exec_.name))
            instructions[-1].executable = exec_

        for tick in self.schedule.schedules:
            for mesh_id, task in enumerate(tick):
                if task is None:
                    continue
                mb, stage_idx = task
                exec_ = self._stage_exec_for(stage_idx)
                if not exec_.invars and not exec_.outvars:
                    continue
                emit_run(exec_, mb, mesh_id)

        # apply-grad runs, in dependency order (one apply comp may consume
        # another's exported values, e.g. a global grad-norm scalar)
        for m in self._apply_topo_order():
            exec_ = self.apply_execs[m]
            if exec_ is None:
                continue
            emit_run(exec_, -1, m)

        # ---- output specs ----
        self.output_specs = []
        sub_outvars = list(self.global_outvars)
        for v in sub_outvars:
            if isinstance(v, Literal):
                self.output_specs.append(("literal", v.val))
                continue
            k = (post_alias.get(v, v), -1)
            if k in location:
                self.output_specs.append(
                    ("env", (k, next(iter(location[k])))))
            elif (v, 0) in location:
                # per-microbatch output (inference)
                meshes = [(mb, next(iter(location[(v, mb)])))
                          for mb in range(self.num_micro_batches)]
                self.output_specs.append(("concat", (v, meshes)))
            elif v in ginvar_idx:
                self.output_specs.append(("input", ginvar_idx[v]))
            else:
                raise ValueError(
                    f"Cannot trace global output {v} to a stage output")

        protected = set()
        for spec_kind, payload in self.output_specs:
            if spec_kind == "env":
                (k, m) = payload
                protected.add((k[0], k[1], m))
            elif spec_kind == "concat":
                v, meshes = payload
                for mb, m in meshes:
                    protected.add((v, mb, m))
        self.instructions = emit_free_instructions(instructions, protected)
        # pre-partitioned per-mesh worker streams (the reference's
        # per-host instruction lists, computed once at emit time)
        self._instruction_streams = partition_streams(
            self.instructions, self.num_meshes)
        self._acct_lock = threading.Lock()
        self._const_cache = None
        self._zero_exec_cache = None
        # register-file replay fast path (built lazily on first eligible
        # launch; see _ensure_lowered).  _register_programs maps lowering
        # mode ("registers" | "overlap") -> RegisterFileProgram; the two
        # modes share identical slot numbering (phase-1 lowering is
        # mode-independent) so the launch-time slot tables are built once.
        self._register_programs = {}
        self._register_program = None   # the "registers" program (tests)
        self._has_cross_mesh = any(
            i.opcode == PipelineInstType.RESHARD and
            i.src_mesh != i.dst_mesh for i in self.instructions)
        self._reg_input_loads = None
        self._reg_const_loads = None
        self._reg_acc_slots = None
        self._reg_output_specs = None
        # certified superoptimization (ISSUE 17): the one-shot rewrite
        # decision (analysis/superopt.py SuperoptOutcome) and, when a
        # rewrite was accepted in auto mode, the rewritten instruction
        # list every lowering mode shares (identical slot_of).
        self._superopt_outcome = None
        self._superopt_instructions = None
        self._warned_register_fallback = False
        # quiesce gate: fault.RecoveryManager pauses new launches and
        # waits out in-flight ones before snapshotting driver state
        self._launch_gate = threading.Event()
        self._launch_gate.set()
        self._inflight_launches = 0
        self._quiesce_cv = threading.Condition()

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def launch_on_driver(self, *flat_args):
        # blocks while quiesced (recovery in progress): a launch racing
        # a mesh failure would dispatch onto dead devices
        self._launch_gate.wait()
        with self._quiesce_cv:
            self._inflight_launches += 1
        t0 = time.perf_counter()
        step_span = _ttrace.begin("pipeshard.step", "runtime")
        try:
            return self._launch(*flat_args)
        except BaseException:
            # post-mortem timeline of the instructions leading up to the
            # failure (no-op when the ring is empty or already dumped)
            _flight.auto_dump("pipeshard step raised")
            raise
        finally:
            _ttrace.end(step_span)
            _DISPATCH_SECONDS.observe(time.perf_counter() - t0)
            with self._quiesce_cv:
                self._inflight_launches -= 1
                self._quiesce_cv.notify_all()

    def quiesce(self, timeout: Optional[float] = None) -> bool:
        """Pause new launches and wait until in-flight pipeshard work
        drains (the recovery state machine's pre-snapshot step).
        Returns True when the driver reached a quiescent point within
        ``timeout``; launches stay blocked until :meth:`resume`."""
        self._launch_gate.clear()
        with self._quiesce_cv:
            drained = self._quiesce_cv.wait_for(
                lambda: self._inflight_launches == 0, timeout)
        if drained:
            try:
                self.sync()  # drain on-device queues too
            except Exception:  # pylint: disable=broad-except
                # a dead mesh cannot sync — quiescing must still succeed
                # driver-side so recovery can proceed
                logger.exception("quiesce: device sync failed")
        return bool(drained)

    def resume(self):
        """Re-open the launch gate after recovery."""
        self._launch_gate.set()

    def _launch(self, *flat_args):
        env: Dict[Tuple[Var, int], Dict[int, Any]] = {}
        n_mb = self.num_micro_batches
        # executed-resharding accounting is per step, comparable to the
        # per-step planned bytes in get_resharding_report
        self._executed_resharding_bytes = 0.0
        self._executed_intra_mesh_bytes = 0.0
        exec_mode = global_config.resharding_execution
        if exec_mode not in ("device_put", "planned"):
            raise ValueError(
                "global_config.resharding_execution must be 'device_put' "
                f"or 'planned', got {exec_mode!r}")
        multiprocess = jax.process_count() > 1
        # Register-file replay fast path (ISSUE 2): the lowered program
        # does no dict hashing / sharding resolution per call.  Fault
        # sites, trace collection, and race checking are NOT exclusions
        # (ISSUE 6): they compile in as per-node hooks on the graph
        # executor, so instrumented launches run the same fast path.
        # Only planned resharding and the multi-process collective-order
        # contract still take the interpreter below.
        dmode = getattr(global_config, "pipeline_dispatch_mode", "auto")
        reg_ok = not multiprocess and exec_mode == "device_put"
        if dmode in ("registers", "overlap") and not reg_ok and \
                not self._warned_register_fallback:
            self._warned_register_fallback = True
            logger.warning(
                "pipeline_dispatch_mode=%r requested but the "
                "launch is not eligible (multiprocess or planned "
                "resharding); falling back to the "
                "instruction interpreter", dmode)
        # overlap mode (ISSUE 4): replay the dataflow graph with eager
        # async cross-mesh transfers.  Eligible when the register path is
        # eligible AND there is actual cross-mesh traffic to overlap.
        overlap_ok = (reg_ok and self.num_meshes > 1 and
                      self._has_cross_mesh and
                      getattr(global_config, "overlap_resharding", True))
        if dmode == "overlap" and reg_ok and not overlap_ok and \
                not self._warned_register_fallback:
            self._warned_register_fallback = True
            logger.warning(
                "pipeline_dispatch_mode='overlap' requested but there is "
                "nothing to overlap (single mesh, no cross-mesh RESHARDs, "
                "or overlap_resharding disabled); using register dispatch")
        if reg_ok and dmode in ("auto", "registers", "overlap"):
            use_overlap = overlap_ok and dmode in ("auto", "overlap")
            return self._launch_registers(
                flat_args, mode="overlap" if use_overlap else "registers")
        # multiprocess + "planned": cross-process RESHARD instructions
        # drive the tile plan via ReshardingTask.run_multiprocess (packed
        # tiles cross the boundary, not a full-array gather); everything
        # else stays host-mediated put_global
        mp_planned = multiprocess and exec_mode == "planned"
        if multiprocess:
            from alpa_tpu.distributed import host_gather, put_global
            _put = put_global
            exec_mode = "device_put"
        else:
            _put = jax.device_put

        # place global inputs
        for v, places in self.input_place.items():
            i = self.global_invars.index(v)
            arg = flat_args[i]
            if self.batch_invars[i]:
                if n_mb == 1:
                    mbs = [arg]
                elif isinstance(arg, jax.Array):
                    from alpa_tpu.distributed import is_process_local
                    if multiprocess and not is_process_local(arg):
                        # global array: collective gather (path choice uses
                        # only global metadata, so processes stay aligned)
                        mbs = np.split(host_gather(arg), n_mb, axis=0)
                    elif multiprocess:
                        mbs = np.split(np.asarray(arg), n_mb, axis=0)
                    else:
                        # split on device: avoids a blocking D2H round trip
                        mbs = jnp.split(arg, n_mb, axis=0)
                else:
                    mbs = np.split(np.asarray(arg), n_mb, axis=0)
                for mb in range(n_mb):
                    slot = env.setdefault((v, mb), {})
                    for mesh_id, sharding in places:
                        slot[mesh_id] = _put(mbs[mb], sharding)
            else:
                slot = env.setdefault((v, -1), {})
                for mesh_id, sharding in places:
                    slot[mesh_id] = _put(arg, sharding)

        # place consts (cached across calls)
        if self._const_cache is None:
            self._const_cache = {}
            for v, places in self.const_place.items():
                val = self.consts_map[v]
                slot = {}
                for mesh_id, sharding in places:
                    slot[mesh_id] = _put(val, sharding)
                self._const_cache[v] = slot
        for v, slot in self._const_cache.items():
            env[(v, -1)] = dict(slot)

        # zero accumulators (compiled once, reused every step)
        self._ensure_zero_execs()
        for mesh_id, vs, compiled in self._zero_exec_cache:
            bufs = compiled()
            for v, buf in zip(vs, bufs):
                env.setdefault((v, -1), {})[mesh_id] = buf

        # interpret.  Two dispatch modes (global_config.
        # pipeline_dispatch_mode):
        #
        # * "sequential": one Python loop over the global stream — the
        #   only collective-safe mode multi-process, where every process
        #   must issue collectives in the same order.
        # * "threaded": the emitter's pre-partitioned PER-MESH instruction
        #   streams (runtime_emitter.partition_streams — the
        #   single-controller analog of the reference's pre-pushed
        #   per-worker instruction lists) each run on their own worker
        #   thread, synchronized by cross-stream dependency events, so a
        #   slow enqueue on one mesh never stalls dispatch onto another.
        #
        # "auto" picks threaded for single-process multi-mesh, sequential
        # otherwise.  Per-opcode wall time is recorded either way so the
        # driver-side dispatch overhead (SURVEY §7 hard part 5) is
        # measurable: on an async backend RUN returns as soon as the work
        # is enqueued, so ``last_dispatch_stats`` bounds the
        # per-instruction driver cost.
        collect = global_config.collect_trace
        stats = {"RUN": [0, 0.0], "RESHARD": [0, 0.0], "FREE": [0, 0.0]}
        ctx = (env, _put, exec_mode, mp_planned, collect, stats)
        dmode = getattr(global_config, "pipeline_dispatch_mode", "auto")
        use_threads = (dmode == "threaded" or
                       (dmode == "auto" and self.num_meshes > 1)) \
            and not multiprocess
        loop_tic = time.perf_counter()
        if use_threads:
            self._run_streams_threaded(ctx)
        else:
            for inst_idx, inst in enumerate(self.instructions):
                inst_tic = time.perf_counter()
                self._exec_inst(inst, ctx, inst_idx)
                s = stats[inst.opcode.name]
                s[0] += 1
                s[1] += time.perf_counter() - inst_tic
        loop_s = time.perf_counter() - loop_tic
        n_inst = max(1, len(self.instructions))
        self.last_dispatch_stats = {
            "n_instructions": len(self.instructions),
            "loop_s": loop_s,
            "per_inst_us": loop_s / n_inst * 1e6,
            "mode": "threaded" if use_threads else "sequential",
            "by_opcode": {k: {"n": n, "s": t}
                          for k, (n, t) in stats.items()},
        }

        # collect outputs
        outs = []
        for kind, payload in self.output_specs:
            if kind == "literal":
                outs.append(payload)
            elif kind == "env":
                k, m = payload
                outs.append(env[k][m])
            elif kind == "input":
                outs.append(flat_args[payload])
            else:  # concat over microbatches (inference outputs)
                v, meshes = payload
                vals = [env[(v, mb)][m] for mb, m in meshes]
                if n_mb == 1:
                    outs.append(vals[0])
                elif vals[0].ndim >= 1:
                    # axis 0 must be the (microbatched) batch dim
                    outs.append(jnp.concatenate(
                        [jax.device_put(
                            x, self.mesh_group[meshes[0][1]]
                            .flat_devices[0]) for x in vals], axis=0))
                else:
                    raise ValueError(
                        "A scalar output of a pipelined forward-only "
                        "function is ambiguous with num_micro_batches > 1 "
                        "(per-microbatch reduction cannot be recombined); "
                        "return per-example values or use "
                        "num_micro_batches=1.")
        return outs

    def _ensure_zero_execs(self):
        """Compile (once) the per-mesh zero-accumulator allocators."""
        if self._zero_exec_cache is not None:
            return
        self._zero_exec_cache = []
        by_mesh: Dict[int, List] = {}
        for v, mesh_id, aval, sharding in self.acc_allocs:
            by_mesh.setdefault(mesh_id, []).append((v, aval, sharding))
        for mesh_id, items in by_mesh.items():
            avals = [a for _, a, _ in items]
            shardings = [s for _, _, s in items]
            compiled = (jax.jit(
                lambda avs=tuple(avals): [
                    jnp.zeros(a.shape, a.dtype) for a in avs
                ],
                out_shardings=shardings).lower().compile())
            self._zero_exec_cache.append(
                (mesh_id, [v for v, _, _ in items], compiled))

    # ------------------------------------------------------------------
    # register-file replay fast path (ISSUE 2)
    # ------------------------------------------------------------------
    def _overlap_window(self) -> int:
        """The in-flight transfer window for overlap lowering: the
        explicit knob when set, otherwise the schedule's hint."""
        w = int(getattr(global_config, "overlap_inflight_window", 0) or 0)
        if w > 0:
            return w
        hint = getattr(self.schedule, "overlap_window_hint", None)
        return int(hint()) if callable(hint) else max(2, self.num_meshes)

    def _make_lowerer(self, mode: str = "registers"):
        """Build the lowering closure for one mode: derives the static
        sharding seed, opt-state/provenance/protected key sets, and the
        equivalence reference from THIS executable, and returns
        ``lower(instructions) -> RegisterFileProgram``.  Shared by
        ``_ensure_lowered`` and the superopt engine (ISSUE 17), which
        lowers candidate instruction lists through the same context —
        so a rewritten program carries coherent OpHook/dataflow/
        PlanModel metadata and is verified against the same reference.
        """
        from alpa_tpu.pipeline_parallel.runtime_emitter import (
            lower_to_register_file)
        n_mb = self.num_micro_batches
        ginvar_idx = {v: i for i, v in enumerate(self.global_invars)}

        # static sharding seed: everything placed at launch
        preplaced: Dict[Tuple[Var, int, int], Any] = {}
        for v, places in self.input_place.items():
            if self.batch_invars[ginvar_idx[v]]:
                for mesh_id, sh in places:
                    for mb in range(n_mb):
                        preplaced[(v, mb, mesh_id)] = sh
            else:
                for mesh_id, sh in places:
                    preplaced[(v, -1, mesh_id)] = sh
        for v, places in self.const_place.items():
            for mesh_id, sh in places:
                preplaced[(v, -1, mesh_id)] = sh
        for v, mesh_id, _aval, sh in self.acc_allocs:
            preplaced[(v, -1, mesh_id)] = sh

        # optimizer-state inputs, classified by pytree path, so the
        # verifier's liveness pass can attribute resident bytes to
        # alpa_opt_state_bytes{mesh} and statically prove the ZeRO
        # saving (ISSUE 10)
        opt_state_keys = set()
        if self.invar_paths:
            from alpa_tpu.shard_parallel.auto_sharding import (
                is_opt_state_path)
            for v, places in self.input_place.items():
                if not is_opt_state_path(self.invar_paths.get(v, "")):
                    continue
                for mesh_id, _sh in places:
                    opt_state_keys.add((v, -1, mesh_id))

        # provenance seed for the numerics certification (ISSUE 14):
        # classify every launch-placed value by its pytree path so the
        # precision-flow analysis can prove params / opt state never
        # cross a lossy hop anywhere along their flow
        provenance_keys: Dict[Tuple[Var, int, int], str] = {}
        if self.invar_paths:
            from alpa_tpu.shard_parallel.auto_sharding import (
                is_opt_state_path, is_param_path)
            for v, places in self.input_place.items():
                path = self.invar_paths.get(v, "")
                if is_opt_state_path(path):
                    prov = "opt_state"
                elif is_param_path(path):
                    prov = "param"
                else:
                    prov = "activation"
                if self.batch_invars[ginvar_idx[v]]:
                    for mesh_id, _sh in places:
                        for mb in range(n_mb):
                            provenance_keys[(v, mb, mesh_id)] = prov
                else:
                    for mesh_id, _sh in places:
                        provenance_keys[(v, -1, mesh_id)] = prov
        for v, mesh_id, _aval, _sh in self.acc_allocs:
            provenance_keys[(v, -1, mesh_id)] = "gradient"

        # program outputs are never FREEd by design — the plan
        # verifier's leak analysis must not flag them (ISSUE 8)
        protected = set()
        for spec_kind, payload in self.output_specs:
            if spec_kind == "env":
                (k, m) = payload
                protected.add((k[0], k[1], m))
            elif spec_kind == "concat":
                v, meshes = payload
                for mb, m in meshes:
                    protected.add((v, mb, m))
        # reference decomposition for the translation validation
        # (ISSUE 15): the driver's pre-lowering RUN stream as serial
        # stage applications over (var, microbatch) value keys —
        # deliberately derived here, before lowering, so the certifier
        # proves the register program against an independent artifact
        superopt_active = getattr(
            global_config, "superopt_mode", "off") in ("suggest", "auto")
        equiv_reference = None
        if (getattr(global_config, "verify_plans", "warn") != "off" or
                superopt_active) and \
                getattr(global_config, "verify_plans_equiv",
                        "warn") != "off":
            from alpa_tpu.analysis import equivalence as _equiv
            equiv_reference = _equiv.build_reference(
                self.instructions, n_mb)

        def _lower(insts):
            # the equivalence reference stays derived from the ORIGINAL
            # driver stream above, so translation validation proves any
            # superopt rewrite still computes the source jaxpr.  With
            # superopt active the verdict gate needs verified programs,
            # so verify_plans=off is upgraded to warn for the lowering.
            old_verify = global_config.verify_plans
            try:
                if superopt_active and old_verify == "off":
                    global_config.verify_plans = "warn"
                return lower_to_register_file(
                    insts, preplaced, mode=mode,
                    overlap_window=self._overlap_window(),
                    protected_keys=frozenset(protected),
                    opt_state_keys=frozenset(opt_state_keys),
                    provenance_keys=provenance_keys,
                    equiv_reference=equiv_reference)
            finally:
                global_config.verify_plans = old_verify

        return _lower

    def _ensure_lowered(self, mode: str = "registers"):
        """Lower the instruction list into a RegisterFileProgram (once
        per mode) and precompute the launch-time slot tables: input
        loads, const loads, accumulator slots, and output slots — so the
        replay loop touches only integer-indexed lists.  Phase-1 lowering
        is mode-independent, so every mode's program has identical
        ``slot_of`` and the slot tables are shared."""
        prog = self._register_programs.get(mode)
        if prog is not None:
            return prog
        _lower = self._make_lowerer(mode)
        superopt_active = getattr(
            global_config, "superopt_mode", "off") in ("suggest", "auto")
        prog = None
        if superopt_active and self._superopt_outcome is None:
            prog = self._run_superopt(_lower)
        if prog is None:
            prog = _lower(self._superopt_instructions
                          if self._superopt_instructions is not None
                          else self.instructions)
        self._register_programs[mode] = prog
        if mode == "registers":
            self._register_program = prog
        slot_of = prog.slot_of
        if self._reg_input_loads is not None:
            return prog
        n_mb = self.num_micro_batches
        ginvar_idx = {v: i for i, v in enumerate(self.global_invars)}

        # input placement: (flat arg index, is_batch, [(slot, sharding,
        # microbatch)]) — resolved once, replayed every launch
        self._reg_input_loads = []
        for v, places in self.input_place.items():
            i = ginvar_idx[v]
            entries = []
            if self.batch_invars[i]:
                for mesh_id, sh in places:
                    for mb in range(n_mb):
                        entries.append((slot_of[(v, mb, mesh_id)], sh, mb))
            else:
                for mesh_id, sh in places:
                    entries.append((slot_of[(v, -1, mesh_id)], sh, -1))
            self._reg_input_loads.append((i, self.batch_invars[i], entries))

        # outputs: mirror output_specs with slots
        out_specs = []
        for kind, payload in self.output_specs:
            if kind == "literal":
                out_specs.append(("literal", payload))
            elif kind == "env":
                k, m = payload
                out_specs.append(("slot", slot_of[(k[0], k[1], m)]))
            elif kind == "input":
                out_specs.append(("input", payload))
            else:  # concat
                v, meshes = payload
                out_specs.append(
                    ("concat", ([slot_of[(v, mb, m)] for mb, m in meshes],
                                meshes)))
        self._reg_output_specs = out_specs
        return prog

    def _run_superopt(self, lower):
        """One-shot certified-superoptimization decision (ISSUE 17;
        analysis/superopt.py).  Lowers the baseline, runs the cached/
        searched rewrite engine with the seven-analysis verdict gate,
        and — in auto mode with an accepted rewrite — stores the
        rewritten instruction list so every later lowering mode shares
        it (identical ``slot_of``).  Returns the program to use for the
        calling mode, or None to fall through to a plain lowering."""
        from alpa_tpu.analysis import superopt as _superopt
        from alpa_tpu.analysis.plan_verifier import PlanVerdict
        smode = getattr(global_config, "superopt_mode", "off")
        baseline = lower(self.instructions)

        def _verify(p, _insts):
            v = getattr(p, "verdict", None)
            return v if v is not None else PlanVerdict()

        try:
            outcome = _superopt.run_superopt(
                list(self.instructions), self.num_meshes, baseline,
                lower, _verify, mode=smode)
        except Exception:  # pylint: disable=broad-except
            logger.exception(
                "superopt: engine failed; keeping the baseline plan")
            self._superopt_outcome = _superopt.SuperoptOutcome(
                mode=smode, searched=True, cache_hit=False,
                accepted=False,
                layout=_superopt.identity_layout(
                    len(self.instructions)),
                baseline_score=_superopt.PlanScore(0.0, ()),
                best_score=_superopt.PlanScore(0.0, ()),
                baseline_fingerprint=baseline.fingerprint(),
                fingerprint=None, rejected=[("superopt", "engine-error")],
                log=[])
            return baseline
        self._superopt_outcome = outcome
        if smode == "auto" and outcome.accepted and \
                outcome.instructions is not None:
            self._superopt_instructions = list(outcome.instructions)
            return outcome.program
        return baseline

    def get_superopt_text(self) -> str:
        """Human-readable superopt decision report (``superopt.txt``
        in monitoring.dump_debug_info; scripts/perf_tool.py superopt)."""
        from alpa_tpu.analysis import superopt as _superopt
        return _superopt.format_superopt_report(self._superopt_outcome)

    def _launch_registers(self, flat_args, mode: str = "registers"):
        """Replay the lowered register-file program: flat list reads and
        writes only — the per-instruction driver cost is the compiled
        executables' C++ dispatch plus the pre-resolved transfers.  In
        ``overlap`` mode the program is the dataflow-graph replay with
        eager async cross-mesh transfers (ISSUE 4)."""
        prog = self._ensure_lowered(mode)
        regs: List[Any] = [None] * prog.num_slots
        n_mb = self.num_micro_batches

        # place global inputs in one batched device_put
        put_vals, put_shs, put_slots = [], [], []
        for arg_idx, is_batch, entries in self._reg_input_loads:
            arg = flat_args[arg_idx]
            if is_batch:
                if n_mb == 1:
                    mbs = [arg]
                elif isinstance(arg, jax.Array):
                    mbs = jnp.split(arg, n_mb, axis=0)
                else:
                    mbs = np.split(np.asarray(arg), n_mb, axis=0)
                for s, sh, mb in entries:
                    put_vals.append(mbs[mb])
                    put_shs.append(sh)
                    put_slots.append(s)
            else:
                for s, sh, _mb in entries:
                    put_vals.append(arg)
                    put_shs.append(sh)
                    put_slots.append(s)
        if put_vals:
            placed = jax.device_put(put_vals, put_shs)
            for s, o in zip(put_slots, placed):
                regs[s] = o

        # consts (placed once, re-slotted per launch)
        if self._reg_const_loads is None:
            slot_of = prog.slot_of
            loads = []
            for v, places in self.const_place.items():
                val = self.consts_map[v]
                for mesh_id, sh in places:
                    loads.append((slot_of[(v, -1, mesh_id)],
                                  jax.device_put(val, sh)))
            self._reg_const_loads = loads
        for s, a in self._reg_const_loads:
            regs[s] = a

        # zero accumulators (compiled once; slots resolved once)
        self._ensure_zero_execs()
        if self._reg_acc_slots is None:
            slot_of = prog.slot_of
            self._reg_acc_slots = [
                (compiled, [slot_of[(v, -1, mesh_id)] for v in vs])
                for mesh_id, vs, compiled in self._zero_exec_cache
            ]
        for compiled, slots in self._reg_acc_slots:
            for s, buf in zip(slots, compiled()):
                regs[s] = buf

        # replay
        loop_tic = time.perf_counter()
        prog.execute(regs)
        loop_s = time.perf_counter() - loop_tic
        n_inst = max(1, prog.n_instructions)
        self.last_dispatch_stats = {
            "n_instructions": prog.n_instructions,
            "n_ops": len(prog.ops),
            "loop_s": loop_s,
            "per_inst_us": loop_s / n_inst * 1e6,
            "mode": prog.mode,
            # hook families compiled into this replay ("trace"/"fault"/
            # "race"/"flight"; empty = raw closures, zero added branches)
            "hooks": prog.last_hooks,
            "by_opcode": {k: {"n": v, "s": 0.0}
                          for k, v in prog.by_opcode.items()},
        }
        if prog.mode == "overlap":
            busy = prog.run_stats["transfer_busy_s"]
            blocked = prog.run_stats["wait_blocked_s"]
            frac = max(0.0, min(1.0, 1.0 - blocked / busy)) if busy > 0 \
                else 1.0
            self.last_dispatch_stats.update(
                n_cross_mesh=prog.n_cross_mesh,
                n_hoisted=prog.n_hoisted,
                n_launches=prog.n_launches,
                overlap_window=prog.overlap_window,
                transfer_busy_s=busy,
                wait_blocked_s=blocked,
                overlap_fraction=frac,
            )
            from alpa_tpu.pipeline_parallel.runtime_emitter import (
                record_overlap_step)
            record_overlap_step(self.last_dispatch_stats)

        # collect outputs
        outs = []
        for kind, payload in self._reg_output_specs:
            if kind == "literal":
                outs.append(payload)
            elif kind == "slot":
                outs.append(regs[payload])
            elif kind == "input":
                outs.append(flat_args[payload])
            else:  # concat over microbatches (inference outputs)
                slots, meshes = payload
                vals = [regs[s] for s in slots]
                if n_mb == 1:
                    outs.append(vals[0])
                elif vals[0].ndim >= 1:
                    outs.append(jnp.concatenate(
                        [jax.device_put(
                            x, self.mesh_group[meshes[0][1]]
                            .flat_devices[0]) for x in vals], axis=0))
                else:
                    raise ValueError(
                        "A scalar output of a pipelined forward-only "
                        "function is ambiguous with num_micro_batches > 1 "
                        "(per-microbatch reduction cannot be recombined); "
                        "return per-example values or use "
                        "num_micro_batches=1.")
        return outs

    def _exec_inst(self, inst, ctx, idx: int = -1):
        """Execute one pipeline instruction (shared by the sequential loop
        and the per-stream worker threads).  ``idx`` is the global
        instruction index, recorded in flight-recorder events."""
        collect = ctx[4]
        # per-instruction span on the destination mesh's track (the
        # interpreter analog of the register replay's op_meta spans).
        # collect_trace records through the recorder even when the
        # telemetry master switch is off — same contract as the graph
        # executor's trace hook — feeding dump_stage_execution_trace.
        trace_on = _ttrace.enabled() or collect
        flight_on = _flight.enabled()
        if not (trace_on or flight_on):
            self._exec_inst_inner(inst, ctx)
            return
        opname = inst.opcode.name
        mesh = (inst.free_keys[0][2]
                if opname == "FREE" and inst.free_keys
                else inst.dst_mesh)
        name = f"{opname} {inst.info}" if inst.info else opname
        span = (_ttrace.get_recorder().span(
                    name, "instruction", None, f"mesh {mesh}")
                if trace_on else contextlib.nullcontext())
        if not flight_on:
            with span:
                self._exec_inst_inner(inst, ctx)
            return
        rec = _flight.get_recorder()
        t0 = _flight.now_us()
        try:
            with span:
                self._exec_inst_inner(inst, ctx)
        except BaseException as e:
            rec.record("exec", name, mesh, idx, (), t0, _flight.now_us(),
                       f"error:{type(e).__name__}")
            raise
        rec.record("exec", name, mesh, idx, (), t0, _flight.now_us(),
                   "ok")

    def _exec_inst_inner(self, inst, ctx):
        env, _put, exec_mode, mp_planned, _collect, _stats = ctx
        if inst.opcode == PipelineInstType.RUN:
            exec_ = inst.executable
            args = [env[k][inst.dst_mesh] for k in inst.input_keys]
            # Safety net: the emitter models shardings statically; any
            # divergence (logged) is reconciled here with a device_put.
            for i, (a, s) in enumerate(zip(args, exec_.in_shardings)):
                if (isinstance(a, jax.Array) and
                        not a.sharding.is_equivalent_to(s, a.ndim)):
                    # Happens when one RUN needs the same value in two
                    # layouts (env holds one layout per mesh).
                    logger.debug(
                        "emit-model sharding miss: %s arg[%d] %s -> %s",
                        inst.info, i, a.sharding.spec, s.spec)
                    args[i] = _put(a, s)
            if fault.instrumented():
                # Donated-buffer stages are NOT idempotent (a re-run
                # would read freed inputs): only injected faults — which
                # fire before the real execution — are retried there.
                outs = fault.call_with_retry(
                    lambda: (fault.fire("stage_launch", stage=inst.info,
                                        mesh_id=inst.dst_mesh),
                             exec_.compiled(*args))[1],
                    site="stage_launch",
                    idempotent=not exec_.donate_idx)
            else:
                outs = exec_.compiled(*args)
            for k, o in zip(inst.output_keys, outs):
                env.setdefault(k, {})[inst.dst_mesh] = o
        elif inst.opcode == PipelineInstType.RESHARD:
            val = env[inst.var_key][inst.src_mesh]

            def transfer():
                fault.fire("cross_mesh_send", var=str(inst.var_key[0]),
                           src_mesh=inst.src_mesh, dst_mesh=inst.dst_mesh)
                if (mp_planned and inst.src_mesh != inst.dst_mesh and
                        inst.plan is not None):
                    if inst.task is None:
                        from alpa_tpu.pipeline_parallel. \
                            cross_mesh_resharding import ReshardingTask
                        inst.task = ReshardingTask(inst.plan,
                                                   inst.dst_sharding)
                    env[inst.var_key][inst.dst_mesh] = \
                        inst.task.run_multiprocess(val)
                elif (exec_mode == "planned" and
                      inst.src_mesh != inst.dst_mesh and
                      inst.plan is not None):
                    # Drive the tile plan literally (per-tile routed
                    # transfers; send_recv or broadcast leg choice from
                    # global_config.resharding_mode, ref :418/:935).
                    if inst.task is None:
                        from alpa_tpu.pipeline_parallel. \
                            cross_mesh_resharding import ReshardingTask
                        inst.task = ReshardingTask(inst.plan,
                                                   inst.dst_sharding)
                    mode = ("broadcast" if global_config.resharding_mode ==
                            "broadcast" else "tiled")
                    env[inst.var_key][inst.dst_mesh] = inst.task.run(
                        val, mode)
                else:
                    env[inst.var_key][inst.dst_mesh] = _put(
                        val, inst.dst_sharding)
                    return
                rep = inst.task.last_report
                with self._acct_lock:
                    self._executed_resharding_bytes += rep.cross_mesh_bytes
                    self._executed_intra_mesh_bytes += rep.intra_mesh_bytes

            if fault.instrumented():
                # a transfer reads the source value functionally:
                # re-running after a failure is safe single-process; the
                # multiprocess collective path must stay lock-step, so
                # it only gets detection (no blind re-runs)
                fault.call_with_retry(transfer, site="cross_mesh_send",
                                      idempotent=not mp_planned)
            else:
                transfer()
        else:  # FREE
            for (v, i, m) in inst.free_keys:
                d = env.get((v, i))
                if d is not None:
                    d.pop(m, None)

    def _run_streams_threaded(self, ctx):
        """Per-mesh worker threads over the emitter's pre-partitioned
        instruction streams.

        Each worker executes its stream in order; cross-stream data and
        anti-dependencies (see runtime_emitter.partition_streams) are
        waited on via per-instruction events.  All dependency edges point
        to earlier global indices, so workers cannot deadlock; an abort
        flag stops every stream promptly if one instruction raises.
        Single-process only: issuing collectives from reordered streams
        would violate the cross-process same-order contract.
        """
        streams = self._instruction_streams
        n = len(self.instructions)
        events = [threading.Event() for _ in range(n)]
        abort = threading.Event()
        errors: List[BaseException] = []
        stats = ctx[5]
        checker = None
        if global_config.debug_dispatch_races:
            # cached across steps (access extraction is per-executable
            # static work); violations reset per launch
            checker = getattr(self, "_race_checker", None)
            if checker is None:
                from alpa_tpu.pipeline_parallel.runtime_emitter import (
                    DispatchRaceChecker)
                checker = DispatchRaceChecker(self.instructions,
                                              streams.stream_of)
                self._race_checker = checker
            checker.reset()

        def worker(stream):
            local = {"RUN": [0, 0.0], "RESHARD": [0, 0.0], "FREE": [0, 0.0]}
            try:
                for idx in stream:
                    for dep in sorted(streams.deps.get(idx, ())):
                        while not events[dep].wait(0.05):
                            if abort.is_set():
                                return
                    if abort.is_set():
                        return
                    inst = self.instructions[idx]
                    accs = checker.begin(idx) if checker else None
                    tic = time.perf_counter()
                    try:
                        self._exec_inst(inst, ctx, idx)
                    finally:
                        if checker:
                            checker.end(idx, accs)
                    s = local[inst.opcode.name]
                    s[0] += 1
                    s[1] += time.perf_counter() - tic
                    events[idx].set()
            except BaseException as e:  # pylint: disable=broad-except
                errors.append(e)
                abort.set()
            finally:
                with self._acct_lock:
                    for k, (cnt, sec) in local.items():
                        stats[k][0] += cnt
                        stats[k][1] += sec

        threads = [
            threading.Thread(target=worker, args=(s,), daemon=True)
            for s in streams.streams if s
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        if checker is not None:
            checker.check()

    def __call__(self, *args):
        return self.launch_on_driver(*args)

    # ---- introspection ----
    def get_hlo_text(self) -> str:
        return "\n\n".join(
            f"=== {s.name} (mesh {s.mesh_id}) ===\n" +
            s.compiled.as_text() for s in self.stage_execs)

    def get_schedule_text(self) -> str:
        return self.schedule.pprint_schedule()

    def get_instruction_text(self) -> str:
        return "\n".join(repr(i) for i in self.instructions)

    def get_plan_verdict(self, mode: str = "registers"):
        """The static plan verifier's :class:`PlanVerdict` for the
        lowered program (ISSUE 8), lowering on demand when no launch
        has run yet.  None when ``verify_plans`` is off or lowering is
        impossible (e.g. multi-process)."""
        prog = self._register_programs.get(mode)
        if prog is None:
            from alpa_tpu.analysis.plan_verifier import (
                PlanVerificationError)
            try:
                prog = self._ensure_lowered(mode)
            except PlanVerificationError as e:
                # verify_plans="error" blocks the compile, but the
                # caller asked for a report, not a launch gate
                return e.verdict
            except Exception:  # pylint: disable=broad-except
                logger.exception("get_plan_verdict: lowering failed")
                return None
        return getattr(prog, "verdict", None)

    def get_plan_verdict_text(self) -> str:
        """``plan_verdict.txt`` content for dump_debug_info."""
        verdict = None
        try:
            verdict = self.get_plan_verdict()
        except Exception:  # pylint: disable=broad-except
            logger.exception("get_plan_verdict_text failed")
        if verdict is None:
            return ("plan verdict: (not available — verify_plans=off, "
                    "lowering failed, or launch not register-eligible)")
        return verdict.format_table()

    def get_model_check_text(self) -> str:
        """``model_check.txt`` content for dump_debug_info (ISSUE 13):
        the model checker's stats + findings for the lowered plan."""
        verdict = None
        try:
            verdict = self.get_plan_verdict()
        except Exception:  # pylint: disable=broad-except
            logger.exception("get_model_check_text failed")
        if verdict is None:
            return ("model check: (not available — verify_plans=off, "
                    "lowering failed, or launch not register-eligible)")
        mc_stats = verdict.stats.get("model_check")
        if not mc_stats:
            return ("model check: (not run — "
                    "verify_plans_model_check=off or plan exceeds "
                    "fixture-mode size gate)")
        from alpa_tpu.analysis import model_check as _mc
        mc_findings = [f for f in verdict.findings()
                       if f.analysis == "model_check"]
        return _mc.format_stats(mc_stats, mc_findings)

    def get_numerics_text(self) -> str:
        """``numerics.txt`` content for dump_debug_info (ISSUE 14): the
        numerics certification's per-output bound table + findings for
        the lowered plan."""
        verdict = None
        try:
            verdict = self.get_plan_verdict()
        except Exception:  # pylint: disable=broad-except
            logger.exception("get_numerics_text failed")
        if verdict is None:
            return ("numerics: (not available — verify_plans=off, "
                    "lowering failed, or launch not register-eligible)")
        num_stats = verdict.stats.get("numerics")
        if not num_stats:
            return "numerics: (not run — verify_plans_numerics=off)"
        from alpa_tpu.analysis import numerics as _num
        num_findings = [f for f in verdict.findings()
                        if f.analysis == "numerics"]
        return _num.format_numerics(num_stats, num_findings)

    def get_equiv_text(self) -> str:
        """``equiv.txt`` content for dump_debug_info (ISSUE 15): the
        translation validation's per-output proof table + findings for
        the lowered plan."""
        verdict = None
        try:
            verdict = self.get_plan_verdict()
        except Exception:  # pylint: disable=broad-except
            logger.exception("get_equiv_text failed")
        if verdict is None:
            return ("equiv: (not available — verify_plans=off, "
                    "lowering failed, or launch not register-eligible)")
        eq_stats = verdict.stats.get("equiv")
        if not eq_stats:
            return "equiv: (not run — verify_plans_equiv=off)"
        from alpa_tpu.analysis import equivalence as _eq
        eq_findings = [f for f in verdict.findings()
                       if f.analysis == "equiv"]
        return _eq.format_equiv(eq_stats, eq_findings)

    def get_perf_report(self):
        """Post-step :class:`~alpa_tpu.telemetry.perf.StepPerfReport`
        (ISSUE 9): critical path, per-mesh bubbles, transfer overlap,
        stage MFU — joined from the last launch's trace spans (or the
        flight ring when full tracing is off) against the lowered
        program's dataflow graph.  Publishes the ``alpa_stage_mfu``/
        ``alpa_step_bubble_fraction``/``alpa_critical_path_us`` gauges.
        None when no step has been recorded."""
        from alpa_tpu.telemetry import perf as _perf
        stats = getattr(self, "last_dispatch_stats", None) or {}
        mode = stats.get("mode")
        prog = self._register_programs.get(mode) if mode else None
        joined = _perf.joined_from_recorder(_ttrace.get_recorder(), prog)
        if joined is None and _flight.enabled():
            joined = _perf.joined_from_flight(
                _flight.get_recorder().snapshot(), prog)
        if joined is None:
            return None
        report = _perf.build_step_report(
            joined, program=prog, schedule=self.schedule,
            stage_execs=(self.stage_execs +
                         [e for e in self.apply_execs if e is not None]),
            mode=mode, run_stats=stats)
        _perf.publish_report(report)
        try:
            # fold the measured step into the calibration store (ISSUE
            # 12): per-stage RUN costs and per-edge wire costs become
            # the drift gauges' samples and, under replan_mode, the
            # planners' measured overrides
            from alpa_tpu.telemetry import calibration as _calibration
            _calibration.ingest_joined(joined)
        except Exception:  # pylint: disable=broad-except
            logger.exception("calibration ingest failed")
        return report

    def get_perf_report_text(self) -> str:
        """``perf_report.txt`` content for dump_debug_info."""
        report = None
        try:
            report = self.get_perf_report()
        except Exception:  # pylint: disable=broad-except
            logger.exception("get_perf_report_text failed")
        if report is None:
            return ("perf report: (not available — no step recorded; "
                    "enable tracing via ALPA_TPU_TRACE=1 or the flight "
                    "ring via ALPA_TPU_FLIGHT=1 and run a step)")
        return report.format_text()

    def get_calibration_text(self) -> str:
        """``calibration.txt`` content for dump_debug_info: the measured
        -cost store's entries ranked by drift from the analytic model."""
        from alpa_tpu.telemetry.calibration import format_calibration_report
        return format_calibration_report()

    def consider_replan(self, report=None):
        """Profile-guided replanning (ISSUE 12): compare the measured
        step against the calibration store's view and — per
        ``global_config.replan_mode`` — recommend or apply a replan.

        * ``off``: returns None; nothing consulted, plans untouched.
        * ``suggest``: re-prices every cross-mesh edge under the
          calibrated cost model, logs the predicted critical-path delta
          from the ISSUE 9 ``simulate_dag`` what-if engine, and returns
          the verdict without applying anything.
        * ``auto``: additionally re-plans the flipped edges (through the
          calibration-fingerprinted compile-cache path, so a warm
          restart replays the same replan with zero solves) and
          hot-swaps the lowered programs — the static plan verifier
          re-runs on the swapped plan in ``_ensure_lowered``.

        Returns a verdict dict (baseline/predicted critical path µs,
        per-edge strategy flips, plan fingerprints) or None when replan
        is off / no measured step is available."""
        from alpa_tpu.analysis.critical_path import simulate_dag
        from alpa_tpu.pipeline_parallel import (cross_mesh_resharding as
                                                _cmr)
        from alpa_tpu.telemetry import calibration as _calibration
        mode = getattr(global_config, "replan_mode", "off")
        if mode == "off":
            return None
        if report is None:
            report = self.get_perf_report()  # ingests into the store
        if report is None or not report.sim_durs_us:
            return None
        store = _calibration.get_calibration_store()
        baseline_us, _ = simulate_dag(report.sim_durs_us,
                                      report.sim_preds)

        # Re-price every cross-mesh edge under the calibrated chooser.
        # resolve_strategy's key carries the store fingerprint, so these
        # decisions cache and replay on warm restart.
        edge_cost_us: Dict[Tuple[str, str], float] = {}
        flips = []
        for inst in self.instructions:
            if inst.opcode != PipelineInstType.RESHARD or \
                    inst.plan is None or inst.src_sharding is None:
                continue
            try:
                chosen, costs, _cached = _cmr.resolve_strategy(
                    inst.plan.shape, inst.plan.itemsize,
                    inst.src_sharding, inst.dst_sharding)
            except Exception:  # pylint: disable=broad-except
                logger.exception("replan: re-pricing %s failed",
                                 inst.info)
                continue
            edge = (str(inst.src_mesh), str(inst.dst_mesh))
            cost_us = costs.get(chosen, 0.0) * 1e6
            edge_cost_us[edge] = max(edge_cost_us.get(edge, 0.0),
                                     cost_us)
            if chosen != inst.plan.strategy:
                flips.append((inst, inst.plan.strategy, chosen))

        # Predicted critical path of the (re)planned step: measured
        # stage medians for RUNs, the calibrated chooser's edge cost
        # (falling back to the measured wire median) for transfer waits.
        durs = list(report.sim_durs_us)
        for op in report.sim_ops:
            m = None
            stage = _calibration._stage_from_name(op.name)  # pylint: disable=protected-access
            if stage is not None:
                m = store.measured_us("stage_run",
                                      _calibration.stage_signature(stage))
            elif op.kind == "wait":
                edge = _calibration._edge_from_name(op.name)  # pylint: disable=protected-access
                if edge is not None:
                    m = edge_cost_us.get(edge)
                    if m is None:
                        m = store.measured_us(
                            "reshard_wire",
                            _calibration.edge_signature(*edge))
            if m is not None and 0 <= op.idx < len(durs):
                durs[op.idx] = m
        predicted_us, _ = simulate_dag(durs, report.sim_preds)
        verdict = {
            "mode": mode,
            "baseline_critical_path_us": baseline_us,
            "predicted_critical_path_us": predicted_us,
            "predicted_ratio": (predicted_us / baseline_us
                                if baseline_us > 0 else 1.0),
            "n_edges_repriced": len(edge_cost_us),
            "strategy_flips": [
                {"edge": f"{i.src_mesh}->{i.dst_mesh}", "var": i.info,
                 "from": old, "to": new} for i, old, new in flips],
            "applied": False,
            "calibration_fingerprint": store.fingerprint(),
        }
        logger.info(
            "replan(%s): predicted critical path %.1f us vs measured "
            "%.1f us (ratio %.3f), %d strategy flip(s)", mode,
            predicted_us, baseline_us, verdict["predicted_ratio"],
            len(flips))
        if mode != "auto":
            return verdict

        # auto: hot-swap — re-plan flipped edges and re-lower so the
        # verifier re-runs on the swapped plan
        verdict["plan_fingerprint_before"] = self.get_plan_fingerprint()
        if flips:
            from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
                ReshardingTask, plan_resharding)
            for inst, _old, _new in flips:
                try:
                    inst.plan = plan_resharding(
                        inst.plan.shape, inst.plan.itemsize,
                        inst.src_sharding, inst.dst_sharding)
                    inst.task = ReshardingTask(inst.plan,
                                               inst.dst_sharding)
                except Exception:  # pylint: disable=broad-except
                    logger.exception("replan: re-planning %s failed; "
                                     "keeping the old plan", inst.info)
            modes = list(self._register_programs)
            self._register_programs.clear()
            self._register_program = None
            # the instruction stream changed: any accepted superopt
            # layout no longer applies — re-decide on the new stream
            self._superopt_outcome = None
            self._superopt_instructions = None
            for m in modes:
                self._ensure_lowered(m)
        verdict["applied"] = bool(flips)
        verdict["plan_fingerprint_after"] = self.get_plan_fingerprint()
        return verdict

    def get_plan_fingerprint(self) -> str:
        """Content hash of the compiled parallel plan: instruction stream
        plus every stage's input/output shardings.  Two executables with
        equal fingerprints replay identically — used by the compile-cache
        determinism tests (a plan loaded from the persistent cache must
        reproduce a fresh solve exactly).

        ``Var`` reprs embed trace-time object ids, so ids are renumbered
        by first appearance — two independent traces of the same program
        hash identically while distinct vars stay distinct."""
        import hashlib
        import re
        parts = [self.get_instruction_text()]
        for ex in self.stage_execs + [e for e in self.apply_execs
                                      if e is not None]:
            parts.append(ex.name)
            parts.append(repr([str(s) for s in ex.in_shardings]))
            parts.append(repr([str(s) for s in ex.out_shardings]))
        text = "\n".join(parts)
        renumber = {}

        def canon(m):
            return renumber.setdefault(m.group(0),
                                       f"id={len(renumber)}")

        text = re.sub(r"id=\d+", canon, text)
        text = re.sub(r"0x[0-9a-fA-F]+", "0x0", text)
        return hashlib.sha256(text.encode()).hexdigest()

    def dump_stage_execution_trace(self, filename: str):
        """Write the collected per-instruction events as a Chrome trace
        JSON (ref dump_stage_execution_trace_internal,
        pipeshard_executable.py:592).

        Events come from the unified ``telemetry.trace`` recorder — the
        same spans every dispatch mode records (interpreter per-inst
        spans, register/overlap ``op_meta`` hook spans) — plus whatever
        legacy ``timer.Tracer`` instants third-party code still logs.
        Run one executable at a time between ``recorder.clear()`` calls
        to attribute events.  Requires ``global_config.collect_trace``
        (or the telemetry master switch) to be True during execution;
        warns with the active dispatch mode when empty."""
        import json
        all_events = _ttrace.get_recorder().to_chrome_trace().get(
            "traceEvents", [])
        # "M" records are per-track metadata the recorder always emits;
        # real content is spans/instants/counters
        timed = [e for e in all_events if e.get("ph") != "M"]
        # deprecated bridge, imported lazily: third-party code may still
        # log through alpa_tpu.timer.tracer and expects to land here
        from alpa_tpu.timer import tracer
        legacy = tracer.to_chrome_trace()
        if not timed and not legacy:
            mode = (getattr(self, "last_dispatch_stats", None)
                    or {}).get("mode")
            logger.warning(
                "dump_stage_execution_trace: no events collected (last "
                "dispatch mode: %s) — set global_config.collect_trace = "
                "True before running", mode)
        with open(filename, "w", encoding="utf-8") as f:
            json.dump({"traceEvents": all_events + legacy}, f)

    def get_resharding_report(self) -> str:
        """Planned cross-mesh traffic per step (tile-level accounting from
        cross_mesh_resharding.plan_resharding)."""
        n = sum(1 for i in self.instructions
                if i.opcode == PipelineInstType.RESHARD and
                i.src_mesh != i.dst_mesh)
        report = (f"{n} cross-mesh transfers, "
                  f"{self._resharding_bytes / 1e6:.3f} MB per step (planned)")
        if self._max_link_bytes:
            report += (f"; max link {self._max_link_bytes / 1e6:.3f} MB "
                       f"(per-device egress/ingress)")
        if self._executed_resharding_bytes:
            report += (
                f"; executed {self._executed_resharding_bytes / 1e6:.3f} MB "
                f"cross-mesh + {self._executed_intra_mesh_bytes / 1e6:.3f} MB "
                f"intra-mesh ({global_config.resharding_execution})")
        return report

    def sync(self):
        self.mesh_group.sync_workers()
