"""Pipeline stage IRs and jaxpr slicing.

Analog of ref ``alpa/pipeline_parallel/computation.py`` (SURVEY.md §2.4):
``JaxPipelineComputation`` (a named jaxpr fragment with explicit
invars/outvars), slicing a fully-marked jaxpr into computations
(``slice_closed_jaxpr_by_full_pipeline_marks:387``), filling backward-layer
missing vars (``:433``), dead code elimination across computations
(``pipeline_dce:574``), and merging computations
(``merge_computation_jaxprs:911``).
"""
import dataclasses
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
from jax._src.core import jaxpr_as_fun
from jax.extend.core import ClosedJaxpr, Jaxpr, Literal, Var

from alpa_tpu.pipeline_parallel.primitive_def import (is_marker,
                                                      is_pipeline_eqn,
                                                      pipeline_p)
from alpa_tpu.util import OrderedSet, clone_jaxpr, new_jaxpr_eqn

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class JaxPipelineComputation:
    """One pipeline layer/stage as a jaxpr fragment (ref computation.py:84).

    ``eqns`` excludes the start/end markers; ``invars``/``outvars`` are the
    *outer* variables crossing the markers.
    """
    name: str
    invars: List[Var]
    outvars: List[Var]
    eqns: List[Any]
    consts_dir: Dict[Var, Any] = dataclasses.field(default_factory=dict)

    def closed_jaxpr(self) -> ClosedJaxpr:
        jaxpr = Jaxpr(
            constvars=list(self.consts_dir.keys()),
            invars=self.invars,
            outvars=self.outvars,
            eqns=self.eqns,
        )
        return ClosedJaxpr(jaxpr, list(self.consts_dir.values()))

    def get_runnable(self):
        return jaxpr_as_fun(self.closed_jaxpr())

    @property
    def avals_in(self):
        return [v.aval for v in self.invars]

    @property
    def avals_out(self):
        return [v.aval for v in self.outvars]


def slice_closed_jaxpr_by_full_pipeline_marks(
        closed_jaxpr: ClosedJaxpr,
        strict: bool = True
) -> Tuple[List[JaxPipelineComputation], Dict]:
    """Slice a marked jaxpr into computations (ref computation.py:387).

    Marker protocol: a start marker maps outer vars -> layer-local vars; an
    end marker maps layer-local vars -> outer vars.  Eqns between markers
    use layer-local vars.  Eqns outside any marker pair (e.g. glue between
    backward layers) are attached to the *following* computation, keeping
    the eqn order valid.
    """
    consts_map = dict(zip(closed_jaxpr.jaxpr.constvars, closed_jaxpr.consts))

    # ---- pass 1: global marker alias map (local -> outer) ----
    # A start marker maps outer -> local; an end marker maps local -> outer.
    # Residuals saved by autodiff reference a *local* var of the forward
    # layer from inside the backward layer, so the substitution must be
    # global, not per-computation.
    alias: Dict[Var, Any] = {}
    for eqn in closed_jaxpr.jaxpr.eqns:
        if is_marker(eqn, "start"):
            for outer, local in zip(eqn.invars, eqn.outvars):
                alias[local] = outer
        elif is_marker(eqn, "end"):
            for local, outer in zip(eqn.invars, eqn.outvars):
                # Do NOT overwrite an existing alias: if ``local`` came in
                # through this layer's start marker (a passthrough), the
                # start-marker alias must keep winning so inside uses
                # resolve to the *incoming* outer var; the passthrough
                # out-name is connected by an identity eqn at slicing.
                if isinstance(local, Var) and local not in alias:
                    alias[local] = outer

    def resolve(v):
        if isinstance(v, Literal):
            return v
        seen = 0
        while isinstance(v, Var) and v in alias and seen < 100:
            v = alias[v]
            seen += 1
        return v

    computations: List[JaxPipelineComputation] = []
    current = None
    floating_eqns: List[Any] = []  # eqns outside any marker pair

    for eqn in closed_jaxpr.jaxpr.eqns:
        if is_marker(eqn, "start"):
            assert current is None, "nested pipeline markers"
            current = JaxPipelineComputation(
                name=eqn.params["name"],
                invars=[resolve(v) for v in eqn.invars
                        if isinstance(resolve(v), Var)],
                outvars=[],
                eqns=list(floating_eqns))
            floating_eqns = []
            continue
        if is_marker(eqn, "end"):
            assert current is not None, "end marker without start"
            outvars = []
            for local, outer in zip(eqn.invars, eqn.outvars):
                out = resolve(outer)
                if not isinstance(out, Var):
                    continue
                src = resolve(local)
                if src is not out:
                    # passthrough (src is the incoming outer var) or a
                    # literal output: define the out-name inside the
                    # computation so every declared outvar is produced
                    current.eqns.append(_identity_eqn(src, out))
                outvars.append(out)
            current.outvars = outvars
            computations.append(current)
            current = None
            continue
        if is_pipeline_eqn(eqn):
            # stray markers (grad/boundary/jvp copies): identity glue
            target = current.eqns if current is not None else floating_eqns
            for iv, ov in zip(eqn.invars, eqn.outvars):
                riv, rov = resolve(iv), resolve(ov)
                if isinstance(rov, Var) and riv is not rov:
                    target.append(_identity_eqn(riv, rov))
            continue
        target = current.eqns if current is not None else floating_eqns
        target.append(
            eqn.replace(invars=[resolve(v) for v in eqn.invars],
                        outvars=[resolve(v) for v in eqn.outvars]))

    if floating_eqns and computations:
        if strict:
            computations[-1].eqns.extend(floating_eqns)
            floating_eqns = []

    # collect consts used per computation
    for comp in computations:
        for e in comp.eqns:
            for v in e.invars:
                if isinstance(v, Var) and v in consts_map:
                    comp.consts_dir[v] = consts_map[v]

    meta = {"floating_eqns": floating_eqns, "alias": alias}
    return computations, meta


def _identity_eqn(invar, outvar):
    from jax.extend.core import Primitive
    return new_jaxpr_eqn([invar], [outvar], pipeline_p,
                         dict(name="copy", mark_type="jvp"))


def mark_missing_vars_in_backward_computation_pipeline_marks(
        computations: List[JaxPipelineComputation],
        global_invars: Sequence[Var]) -> List[JaxPipelineComputation]:
    """Backward computations may consume forward intermediates that never
    passed through markers (residuals); add them to invars
    (ref computation.py:433)."""
    defined_by = {}
    for ci, comp in enumerate(computations):
        for e in comp.eqns:
            for v in e.outvars:
                defined_by[v] = ci
    for ci, comp in enumerate(computations):
        known = OrderedSet(comp.invars)
        defined_here = OrderedSet()
        for e in comp.eqns:
            defined_here.update([v for v in e.outvars])
        for e in comp.eqns:
            for v in e.invars:
                if (isinstance(v, Var) and v not in known and
                        v not in defined_here and v not in comp.consts_dir):
                    comp.invars.append(v)
                    known.add(v)
                    # also export it from its producer
                    src = defined_by.get(v)
                    if src is not None and src != ci and \
                            v not in computations[src].outvars:
                        computations[src].outvars.append(v)
    return computations


def pipeline_dce(computations: List[JaxPipelineComputation],
                 global_outvars: Sequence[Var]
                 ) -> List[JaxPipelineComputation]:
    """Remove dead eqns/outvars across computations (ref computation.py:574).

    Walk computations in reverse: a computation's live outvars are those
    used by later computations or the global outputs; DCE its eqns against
    them; its remaining invars feed the liveness of earlier computations.
    """
    live = OrderedSet([v for v in global_outvars if isinstance(v, Var)])
    for comp in reversed(computations):
        comp.outvars = [v for v in comp.outvars if v in live]
        # values defined here that are globally live but never passed
        # through a marker (e.g. tied-parameter gradient sums living in
        # inter-layer glue) must be kept and exported
        defined_here = OrderedSet()
        for e in comp.eqns:
            defined_here.update(e.outvars)
        for v in live:
            if v in defined_here and v not in comp.outvars:
                comp.outvars.append(v)
        live_local = OrderedSet(comp.outvars)
        new_eqns = []
        for e in reversed(comp.eqns):
            if any(v in live_local for v in e.outvars) or _has_effects(e):
                new_eqns.append(e)
                for v in e.invars:
                    if isinstance(v, Var):
                        live_local.add(v)
        comp.eqns = list(reversed(new_eqns))
        comp.invars = [v for v in comp.invars if v in live_local]
        comp.consts_dir = {
            v: c for v, c in comp.consts_dir.items() if v in live_local
        }
        live.update(comp.invars)
    return [c for c in computations if c.eqns or c.outvars]


def _has_effects(eqn) -> bool:
    try:
        return bool(eqn.effects)
    except Exception:  # pylint: disable=broad-except
        return False


def merge_computations(computations: List[JaxPipelineComputation],
                       name: str) -> JaxPipelineComputation:
    """Concatenate computations into one (ref merge_computation_jaxprs:911)."""
    invars = OrderedSet()
    defined = OrderedSet()
    eqns = []
    consts = {}
    for comp in computations:
        for v in comp.invars:
            if v not in defined:
                invars.add(v)
        eqns.extend(comp.eqns)
        for e in comp.eqns:
            defined.update(e.outvars)
        consts.update(comp.consts_dir)
    outvars = OrderedSet()
    for comp in computations:
        outvars.update(comp.outvars)
    return JaxPipelineComputation(name, list(invars), list(outvars), eqns,
                                  consts)
