"""Auto stage construction: cost tensor + the OSDI'22 dynamic program.

Analog of ref ``get_compute_cost`` (stage_profiling.py:1163) +
``training_dp`` (stage_construction.py:235-311).  The compute-cost tensor
C[i, j, m] (layers i..j on submesh choice m) is filled by the static cost
model (mesh_profiling.estimate_stage_cost — the HloCostModelProfileWorker
analog, default on TPU) and the DP minimizing
``sum(stage costs) + (B-1) * max(stage cost)`` runs in native C++
(csrc/stage_dp.cc, built to alpa_tpu/_native/libstage_dp.so) with a pure
Python fallback.
"""
import ctypes
import logging
import os
import subprocess
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from alpa_tpu.global_env import global_config
from alpa_tpu.telemetry import trace as _ttrace

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "_native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libstage_dp.so")
_CSRC_DIR = os.path.join(os.path.dirname(os.path.dirname(_NATIVE_DIR)),
                         "csrc")

_lib = None
_lib_tried = False

# Must match csrc/stage_dp.cc kAbiVersion.  A stale .so called through a
# newer ctypes signature silently corrupts the output buffers, so the
# loader refuses any library that can't prove the right version.
_ABI_VERSION = 2

# inflight_mode codes (csrc/stage_dp.cc inflight_count)
_INFLIGHT_MODES = {"1f1b": 0, "pipedream_flush": 0, "gpipe": 1,
                   "1f1b_overlap_friendly": 2, "inference": 3}


def _load_native():
    """Load (building if needed) the C++ DP solver.

    ``make`` runs unconditionally — it is timestamp-incremental, so this is
    a no-op when the .so is fresh, and it transparently rebuilds after a
    source change (an in-place upgrade otherwise keeps a stale binary with
    an incompatible ABI).
    """
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    makefile = os.path.join(_CSRC_DIR, "Makefile")
    if os.path.exists(makefile):
        try:
            subprocess.run(["make", "-C", _CSRC_DIR], check=True,
                           capture_output=True, timeout=120)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning("building libstage_dp.so failed: %s", e)
    if os.path.exists(_LIB_PATH):
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            try:
                lib.stage_dp_abi_version.restype = ctypes.c_int32
                abi = int(lib.stage_dp_abi_version())
            except AttributeError:
                abi = -1
            if abi != _ABI_VERSION:
                logger.warning(
                    "libstage_dp.so ABI %d != expected %d (stale build?); "
                    "using the Python fallback", abi, _ABI_VERSION)
                return None
            lib.stage_dp_solve.restype = ctypes.c_int
            lib.stage_dp_solve.argtypes = [
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32,
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                ctypes.c_double,
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ]
            _lib = lib
        except OSError as e:
            logger.warning("loading libstage_dp.so failed: %s", e)
    return _lib


def stage_dp_solve(costs: np.ndarray,
                   submesh_sizes: Sequence[int],
                   num_devices: int,
                   num_micro_batches: int,
                   mem_param: Optional[np.ndarray] = None,
                   mem_act: Optional[np.ndarray] = None,
                   mem_budget: float = 0.0,
                   inflight_mode: str = "1f1b"
                   ) -> Optional[List[Tuple[int, int, int]]]:
    """Solve the stage-construction DP.

    costs: (L, L, M) float64; costs[i, j, m] = cost of layers i..j (incl.)
    on submesh m (inf = infeasible).  Memory feasibility is position-aware
    (ref max_n_succ_stages, stage_profiling.py:756): the s-th stage from
    the pipeline end holds inflight(s) microbatches of activations, where
    inflight depends on the schedule (``inflight_mode``): "1f1b" min(s, B),
    "gpipe" B, "1f1b_overlap_friendly" min(2s-1, B) (eager forwards),
    "inference" 1 (forward-only — nothing stacks).  The check is
    ``mem_param + inflight(s) * mem_act <= mem_budget``.
    Returns list of (start_layer, end_layer_exclusive, submesh_idx) or
    None if infeasible.
    """
    L, _, M = costs.shape
    costs = np.ascontiguousarray(costs, np.float64)
    sizes = np.ascontiguousarray(submesh_sizes, np.int64)
    if mem_param is None:
        mem_param = np.zeros_like(costs)
    if mem_act is None:
        mem_act = np.zeros_like(costs)
    mem_param = np.ascontiguousarray(mem_param, np.float64)
    mem_act = np.ascontiguousarray(mem_act, np.float64)
    mode = _INFLIGHT_MODES.get(inflight_mode, 0)

    lib = _load_native()
    if lib is not None:
        starts = np.zeros(L, np.int32)
        meshes = np.zeros(L, np.int32)
        S = lib.stage_dp_solve(L, M, num_devices, num_micro_batches, mode,
                               costs, sizes, mem_param, mem_act, mem_budget,
                               starts, meshes)
        if S < 0:
            return None
        out = []
        for t in range(S):
            end = starts[t + 1] if t + 1 < S else L
            out.append((int(starts[t]), int(end), int(meshes[t])))
        return out
    return _stage_dp_python(costs, sizes, num_devices, num_micro_batches,
                            mem_param, mem_act, mem_budget, mode)


def _inflight_count(s, B, mode):
    b = max(B, 1)
    if mode == 1:  # gpipe
        return b
    if mode == 2:  # overlap-friendly 1f1b
        return min(2 * s - 1, b)
    if mode == 3:  # inference
        return 1
    return min(s, b)  # 1f1b


def _stage_dp_python(C, sizes, D, B, mem_param, mem_act, mem_budget, mode=0):
    """Pure-Python fallback, same algorithm as csrc/stage_dp.cc
    (f[l][d][s] with the suffix-stage-count dimension for position-aware
    schedule-dependent memory feasibility)."""
    L, _, M = C.shape
    INF = float("inf")
    finite = C[np.isfinite(C)]
    if finite.size == 0:
        return None
    candidates = np.unique(finite)
    best_obj, best_part = INF, None

    for t_max in candidates:
        if best_part is not None and (B - 1) * t_max >= best_obj:
            break
        f = np.full((L + 1, D + 1, L + 1), INF)
        cj = np.full((L + 1, D + 1, L + 1), -1, np.int32)
        cm = np.full((L + 1, D + 1, L + 1), -1, np.int32)
        f[L][0][0] = 0.0
        for l in range(L - 1, -1, -1):
            for d in range(1, D + 1):
                for s in range(1, L - l + 1):
                    inflight = _inflight_count(s, B, mode)
                    for j in range(l, L):
                        for m in range(M):
                            n = int(sizes[m])
                            if n > d:
                                continue
                            c = C[l, j, m]
                            if not np.isfinite(c) or c > t_max:
                                continue
                            if mem_budget > 0 and \
                                    mem_param[l, j, m] + inflight * \
                                    mem_act[l, j, m] > mem_budget:
                                continue
                            rest = f[j + 1][d - n][s - 1]
                            if rest == INF:
                                continue
                            if c + rest < f[l][d][s]:
                                f[l][d][s] = c + rest
                                cj[l][d][s] = j
                                cm[l][d][s] = m
        s_best = int(np.argmin(f[0][D]))
        if f[0][D][s_best] == INF:
            continue
        obj = f[0][D][s_best] + (B - 1) * t_max
        if obj < best_obj:
            part = []
            l, d, s = 0, D, s_best
            ok = True
            while l < L:
                j, m = int(cj[l][d][s]), int(cm[l][d][s])
                if j < 0:
                    ok = False
                    break
                part.append((l, j + 1, m))
                d -= int(sizes[m])
                l = j + 1
                s -= 1
            if ok and d == 0 and s == 0:
                best_obj, best_part = obj, part
    return best_part


########################################
# compute-cost tensor disk cache
########################################


def compute_cost_cache_key(layer_comps, choices, profiling_mode,
                           with_memory=False, calibration=None,
                           db_file=None, measured_limit=None,
                           exact_ilp=None, sharding_option=None,
                           objective: str = "training") -> str:
    """Content key: the layers' jaxprs + the submesh search space + the
    profiling mode + whether memory tensors were computed + the effective
    calibration.  Any change invalidates the cache.

    ``with_memory`` matters because the stored mem_param/mem_act tensors
    are all-zero when no memory budget was set at write time; reusing them
    under a budget would make the DP's feasibility check vacuous.
    ``calibration``/``db_file`` matter because the cost tensor bakes in the
    profiling DB's fit — switching DBs or TPU generations must miss (an
    in-place re-profile changes the fitted dot_points/collective_ab and so
    the key).  ``measured_limit`` matters in measured mode: a wider
    refinement sweep produces a different tensor.  ``exact_ilp`` (merged
    -span ILP vs additive prefix sums) and ``sharding_option`` (feeds
    every per-span ILP solve) also shape the tensor and must miss.
    """
    import hashlib
    h = hashlib.sha256()
    for c in layer_comps:
        h.update(str(c.closed_jaxpr() if hasattr(c, "closed_jaxpr")
                     else c).encode())
    h.update(repr(list(choices)).encode())
    h.update(profiling_mode.encode())
    h.update(b"mem" if with_memory else b"nomem")
    h.update(repr(db_file).encode())
    if profiling_mode == "measured":
        h.update(repr(measured_limit).encode())
    h.update(repr(exact_ilp).encode())
    h.update(repr(sharding_option).encode())
    # the memory tensors carry the objective-dependent optimizer-state
    # (ZeRO) term; training vs inference tensors must not alias
    h.update(objective.encode())
    if calibration is not None:
        h.update(repr(sorted(calibration.dot_points)).encode())
        h.update(repr(sorted(calibration.collective_ab.items())).encode())
    # the cost tensor bakes estimate_stage_cost's calibration-store
    # consults in (ISSUE 12) — no token under replan_mode=off
    from alpa_tpu.telemetry.calibration import calibration_cache_token
    tok = calibration_cache_token()
    if tok:
        h.update(tok.encode())
    return h.hexdigest()[:16]


def load_compute_cost_cache(path, key, shape):
    """(costs, mem_param, mem_act) from ``path`` if the stored key and
    shapes match, else None."""
    if not os.path.exists(path):
        return None
    try:
        with np.load(path, allow_pickle=False) as z:
            if str(z["key"]) != key or z["costs"].shape != shape:
                logger.info("compute-cost cache %s stale (key/shape "
                            "mismatch); recomputing", path)
                return None
            return z["costs"], z["mem_param"], z["mem_act"]
    except Exception as e:  # pylint: disable=broad-except
        logger.warning("compute-cost cache %s unreadable: %s", path, e)
        return None


def save_compute_cost_cache(path, key, costs, mem_param, mem_act):
    try:
        np.savez(path, key=np.str_(key), costs=costs, mem_param=mem_param,
                 mem_act=mem_act)
        logger.info("auto-stage DP: saved compute-cost cache %s", path)
    except OSError as e:
        logger.warning("saving compute-cost cache %s failed: %s", path, e)


########################################
# orchestration: cost tensor + DP -> stage assignment
########################################


def auto_stage_dp(num_layers, virtual_mesh, stage_option, layer_flops,
                  layer_comps, num_micro_batches, auto_sharding_option,
                  objective: str = "training", schedule: str = "1f1b"):
    """Fill the cost tensor with the static cost model and run the DP
    (ref cluster_layers_and_slice_mesh auto branch, stage_construction.py:
    571 + SURVEY.md §3.4)."""
    from alpa_tpu.mesh_profiling import (estimate_stage_cost,
                                         estimate_stage_memory_split)
    from alpa_tpu.pipeline_parallel.stage_construction import (
        get_sliced_virtual_submeshes, get_submesh_choices)

    tic = time.time()
    choices = get_submesh_choices(
        virtual_mesh.num_hosts, virtual_mesh.num_devices_per_host,
        getattr(stage_option, "submesh_physical_shape_space",
                "power_of_two"))
    sizes = [h * d for (h, d) in choices]
    L, M = num_layers, len(choices)
    D = virtual_mesh.num_devices

    from alpa_tpu.device_mesh import LogicalDeviceMesh

    if getattr(stage_option, "submesh_logical_shape_space",
               "single_node_model_parallel") != "single_node_model_parallel":
        logger.warning(
            "submesh_logical_shape_space=%r: per-stage logical shapes are "
            "searched by the intra-op planner, not here",
            stage_option.submesh_logical_shape_space)

    # Calibrate from a profiling DB (ref ProfilingResultDatabase path):
    # an explicit per-option DB wins, else the process-global one
    # (global_config.profiling_database_filename).  The fit supplies
    # size-dependent sec/flop and per-collective alpha-beta in real
    # seconds, so the DP's decisions trace back to measurements.
    from alpa_tpu.mesh_profiling import (calibration_from_file,
                                         get_effective_calibration)
    db_file = getattr(stage_option, "profiling_database_filename", None)
    cal = calibration_from_file(db_file) if db_file else None
    if cal is None:
        # measured DB backfilled with analytic per-generation link
        # constants on TPU (published ICI bandwidths; VERDICT r2 next #8)
        cal = get_effective_calibration()

    # Span cost estimation strategy: exact merged-span ILP for small
    # search spaces (or when forced via use_hlo_cost_model=False);
    # otherwise ADDITIVE per-layer ILP — L*M solves whose prefix sums give
    # every span.  Running the merged ILP on huge spans is both slow and
    # wrong: past the solver time limit the greedy fallback returns
    # replication-heavy plans whose comm terms invert the cost ladder
    # (wide submeshes looked slower than one device).
    exact_ilp = not getattr(stage_option, "use_hlo_cost_model", True) or \
        (L * L * M <= 256)
    mem_budget = float(
        getattr(stage_option, "memory_budget_per_device", None) or 0.0)
    measured_limit = getattr(stage_option, "measured_candidates_limit", 16)

    # Disk cache of the cost tensors (ref compute-cost-<time>.npy,
    # stage_profiling.py:53), keyed by the model + search-space content so
    # auto-stage decisions are reproducible across runs without re-running
    # the cost model / measured sweep.
    cache_file = getattr(stage_option, "cached_compute_cost", None)
    cache_key = None
    if cache_file:
        cache_key = compute_cost_cache_key(
            layer_comps, choices,
            getattr(stage_option, "profiling_mode", "cost_model"),
            with_memory=mem_budget > 0, calibration=cal, db_file=db_file,
            measured_limit=measured_limit, exact_ilp=exact_ilp,
            sharding_option=auto_sharding_option, objective=objective)
        cached = load_compute_cost_cache(cache_file, cache_key, (L, L, M))
        if cached is not None:
            costs, mem_param, mem_act = cached
            logger.info("auto-stage DP: loaded compute-cost cache %s",
                        cache_file)
            cache_file = None  # hit: skip recompute + rewrite

    if cache_key is None or cache_file:
        costs = np.full((L, L, M), np.inf)
        mem_param = np.zeros((L, L, M))
        mem_act = np.zeros((L, L, M))
        for m, (h, d) in enumerate(choices):
            # cost-model-only logical mesh of the candidate submesh shape
            shape = (h * d, 1) if h == 1 else (h, d)
            logical = LogicalDeviceMesh(
                None, np.arange(h * d).reshape(shape),
                mesh_beta=(0.1 if h > 1 else 0.01, 0.01),
                calibration=cal)
            kwargs = {}
            if cal is not None:
                kwargs["sec_per_flop"] = cal.sec_per_flop
            if exact_ilp:
                for i in range(L):
                    for j in range(i, L):
                        costs[i, j, m] = estimate_stage_cost(
                            layer_comps[i:j + 1], logical,
                            auto_sharding_option, use_ilp=True, **kwargs)
            else:
                per_layer = [
                    estimate_stage_cost([layer_comps[l]], logical,
                                        auto_sharding_option, use_ilp=True,
                                        **kwargs)
                    for l in range(L)
                ]
                pref = np.concatenate([[0.0], np.cumsum(per_layer)])
                for i in range(L):
                    for j in range(i, L):
                        costs[i, j, m] = pref[j + 1] - pref[i]
            if mem_budget > 0:
                for i in range(L):
                    for j in range(i, L):
                        mem_param[i, j, m], mem_act[i, j, m] = \
                            estimate_stage_memory_split(
                                layer_comps[i:j + 1], logical,
                                as_option=auto_sharding_option,
                                objective=objective)

        if getattr(stage_option, "profiling_mode",
                   "cost_model") == "measured":
            from alpa_tpu.mesh_profiling import refine_costs_measured
            n = refine_costs_measured(
                costs, layer_comps, sizes, auto_sharding_option,
                limit=measured_limit,
                compile_workers=getattr(stage_option,
                                        "measured_compile_workers", 4))
            logger.info("measured stage profiling refined %d candidates", n)

        if cache_file:
            save_compute_cost_cache(cache_file, cache_key, costs, mem_param,
                                    mem_act)

    # stage_imbalance_tolerance: cap the DP's max-stage-cost threshold at
    # tolerance * (best perfectly-balanced stage cost estimate).
    tol = float(getattr(stage_option, "stage_imbalance_tolerance", np.inf))
    if np.isfinite(tol):
        finite = costs[np.isfinite(costs)]
        if finite.size:
            balanced = float(np.nanmin(
                [costs[0, L - 1, m] for m in range(M)
                 if np.isfinite(costs[0, L - 1, m])] or [np.inf]))
            cap = tol * balanced / max(1, 1)
            costs = np.where(costs <= cap, costs, np.inf)

    # objective="inference" (ref inference_dp, stage_construction.py:403):
    # a forward-only pipeline's throughput is bottlenecked by the slowest
    # stage, so minimize max stage cost first (sum as tie-break) — the
    # training objective with B -> large.  The memory feasibility check is
    # decoupled from B_eff via inflight_mode: a forward-only pipeline holds
    # ~1 microbatch per stage regardless of the objective's B, and training
    # schedules each have their own in-flight profile.
    if objective == "inference":
        B_eff, inflight_mode = 4096, "inference"
    else:
        B_eff, inflight_mode = num_micro_batches, schedule
    _ttrace.instant("stage-dp-costs", "compile",
                    {"L": L, "M": M})
    part = stage_dp_solve(costs, sizes, D, B_eff, mem_param,
                          mem_act, mem_budget=mem_budget,
                          inflight_mode=inflight_mode)
    _ttrace.instant("stage-dp-solved", "compile",
                    {"stages": len(part) if part else 0})
    if part is None:
        raise RuntimeError(
            "auto stage construction found no feasible partition")
    logger.info("auto-stage DP: %d stages in %.2f s: %s",
                len(part), time.time() - tic,
                [(a, b, choices[m]) for a, b, m in part])

    fwd_ids = [list(range(a, b)) for a, b, _m in part]
    phys_shapes = [list(choices[m]) for _a, _b, m in part]
    submeshes = get_sliced_virtual_submeshes(virtual_mesh, phys_shapes)
    logical_shapes = [None] * len(part)
    as_dicts = [{}] * len(part)
    return fwd_ids, submeshes, logical_shapes, as_dicts
