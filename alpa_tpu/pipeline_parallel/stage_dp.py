"""Auto stage construction via the OSDI'22 dynamic program.

Analog of ref ``training_dp_impl`` (``stage_construction.py:235``) +
``get_compute_cost`` (``stage_profiling.py:1163``).  The DP and the
cost-model-based stage profiling land with the auto-stage milestone; a
clear error guards the entry until then.
"""


def auto_stage_dp(num_layers, virtual_mesh, stage_option, layer_flops,
                  layer_comps, num_micro_batches, auto_sharding_option):
    raise NotImplementedError(
        "AutoStageOption (profile-and-DP stage construction) is not wired "
        "yet; use UniformStageOption or ManualStageOption.")
