"""Pipeshard compilation: the flagship inter+intra-op compile path.

Analog of ref ``compile_pipeshard_executable``
(``alpa/pipeline_parallel/compile_executable.py:48``; call stack SURVEY.md
§3.3):

  trace (layer-marked, microbatch avals)
  -> split at gradient marker (apply_grad.py)
  -> slice into layer computations (computation.py)
  -> cluster layers into stages + slice the cluster into submeshes
     (stage_construction.py)
  -> rewrite backward stages to accumulate gradients
  -> partition apply_grad across meshes
  -> intra-op plan + jit-compile every stage on its submesh
     (shard_parallel planner)
  -> generate schedule (schedules.py) and emit the static instruction list
     (runtime_emitter.py)
  -> PipeshardDriverExecutable (pipeshard_executable.py)
"""
import itertools
import logging
import re
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.extend.core import ClosedJaxpr, Literal, Var

from alpa_tpu.device_mesh import VirtualPhysicalMesh
from alpa_tpu.global_env import global_config
from alpa_tpu.pipeline_parallel.apply_grad import (
    apply_grad_get_mean, apply_partition_is_acyclic,
    compute_grad_to_accumulate_grad, partition_apply_grad,
    split_compute_grad_and_apply_grad)
from alpa_tpu.pipeline_parallel.computation import (
    JaxPipelineComputation,
    mark_missing_vars_in_backward_computation_pipeline_marks, merge_computations,
    pipeline_dce, slice_closed_jaxpr_by_full_pipeline_marks)
from alpa_tpu.pipeline_parallel.layer_construction import (
    AutoLayerOption, LayerOption, ManualLayerOption, set_current_layer_option)
from alpa_tpu.pipeline_parallel.schedules import create_pipeline_schedule
from alpa_tpu.pipeline_parallel.stage_construction import (
    StageOption, cluster_layers_and_slice_mesh)
from alpa_tpu.util import OrderedSet, clone_jaxpr

logger = logging.getLogger(__name__)


def _layer_index_of(name: str) -> Optional[int]:
    m = re.search(r"layer_(\d+)", name)
    return int(m.group(1)) if m else None


def _is_backward_name(name: str) -> bool:
    return "backward" in name


def compile_pipeshard_executable(fun: Callable,
                                 virtual_mesh: VirtualPhysicalMesh,
                                 in_avals: Sequence[Any],
                                 in_tree,
                                 in_paths: Sequence[str],
                                 donated_invars: Sequence[bool],
                                 batch_invars: Sequence[bool],
                                 num_micro_batches: int,
                                 as_option,
                                 pipeline_schedule: str,
                                 layer_option: Optional[LayerOption],
                                 stage_option: Optional[StageOption]):
    from alpa_tpu.pipeline_parallel.pipeshard_executable import (
        PipeshardDriverExecutable)

    tic = time.time()
    num_micro_batches = num_micro_batches or 1
    layer_option = layer_option or AutoLayerOption(
        layer_num=min(8, virtual_mesh.num_hosts if virtual_mesh.num_hosts > 1
                      else virtual_mesh.num_devices))

    # ---- trace at microbatch avals with the layer transform active ----
    batch_flat_idx = [i for i, b in enumerate(batch_invars) if b]
    micro_avals = list(in_avals)
    for i in batch_flat_idx:
        a = in_avals[i]
        b = a.shape[0]
        assert b % num_micro_batches == 0, (
            f"batch size {b} not divisible by num_micro_batches="
            f"{num_micro_batches}")
        micro_avals[i] = jax.ShapeDtypeStruct(
            (b // num_micro_batches,) + tuple(a.shape[1:]), a.dtype)

    set_current_layer_option(layer_option)
    try:
        # Fresh closure: jax caches traces by (fun object, avals); the layer
        # transform changes tracing behavior via context, so a cached
        # marker-free trace (e.g. from donation inference) must not be hit.
        closed_jaxpr = jax.make_jaxpr(lambda *a: fun(*a))(*micro_avals)
    finally:
        set_current_layer_option(None)

    global_invars = list(closed_jaxpr.jaxpr.invars)
    global_outvars = list(closed_jaxpr.jaxpr.outvars)
    consts_map = dict(zip(closed_jaxpr.jaxpr.constvars, closed_jaxpr.consts))

    inference_mode = not any(
        _has_grad_marker(e) for e in closed_jaxpr.jaxpr.eqns)

    if inference_mode:
        # Forward-only functions never pass through alpa_tpu.grad, so the
        # layer transform must be applied here to get layer markers.
        from alpa_tpu.pipeline_parallel.layer_construction import (
            layer_level_transform)
        from alpa_tpu.pipeline_parallel.primitive_def import pipeline_p
        has_markers = any(
            e.primitive is pipeline_p and e.params["mark_type"] == "start"
            for e in closed_jaxpr.jaxpr.eqns)
        if not has_markers:
            transformed = layer_level_transform(fun, layer_option)
            closed_jaxpr = jax.make_jaxpr(
                lambda *a: transformed(*a))(*micro_avals)
            global_invars = list(closed_jaxpr.jaxpr.invars)
            global_outvars = list(closed_jaxpr.jaxpr.outvars)
            consts_map = dict(
                zip(closed_jaxpr.jaxpr.constvars, closed_jaxpr.consts))
        return _compile_inference(fun, virtual_mesh, closed_jaxpr, in_avals,
                                  micro_avals, in_tree, batch_invars,
                                  num_micro_batches, as_option,
                                  stage_option, tic)

    # ---- split at the gradient marker ----
    compute_eqns, grad_pairs, apply_eqns = \
        split_compute_grad_and_apply_grad(closed_jaxpr)
    compute_jaxpr = clone_jaxpr(closed_jaxpr, eqns=compute_eqns,
                                outvars=[p for p, _ in grad_pairs])

    # ---- slice into layer computations ----
    computations, _meta = slice_closed_jaxpr_by_full_pipeline_marks(
        compute_jaxpr)
    if not computations:
        raise ValueError(
            "No pipeline layers found: use ManualLayerOption with "
            "mark_pipeline_boundary() or AutoLayerOption.")
    computations = \
        mark_missing_vars_in_backward_computation_pipeline_marks(
            computations, global_invars)
    computations = pipeline_dce(computations, compute_jaxpr.jaxpr.outvars)

    # classify forward/backward and group by layer
    fwd_comps, bwd_comps = [], []
    for comp in computations:
        (bwd_comps if _is_backward_name(comp.name) else
         fwd_comps).append(comp)
    num_layers = len(fwd_comps)
    assert num_layers > 0, "no forward layers"

    # backward comp for forward layer i (may be missing for layers with no
    # params, rare) — match by layer index
    bwd_by_layer: Dict[int, List[JaxPipelineComputation]] = {}
    for comp in bwd_comps:
        li = _layer_index_of(comp.name)
        bwd_by_layer.setdefault(li if li is not None else num_layers - 1,
                                []).append(comp)

    # ---- cluster layers into stages + slice mesh ----
    fwd_stage_layer_ids, submeshes, logical_shapes, as_dicts = \
        cluster_layers_and_slice_mesh(
            num_layers, virtual_mesh, stage_option,
            num_micro_batches=num_micro_batches,
            layer_comps=fwd_comps,
            auto_sharding_option=as_option,
            schedule=pipeline_schedule)
    num_stages = len(fwd_stage_layer_ids)

    # merge layer computations into stage computations
    fwd_stages: List[JaxPipelineComputation] = []
    bwd_stages: List[JaxPipelineComputation] = []
    for s, layer_ids in enumerate(fwd_stage_layer_ids):
        fwd_stages.append(
            merge_computations([fwd_comps[i] for i in layer_ids],
                               f"stage_{s}_fwd"))
        bwd_list = [
            c for i in reversed(layer_ids) for c in bwd_by_layer.get(i, [])
        ]
        bwd_stages.append(
            merge_computations(bwd_list, f"stage_{s}_bwd")
            if bwd_list else JaxPipelineComputation(
                f"stage_{s}_bwd", [], [], []))

    # ---- gradient accumulation rewrite ----
    all_stages = fwd_stages + bwd_stages
    # Merged stages export the union of their member layers' outvars,
    # including intra-stage activations; prune to values actually consumed
    # outside the stage (other stages, gradients, global outputs) so they
    # are neither materialized nor held across microbatches.
    _prune_stage_outvars(all_stages, grad_pairs, global_outvars)
    # ensure every grad pre-var is exported by some stage
    _export_vars(all_stages, [p for p, _ in grad_pairs])
    all_stages, acc_info = compute_grad_to_accumulate_grad(
        all_stages, [p for p, _ in grad_pairs])

    # ---- apply-grad processing ----
    apply_eqns, mean_sub = apply_grad_get_mean(apply_eqns, grad_pairs,
                                               num_micro_batches)
    # Global outputs that are marked values directly (e.g. the returned
    # loss) must read the microbatch-mean, not the raw accumulated sum.
    global_outvars = [
        mean_sub.get(v, v) if isinstance(v, Var) else v
        for v in global_outvars
    ]
    # var -> mesh placement seeds
    var_mesh: Dict[Var, int] = {}
    for pre, post in grad_pairs:
        if pre in acc_info:
            _, _, comp_idx = acc_info[pre]
            # acc_info indexes into fwd_stages + bwd_stages, where
            # bwd_stages[m] runs on mesh m (layers already reversed).
            mesh_id = comp_idx if comp_idx < num_stages else \
                comp_idx - num_stages
            var_mesh[post] = mesh_id
    # params used by forward stage s -> mesh s
    ginvar_set = set(global_invars)
    for s, comp in enumerate(fwd_stages):
        for v in comp.invars:
            if v in ginvar_set:
                var_mesh.setdefault(v, s)
    for s, comp in enumerate(bwd_stages):
        for v in comp.invars:
            if v in ginvar_set:
                var_mesh.setdefault(v, s)

    apply_comps, apply_var_mesh = partition_apply_grad(
        apply_eqns, var_mesh, num_stages, global_outvars, consts_map)
    if not apply_partition_is_acyclic(apply_comps):
        # Mutual cross-mesh dependence (e.g. global-norm clipping reads all
        # grads and feeds scaled grads back to every mesh): fall back to a
        # single-mesh apply; gradients are resharded to mesh 0.
        logger.warning(
            "apply_grad partition is cyclic (global cross-gradient op?); "
            "running the whole apply_grad on mesh 0")
        apply_comps, apply_var_mesh = partition_apply_grad(
            apply_eqns, var_mesh, num_stages, global_outvars, consts_map,
            force_mesh=0)

    if global_config.print_compilation_time:
        logger.warning("pipeshard front-end took %.2f s", time.time() - tic)

    return PipeshardDriverExecutable(
        virtual_mesh=virtual_mesh,
        fwd_stages=fwd_stages,
        bwd_stages=bwd_stages,
        apply_comps=apply_comps,
        submeshes=submeshes,
        logical_shapes=logical_shapes,
        as_dicts=as_dicts,
        as_option=as_option,
        schedule_name=pipeline_schedule,
        num_micro_batches=num_micro_batches,
        global_invars=global_invars,
        global_outvars=global_outvars,
        batch_invars=batch_invars,
        donated_invars=donated_invars,
        grad_pairs=grad_pairs,
        acc_info=acc_info,
        in_avals=in_avals,
        micro_avals=micro_avals,
        consts_map=consts_map,
        apply_var_mesh=apply_var_mesh,
        invar_paths=dict(zip(global_invars, in_paths)),
    )


def search_pipeshard_plan(fun: Callable,
                          virtual_mesh: VirtualPhysicalMesh,
                          in_avals: Sequence[Any],
                          batch_invars: Sequence[bool],
                          num_micro_batches: int,
                          as_option,
                          pipeline_schedule: str = "1f1b",
                          layer_option: Optional[LayerOption] = None,
                          stage_option: Optional[StageOption] = None
                          ) -> Dict[str, Any]:
    """Plan-only auto search: trace, slice layers, run the stage DP — no
    stage compilation, no devices needed (``virtual_mesh`` may be fully
    virtual).  Returns a JSON-friendly solution record, the analog of the
    reference's recorded auto-search results (ref
    benchmark/alpa/suite_auto_gpt.py:71-84 "solution" tuples).

    Used to produce committed plan artifacts for models far beyond the
    attached hardware (e.g. GPT-6.7B on 8 virtual devices).
    """
    tic = time.time()
    num_micro_batches = num_micro_batches or 1
    layer_option = layer_option or AutoLayerOption(layer_num=8)

    batch_flat_idx = [i for i, b in enumerate(batch_invars) if b]
    micro_avals = list(in_avals)
    for i in batch_flat_idx:
        a = in_avals[i]
        assert a.shape[0] % num_micro_batches == 0
        micro_avals[i] = jax.ShapeDtypeStruct(
            (a.shape[0] // num_micro_batches,) + tuple(a.shape[1:]), a.dtype)

    set_current_layer_option(layer_option)
    try:
        closed_jaxpr = jax.make_jaxpr(lambda *a: fun(*a))(*micro_avals)
    finally:
        set_current_layer_option(None)

    global_invars = list(closed_jaxpr.jaxpr.invars)
    compute_eqns, grad_pairs, _apply_eqns = \
        split_compute_grad_and_apply_grad(closed_jaxpr)
    compute_jaxpr = clone_jaxpr(closed_jaxpr, eqns=compute_eqns,
                                outvars=[p for p, _ in grad_pairs])
    computations, _meta = slice_closed_jaxpr_by_full_pipeline_marks(
        compute_jaxpr)
    computations = \
        mark_missing_vars_in_backward_computation_pipeline_marks(
            computations, global_invars)
    computations = pipeline_dce(computations, compute_jaxpr.jaxpr.outvars)
    fwd_comps = [c for c in computations
                 if not _is_backward_name(c.name)]

    fwd_stage_layer_ids, submeshes, _logical_shapes, _as_dicts = \
        cluster_layers_and_slice_mesh(
            len(fwd_comps), virtual_mesh, stage_option,
            num_micro_batches=num_micro_batches,
            layer_comps=fwd_comps,
            auto_sharding_option=as_option,
            schedule=pipeline_schedule)
    return {
        "num_layers": len(fwd_comps),
        "num_micro_batches": num_micro_batches,
        "pipeline_schedule": pipeline_schedule,
        "num_stages": len(fwd_stage_layer_ids),
        "forward_stage_layer_ids": [list(map(int, ids))
                                    for ids in fwd_stage_layer_ids],
        "submesh_shapes": [list(map(int, s.shape)) for s in submeshes],
        "search_seconds": round(time.time() - tic, 2),
    }


def _has_grad_marker(eqn) -> bool:
    from alpa_tpu.pipeline_parallel.primitive_def import is_marker
    return is_marker(eqn, "grad")


def _prune_stage_outvars(stages: List[JaxPipelineComputation], grad_pairs,
                         global_outvars):
    external = set(p for p, _ in grad_pairs)
    external.update(v for v in global_outvars if isinstance(v, Var))
    invars_of = [set(s.invars) for s in stages]
    for i, comp in enumerate(stages):
        used_elsewhere = set()
        for j, inv in enumerate(invars_of):
            if j != i:
                used_elsewhere |= inv
        comp.outvars = [
            v for v in comp.outvars
            if v in external or v in used_elsewhere
        ]


def _export_vars(stages: List[JaxPipelineComputation], needed: Sequence[Var]):
    """Make sure each needed var is an outvar of the stage defining it."""
    for v in needed:
        found = any(v in s.outvars for s in stages)
        if found:
            continue
        for s in stages:
            if any(v in e.outvars for e in s.eqns):
                s.outvars.append(v)
                break


def _compile_inference(fun, virtual_mesh, closed_jaxpr, in_avals,
                       micro_avals, in_tree, batch_invars,
                       num_micro_batches, as_option, stage_option, tic):
    """Forward-only pipeshard compile (inference schedule)."""
    from alpa_tpu.pipeline_parallel.pipeshard_executable import (
        PipeshardDriverExecutable)

    global_invars = list(closed_jaxpr.jaxpr.invars)
    global_outvars = list(closed_jaxpr.jaxpr.outvars)
    consts_map = dict(zip(closed_jaxpr.jaxpr.constvars, closed_jaxpr.consts))

    computations, _ = slice_closed_jaxpr_by_full_pipeline_marks(closed_jaxpr)
    if not computations:
        raise ValueError(
            "No pipeline layers found. For training, use alpa_tpu.grad / "
            "value_and_grad (plain jax.grad hides the gradient boundary "
            "and disables the layer transform); for inference, mark layers "
            "with mark_pipeline_boundary() or use AutoLayerOption.")
    computations = \
        mark_missing_vars_in_backward_computation_pipeline_marks(
            computations, global_invars)
    computations = pipeline_dce(computations, global_outvars)

    num_layers = len(computations)
    fwd_stage_layer_ids, submeshes, logical_shapes, as_dicts = \
        cluster_layers_and_slice_mesh(
            num_layers, virtual_mesh, stage_option,
            num_micro_batches=num_micro_batches,
            layer_comps=computations, auto_sharding_option=as_option,
            objective="inference")
    fwd_stages = [
        merge_computations([computations[i] for i in ids], f"stage_{s}_fwd")
        for s, ids in enumerate(fwd_stage_layer_ids)
    ]

    return PipeshardDriverExecutable(
        virtual_mesh=virtual_mesh,
        fwd_stages=fwd_stages,
        bwd_stages=[],
        apply_comps=[],
        submeshes=submeshes,
        logical_shapes=logical_shapes,
        as_dicts=as_dicts,
        as_option=as_option,
        schedule_name="inference",
        num_micro_batches=num_micro_batches,
        global_invars=global_invars,
        global_outvars=global_outvars,
        batch_invars=batch_invars,
        donated_invars=(False,) * len(in_avals),
        grad_pairs=[],
        acc_info={},
        in_avals=in_avals,
        micro_avals=micro_avals,
        consts_map=consts_map,
        apply_var_mesh={},
    )
