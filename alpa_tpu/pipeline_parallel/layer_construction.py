"""Layer construction: cluster a loss function's jaxpr into pipeline layers.

Analog of ref ``alpa/pipeline_parallel/layer_construction.py`` (SURVEY.md
§2.4): group equations into K layers either at user-placed boundary markers
(``ManualLayerOption``) or automatically by a DP minimizing max per-layer
flops + cross-layer communication (``AutoLayerOption``, ref
``cluster_jaxpr_by_cost:342``), then wrap every layer in full start/end
pipeline markers (so autodiff transposes them into backward-layer markers)
and optionally apply per-layer rematerialization (ref ``manual_remat:542``,
``automatic_remat:571``).

The transform applies to the *loss function* before differentiation:
``alpa_tpu.grad`` consults the active layer option
(``set_current_layer_option``) installed by the pipeline compile driver.
"""
import dataclasses
import logging
import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax._src.core import jaxpr_as_fun
from jax.extend.core import ClosedJaxpr, Literal, Var

from alpa_tpu.pipeline_parallel.primitive_def import pipeline_p
from alpa_tpu.util import (OrderedSet, clone_jaxpr, jaxpr_eqn_flops,
                           new_jaxpr_eqn)

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class LayerOption:
    """Base layer option (ref layer_construction.py:35)."""
    remat_layer: bool = False


@dataclasses.dataclass
class ManualLayerOption(LayerOption):
    """Split at user-placed ``mark_pipeline_boundary()`` calls
    (ref layer_construction.py:46)."""


@dataclasses.dataclass
class AutoLayerOption(LayerOption):
    """Automatic clustering into ``layer_num`` layers
    (ref layer_construction.py:70)."""
    layer_num: int = 2
    # cost tolerance: allow up to eps relative imbalance for less comm
    eps: float = 0.6
    # layers must contain at least this many non-trivial ops
    cost_criteria: str = "flops"


@dataclasses.dataclass
class FollowLayerOption(LayerOption):
    """Reuse the layer count decided for another parallelized function
    (ref layer_construction.py:121): cluster this function automatically
    into the same number of layers so stage assignments line up."""
    src_executable: Any = None
    layer_num: int = 2

    def resolved_layer_num(self) -> int:
        ex = self.src_executable
        if ex is None:
            return self.layer_num
        n = getattr(ex, "num_fwd_stages", None)
        if n is None:
            raise ValueError(
                "FollowLayerOption.src_executable must be a pipeshard "
                f"executable (got {type(ex).__name__}, which has no "
                "stages to follow); pass layer_num explicitly instead")
        return int(n)


# ---- active-option context used by alpa_tpu.grad ----
_layer_ctx = threading.local()


def set_current_layer_option(opt: Optional[LayerOption]):
    _layer_ctx.opt = opt


def current_layer_option() -> Optional[LayerOption]:
    return getattr(_layer_ctx, "opt", None)


########################################
# clustering
########################################


def _eqn_is_boundary(eqn) -> bool:
    return (eqn.primitive is pipeline_p and
            eqn.params["mark_type"] == "boundary")


def slice_eqns_by_boundary(closed_jaxpr: ClosedJaxpr) -> List[List]:
    """Split eqns at boundary markers (ref slice_eqns_by_pipeline_marks)."""
    groups, cur = [], []
    for eqn in closed_jaxpr.jaxpr.eqns:
        if _eqn_is_boundary(eqn):
            if cur:
                groups.append(cur)
            cur = []
        else:
            cur.append(eqn)
    if cur:
        groups.append(cur)
    return groups


HEAVY_PRIMS = frozenset({"dot_general", "conv_general_dilated"})


def _segment_eqns(eqns) -> List[Tuple[int, int]]:
    """Coarsen eqns into segments that each end right after a heavy op —
    the only sensible layer cut points.  Keeps the DP at O(#dots^2 * k)
    instead of O(#eqns^2 * k)."""
    bounds = []
    start = 0
    for i, e in enumerate(eqns):
        if e.primitive.name in HEAVY_PRIMS:
            bounds.append((start, i + 1))
            start = i + 1
    if start < len(eqns):
        bounds.append((start, len(eqns)))
    return bounds


def cluster_eqns_by_cost(closed_jaxpr: ClosedJaxpr, layer_num: int,
                         eps: float = 0.6) -> List[List]:
    """DP clustering of eqns into ``layer_num`` contiguous groups.

    Re-derivation of ref ``cluster_jaxpr_by_cost`` (layer_construction.py:
    342-422): minimize cross-layer transferred bytes subject to each layer's
    flops <= (1 + eps) * (total / layer_num).  The DP runs over heavy-op
    segments (cut points only after dots/convs), not raw eqns.
    """
    all_eqns = closed_jaxpr.jaxpr.eqns
    if len(all_eqns) == 0 or layer_num <= 1:
        return [list(all_eqns)]
    segments = _segment_eqns(all_eqns)
    # treat each segment as one "super eqn"
    eqns = segments
    n = len(eqns)
    if n <= layer_num:
        return [list(all_eqns[a:b]) for a, b in segments]
    flops = np.array([
        sum(jaxpr_eqn_flops(e) for e in all_eqns[a:b]) for a, b in segments
    ])
    total = flops.sum()
    budget = (1 + eps) * total / layer_num

    # cumulative flops for O(1) range cost
    cum = np.concatenate([[0], np.cumsum(flops)])

    # outgoing bytes if we cut after segment i: vars defined in seg <= i
    # used in seg > i
    seg_of = np.zeros(len(all_eqns), dtype=int)
    for si, (a, b) in enumerate(segments):
        seg_of[a:b] = si
    defined_at = {}
    for i, e in enumerate(all_eqns):
        for v in e.outvars:
            defined_at[v] = seg_of[i]
    last_use = {}
    for i, e in enumerate(all_eqns):
        for v in e.invars:
            if isinstance(v, Var) and v in defined_at:
                last_use[v] = seg_of[i]
    for v in closed_jaxpr.jaxpr.outvars:
        if isinstance(v, Var) and v in defined_at:
            last_use[v] = n
    cut_bytes = np.zeros(n + 1)
    for v, d in defined_at.items():
        lu = last_use.get(v, d)
        if lu > d and hasattr(v.aval, "shape"):
            b = float(np.prod(v.aval.shape) if v.aval.shape else 1) * \
                v.aval.dtype.itemsize
            # v crosses every cut in (d, lu]
            cut_bytes[d + 1:lu + 1] += b

    # f[k][i]: lexicographic (comm bytes, sum of squared layer flops) of
    # grouping the first i segments into k layers.  The flops budget
    # applies to EVERY layer including the last (letting the final layer
    # escape it once produced 26-of-32-layers-in-one-cluster partitions);
    # the squared-flops term breaks comm ties toward balance — in a
    # uniform transformer every block-boundary cut moves the same bytes,
    # so comm alone cannot distinguish [4,4] from [7,1].
    f = np.full((layer_num + 1, n + 1, 2), float("inf"))
    arg = np.zeros((layer_num + 1, n + 1), dtype=int)
    f[0][0] = (0.0, 0.0)
    for k in range(1, layer_num + 1):
        for i in range(1, n + 1):
            for j in range(0, i):
                if cum[i] - cum[j] > budget:
                    continue
                if f[k - 1][j][0] == float("inf"):
                    continue
                seg_fl = float(cum[i] - cum[j])
                c = (f[k - 1][j][0] + (cut_bytes[j] if j > 0 else 0.0),
                     f[k - 1][j][1] + seg_fl * seg_fl)
                if c < tuple(f[k][i]):
                    f[k][i] = c
                    arg[k][i] = j
    def _segs_to_eqns(seg_lo: int, seg_hi: int):
        return list(all_eqns[segments[seg_lo][0]:segments[seg_hi - 1][1]])

    if f[layer_num][n][0] == float("inf"):
        # fall back to equal-flops split over segments
        return _equal_flops_split(all_eqns, segments, flops, layer_num)
    # backtrack
    cuts = []
    i = n
    for k in range(layer_num, 0, -1):
        j = arg[k][i]
        cuts.append((j, i))
        i = j
    cuts.reverse()
    return [_segs_to_eqns(a, b) for a, b in cuts if b > a]


def _equal_flops_split(all_eqns, segments, flops, layer_num):
    total = flops.sum()
    target = total / layer_num
    groups, cur, acc = [], [], 0.0
    for (a, b), fl in zip(segments, flops):
        cur.extend(all_eqns[a:b])
        acc += fl
        if acc >= target and len(groups) < layer_num - 1:
            groups.append(cur)
            cur, acc = [], 0.0
    if cur:
        groups.append(cur)
    return groups


########################################
# marker insertion
########################################


def add_pipeline_marks_for_sliced_eqns(closed_jaxpr: ClosedJaxpr,
                                       sliced_eqns: List[List]
                                       ) -> ClosedJaxpr:
    """Wrap each eqn group in full start/end pipeline markers
    (ref layer_construction.py add_pipeline_marks_for_sliced_eqns).

    Every value entering a layer passes through its start marker and every
    value leaving through its end marker, so jaxpr slicing after autodiff
    can reconstruct layer boundaries exactly.
    """
    from alpa_tpu.util import gensym_var

    jaxpr = closed_jaxpr.jaxpr
    global_invars = OrderedSet(jaxpr.invars)
    global_consts = OrderedSet(jaxpr.constvars)

    var_layer = {}
    for li, group in enumerate(sliced_eqns):
        for e in group:
            for v in e.outvars:
                var_layer[v] = li

    new_eqns = []
    # per-layer remapping of vars
    for li, group in enumerate(sliced_eqns):
        # inputs: vars used in this layer defined outside it
        layer_invars = OrderedSet()
        for e in group:
            for v in e.invars:
                if isinstance(v, Literal):
                    continue
                if var_layer.get(v, -1) != li:
                    layer_invars.add(v)
        # outputs: vars defined here used later / globally
        layer_outvars = OrderedSet()
        used_later = OrderedSet()
        for lj in range(li + 1, len(sliced_eqns)):
            for e in sliced_eqns[lj]:
                for v in e.invars:
                    if isinstance(v, Var):
                        used_later.add(v)
        for v in jaxpr.outvars:
            if isinstance(v, Var):
                used_later.add(v)
        for e in group:
            for v in e.outvars:
                if v in used_later:
                    layer_outvars.add(v)

        in_list = list(layer_invars)
        in_map = {v: gensym_var(v.aval) for v in in_list}
        start_eqn = new_jaxpr_eqn(
            in_list, [in_map[v] for v in in_list], pipeline_p,
            dict(name=f"layer_{li}", mark_type="start"))
        new_eqns.append(start_eqn)

        out_list = list(layer_outvars)
        out_pre = {v: gensym_var(v.aval) for v in out_list}

        sub = dict(in_map)
        sub.update(out_pre)

        def substitute(v):
            if isinstance(v, Literal):
                return v
            return sub.get(v, v)

        for e in group:
            new_eqns.append(
                e.replace(invars=[substitute(v) for v in e.invars],
                          outvars=[out_pre.get(v, v) for v in e.outvars]))
        end_eqn = new_jaxpr_eqn(
            [out_pre[v] for v in out_list], out_list, pipeline_p,
            dict(name=f"layer_{li}", mark_type="end"))
        new_eqns.append(end_eqn)

    return clone_jaxpr(closed_jaxpr, eqns=new_eqns)


########################################
# the loss-function transform
########################################


def manual_remat(fun: Optional[Callable] = None):
    """Rematerialize each manually-marked layer of ``fun`` (boundaries
    from ``mark_pipeline_boundary()``), outside any pipeline compile —
    ref ``manual_remat`` (layer_construction.py:542).  Usable as a bare
    decorator or called with the function."""

    def decorate(f):
        return layer_level_transform(f, ManualLayerOption(remat_layer=True))

    return decorate if fun is None else decorate(fun)


def automatic_remat(fun: Optional[Callable] = None, *,
                    layer_num: int = 2, eps: float = 0.6):
    """Rematerialize ``fun`` at automatically-clustered layer boundaries
    (flops-balanced DP) — ref ``automatic_remat``
    (layer_construction.py:571)."""

    def decorate(f):
        return layer_level_transform(
            f, AutoLayerOption(layer_num=layer_num, eps=eps,
                               remat_layer=True))

    return decorate if fun is None else decorate(fun)


def layer_level_transform(fn: Callable, layer_option: LayerOption) -> Callable:
    """Wrap a loss function so tracing it yields a fully layer-marked jaxpr
    (ref manual/automatic_layer_construction decorators)."""

    def wrapped(*args, **kwargs):
        closed_jaxpr, out_tree = _make_jaxpr_with_tree(fn, *args, **kwargs)
        if isinstance(layer_option, AutoLayerOption):
            sliced = cluster_eqns_by_cost(closed_jaxpr,
                                          layer_option.layer_num,
                                          layer_option.eps)
        elif isinstance(layer_option, FollowLayerOption):
            sliced = cluster_eqns_by_cost(closed_jaxpr,
                                          layer_option.resolved_layer_num())
        else:
            sliced = slice_eqns_by_boundary(closed_jaxpr)
        marked = add_pipeline_marks_for_sliced_eqns(closed_jaxpr, sliced)
        run = (_remat_by_layer(marked) if layer_option.remat_layer
               else jaxpr_as_fun(marked))
        flat_args = jax.tree_util.tree_leaves((args, kwargs))
        out_flat = run(*flat_args)
        return jax.tree_util.tree_unflatten(out_tree, out_flat)

    return wrapped


def _make_jaxpr_with_tree(fn, *args, **kwargs):
    flat_args, in_tree = jax.tree_util.tree_flatten((args, kwargs))
    out_store = [None]

    def flat_fn(*flat):
        a, kw = jax.tree_util.tree_unflatten(in_tree, list(flat))
        out = fn(*a, **kw)
        out_flat, tree = jax.tree_util.tree_flatten(out)
        out_store[0] = tree
        return out_flat

    closed_jaxpr = jax.make_jaxpr(flat_fn)(*flat_args)
    return closed_jaxpr, out_store[0]


def _remat_by_layer(marked_jaxpr: ClosedJaxpr) -> Callable:
    """Apply jax.checkpoint per layer: rebuild the function layer by layer,
    wrapping each layer's computation in jax.remat and re-emitting the full
    start/end marker pair around it so downstream slicing still works
    (ref remat integration, layer_construction.py:542-606)."""
    from alpa_tpu.pipeline_parallel.computation import (
        mark_missing_vars_in_backward_computation_pipeline_marks,
        slice_closed_jaxpr_by_full_pipeline_marks)

    computations, _meta = slice_closed_jaxpr_by_full_pipeline_marks(
        marked_jaxpr, strict=False)
    computations = mark_missing_vars_in_backward_computation_pipeline_marks(
        computations, marked_jaxpr.jaxpr.invars)

    def run(*flat_args):
        env = {}
        jaxpr = marked_jaxpr.jaxpr
        for v, a in zip(jaxpr.invars, flat_args):
            env[v] = a
        for cv, c in zip(jaxpr.constvars, marked_jaxpr.consts):
            env[cv] = c

        for comp in computations:
            fn = jax.checkpoint(jaxpr_as_fun(comp.closed_jaxpr()))
            args = [env[v] for v in comp.invars]
            # full marker protocol: start(inputs) -> remat body -> end(outs)
            args = pipeline_p.bind(*args, name=comp.name, mark_type="start")
            outs = fn(*args)
            outs = pipeline_p.bind(*outs, name=comp.name, mark_type="end")
            for v, o in zip(comp.outvars, outs):
                env[v] = o

        def read(v):
            if isinstance(v, Literal):
                return v.val
            return env[v]

        return [read(v) for v in jaxpr.outvars]

    return run
