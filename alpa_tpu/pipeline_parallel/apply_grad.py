"""Gradient split / accumulation / apply-grad partitioning for pipelines.

Analog of ref ``alpa/pipeline_parallel/apply_grad.py`` (SURVEY.md §2.4):

* ``split_compute_grad_and_apply_grad`` (ref :351) — split the train-step
  jaxpr at the gradient marker,
* ``compute_grad_to_accumulate_grad`` (ref :504) — rewrite backward
  computations so each microbatch adds into accumulator invars,
* ``apply_grad_get_mean`` (ref :650) — divide accumulated values by the
  number of microbatches,
* ``process_apply_gradient`` (ref :591) — partition the apply_grad eqns
  across meshes following the placement of the gradients they consume.
"""
import dataclasses
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
from jax.extend.core import ClosedJaxpr, Literal, Var

from alpa_tpu.pipeline_parallel.computation import JaxPipelineComputation
from alpa_tpu.pipeline_parallel.primitive_def import is_marker
from alpa_tpu.util import OrderedSet, clone_jaxpr, gensym_var, new_jaxpr_eqn

logger = logging.getLogger(__name__)


def split_compute_grad_and_apply_grad(closed_jaxpr: ClosedJaxpr):
    """Split at the gradient marker (ref apply_grad.py:351).

    Returns (compute_eqns, grad_pairs, apply_eqns) where grad_pairs is the
    list of (pre-marker var, post-marker var) for every marked value.
    """
    eqns = closed_jaxpr.jaxpr.eqns
    marker_idx = [i for i, e in enumerate(eqns) if is_marker(e, "grad")]
    if not marker_idx:
        raise ValueError(
            "PipeshardParallel requires alpa_tpu.grad / value_and_grad "
            "inside the parallelized function (gradient marker not found).")
    i = marker_idx[-1]
    marker = eqns[i]
    grad_pairs = [(iv, ov) for iv, ov in zip(marker.invars, marker.outvars)
                  if isinstance(iv, Var)]
    return list(eqns[:i]), grad_pairs, list(eqns[i + 1:])


def compute_grad_to_accumulate_grad(
        computations: List[JaxPipelineComputation],
        grad_vars: Sequence[Var]
) -> Tuple[List[JaxPipelineComputation], Dict[Var, Var]]:
    """Rewrite computations producing gradient values so they *accumulate*
    (ref apply_grad.py:504).

    For each grad var g produced by computation C, add an accumulator invar
    acc_g to C and a summed outvar g_sum = g + acc_g.  The runtime feeds
    zeros for microbatch 0 and the previous sum afterwards, donating the
    accumulator.  Returns ``acc_info``: grad var ->
    (accumulator invar, summed outvar, computation index).
    """
    grad_set = set(grad_vars)
    acc_info: Dict[Var, Tuple[Var, Var, int]] = {}
    for ci, comp in enumerate(computations):
        produced = [v for v in comp.outvars if v in grad_set]
        if not produced:
            continue
        for g in produced:
            acc = gensym_var(g.aval)
            new_out = gensym_var(g.aval)
            add_eqn = _make_add_eqn(g, acc, new_out)
            comp.eqns.append(add_eqn)
            comp.invars.append(acc)
            comp.outvars = [new_out if v is g else v for v in comp.outvars]
            acc_info[g] = (acc, new_out, ci)
    return computations, acc_info


def _make_add_eqn(a: Var, b: Var, out: Var):
    from jax.extend.core import Primitive
    from jax._src.lax import lax as lax_internal
    add_p = lax_internal.add_p
    return new_jaxpr_eqn([a, b], [out], add_p, {})


@dataclasses.dataclass
class ApplyGradConfig:
    """Partitioned apply-grad: one computation per mesh plus metadata."""
    computations: List[JaxPipelineComputation]
    mesh_assignment: List[int]
    # invars of the apply computations that are accumulated gradients
    grad_invars: List[Var]
    num_micro_batches: int


def apply_grad_get_mean(apply_eqns: List, grad_pairs, num_micro_batches: int,
                        gensym=gensym_var):
    """Insert g / num_micro_batches at the head of apply_grad
    (ref apply_grad.py:650).  Returns (new_eqns, substitution): apply eqns
    should consume the divided values."""
    from jax._src.lax import lax as lax_internal

    div_eqns = []
    sub = {}
    for pre, post in grad_pairs:
        scaled = gensym(post.aval)
        # div by scalar: mul by reciprocal via integer_pow? use div_p with
        # a literal denominator of matching dtype.
        denom = Literal(np.array(num_micro_batches, post.aval.dtype),
                        post.aval.update(shape=()))
        div_eqns.append(
            new_jaxpr_eqn([post, denom], [scaled], lax_internal.div_p, {}))
        sub[post] = scaled
    new_apply = []
    for e in apply_eqns:
        new_apply.append(
            e.replace(invars=[sub.get(v, v) if isinstance(v, Var) else v
                              for v in e.invars]))
    return div_eqns + new_apply, sub


def apply_partition_is_acyclic(comps: List[JaxPipelineComputation]) -> bool:
    """Check the comp-level dependency graph for cycles (mutual cross-mesh
    value exchange, e.g. global-norm clipping)."""
    outs_of = {}
    for m, c in enumerate(comps):
        for v in c.outvars:
            outs_of[v] = m
    deps = {m: set() for m in range(len(comps))}
    for m, c in enumerate(comps):
        for v in c.invars:
            src = outs_of.get(v)
            if src is not None and src != m:
                deps[m].add(src)
    # DFS cycle check
    state = {}

    def visit(m):
        if state.get(m) == 2:
            return True
        if state.get(m) == 1:
            return False
        state[m] = 1
        for d in deps[m]:
            if not visit(d):
                return False
        state[m] = 2
        return True

    return all(visit(m) for m in range(len(comps)))


def partition_apply_grad(apply_eqns: List,
                         var_mesh: Dict[Var, int],
                         num_meshes: int,
                         global_outvars: Sequence[Var],
                         consts_map: Dict[Var, Any],
                         force_mesh: Optional[int] = None
                         ) -> Tuple[List[JaxPipelineComputation], Dict[Var, int]]:
    """Assign each apply-grad eqn to a mesh by propagating the placement of
    its inputs (ref process_apply_gradient:591 / propagate_mesh_assignment).

    Eqns whose inputs span meshes go to the mesh holding the largest input
    (so gradient-sized values stay put and scalars travel); values are
    ferried by the runtime's cross-mesh resharding.  Returns one computation
    per mesh (possibly empty) and the output->mesh map.
    """
    import numpy as _np

    eqn_mesh: List[int] = []
    local_var_mesh = dict(var_mesh)
    for e in apply_eqns:
        if force_mesh is not None:
            m = force_mesh
        else:
            best_m, best_size = None, -1.0
            for v in e.invars:
                if isinstance(v, Var) and v in local_var_mesh:
                    size = float(_np.prod(v.aval.shape)) if getattr(
                        v.aval, "shape", None) else 1.0
                    if size > best_size:
                        best_m, best_size = local_var_mesh[v], size
            m = best_m if best_m is not None else 0
        eqn_mesh.append(m)
        for v in e.outvars:
            local_var_mesh[v] = m

    comps = []
    global_out_set = {gv for gv in global_outvars if isinstance(gv, Var)}
    for mesh_id in range(num_meshes):
        eqns_m = [e for e, m in zip(apply_eqns, eqn_mesh) if m == mesh_id]
        invars = OrderedSet()
        defined = OrderedSet()
        for e in eqns_m:
            for v in e.invars:
                if isinstance(v, Var) and v not in defined and \
                        v not in consts_map:
                    invars.add(v)
            defined.update(e.outvars)
        outvars = OrderedSet()
        for e in eqns_m:
            for v in e.outvars:
                if v in global_out_set:
                    outvars.add(v)
        # also export vars needed by other meshes
        for e, m in zip(apply_eqns, eqn_mesh):
            if m == mesh_id:
                continue
            for v in e.invars:
                if isinstance(v, Var) and v in defined:
                    outvars.add(v)
        consts = {
            v: consts_map[v] for e in eqns_m for v in e.invars
            if isinstance(v, Var) and v in consts_map
        }
        comps.append(
            JaxPipelineComputation(f"apply_grad_{mesh_id}", list(invars),
                                   list(outvars), eqns_m, consts))
    return comps, local_var_mesh
