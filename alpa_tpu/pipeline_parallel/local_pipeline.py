"""Single-device pipeline interpreter for debugging.

Analog of ref ``alpa/pipeline_parallel/local_pipeline.py:16``
(``LocalPipelineRunner``): runs the layer computations sequentially on one
device, exactly following the sliced jaxprs — useful for isolating pipeline
slicing bugs from runtime bugs.
"""
import logging
from typing import Any, Dict, List, Sequence

import jax
from jax._src.core import jaxpr_as_fun
from jax.extend.core import Literal, Var

from alpa_tpu.pipeline_parallel.computation import (
    JaxPipelineComputation,
    mark_missing_vars_in_backward_computation_pipeline_marks, pipeline_dce,
    slice_closed_jaxpr_by_full_pipeline_marks)
from alpa_tpu.pipeline_parallel.layer_construction import (
    AutoLayerOption, set_current_layer_option)

logger = logging.getLogger(__name__)


class LocalPipelineExecutable:
    """Interpret sliced computations sequentially on the default device."""

    def __init__(self, fun, in_avals, layer_option=None):
        set_current_layer_option(layer_option or AutoLayerOption(layer_num=2))
        try:
            closed_jaxpr = jax.make_jaxpr(fun)(*in_avals)
        finally:
            set_current_layer_option(None)
        self.closed_jaxpr = closed_jaxpr
        computations, _ = slice_closed_jaxpr_by_full_pipeline_marks(
            closed_jaxpr)
        if computations:
            computations = \
                mark_missing_vars_in_backward_computation_pipeline_marks(
                    computations, closed_jaxpr.jaxpr.invars)
        self.computations = computations
        self.in_avals = in_avals
        self.out_tree = None

    def launch_on_driver(self, *flat_args):
        jaxpr = self.closed_jaxpr.jaxpr
        env: Dict[Var, Any] = {}
        for v, a in zip(jaxpr.invars, flat_args):
            env[v] = a
        for cv, c in zip(jaxpr.constvars, self.closed_jaxpr.consts):
            env[cv] = c

        def read(v):
            return v.val if isinstance(v, Literal) else env[v]

        if not self.computations:
            fn = jaxpr_as_fun(self.closed_jaxpr)
            return fn(*flat_args)

        for comp in self.computations:
            fn = comp.get_runnable()
            args = [read(v) for v in comp.invars]
            outs = fn(*args)
            for v, o in zip(comp.outvars, outs):
                env[v] = o
        # any eqns outside computations (e.g. grad marker, apply) run via
        # the full jaxpr fallback when outputs are missing
        missing = [
            v for v in jaxpr.outvars
            if isinstance(v, Var) and v not in env
        ]
        if missing:
            fn = jaxpr_as_fun(self.closed_jaxpr)
            return fn(*flat_args)
        return [read(v) for v in jaxpr.outvars]


def compile_local_pipeline_executable(fun, in_avals, in_tree):
    return LocalPipelineExecutable(fun, in_avals)
