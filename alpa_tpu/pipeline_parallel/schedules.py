"""Static pipeline schedules: GPipe, 1F1B (PipeDream-flush), inference.

Analog of ref ``alpa/pipeline_parallel/schedules.py`` (SURVEY.md §2.4): a
schedule is a list of clock ticks; each tick lists, per mesh, the
(microbatch_idx, stage_idx) task to run (or None).  Stage->mesh placement
follows the standard symmetric layout: forward stage i and backward stage
(2k-1-i) run on mesh i.
"""
import dataclasses
import logging
from typing import List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

Task = Optional[Tuple[int, int]]  # (microbatch, stage)


def gen_dependency_with_stages(num_stages: int):
    """Adjacency matrix of stage dependencies for the symmetric fwd/bwd
    layout (ref schedules.py:16)."""
    d = np.zeros((num_stages, num_stages), dtype=bool)
    for i in range(1, num_stages):
        d[i][i - 1] = True
    return d


class PipelineSchedule:
    """Base class (ref schedules.py:58)."""

    def __init__(self, *, num_stages: int, num_meshes: int,
                 num_batch: int):
        self.num_stages = num_stages
        self.num_meshes = num_meshes
        self.num_batch = num_batch
        self._schedules: List[List[Task]] = self._generate_schedule()

    @property
    def schedules(self) -> List[List[Task]]:
        return self._schedules

    def _generate_schedule(self):
        raise NotImplementedError

    @property
    def num_clock(self) -> int:
        return len(self._schedules)

    def stage_mesh_mapping(self, stage_idx: int) -> int:
        """Symmetric placement: fwd stage i and bwd stage 2M-1-i on mesh i
        (ref schedules.py:128-162)."""
        m = self.num_meshes
        if stage_idx < m:
            return stage_idx
        if stage_idx < 2 * m:
            return 2 * m - 1 - stage_idx
        # apply-grad stages: stage 2m+i on mesh i
        return stage_idx - 2 * m

    def mesh_stage_mapping(self, mesh_idx: int) -> List[int]:
        return [
            s for s in range(self.num_stages)
            if self.stage_mesh_mapping(s) == mesh_idx
        ]

    def pprint_schedule(self) -> str:
        lines = ["k\t" + "\t".join(f"mesh{i}" for i in range(self.num_meshes))]
        for k, tick in enumerate(self._schedules):
            lines.append(f"{k}\t" + "\t".join(
                (f"b{t[0]}s{t[1]}" if t else "-") for t in tick))
        return "\n".join(lines)

    def overlap_window_hint(self) -> int:
        """Default in-flight transfer window for overlap dispatch (ISSUE
        4): roughly one eagerly-launched cross-mesh transfer per pipeline
        rank keeps every mesh's next input moving without unbounded
        staging memory."""
        return max(2, min(8, self.num_meshes))


class GpipeSchedule(PipelineSchedule):
    """All forwards, then all backwards (ref schedules.py:192)."""

    def _generate_schedule(self):
        m, n = self.num_meshes, self.num_batch
        schedules = []
        # forward waves
        num_clock = m + n - 1
        for k in range(num_clock):
            tick: List[Task] = []
            for d in range(m):
                mb = k - d
                tick.append((mb, d) if 0 <= mb < n else None)
            schedules.append(tick)
        # backward waves: bwd stage for mesh d is (2m-1-d)
        for k in range(num_clock):
            tick = []
            for d in range(m):
                mb = k - (m - 1 - d)
                tick.append((mb, 2 * m - 1 - d) if 0 <= mb < n else None)
            schedules.append(tick)
        return schedules


class PipeDreamFlush(PipelineSchedule):
    """1F1B with flush (ref schedules.py:271): same latency as GPipe but
    bounded activation memory (at most `m - mesh_idx` in-flight
    microbatches per mesh)."""

    def _warmup_depth(self, mesh_idx: int) -> int:
        return self.num_meshes - mesh_idx - 1

    def _generate_schedule(self):
        m, n = self.num_meshes, self.num_batch
        # per-mesh operation list: ('F'|'B', microbatch)
        per_mesh_ops: List[List[Tuple[str, int]]] = []
        for d in range(m):
            warmup = min(self._warmup_depth(d), n)
            ops = [("F", i) for i in range(warmup)]
            fwd_i, bwd_i = warmup, 0
            # steady 1F1B
            while fwd_i < n:
                ops.append(("F", fwd_i))
                fwd_i += 1
                ops.append(("B", bwd_i))
                bwd_i += 1
            while bwd_i < n:
                ops.append(("B", bwd_i))
                bwd_i += 1
            per_mesh_ops.append(ops)

        # simulate clock ticks with dependency: F(mb,d) needs F(mb,d-1) done;
        # B(mb,d) needs B(mb,d+1) done (and F(mb,d)).
        fwd_done = np.full((n, m), -1)  # clock when done
        bwd_done = np.full((n, m), -1)
        ptr = [0] * m
        schedules = []
        clock = 0
        total_ops = sum(len(o) for o in per_mesh_ops)
        done_ops = 0
        while done_ops < total_ops and clock < 10 * total_ops + 10:
            tick: List[Task] = [None] * m
            for d in range(m):
                if ptr[d] >= len(per_mesh_ops[d]):
                    continue
                kind, mb = per_mesh_ops[d][ptr[d]]
                if kind == "F":
                    ready = d == 0 or (0 <= fwd_done[mb][d - 1] < clock)
                    if ready:
                        tick[d] = (mb, d)
                        fwd_done[mb][d] = clock
                        ptr[d] += 1
                        done_ops += 1
                else:
                    ready_up = (d == m - 1) or (0 <= bwd_done[mb][d + 1] <
                                                clock)
                    ready_fwd = 0 <= fwd_done[mb][d] < clock
                    if ready_up and ready_fwd:
                        tick[d] = (mb, 2 * m - 1 - d)
                        bwd_done[mb][d] = clock
                        ptr[d] += 1
                        done_ops += 1
            schedules.append(tick)
            clock += 1
        assert done_ops == total_ops, "1F1B schedule failed to converge"
        return schedules


class OverlapFriendlyPipeDreamSchedule(PipeDreamFlush):
    """1F1B with a doubled warmup depth (ref
    OverlapFriendlyPipeDreamSchedule, schedules.py:452): each mesh runs up
    to ``2*(m - d) - 1`` forward microbatches before its first backward, so
    more cross-mesh activations are in flight at once — the async dispatch
    queue (the reference: NCCL sends) gets more transfers to overlap with
    compute.  Trade-off: proportionally more live activation memory."""

    def _warmup_depth(self, mesh_idx: int) -> int:
        return 2 * (self.num_meshes - mesh_idx) - 1

    def overlap_window_hint(self) -> int:
        # the doubled warmup keeps ~2× more activations in flight, so the
        # overlap dispatcher gets a proportionally deeper window
        return max(2, min(16, 2 * self.num_meshes))


class InferenceSchedule(PipelineSchedule):
    """Forward-only pipelined batches (ref schedules.py:393)."""

    def _generate_schedule(self):
        m, n = self.num_meshes, self.num_batch
        schedules = []
        for k in range(m + n - 1):
            tick: List[Task] = []
            for d in range(m):
                mb = k - d
                tick.append((mb, d) if 0 <= mb < n else None)
            schedules.append(tick)
        return schedules

    def stage_mesh_mapping(self, stage_idx: int) -> int:
        if stage_idx < self.num_meshes:
            return stage_idx
        return stage_idx - self.num_meshes


def create_pipeline_schedule(name: str, *, num_stages: int, num_meshes: int,
                             num_batch: int) -> PipelineSchedule:
    """(ref schedules.py:528)"""
    if name == "1f1b_overlap_friendly":
        # The reference also reorders sends by producer order so NCCL comm
        # overlaps compute (emitter :1109); here dispatch is already fully
        # asynchronous (the jax runtime overlaps transfers with compute),
        # so only the schedule half — eager forwards — carries over.
        return OverlapFriendlyPipeDreamSchedule(num_stages=num_stages,
                                                num_meshes=num_meshes,
                                                num_batch=num_batch)
    if name == "gpipe":
        return GpipeSchedule(num_stages=num_stages, num_meshes=num_meshes,
                             num_batch=num_batch)
    if name in ("1f1b", "pipedream_flush"):
        return PipeDreamFlush(num_stages=num_stages, num_meshes=num_meshes,
                              num_batch=num_batch)
    if name == "inference":
        return InferenceSchedule(num_stages=num_stages,
                                 num_meshes=num_meshes, num_batch=num_batch)
    raise ValueError(f"unknown pipeline schedule: {name}")
