"""Lossy cross-mesh transfer codec (ISSUE 7; "EQuARX: Efficient
Quantized AllReduce in XLA", PAPERS.md).

Opt-in per-edge quantize-on-send / dequantize-on-receive for fp32/bf16
activation edges: the source mesh encodes the value into a narrow wire
dtype with blockwise scales, only the narrow payload (plus one fp32
scale per 256-element block) crosses meshes, and the destination mesh
decodes straight into the requested sharding.  Never applied to
microbatch-invariant values (weights, optimizer state) — the
``make_transfer`` factory enforces that — and off by default
(``global_config.reshard_quantize = "off"``).

Error contract (held by seeded property tests in
tests/pipeline_parallel/test_reshard_strategies.py):

* ``int8`` — symmetric per-block scaling, ``scale = amax_block / 127``.
  Round-trip error of any element is at most ``scale / 2 =
  amax_block / 254``, i.e. relative to the block's max magnitude the
  error is bounded by ``1/254 < 0.4%``.  Exact zeros survive exactly;
  all-zero blocks are bit-exact.
* ``fp8`` (``float8_e4m3fn``, gated on jax exposing the dtype) —
  per-block ``scale = amax_block / 448`` maps the block onto the e4m3
  dynamic range; 3 mantissa bits give a documented (and tested) bound of
  ``7%`` of the block max magnitude (worst-case e4m3 relative rounding
  step is 1/16 ≈ 6.25%).

Wire accounting: an fp32 edge under int8 moves ``N + 4 * ceil(N/256)``
bytes instead of ``4 * N`` — a ~3.94x reduction for block-aligned sizes
(the ≥3.5x acceptance floor in benchmark/resharding_collectives.json).

Gradient variant (ISSUE 19; same EQuARX lineage): the ``grad_*``
entries of :data:`ERROR_BOUND` cover the quantized gradient-collective
path used for DP/ZeRO gradient sync.  Two changes vs the activation
codec make it safe on the training path:

* **Stochastic rounding** (:func:`encode_stochastic`) — each element
  rounds to a neighbouring grid point with probability proportional to
  its distance, so ``E[decode(encode(x))] = x`` exactly and quantization
  noise cannot bias the optimizer.  The price is a worst-case error of
  one *full* step (``1/127`` of block max for int8, one e4m3 step for
  fp8) instead of round-to-nearest's half step.
* **Error feedback** (:func:`grad_compress`) — the residual
  ``x - decode(encode(x))`` is carried into the next quantization, so
  the *cumulative* multi-step error stays bounded by the single-shot
  bound instead of growing with the step count
  (:func:`grad_error_bound` encodes that amortization rule for the
  numerics certifier).

:func:`grad_reduce_scatter` composes quantize → partial-reduce →
requantize for the ZeRO reduce-scatter path; the ``grad_*_rs`` bounds
document both hops.
"""
import logging
from typing import Optional

import numpy as np

from alpa_tpu.telemetry import metrics as _tmetrics
from alpa_tpu.telemetry import trace as _ttrace

logger = logging.getLogger(__name__)

# elements per scaling block; one fp32 scale crosses per block
BLOCK = 256

#: Machine-readable round-trip error contract, per codec mode: the
#: worst-case element error as a fraction of the block's max magnitude
#: (the docstring bounds above, as constants).  Single source of truth
#: for every consumer — the numerics certification analysis
#: (``alpa_tpu.analysis.numerics``) composes exactly these constants
#: per lossy hop, ``plan_verifier.verify_edge`` prints them, and the
#: codec contract tests pin the codec against them.  The ``codec-bound``
#: repo-lint rule requires any module defining a lossy encode/decode
#: pair to declare this dict.
ERROR_BOUND = {
    "int8": 1.0 / 254.0,    # scale/2 = amax_block/254
    "fp8": 0.07,            # e4m3 rounding, documented 7% of blockmax
    # Gradient variants (ISSUE 19): stochastic rounding picks the
    # neighbour *probabilistically* so the expectation is exact, which
    # doubles the worst-case single-element step vs round-to-nearest —
    # a full quantization step instead of half of one.
    "grad_int8": 1.0 / 127.0,   # one full step = scale = amax_block/127
    "grad_fp8": 0.08,           # full e4m3 step, 32/448 ≈ 7.14% + slack
    # Two-hop reduce-scatter composition: each replica quantizes its
    # contribution (hop 1), the partial sum is requantized for the
    # scatter hop (hop 2).  First-order additive, same convention the
    # numerics analysis uses for chained RESHARD hops.
    "grad_int8_rs": 2.0 / 127.0,
    "grad_fp8_rs": 0.16,
}

# dtypes the codec accepts; everything else passes through untouched
_ELIGIBLE_DTYPES = ("float32", "bfloat16")

_REG = _tmetrics.get_registry()
_Q_EDGES = _REG.counter(
    "alpa_reshard_quantized_edges_total",
    "Cross-mesh transfers executed through the quantized codec",
    labelnames=("codec",))
_Q_BYTES_SAVED = _REG.counter(
    "alpa_reshard_quantized_bytes_saved_total",
    "Wire bytes saved by the quantized codec vs the lossless payload")


def have_fp8() -> bool:
    """True when this jax build exposes ``float8_e4m3fn``."""
    import jax.numpy as jnp
    return hasattr(jnp, "float8_e4m3fn")


def eligible(aval, mode: str, min_bytes: Optional[int] = None) -> bool:
    """Whether one edge's value may go through the codec: supported
    codec mode, fp32/bf16 payload, and at least
    ``global_config.reshard_quantize_min_bytes`` on the wire (small
    edges aren't bandwidth-bound; the scale overhead isn't worth it)."""
    if mode not in ("int8", "fp8"):
        return False
    if mode == "fp8" and not have_fp8():
        return False
    if str(np.dtype(aval.dtype)) not in _ELIGIBLE_DTYPES:
        return False
    shape = tuple(getattr(aval, "shape", ()))
    nbytes = int(np.prod(shape, dtype=np.int64)) * \
        np.dtype(aval.dtype).itemsize
    if min_bytes is None:
        from alpa_tpu.global_env import global_config
        min_bytes = getattr(global_config, "reshard_quantize_min_bytes",
                            65536)
    return nbytes >= min_bytes


def _wire_dtype(mode: str):
    import jax.numpy as jnp
    return jnp.int8 if mode == "int8" else jnp.float8_e4m3fn


def _wire_max(mode: str) -> float:
    # symmetric int8 uses ±127; e4m3fn tops out at ±448
    return 127.0 if mode == "int8" else 448.0


def encode(x, mode: str):
    """Blockwise-scaled quantization: flatten, pad to a BLOCK multiple,
    and emit ``(q, scales)`` with ``q[i] ≈ x[i] / scale[block(i)]`` in
    the narrow wire dtype.  Pure jax — jit-compiled on the source mesh
    by the transfer executor."""
    import jax.numpy as jnp
    n = int(np.prod(x.shape, dtype=np.int64)) if x.ndim else 1
    nb = -(-n // BLOCK)
    flat = jnp.ravel(x).astype(jnp.float32)
    flat = jnp.pad(flat, (0, nb * BLOCK - n))
    blocks = flat.reshape(nb, BLOCK)
    amax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / _wire_max(mode), 1.0)
    q = blocks / scale
    if mode == "int8":
        q = jnp.clip(jnp.round(q), -127, 127)
    return q.astype(_wire_dtype(mode)), scale.astype(jnp.float32)


def decode(q, scale, shape, dtype, mode: str):
    """Inverse of :func:`encode` (up to the documented rounding error):
    rescale, trim the padding, restore shape and payload dtype."""
    import jax.numpy as jnp
    del mode
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    flat = (q.astype(jnp.float32) * scale).reshape(-1)[:n]
    return flat.reshape(shape).astype(dtype)


def wire_bytes(shape, itemsize: int, mode: str) -> int:
    """Bytes the codec actually puts on the wire for one value."""
    del mode  # both wire dtypes are 1 byte/element
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    nb = -(-n // BLOCK)
    del itemsize
    return n + 4 * nb


class QuantizedTransfer:
    """Executor for one quantized cross-mesh RESHARD edge: encode on the
    source mesh (jit), ``device_put`` the narrow payload + scales to the
    destination mesh, decode on the destination mesh (jit, straight into
    ``dst_sharding``).  The emulated wire idle sees only the narrow
    payload's byte count, so the bench's wall-clock win is honest."""

    __slots__ = ("mode", "dst_sharding", "src_sharding", "shape",
                 "dtype", "ndim", "nbytes", "wire", "fast", "_enc",
                 "_dec", "_land_q", "_land_s")

    def __init__(self, aval, src_sharding, dst_sharding, mode):
        self.mode = mode
        self.dst_sharding = dst_sharding
        self.src_sharding = src_sharding
        self.shape = tuple(aval.shape)
        self.dtype = aval.dtype
        self.ndim = len(self.shape)
        self.fast = False
        self.nbytes = int(np.prod(self.shape, dtype=np.int64) *
                          np.dtype(aval.dtype).itemsize)
        self.wire = None
        self._enc = None
        self._dec = None
        self._land_q = None
        self._land_s = None

    @property
    def wire_nbytes(self) -> int:
        return wire_bytes(self.shape, np.dtype(self.dtype).itemsize,
                          self.mode)

    def _landing_shardings(self):
        """Destination-mesh landing layout for (q, scales): shard the
        block axis over one destination mesh axis when it divides
        evenly, else land replicated (the decode jit re-lays anyway)."""
        from jax.sharding import NamedSharding, PartitionSpec
        if self._land_q is not None:
            return self._land_q, self._land_s
        mesh = self.dst_sharding.mesh
        n = int(np.prod(self.shape, dtype=np.int64)) if self.shape else 1
        nb = -(-n // BLOCK)
        axis = None
        for name, size in dict(mesh.shape).items():
            if size > 1 and nb % size == 0:
                axis = name
                break
        spec = PartitionSpec(axis) if axis else PartitionSpec()
        self._land_q = NamedSharding(mesh, spec)
        self._land_s = NamedSharding(mesh, spec)
        return self._land_q, self._land_s

    def __call__(self, val):
        if _ttrace.enabled():
            with _ttrace.get_recorder().span(
                    "reshard.edge", "resharding",
                    {"bytes": self.wire_nbytes, "codec": self.mode}):
                return self._transfer(val)
        return self._transfer(val)

    def _transfer(self, val):
        import jax
        if self._enc is None:
            self._enc = jax.jit(lambda x: encode(x, self.mode))
            self._dec = jax.jit(
                lambda q, s: decode(q, s, self.shape, self.dtype,
                                    self.mode),
                out_shardings=self.dst_sharding)
        q, scale = self._enc(val)
        land_q, land_s = self._landing_shardings()
        q = jax.device_put(q, land_q)
        scale = jax.device_put(scale, land_s)
        _apply = _sync()
        _apply((q, scale), wire=self.wire)
        out = self._dec(q, scale)
        _Q_EDGES.labels(self.mode).inc()
        _Q_BYTES_SAVED.inc(max(0, self.nbytes - self.wire_nbytes))
        return out


def _sync():
    from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
        _apply_sync_semantics)
    return _apply_sync_semantics


def maybe_quantized_transfer(aval, src_sharding, dst_sharding,
                             mode: str) -> Optional[QuantizedTransfer]:
    """A :class:`QuantizedTransfer` when the edge is eligible under
    ``mode``, else None (the caller falls back to a lossless path)."""
    try:
        if not eligible(aval, mode):
            return None
        t = QuantizedTransfer(aval, src_sharding, dst_sharding, mode)
        # busiest-link wire stats for the narrow payload: the "link"
        # model charges the quantized edge only its reduced bytes
        wb = t.wire_nbytes
        ndst = max(1, len(dst_sharding.mesh.devices.flat))
        t.wire = (1, float(wb) / ndst)
        return t
    except Exception:  # pylint: disable=broad-except
        logger.warning("quantized transfer setup failed; falling back",
                       exc_info=True)
        return None


# --------------------------------------------------------------------------
# Gradient codec (ISSUE 19): stochastic rounding + error feedback for
# quantized gradient collectives in DP/ZeRO training.
# --------------------------------------------------------------------------

#: Codec modes the gradient path accepts (`global_config.grad_quantize`).
GRAD_MODES = ("int8", "fp8")

# Smallest *normal* fp32 the per-block scale is clamped to.  XLA CPU
# flushes subnormals to zero (FTZ), so a subnormal ``amax / wire_max``
# would read as 0 and the unclamped division would produce inf.  A
# normal-range floor survives FTZ: blocks whose max magnitude is below
# ``wire_max * _SCALE_FLOOR`` degrade from the relative ERROR_BOUND to
# an *absolute* error of one floor step (~1.18e-38 — far below any
# gradient signal), and all-zero blocks stay bit-exact.
_SCALE_FLOOR = np.float32(1.1754944e-38)

_GQ_TENSORS = _REG.counter(
    "alpa_grad_quantized_tensors_total",
    "Gradient tensors the plan routed through the quantized "
    "gradient-collective codec",
    labelnames=("codec",))
_GQ_BYTES_SAVED = _REG.counter(
    "alpa_grad_quantized_bytes_saved_total",
    "Gradient-sync wire bytes saved by the quantized codec vs "
    "full-precision collectives")
_GQ_EF_NORM = _REG.gauge(
    "alpa_grad_error_feedback_norm",
    "L2 norm of the most recent per-replica error-feedback residual "
    "carried into the next step's gradient quantization")


def note_grad_quantized(codec: str, full_bytes: int,
                        wire_nbytes: int) -> None:
    """Record one gradient tensor routed through the codec (called at
    plan time — the byte math is static, so counting happens where the
    ILP makes the choice, not inside the jitted step)."""
    _GQ_TENSORS.labels(codec).inc()
    _GQ_BYTES_SAVED.inc(max(0, int(full_bytes) - int(wire_nbytes)))


def note_error_feedback_norm(value: float) -> None:
    """Export the residual-buffer L2 norm (host-side, set by the bench
    and tests after pulling the residual off the device)."""
    _GQ_EF_NORM.set(float(value))


def encode_stochastic(x, mode: str, key):
    """Blockwise quantization with *stochastic rounding*: same layout as
    :func:`encode` (``(q, scales)``, one fp32 scale per 256-element
    block) but each element rounds up with probability equal to its
    fractional distance, so the expectation is exact —
    ``E[decode(encode_stochastic(x))] = x``.

    * ``int8`` — ``lo = floor(x/scale)``; round up when ``u < frac``.
      Worst-case element error is one full step ``scale =
      amax_block/127`` (``ERROR_BOUND["grad_int8"]``).
    * ``fp8`` — rounds onto the exact ``float8_e4m3fn`` grid: step is
      ``2^(floor(log2 |q|) - 3)`` (3 mantissa bits), ``2^-9`` in the
      subnormal range below ``2^-6``.  Worst step at the top of the
      range is ``32`` of ``448`` → ``ERROR_BOUND["grad_fp8"]``.
    """
    import jax
    import jax.numpy as jnp
    if mode not in GRAD_MODES:
        raise ValueError(f"unknown gradient codec mode: {mode!r}")
    n = int(np.prod(x.shape, dtype=np.int64)) if x.ndim else 1
    nb = -(-n // BLOCK)
    flat = jnp.ravel(x).astype(jnp.float32)
    flat = jnp.pad(flat, (0, nb * BLOCK - n))
    blocks = flat.reshape(nb, BLOCK)
    amax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scale = jnp.where(
        amax > 0,
        jnp.maximum(amax / _wire_max(mode), _SCALE_FLOOR),
        1.0).astype(jnp.float32)
    q = blocks / scale
    u = jax.random.uniform(key, blocks.shape, dtype=jnp.float32)
    if mode == "int8":
        lo = jnp.floor(q)
        q = lo + (u < (q - lo)).astype(jnp.float32)
        q = jnp.clip(q, -127.0, 127.0)
    else:
        a = jnp.abs(q)
        e = jnp.floor(jnp.log2(jnp.maximum(a, 2.0 ** -6)))
        step = jnp.where(a < 2.0 ** -6, 2.0 ** -9, jnp.exp2(e - 3.0))
        lo = jnp.floor(q / step) * step
        q = lo + jnp.where(u < (q - lo) / step, step, 0.0)
        q = jnp.clip(q, -_wire_max(mode), _wire_max(mode))
    return q.astype(_wire_dtype(mode)), scale


def grad_compress(g, mode: str, key, residual=None):
    """One error-feedback quantization of a gradient tensor.

    Adds the carried ``residual`` (what previous steps failed to
    transmit), stochastically quantize-dequantizes through the wire
    dtype, and returns ``(g_hat, new_residual)`` where ``new_residual =
    (g + residual) - g_hat`` is carried into the *next* step's call.
    With the residual threaded, the cumulative error of the transmitted
    sum over any window stays bounded by the single-shot
    ``ERROR_BOUND[f"grad_{mode}"]`` — the amortization rule
    :func:`grad_error_bound` gives the numerics certifier.
    """
    import jax.numpy as jnp
    x = g if residual is None else g + residual.astype(g.dtype)
    q, scale = encode_stochastic(x, mode, key)
    g_hat = decode(q, scale, tuple(x.shape), x.dtype, mode)
    new_residual = (x.astype(jnp.float32) -
                    g_hat.astype(jnp.float32)).astype(x.dtype)
    return g_hat, new_residual


def grad_reduce_scatter(grads, mode: str, key, residuals=None):
    """Quantize → partial-reduce → requantize composition for the ZeRO
    reduce-scatter path (emulated replica-by-replica, the same way the
    repo's wire model emulates collectives).

    Each replica's gradient goes through one :func:`grad_compress` hop
    (its residual feeds back locally); the reducer averages the decoded
    contributions and *requantizes* the partial sum for the scatter
    hop.  Two stochastic hops total — the ``grad_*_rs``
    :data:`ERROR_BOUND` entries document the composed bound.  Returns
    ``(mean_gradient, new_residuals)``.
    """
    import jax
    import jax.numpy as jnp
    n = len(grads)
    keys = jax.random.split(key, n + 1)
    hats, new_res = [], []
    for i, g in enumerate(grads):
        r = None if residuals is None else residuals[i]
        h, nr = grad_compress(g, mode, keys[i], r)
        hats.append(h)
        new_res.append(nr)
    partial = hats[0].astype(jnp.float32)
    for h in hats[1:]:
        partial = partial + h.astype(jnp.float32)
    partial = (partial / n).astype(grads[0].dtype)
    q, scale = encode_stochastic(partial, mode, keys[n])
    out = decode(q, scale, tuple(partial.shape), partial.dtype, mode)
    return out, new_res


def grad_error_bound(mode: str, reduce_scatter: bool = False,
                     error_feedback: bool = True, hops: int = 1) -> float:
    """Composed relative error bound for a quantized gradient sync.

    ``reduce_scatter`` selects the two-hop ``grad_*_rs`` entry.  With
    error feedback the residual carries untransmitted mass forward, so
    the cumulative bound over any number of accumulation hops equals
    the single-shot bound; without it the worst case is additive in
    ``hops`` (one per microbatch quantization).
    """
    bkey = f"grad_{mode}" + ("_rs" if reduce_scatter else "")
    per_hop = ERROR_BOUND[bkey]
    if error_feedback:
        return per_hop
    return per_hop * max(1, int(hops))


def grad_wire_bytes(shape, itemsize: int, mode: str) -> int:
    """Wire bytes for one gradient tensor under the codec (same layout
    as the activation codec: 1 byte/element + one fp32 scale per
    block)."""
    return wire_bytes(shape, itemsize, mode)


def grad_eligible(shape, dtype, mode: str,
                  min_bytes: Optional[int] = None) -> bool:
    """Whether one gradient tensor may go through the gradient codec
    under ``global_config.grad_quantize`` /
    ``grad_quantize_min_bytes``."""
    if mode not in GRAD_MODES:
        return False
    if mode == "fp8" and not have_fp8():
        return False
    if str(np.dtype(dtype)) not in _ELIGIBLE_DTYPES:
        return False
    n = int(np.prod(tuple(shape), dtype=np.int64)) if shape else 1
    nbytes = n * np.dtype(dtype).itemsize
    if min_bytes is None:
        from alpa_tpu.global_env import global_config
        min_bytes = getattr(global_config, "grad_quantize_min_bytes",
                            65536)
    return nbytes >= int(min_bytes)
