"""Pipeline instruction emitter.

Analog of ref ``alpa/pipeline_parallel/runtime_emitter.py`` (SURVEY.md
§2.4): walk the schedule tick by tick and compile it into a static
instruction list.  Single-controller simplifications vs the reference:

* ``SEND``/``RECV``/``BROADCAST`` collapse into one ``RESHARD`` instruction
  executed as ``jax.device_put`` (the jax runtime moves data between meshes
  over ICI/DCN; ref cross_mesh_resharding's NCCL P2P machinery becomes the
  runtime's transfer engine).
* There is one global instruction stream instead of per-host worker
  streams; cross-mesh overlap is explicit (ISSUE 4): the lowering builds
  an instruction-level dataflow graph over register slots and the
  ``overlap`` dispatch mode replays it with cross-mesh RESHARDs launched
  eagerly on a transfer pool the moment their producers retire, bounded
  by an in-flight window (jax's async dispatch remains the fallback
  overlap story for the interpreter modes).
* ``FREE`` is emitted from liveness analysis like the reference
  (``_compile_free``, ref runtime_emitter.py:1087) and drops env references
  so buffers are reclaimed promptly.

Value identity: (var, instance) where instance = microbatch index for
per-microbatch values and -1 for microbatch-invariant ones (params, grad
accumulators, apply-grad results).
"""
import dataclasses
import enum
import heapq
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from jax.extend.core import Var

from alpa_tpu import fault as _fault
from alpa_tpu.global_env import global_config
from alpa_tpu.telemetry import flight as _flight
from alpa_tpu.telemetry import metrics as _tmetrics
from alpa_tpu.telemetry import trace as _ttrace

logger = logging.getLogger(__name__)


class PipelineInstType(enum.IntEnum):
    """(ref runtime_emitter.py:31)"""
    RUN = 0
    RESHARD = 1
    FREE = 2


@dataclasses.dataclass
class PipelineInstruction:
    """(ref runtime_emitter.py:47)"""
    opcode: PipelineInstType
    # RUN
    stage_id: Optional[int] = None
    micro_batch: Optional[int] = None
    input_keys: Optional[List[Tuple[int, int]]] = None   # (var_id, inst)
    output_keys: Optional[List[Tuple[int, int]]] = None
    # RESHARD
    var_key: Optional[Tuple[int, int]] = None
    src_mesh: Optional[int] = None
    dst_mesh: Optional[int] = None
    dst_sharding: Any = None
    # the source-side sharding the plan was built against — kept so a
    # profile-guided replan (ISSUE 12) can re-price and re-plan the edge
    # without re-deriving the emitter's sharding environment
    src_sharding: Any = None
    # tile-level transfer plan (cross_mesh_resharding.ReshardingTaskSpec)
    plan: Any = None
    # cached executor for planned execution mode
    task: Any = None
    # FREE
    free_keys: Optional[List[Tuple[int, int, int]]] = None  # (var,inst,mesh)
    info: str = ""

    def __repr__(self):
        if self.opcode == PipelineInstType.RUN:
            return (f"RUN(stage={self.stage_id}, mb={self.micro_batch})")
        if self.opcode == PipelineInstType.RESHARD:
            return (f"RESHARD({self.var_key}, {self.src_mesh}->"
                    f"{self.dst_mesh})")
        return f"FREE({len(self.free_keys)})"


@dataclasses.dataclass
class PlacementSpecEntry:
    """Where a global input lives: list of (mesh_id, sharding)."""
    mesh_ids: List[int]
    shardings: List[Any]
    is_batch: bool = False


@dataclasses.dataclass
class PipeshardConfig:
    """The full compiled artifact (ref runtime_emitter.py:228)."""
    instructions: List[PipelineInstruction]
    # global invar index -> placement
    input_placements: List[PlacementSpecEntry]
    # accumulator allocations: (var_id, mesh_id, aval, sharding)
    acc_allocs: List[Tuple[int, int, Any, Any]]
    # flat output -> (var_id, inst, mesh_id)
    output_specs: List[Tuple[int, int, int]]
    num_micro_batches: int
    num_meshes: int
    var_ids: Dict[Var, int]
    # (var_id, inst) -> producing mesh (for debugging)
    schedule_text: str = ""


@dataclasses.dataclass
class InstructionStreams:
    """Per-mesh instruction streams with cross-stream dependencies — the
    single-controller analog of the reference's pre-pushed per-worker
    instruction lists (ref runtime_emitter.py:258 PipelineInstEmitter ->
    per-worker lists; pipeshard_executable.py:489 execute_on_worker).

    ``streams[m]`` is the ordered list of global instruction indices mesh
    ``m``'s worker executes; ``deps[i]`` is the set of global indices in
    OTHER streams instruction ``i`` must wait for.  Dependencies cover
    read-after-write (a consumer waits for its producer), plus
    write/kill-after-read anti-dependencies (donating or freeing a buffer
    waits for every earlier reader) — all edges point to earlier global
    indices, so stream workers that execute in-stream in order can never
    deadlock.
    """
    streams: List[List[int]]
    deps: Dict[int, set]
    stream_of: Dict[int, int]
    # per-(src, dst)-mesh FIFO channel metadata: edge -> the cross-mesh
    # RESHARD indices that travel it, in emission (= send) order.  The
    # ISSUE-13 model checker binds its SEND/RECV micro-ops to these
    # channels (carried on PlanModel.channels).
    channels: Dict[Tuple[int, int], List[int]] = dataclasses.field(
        default_factory=dict)


def instructions_independent(a, b) -> bool:
    """True when two instructions commute: no value key is touched by
    both with at least one side writing/killing it.  The model checker
    (and any reordering optimization) may swap independent ops without
    changing program meaning."""
    acc_a = instruction_accesses(a)
    acc_b = instruction_accesses(b)
    keys_b: Dict[Tuple[int, int, int], str] = {}
    for key, kind in acc_b:
        if keys_b.get(key) != "write" and keys_b.get(key) != "kill":
            keys_b[key] = kind
    for key, kind in acc_a:
        other = keys_b.get(key)
        if other is None:
            continue
        if kind != "read" or other != "read":
            return False
    return True


def instruction_accesses(inst) -> List[Tuple[Tuple[int, int, int], str]]:
    """The (value key, access kind) pairs one instruction touches —
    kind "read" | "write" | "kill" (donation or FREE).  Shared by the
    stream partitioner (dependency edges) and the dispatch race checker
    (runtime conflict detection)."""
    acc = []
    if inst.opcode == PipelineInstType.RUN:
        ex = getattr(inst, "executable", None)
        donated = set(getattr(ex, "donate_idx", ()) or ())
        for pos, k in enumerate(inst.input_keys):
            kind = "kill" if pos in donated else "read"
            acc.append(((k[0], k[1], inst.dst_mesh), kind))
        for k in inst.output_keys:
            acc.append(((k[0], k[1], inst.dst_mesh), "write"))
    elif inst.opcode == PipelineInstType.RESHARD:
        acc.append(
            ((inst.var_key[0], inst.var_key[1], inst.src_mesh), "read"))
        acc.append(
            ((inst.var_key[0], inst.var_key[1], inst.dst_mesh), "write"))
    else:  # FREE
        for key in inst.free_keys:
            acc.append((tuple(key), "kill"))
    return acc


def partition_streams(instructions: List[PipelineInstruction],
                      num_meshes: int) -> InstructionStreams:
    """Split the global instruction list into per-mesh streams.

    Assignment: RUN executes on its ``dst_mesh``; RESHARD on its
    ``dst_mesh`` (the destination initiates the pull, matching the jax
    transfer model); FREE follows the stream of the preceding
    instruction — its last user, since emit_free_instructions places
    each FREE immediately after the last use (stream 0 if the list
    starts with a FREE).
    """
    streams: List[List[int]] = [[] for _ in range(num_meshes)]
    stream_of: Dict[int, int] = {}
    deps: Dict[int, set] = {}
    channels: Dict[Tuple[int, int], List[int]] = {}
    # key -> ordered access history: (global_idx, stream, kind)
    history: Dict[Tuple[int, int, int], List[Tuple[int, int, str]]] = {}

    prev_stream = 0
    for i, inst in enumerate(instructions):
        if inst.opcode == PipelineInstType.RUN:
            m = inst.dst_mesh
        elif inst.opcode == PipelineInstType.RESHARD:
            m = inst.dst_mesh
            if inst.src_mesh != inst.dst_mesh:
                channels.setdefault(
                    (inst.src_mesh, inst.dst_mesh), []).append(i)
        else:
            m = prev_stream
        m = m if 0 <= m < num_meshes else 0
        streams[m].append(i)
        stream_of[i] = m
        prev_stream = m

        d = set()
        for key, kind in instruction_accesses(inst):
            hist = history.setdefault(key, [])
            if kind == "read":
                # wait for the latest write from another stream
                for j, sm, k in reversed(hist):
                    if k in ("write", "kill"):
                        if sm != m:
                            d.add(j)
                        break
            else:  # write or kill: wait for every earlier access
                for j, sm, k in hist:
                    if sm != m:
                        d.add(j)
            hist.append((i, m, kind))
        if d:
            deps[i] = d
    return InstructionStreams(streams=streams, deps=deps,
                              stream_of=stream_of, channels=channels)


class DispatchRaceChecker:
    """Runtime race detector for threaded per-mesh dispatch (SURVEY §5
    race detection — a capability the reference does not have).

    With ``global_config.debug_dispatch_races`` on, every worker reports
    its instruction's value accesses before executing and withdraws them
    after.  Two accesses CONFLICT when they touch the same (var,
    microbatch, mesh) key from different streams and at least one is a
    write or kill (donation/FREE).  A conflict observed live means the
    partitioner's dependency edges failed to serialize the pair — the
    exact bug class that would otherwise surface as silent numeric
    corruption or a use-after-donate crash far from its cause.
    """

    def __init__(self, instructions, stream_of):
        import threading
        self._stream_of = stream_of
        # instructions and streams are fixed for the executable's
        # lifetime: extract every access list once, not per step
        self._accs = [instruction_accesses(i) for i in instructions]
        self._lock = threading.Lock()
        # key -> {idx: kind} of instructions currently executing
        self._active: Dict[Tuple, Dict[int, str]] = {}
        self.violations: List[str] = []

    @staticmethod
    def _conflict(a: str, b: str) -> bool:
        return a != "read" or b != "read"

    def begin(self, idx: int):
        accs = self._accs[idx]
        me = self._stream_of[idx]
        with self._lock:
            for key, kind in accs:
                holders = self._active.setdefault(key, {})
                for other, okind in holders.items():
                    if self._stream_of[other] != me and \
                            self._conflict(kind, okind):
                        self.violations.append(
                            f"inst {idx} ({kind} {key}) raced inst "
                            f"{other} ({okind}) across streams "
                            f"{me}/{self._stream_of[other]}")
                holders[idx] = kind
        return accs

    def end(self, idx: int, accs):
        with self._lock:
            for key, _ in accs:
                holders = self._active.get(key)
                if holders is not None:
                    holders.pop(idx, None)
                    if not holders:
                        self._active.pop(key, None)

    def reset(self):
        """Clear violations AND in-flight accesses (an aborted launch can
        leave registrations behind); call at the start of every launch."""
        with self._lock:
            self._active = {}
            self.violations = []

    def check(self):
        if self.violations:
            raise RuntimeError(
                "threaded dispatch raced (stream dependency edges failed "
                "to serialize conflicting accesses):\n  " +
                "\n  ".join(self.violations[:10]))


########################################
# instruction dataflow graph (ISSUE 4 tentpole)
########################################


@dataclasses.dataclass
class DataflowNode:
    """One lowered instruction's register-slot footprint."""
    idx: int                            # flat (emitted) instruction index
    kind: str                           # "RUN" | "RESHARD" | "FREE"
    reads: Tuple[int, ...] = ()
    writes: Tuple[int, ...] = ()
    kills: Tuple[int, ...] = ()         # donation / FREE targets
    edge: Optional[Tuple[int, int]] = None  # RESHARD (src_mesh, dst_mesh)
    cross_mesh: bool = False
    info: str = ""


@dataclasses.dataclass
class InstructionDataflowGraph:
    """Explicit producer/consumer edges over register slots, built at
    lowering time for every instruction (ISSUE 4).

    Edge kinds:

    * RAW — a reader depends on the last writer of every slot it reads.
    * WAW/WAR and kill — a writer or killer depends on the previous
      writer AND on every reader since.  Donation (``donate_argnums`` on
      accumulator inputs) and FREE are kills: they invalidate the buffer,
      so an eagerly launched transfer reading the slot must retire before
      the donating RUN or the FREE executes.

    Every edge points to an earlier flat index, so any replay that
    respects ``preds`` is deadlock-free by construction.  The ``overlap``
    dispatch mode replays this graph instead of the flat list; the fuzz
    test in tests/runtime/test_overlap_dispatch.py drives randomized
    topologies through :func:`schedule_overlap` and checks the replay
    invariants directly.
    """
    nodes: List[DataflowNode]
    preds: List[Tuple[int, ...]]
    succs: List[Tuple[int, ...]]

    @classmethod
    def build(cls, nodes: Sequence[DataflowNode]
              ) -> "InstructionDataflowGraph":
        last_writer: Dict[int, int] = {}
        readers_since: Dict[int, List[int]] = {}
        preds: List[set] = [set() for _ in nodes]
        for node in nodes:
            i = node.idx
            for s in node.reads:
                w = last_writer.get(s)
                if w is not None and w != i:
                    preds[i].add(w)
                readers_since.setdefault(s, []).append(i)
            for s in tuple(node.writes) + tuple(node.kills):
                w = last_writer.get(s)
                if w is not None and w != i:
                    preds[i].add(w)
                for r in readers_since.get(s, ()):
                    if r != i:
                        preds[i].add(r)
                readers_since[s] = []
                last_writer[s] = i
        succs: List[set] = [set() for _ in nodes]
        for i, ps in enumerate(preds):
            for p in ps:
                succs[p].add(i)
        return cls(list(nodes),
                   [tuple(sorted(p)) for p in preds],
                   [tuple(sorted(s)) for s in succs])

    @property
    def n_cross_mesh(self) -> int:
        return sum(1 for n_ in self.nodes if n_.cross_mesh)

    def check(self) -> None:
        """Static lowering-time hazard pass (ISSUE 6): independently
        re-derive every slot hazard with a forward walk and assert the
        graph's ``preds`` cover it.  Runs on EVERY compile (called by
        :func:`lower_to_register_file`), not just under debug — it is
        O(edges) over an in-memory list, so the cost is lowering noise.

        Catches the bug class where an edit to :meth:`build`, the
        lowering, or a hand-constructed graph drops a dependency edge:
        a reader without an edge to its slot's last writer (RAW), a
        writer/killer without edges to the previous writer (WAW) or to
        readers since (write-after-read on a live slot), a FREE/kill of
        a cross-mesh transfer destination with no edge to the transfer
        (the in-flight-FREE hazard the overlap replay relies on), a
        forward-pointing edge (deadlock risk), or a node list whose
        positions disagree with node indices.

        Also validates RESHARD node structure (ISSUE 8): every RESHARD
        node must carry its mesh edge, read exactly one slot and write
        exactly one slot (grouped/coalesced transfers batch at the OP
        level — each dataflow node keeps its single-edge footprint, so
        the plan verifier can reconstruct group footprints as member
        unions), and its ``cross_mesh`` flag must agree with the edge.
        """
        nodes = self.nodes
        problems: List[str] = []
        for i, node in enumerate(nodes):
            if node.idx != i:
                problems.append(
                    f"node at position {i} carries idx {node.idx}")
            if node.kind == "RESHARD":
                if node.edge is None:
                    problems.append(
                        f"RESHARD node {i} carries no mesh edge")
                elif node.cross_mesh != (node.edge[0] != node.edge[1]):
                    problems.append(
                        f"RESHARD node {i} cross_mesh={node.cross_mesh}"
                        f" disagrees with edge {node.edge}")
                if len(node.reads) != 1 or len(node.writes) != 1:
                    problems.append(
                        f"RESHARD node {i} must read/write exactly one "
                        f"slot each, has reads={node.reads} "
                        f"writes={node.writes}")
        last_writer: Dict[int, int] = {}
        readers_since: Dict[int, List[int]] = {}
        for node in nodes:
            if len(problems) > 20:
                break
            i = node.idx
            preds = set(self.preds[i]) if i < len(self.preds) else set()
            for p in preds:
                if p >= i:
                    problems.append(
                        f"node {i} ({node.kind}) has a non-backward "
                        f"edge to node {p}")
            for s in node.reads:
                w = last_writer.get(s)
                if w is not None and w != i and w not in preds:
                    problems.append(
                        f"RAW hazard: node {i} ({node.kind}) reads slot "
                        f"{s} with no edge to its writer, node {w}")
                readers_since.setdefault(s, []).append(i)
            for s in tuple(node.writes) + tuple(node.kills):
                kill = s in node.kills
                verb = "kills" if kill else "writes"
                w = last_writer.get(s)
                if w is not None and w != i and w not in preds:
                    if kill and nodes[w].cross_mesh:
                        problems.append(
                            f"FREE of an in-flight transfer destination:"
                            f" node {i} ({node.kind}) kills slot {s} "
                            f"with no edge to cross-mesh transfer node "
                            f"{w}")
                    else:
                        problems.append(
                            f"WAW hazard: node {i} ({node.kind}) {verb} "
                            f"slot {s} with no edge to its previous "
                            f"writer, node {w}")
                for r in readers_since.get(s, ()):
                    if r != i and r not in preds:
                        problems.append(
                            f"write-after-read on a live slot: node {i} "
                            f"({node.kind}) {verb} slot {s} with no "
                            f"edge to its reader, node {r}")
                readers_since[s] = []
                last_writer[s] = i
        if problems:
            raise RuntimeError(
                "instruction dataflow graph failed the static hazard "
                "check (a dependency edge is missing or malformed):\n  "
                + "\n  ".join(problems[:20]))


def schedule_overlap(graph: InstructionDataflowGraph, window: int
                     ) -> Tuple[List[Tuple[str, int]], int]:
    """Greedy overlap schedule: replay the dataflow graph with cross-mesh
    RESHARDs hoisted and launched eagerly the moment their producers
    retire, bounded by an in-flight-transfer ``window`` (caps host/staging
    memory: at most ``window`` launched-but-unwaited transfers exist).

    Returns ``(plan, n_hoisted)`` where ``plan`` is a list of
    ``("exec" | "launch" | "wait", node_idx)`` issue steps and
    ``n_hoisted`` counts transfers launched before their flat position.

    Invariants (held by construction, asserted by the fuzz test):

    * every node appears exactly once as exec or launch, and every
      launch has exactly one later wait;
    * a node issues only after ALL its graph predecessors retired
      (exec'd, or waited for transfers) — no op reads a slot before its
      producer transfer lands, and no donation/FREE fires while a
      transfer still uses the slot;
    * non-transfer ops keep their flat relative order, so the schedule
      is the flat order with transfers slid earlier (launch) and their
      completion points slid as late as the first dependent allows;
    * at most ``window`` transfers are in flight at any step.
    """
    nodes = graph.nodes
    n = len(nodes)
    window = max(1, int(window))
    unmet = [len(graph.preds[i]) for i in range(n)]
    issued = [False] * n
    retired = [False] * n
    inflight: List[int] = []            # launch order (FIFO)
    ready: List[int] = []               # min-heap of launchable transfers
    plan: List[Tuple[str, int]] = []
    n_hoisted = 0

    def retire(i):
        retired[i] = True
        for s in graph.succs[i]:
            unmet[s] -= 1
            if unmet[s] == 0 and nodes[s].cross_mesh and not issued[s]:
                heapq.heappush(ready, s)

    def wait(i):
        plan.append(("wait", i))
        inflight.remove(i)
        retire(i)

    def launch(i, cur):
        nonlocal n_hoisted
        plan.append(("launch", i))
        issued[i] = True
        inflight.append(i)
        if i > cur:
            n_hoisted += 1

    def pump(cur):
        while ready and len(inflight) < window:
            i = heapq.heappop(ready)
            if not issued[i]:
                launch(i, cur)

    for i in range(n):
        if unmet[i] == 0 and nodes[i].cross_mesh:
            heapq.heappush(ready, i)
    pump(-1)

    for cur in range(n):
        node = nodes[cur]
        if node.cross_mesh:
            if not issued[cur]:
                # make room, then settle any in-flight transfer this one
                # chains on (e.g. multi-hop reshard of the same value)
                while len(inflight) >= window:
                    wait(inflight[0])
                for p in graph.preds[cur]:
                    if not retired[p]:
                        wait(p)
                launch(cur, cur)
            pump(cur)
            continue
        # non-transfer op: settle exactly the transfers it depends on
        for p in graph.preds[cur]:
            if not retired[p]:
                wait(p)
        plan.append(("exec", cur))
        issued[cur] = True
        retire(cur)
        pump(cur)
    while inflight:
        wait(inflight[0])
    return plan, n_hoisted


########################################
# register-file lowering (replay fast path)
########################################


def _equiv_shardings(s1, s2, ndim) -> bool:
    if s1 is None or s2 is None:
        return True
    try:
        return s1.is_equivalent_to(s2, ndim)
    except Exception:  # pylint: disable=broad-except
        return s1 == s2


########################################
# per-node hook points (ISSUE 6 tentpole)
########################################


@dataclasses.dataclass(frozen=True)
class OpHook:
    """One op's hook point, compiled into the replay plan at lowering
    time (ISSUE 6).  The hook is pure metadata: which dataflow node the
    op replays, its slot footprint, and which fault site the
    interpreter would have fired for it.  At execute time, when any
    instrumentation is active, :meth:`RegisterFileProgram.execute`
    compiles a wrapped op list from these — tracing spans, flight
    recorder events, slot-hazard assertions, fault-site checks — and
    replays that; with everything off the raw closures run with zero
    added branches.

    A batched group op carries the union slot footprint and one fault
    info dict per member, so FaultSpec hit counts match the
    interpreter's per-instruction fires exactly.
    """
    kind: str                             # "exec" | "launch" | "wait"
    name: str                             # span/event label
    node: int                             # dataflow node idx (group: first)
    mesh: int
    reads: Tuple[int, ...] = ()
    writes: Tuple[int, ...] = ()
    kills: Tuple[int, ...] = ()
    slots: Tuple[int, ...] = ()           # union footprint (flight events)
    fault_site: Optional[str] = None      # fault.py site name
    fault_infos: Tuple[Any, ...] = ()     # one info dict per member
    idempotent: bool = True               # retry semantics (donation)
    # RUN eqn-classification facts (ISSUE 14 numerics certification):
    # matmul/reduce/cast counts + narrowest accumulation dtype
    precision: Optional[Any] = None
    # RUN stage-decomposition facts (ISSUE 15 translation validation):
    # {"stage": sig, "mb": int, "donate": [pos...], "acc": {out: in}}
    equiv: Optional[Any] = None
    # flat instruction indices this op replays: (idx,) for singletons,
    # every folded member for batched groups — the plan verifier
    # (ISSUE 8) checks the footprint above equals the union of the
    # members' dataflow-node footprints
    members: Tuple[int, ...] = ()


class SlotHazardChecker:
    """Graph-node flavor of the dispatch race checker (ISSUE 6): the
    threaded interpreter's :class:`DispatchRaceChecker` validates
    cross-stream value accesses; this validates the register/overlap
    replay's slot accesses against in-flight transfers at replay time.

    The overlap schedule promises that between a transfer's launch and
    its wait, nothing reads the destination slot, writes either
    endpoint slot, or donates/frees them.  With
    ``global_config.debug_dispatch_races`` on, every op's hook reports
    its footprint here; a violation means the dataflow graph or the
    scheduler failed to serialize the pair — the bug class that would
    otherwise surface as a torn read of a ``_PendingTransfer`` or a
    use-after-free far from its cause.  Driver-thread only (hooks run
    on the dispatch thread), so no lock is needed.
    """

    def __init__(self):
        self._inflight_src: Dict[int, int] = {}   # slot -> launch node
        self._inflight_dst: Dict[int, int] = {}
        self.violations: List[str] = []

    def begin_step(self):
        self._inflight_src.clear()
        self._inflight_dst.clear()
        self.violations = []

    def on_launch(self, hook: OpHook):
        for s in hook.reads:
            self._inflight_src[s] = hook.node
        for s in hook.writes:
            self._inflight_dst[s] = hook.node

    def on_wait(self, hook: OpHook):
        for s in hook.reads:
            self._inflight_src.pop(s, None)
        for s in hook.writes:
            self._inflight_dst.pop(s, None)

    def on_exec(self, hook: OpHook):
        for s in hook.reads:
            n = self._inflight_dst.get(s)
            if n is not None:
                self.violations.append(
                    f"{hook.name} (node {hook.node}) reads slot {s} "
                    f"still owned by in-flight transfer node {n}")
        for s in hook.writes:
            for owners, role in ((self._inflight_src, "source"),
                                 (self._inflight_dst, "destination")):
                n = owners.get(s)
                if n is not None:
                    self.violations.append(
                        f"{hook.name} (node {hook.node}) writes slot "
                        f"{s}, the {role} of in-flight transfer node "
                        f"{n}")
        for s in hook.kills:
            for owners, role in ((self._inflight_src, "source"),
                                 (self._inflight_dst, "destination")):
                n = owners.get(s)
                if n is not None:
                    self.violations.append(
                        f"{hook.name} (node {hook.node}) frees/donates "
                        f"slot {s}, the {role} of in-flight transfer "
                        f"node {n}")

    def check(self):
        if self.violations:
            raise RuntimeError(
                "register/overlap replay raced an in-flight transfer "
                "(graph schedule failed to serialize slot accesses):"
                "\n  " + "\n  ".join(self.violations[:10]))


def _wrap_fault(op, hook: OpHook):
    """Fault-site hook: fire every member's site before the op, retry
    under the site policy — same semantics (and same FaultSpec hit
    counts) as the interpreter's per-instruction wrapping."""
    def wrapped(regs, _op=op, _site=hook.fault_site,
                _infos=hook.fault_infos, _idem=hook.idempotent):
        def attempt():
            for info in _infos:
                _fault.fire(_site, **info)
            _op(regs)
        _fault.call_with_retry(attempt, site=_site, idempotent=_idem)
    return wrapped


def _wrap_hazard(op, hook: OpHook, checker: SlotHazardChecker):
    if hook.kind == "launch":
        def wrapped(regs, _op=op, _h=hook, _c=checker):
            _c.on_launch(_h)
            _op(regs)
    elif hook.kind == "wait":
        def wrapped(regs, _op=op, _h=hook, _c=checker):
            _op(regs)
            _c.on_wait(_h)
    else:
        def wrapped(regs, _op=op, _h=hook, _c=checker):
            _c.on_exec(_h)
            _op(regs)
    return wrapped


def _wrap_flight(op, hook: OpHook, rec):
    """Flight-recorder hook: one ring event per op, outcome included —
    the op's exception (if any) is re-raised after recording."""
    def wrapped(regs, _op=op, _rec=rec.record, _now=_flight.now_us,
                _k=hook.kind, _n=hook.name, _m=hook.mesh,
                _nd=hook.node, _s=hook.slots):
        t0 = _now()
        try:
            _op(regs)
        except BaseException as e:  # noqa: B036 — record, then re-raise
            _rec(_k, _n, _m, _nd, _s, t0, _now(),
                 f"error:{type(e).__name__}")
            raise
        _rec(_k, _n, _m, _nd, _s, t0, _now(), "ok")
    return wrapped


def _wrap_trace(op, name, cat, track, rec):
    def wrapped(regs, _op=op, _span=rec.span, _n=name, _c=cat, _t=track):
        with _span(_n, _c, None, _t):
            _op(regs)
    return wrapped


@dataclasses.dataclass
class RegisterFileProgram:
    """The instruction list lowered to a flat register file (ISSUE 2).

    Replay becomes ``for op in ops: op(regs)`` over ``regs = [None] *
    num_slots``: every ``(var, microbatch, mesh)`` key was resolved to an
    integer slot at build time, RUN inputs/outputs are precomputed index
    tuples closed over each op, FREE is slot clears, and RESHARD carries a
    pre-built :class:`~alpa_tpu.pipeline_parallel.cross_mesh_resharding.
    DirectTransfer` executor (adjacent same-edge transfers coalesced into
    one batched call) — no dict hashing, no sharding resolution, no
    per-call planning on the hot path.
    """
    num_slots: int
    ops: List[Any]                      # each: fn(regs) -> None
    n_instructions: int                 # original instruction count
    by_opcode: Dict[str, int]           # original counts per opcode
    slot_of: Dict[Tuple[Var, int, int], int]
    n_coalesced_groups: int
    n_fixups: int
    text: str                           # one line per op, for fingerprints
    # --- ISSUE 4: dataflow graph + overlap mode ---
    mode: str = "registers"
    graph: Optional[InstructionDataflowGraph] = None
    n_cross_mesh: int = 0               # cross-mesh RESHARDs in the list
    n_hoisted: int = 0                  # transfers launched before flat pos
    n_launches: int = 0                 # async launch ops (groups count 1)
    n_free_hops: int = 0                # FREEs hopped by extended coalescing
    overlap_window: int = 0             # in-flight window (overlap mode)
    run_stats: Dict[str, float] = dataclasses.field(
        default_factory=lambda: {"transfer_busy_s": 0.0,
                                 "wait_blocked_s": 0.0})
    # telemetry (ISSUE 5): per-op (span name, category, track) built at
    # lowering time; only consulted when tracing is on — the hot replay
    # checks the enabled flag ONCE per step, not per op.
    op_meta: Optional[List[Tuple[str, str, str]]] = None
    # hook points (ISSUE 6): per-op OpHook metadata built at lowering
    # time.  None (synthetic/legacy programs) keeps the pre-hook
    # execute() path byte for byte.
    hooks: Optional[List[OpHook]] = None
    # which hook families ran last step (stats/debugging)
    last_hooks: Tuple[str, ...] = ()
    # static verification verdict (ISSUE 8): attached by
    # lower_to_register_file when global_config.verify_plans != "off";
    # surfaced via dump_debug_info's plan_verdict.txt and
    # PipeshardDriverExecutable.get_plan_verdict()
    verdict: Any = None
    # compiled wrapped-op cache, keyed by the active-hook signature
    _hook_sig: Any = dataclasses.field(default=None, init=False,
                                       repr=False, compare=False)
    _hooked_ops: Optional[List[Any]] = dataclasses.field(
        default=None, init=False, repr=False, compare=False)
    _hazard: Optional[SlotHazardChecker] = dataclasses.field(
        default=None, init=False, repr=False, compare=False)

    def execute(self, regs: List[Any]):
        rs = self.run_stats
        rs["transfer_busy_s"] = 0.0
        rs["wait_blocked_s"] = 0.0
        if self.hooks is not None:
            sig = self._active_hook_signature()
            if sig is not None:
                self._execute_hooked(regs, sig)
                return
            self.last_hooks = ()
        if _ttrace.enabled():
            self._execute_traced(regs)
            return
        for op in self.ops:
            op(regs)

    def _execute_traced(self, regs: List[Any]):
        meta = self.op_meta
        if meta is None or len(meta) != len(self.ops):
            for op in self.ops:
                op(regs)
            return
        rec = _ttrace.get_recorder()
        for op, (name, cat, track) in zip(self.ops, meta):
            with rec.span(name, cat, None, track):
                op(regs)

    # ---- hook compilation (ISSUE 6) ---------------------------------

    def _active_hook_signature(self):
        """One cheap per-step check deciding whether (and how) the op
        list must be replayed hooked.  None = nothing active: the raw
        closures run with zero added branches, preserving the <2%
        disabled-overhead guard.  Recorder identities are part of the
        signature because tests (and trace_tool record) swap recorders
        mid-process via set_recorder."""
        trace_on = _ttrace.enabled() or global_config.collect_trace
        fault_on = _fault.instrumented()
        race_on = global_config.debug_dispatch_races
        flight_on = _flight.enabled()
        if not (trace_on or fault_on or race_on or flight_on):
            return None
        return (trace_on,
                id(_ttrace.get_recorder()) if trace_on else 0,
                fault_on, race_on, flight_on,
                id(_flight.get_recorder()) if flight_on else 0)

    def _execute_hooked(self, regs: List[Any], sig):
        if sig != self._hook_sig:
            self._hooked_ops, self._hazard = self._compile_hooks(sig)
            self._hook_sig = sig
        self.last_hooks = tuple(
            name for on, name in zip(
                (sig[0], sig[2], sig[3], sig[4]),
                ("trace", "fault", "race", "flight")) if on)
        hz = self._hazard
        if hz is not None:
            hz.begin_step()
        for op in self._hooked_ops:
            op(regs)
        if hz is not None:
            hz.check()

    def _compile_hooks(self, sig):
        """Build the wrapped-op list for the active instrumentation.
        Wrapper nesting, outermost first: trace span > flight event >
        hazard check > fault site — so a fault retry re-fires inside
        one span, and the flight event's outcome reflects the final
        (post-retry) result."""
        trace_on, _tid, fault_on, race_on, flight_on, _fid = sig
        hooks = self.hooks
        if hooks is None or len(hooks) != len(self.ops):
            return list(self.ops), None
        trec = _ttrace.get_recorder() if trace_on else None
        frec = _flight.get_recorder() if flight_on else None
        hazard = SlotHazardChecker() if race_on else None
        meta = self.op_meta
        if meta is None or len(meta) != len(self.ops):
            trace_on, meta = False, None
        wrapped: List[Any] = []
        for i, (op, hook) in enumerate(zip(self.ops, hooks)):
            w = op
            if fault_on and hook.fault_site is not None:
                w = _wrap_fault(w, hook)
            if hazard is not None:
                w = _wrap_hazard(w, hook, hazard)
            if flight_on:
                w = _wrap_flight(w, hook, frec)
            if trace_on:
                name, cat, track = meta[i]
                w = _wrap_trace(w, name, cat, track, trec)
            wrapped.append(w)
        return wrapped, hazard

    def fingerprint(self) -> str:
        import hashlib
        return hashlib.sha256(self.text.encode()).hexdigest()


def _make_run_op(compiled, in_slots, out_slots, fixups):
    """RUN as a closure: gather args by slot index, call the compiled
    fast path, scatter outputs.  ``fixups`` carries the (rare) arg
    positions whose statically-tracked layout differs from the stage's
    expected sharding — the register-file analog of the interpreter's
    per-arg safety net, resolved at lowering instead of per call."""
    if fixups:

        def op(regs, _c=compiled, _i=in_slots, _o=out_slots, _f=fixups):
            import jax
            args = [regs[s] for s in _i]
            for pos, sh, ndim in _f:
                a = args[pos]
                if not a.sharding.is_equivalent_to(sh, ndim):
                    args[pos] = jax.device_put(a, sh)
            outs = _c(*args)
            for s, o in zip(_o, outs):
                regs[s] = o
    else:

        def op(regs, _c=compiled, _i=in_slots, _o=out_slots):
            outs = _c(*[regs[s] for s in _i])
            for s, o in zip(_o, outs):
                regs[s] = o

    return op


def _make_reshard_op(transfer, src_slot, dst_slot):
    def op(regs, _t=transfer, _s=src_slot, _d=dst_slot):
        regs[_d] = _t(regs[_s])

    return op


def _make_reshard_group_op(group, src_slots, dst_slots):
    def op(regs, _g=group, _s=src_slots, _d=dst_slots):
        outs = _g([regs[s] for s in _s])
        for d, o in zip(_d, outs):
            regs[d] = o

    return op


def _make_free_op(slots):
    def op(regs, _s=slots):
        for i in _s:
            regs[i] = None

    return op


########################################
# overlap mode: async transfer launch/wait ops (ISSUE 4)
########################################

_TRANSFER_POOL = None
_TRANSFER_POOL_LOCK = threading.Lock()


def _transfer_pool():
    """Process-wide transfer thread pool shared by every overlap-mode
    program (the scheduler's in-flight window — not the pool size — is
    what bounds concurrent transfers and staging memory)."""
    global _TRANSFER_POOL
    if _TRANSFER_POOL is None:
        with _TRANSFER_POOL_LOCK:
            if _TRANSFER_POOL is None:
                from concurrent.futures import ThreadPoolExecutor
                _TRANSFER_POOL = ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="alpa-overlap")
    return _TRANSFER_POOL


class _PendingTransfer:
    """A launched-but-unwaited cross-mesh transfer, parked in its dst
    slot until the matching wait op resolves it.  The dataflow graph
    guarantees nothing reads the slot in between."""
    __slots__ = ("future",)

    def __init__(self, future):
        self.future = future


# launched-but-unretired transfers, exported to the trace as the
# "transfers_in_flight" counter track (only touched when tracing is on)
_INFLIGHT_LOCK = threading.Lock()
_INFLIGHT = 0


def _inflight_delta(d: int):
    global _INFLIGHT
    with _INFLIGHT_LOCK:
        _INFLIGHT += d
        v = _INFLIGHT
    _ttrace.counter("transfers_in_flight", v)


def _record_pool_spans(label, t_sub_us, t_run_us, t_end_us):
    """Pool-side span family for one transfer: the labeled parent covers
    submit→retire, with ``reshard.wait`` (queue time between the driver
    submit and the worker picking it up — scheduler backpressure, not
    network) and ``reshard.wire`` (actual transfer execution) children.
    All timestamps come from ``trace.now_us`` so the driver-side submit
    stamp and the worker-side stamps share one epoch; insertion order
    parent-first keeps B-tie nesting correct in the Chrome export."""
    rec = _ttrace.get_recorder()
    rec.complete(label, "transfer", t_sub_us, t_end_us - t_sub_us)
    rec.complete("reshard.wait", "transfer", t_sub_us,
                 max(0.0, t_run_us - t_sub_us))
    rec.complete("reshard.wire", "transfer", t_run_us,
                 max(0.0, t_end_us - t_run_us))


def _make_launch_op(transfer, src_slot, dst_slot, label="transfer"):
    # regs[src] is captured on the driver thread at launch time, so a
    # later donation/FREE of the src slot (which the schedule orders
    # after this launch's wait anyway) can never race the worker.
    def op(regs, _t=transfer, _s=src_slot, _d=dst_slot, _l=label):
        v = regs[_s]
        traced = _ttrace.enabled()
        t_sub = _ttrace.now_us() if traced else 0.0

        def work(_v=v, _tt=_t, _ll=_l, _traced=traced, _sub=t_sub):
            t_run = _ttrace.now_us() if _traced else 0.0
            t0 = time.perf_counter()
            out = _tt(_v)
            busy = time.perf_counter() - t0
            if _traced:
                _record_pool_spans(_ll, _sub, t_run, _ttrace.now_us())
            return out, busy

        if traced:
            _inflight_delta(1)
        regs[_d] = _PendingTransfer(_transfer_pool().submit(work))

    return op


def _make_wait_op(dst_slot, stats):
    def op(regs, _d=dst_slot, _st=stats):
        p = regs[_d]
        if type(p) is _PendingTransfer:
            t0 = time.perf_counter()
            out, busy = p.future.result()
            _st["wait_blocked_s"] += time.perf_counter() - t0
            _st["transfer_busy_s"] += busy
            regs[_d] = out
            if _ttrace.enabled():
                _inflight_delta(-1)

    return op


def _make_launch_group_op(group, src_slots, dst_slots,
                          label="transfer-group"):
    # The whole batched group travels as one future, parked at the first
    # member's dst slot; the group wait scatters every output.
    def op(regs, _g=group, _s=src_slots, _d=dst_slots, _l=label):
        vals = [regs[s] for s in _s]
        traced = _ttrace.enabled()
        t_sub = _ttrace.now_us() if traced else 0.0

        def work(_v=vals, _gg=_g, _ll=_l, _traced=traced, _sub=t_sub):
            t_run = _ttrace.now_us() if _traced else 0.0
            t0 = time.perf_counter()
            outs = _gg(_v)
            busy = time.perf_counter() - t0
            if _traced:
                _record_pool_spans(_ll, _sub, t_run, _ttrace.now_us())
            return outs, busy

        if traced:
            _inflight_delta(1)
        regs[_d[0]] = _PendingTransfer(_transfer_pool().submit(work))

    return op


def _make_wait_group_op(dst_slots, stats):
    def op(regs, _d=dst_slots, _st=stats):
        p = regs[_d[0]]
        if type(p) is _PendingTransfer:
            t0 = time.perf_counter()
            outs, busy = p.future.result()
            _st["wait_blocked_s"] += time.perf_counter() - t0
            _st["transfer_busy_s"] += busy
            for d, o in zip(_d, outs):
                regs[d] = o
            if _ttrace.enabled():
                _inflight_delta(-1)

    return op


# process-wide overlap runtime counters, kept in the central metrics
# registry (ISSUE 5) and surfaced via monitoring.get_overlap_stats —
# the same series GET /metrics exports as alpa_overlap_*.
_OVERLAP_REG = _tmetrics.get_registry()
_OVERLAP_STEPS = _OVERLAP_REG.counter(
    "alpa_overlap_steps_total", "Overlap-mode pipeshard steps executed")
_OVERLAP_BUSY = _OVERLAP_REG.counter(
    "alpa_overlap_transfer_busy_seconds_total",
    "Accumulated pool-side transfer execution time")
_OVERLAP_BLOCKED = _OVERLAP_REG.counter(
    "alpa_overlap_wait_blocked_seconds_total",
    "Accumulated driver time blocked in transfer waits")
_OVERLAP_HOISTED = _OVERLAP_REG.counter(
    "alpa_overlap_hoisted_total",
    "Cross-mesh transfers launched ahead of flat instruction order")
_OVERLAP_LAUNCHES = _OVERLAP_REG.counter(
    "alpa_overlap_launches_total",
    "Async transfer launches issued (a batched group counts once)")
_OVERLAP_LAST_FRACTION = _OVERLAP_REG.gauge(
    "alpa_overlap_last_overlap_fraction",
    "Last step's 1 - wait_blocked/transfer_busy overlap fraction")
_OVERLAP_LAST_WINDOW = _OVERLAP_REG.gauge(
    "alpa_overlap_last_window",
    "Last step's in-flight transfer window")


def record_overlap_step(stats: Dict[str, Any]) -> None:
    """Fold one overlap-mode step's dispatch stats into the registry
    (called by pipeshard_executable after each launch)."""
    _OVERLAP_STEPS.inc()
    _OVERLAP_BUSY.inc(stats.get("transfer_busy_s", 0.0))
    _OVERLAP_BLOCKED.inc(stats.get("wait_blocked_s", 0.0))
    _OVERLAP_HOISTED.inc(stats.get("n_hoisted", 0))
    _OVERLAP_LAUNCHES.inc(stats.get("n_launches", 0))
    _OVERLAP_LAST_FRACTION.set(stats.get("overlap_fraction", 0.0))
    _OVERLAP_LAST_WINDOW.set(stats.get("overlap_window", 0))


def get_overlap_runtime_stats() -> Dict[str, Any]:
    """Thin view over the registry; dict shape is unchanged from the
    pre-telemetry module-private counters."""
    return {
        "steps": int(_OVERLAP_STEPS.value),
        "transfer_busy_s": _OVERLAP_BUSY.value,
        "wait_blocked_s": _OVERLAP_BLOCKED.value,
        "n_hoisted": int(_OVERLAP_HOISTED.value),
        "n_launches": int(_OVERLAP_LAUNCHES.value),
        "last_overlap_fraction": _OVERLAP_LAST_FRACTION.value,
        "last_window": int(_OVERLAP_LAST_WINDOW.value),
    }


def reset_overlap_runtime_stats() -> None:
    for fam in (_OVERLAP_STEPS, _OVERLAP_BUSY, _OVERLAP_BLOCKED,
                _OVERLAP_HOISTED, _OVERLAP_LAUNCHES,
                _OVERLAP_LAST_FRACTION, _OVERLAP_LAST_WINDOW):
        fam.reset()


def lower_to_register_file(
        instructions: List[PipelineInstruction],
        preplaced_shardings: Dict[Tuple[Var, int, int], Any],
        mode: str = "registers",
        overlap_window: int = 4,
        protected_keys=frozenset(),
        opt_state_keys=frozenset(),
        provenance_keys=None,
        equiv_reference=None,
) -> RegisterFileProgram:
    """Lower the emitted instruction list into a :class:`RegisterFileProgram`.

    ``preplaced_shardings`` seeds the static sharding model with the
    launch-placed values (global inputs, consts, zero accumulators):
    key ``(var, microbatch-instance, mesh)`` -> sharding.  The lowering
    walks the instructions in global order tracking the layout each slot
    holds, so RESHARD executors know their source sharding statically and
    RUN args that would need the interpreter's per-call relayout safety
    net become precomputed fixups.

    Two phases (ISSUE 4).  Phase 1 is mode-independent: slot allocation,
    static sharding propagation, and the per-instruction dataflow graph
    are identical for every ``mode``, so programs lowered from the same
    instruction list share ``slot_of`` and the launch-time slot tables
    can be reused across modes.  Phase 2 emits ops per mode:

    * ``registers`` — flat instruction order, with same-edge RESHARD
      coalescing extended past intervening FREEs (PR 2's pass required
      global adjacency, but FREEs emitted right after a value's last use
      split otherwise-contiguous same-edge runs).  Hopping a FREE is safe
      because FREE always follows its slots' last use — the batched group
      runs first and the FREE is re-emitted right after it; a same-edge
      RESHARD touching a hopped slot ends the group instead of joining.
    * ``overlap`` — replay :func:`schedule_overlap`'s plan: cross-mesh
      RESHARDs become launch/wait pairs over a shared transfer thread
      pool with a bounded in-flight window, and consecutive same-edge
      launches merge into one batched group launch.  Same-mesh relayouts
      and everything else execute synchronously in flat relative order.
    """
    from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
        DirectTransfer, DirectTransferGroup, make_transfer)

    if mode not in ("registers", "overlap"):
        raise ValueError(f"unknown lowering mode: {mode!r}")

    slot_of: Dict[Tuple[Var, int, int], int] = {}

    def slot(key):
        s = slot_of.get(key)
        if s is None:
            s = slot_of[key] = len(slot_of)
        return s

    cur_sharding: Dict[int, Any] = {}
    for key, sh in preplaced_shardings.items():
        cur_sharding[slot(key)] = sh

    # ---- phase 1: slot allocation + sharding propagation (mode-free) ----
    recs: List[Dict[str, Any]] = []
    by_opcode = {"RUN": 0, "RESHARD": 0, "FREE": 0}
    n_fixups = 0

    # numerics certification (ISSUE 14): classify each stage's
    # matmul/reduce/cast population once per executable, only when the
    # verifier will actually consume it (both knobs on)
    want_numerics = (
        getattr(global_config, "verify_plans", "warn") != "off" and
        getattr(global_config, "verify_plans_numerics", "warn") != "off")
    _prec_cache: Dict[int, Any] = {}

    def _precision_of(ex):
        if not want_numerics:
            return None
        key = id(ex)
        if key not in _prec_cache:
            from alpa_tpu.shard_parallel.eqn_classify import (
                classify_stage_precision)
            _prec_cache[key] = classify_stage_precision(ex)
        return _prec_cache[key]

    # translation validation (ISSUE 15): the per-RUN stage signature /
    # donation / accumulation facts the symbolic executor applies —
    # derived by the same shared helper the driver's reference
    # decomposition uses, so a correct lowering matches by construction
    want_equiv = (
        getattr(global_config, "verify_plans", "warn") != "off" and
        getattr(global_config, "verify_plans_equiv", "warn") != "off"
        and equiv_reference is not None)

    def _equiv_of(inst, ex):
        if not want_equiv:
            return None
        from alpa_tpu.analysis.equivalence import stage_equiv_info
        info = stage_equiv_info(ex)
        mb = getattr(inst, "micro_batch", None)
        return {"stage": info["stage"],
                "mb": int(mb) if mb is not None else -1,
                "donate": list(info["donate"]),
                "acc": dict(info["acc"])}

    # quantized gradient collectives (ISSUE 19): when the knob is on,
    # every RUN record carries the codec facts; the numerics analysis
    # composes the bound only where the stage actually donates
    # gradient-provenance accumulators, so the tag alone never taints a
    # forward stage.  None at grad_quantize=off — records (and
    # therefore plan fingerprints) are byte-identical to main.
    _gq_mode = getattr(global_config, "grad_quantize", "off")
    _grad_tag = None
    if _gq_mode != "off":
        _mbs = {int(getattr(inst, "micro_batch", 0) or 0)
                for inst in instructions
                if inst.opcode == PipelineInstType.RUN and
                getattr(inst, "micro_batch", None) is not None}
        _grad_tag = {
            "mode": _gq_mode,
            "ef": bool(getattr(global_config, "grad_error_feedback",
                               True)),
            "hops": max(1, len(_mbs)),
            "rs": False,
        }

    for inst in instructions:
        if inst.opcode == PipelineInstType.RUN:
            by_opcode["RUN"] += 1
            ex = inst.executable
            in_slots, fixups = [], []
            for pos, k in enumerate(inst.input_keys):
                s = slot((k[0], k[1], inst.dst_mesh))
                in_slots.append(s)
                need = ex.in_shardings[pos]
                ndim = len(getattr(ex.invars[pos].aval, "shape", ()))
                if not _equiv_shardings(cur_sharding.get(s), need, ndim):
                    fixups.append((pos, need, ndim))
            out_slots = []
            for pos, k in enumerate(inst.output_keys):
                s = slot((k[0], k[1], inst.dst_mesh))
                out_slots.append(s)
                cur_sharding[s] = ex.out_shardings[pos]
            n_fixups += len(fixups)
            donated = set(getattr(ex, "donate_idx", ()) or ())
            kills = tuple(sorted({in_slots[p] for p in donated
                                  if p < len(in_slots)}))
            recs.append({
                "kind": "RUN",
                "op": _make_run_op(ex.compiled, tuple(in_slots),
                                   tuple(out_slots), tuple(fixups)),
                "reads": tuple(in_slots),
                "writes": tuple(out_slots),
                "kills": kills,
                "name": f"RUN {inst.info}",
                "mesh": inst.dst_mesh,
                # fault hook point: same site/info/retry semantics the
                # interpreter uses for this instruction (ISSUE 6)
                "site": "stage_launch",
                "finfo": {"stage": inst.info, "mesh_id": inst.dst_mesh},
                "precision": _precision_of(ex),
                "equiv": _equiv_of(inst, ex),
                "grad_quant": _grad_tag,
                "idem": not donated,
                "line": (f"RUN {inst.info} mb={inst.micro_batch} "
                         f"in={in_slots} out={out_slots} "
                         f"fix={[(p, str(s)) for p, s, _ in fixups]}"),
            })
        elif inst.opcode == PipelineInstType.RESHARD:
            by_opcode["RESHARD"] += 1
            v = inst.var_key[0]
            ss = slot((v, inst.var_key[1], inst.src_mesh))
            ds = slot((v, inst.var_key[1], inst.dst_mesh))
            # collective lowering (ISSUE 7): the factory replays the
            # planner's per-edge strategy (DirectTransfer,
            # CollectiveTransfer, or the opt-in quantized codec); weight
            # edges (microbatch-invariant, var_key[1] < 0) stay lossless
            t = make_transfer(v.aval, cur_sharding.get(ss),
                              inst.dst_sharding,
                              cross=inst.src_mesh != inst.dst_mesh,
                              plan=inst.plan,
                              weight=inst.var_key[1] < 0)
            strategy = getattr(t, "strategy", None) or \
                ("quantized" if not isinstance(t, DirectTransfer)
                 else "direct_p2p")
            tag = "" if strategy == "direct_p2p" else f" [{strategy}]"
            cur_sharding[ds] = inst.dst_sharding
            recs.append({
                "kind": "RESHARD",
                "op": _make_reshard_op(t, ss, ds),
                "transfer": t,
                # only DirectTransfers coalesce into batched groups
                "groupable": isinstance(t, DirectTransfer),
                "ss": ss,
                "ds": ds,
                "edge": (inst.src_mesh, inst.dst_mesh),
                "cross": inst.src_mesh != inst.dst_mesh,
                "reads": (ss,),
                "writes": (ds,),
                "kills": (),
                "name": f"RESHARD {inst.src_mesh}->{inst.dst_mesh}{tag}",
                "mesh": inst.dst_mesh,
                "site": "cross_mesh_send",
                "finfo": {"var": str(v), "src_mesh": inst.src_mesh,
                          "dst_mesh": inst.dst_mesh,
                          "strategy": strategy,
                          "codec": getattr(t, "mode", None)
                          if strategy == "quantized" else None},
                "codec": getattr(t, "mode", None)
                if strategy == "quantized" else None,
                "idem": True,
                "line": (f"RESHARD {inst.var_key} {inst.src_mesh}->"
                         f"{inst.dst_mesh} slot {ss}->{ds} fast={t.fast}" +
                         ("" if strategy == "direct_p2p"
                          else f" strategy={strategy}")),
            })
        else:  # FREE
            by_opcode["FREE"] += 1
            slots = tuple(slot((k[0], k[1], k[2])) for k in inst.free_keys)
            recs.append({
                "kind": "FREE",
                "op": _make_free_op(slots),
                "slots": slots,
                "reads": (),
                "writes": (),
                "kills": slots,
                "name": "FREE",
                "mesh": inst.free_keys[0][2] if inst.free_keys else 0,
                "line": f"FREE {list(slots)}",
            })

    nodes = [
        DataflowNode(idx=i, kind=r["kind"], reads=r["reads"],
                     writes=r["writes"], kills=r["kills"],
                     edge=r.get("edge"), cross_mesh=r.get("cross", False),
                     info=r["line"])
        for i, r in enumerate(recs)
    ]
    graph = InstructionDataflowGraph.build(nodes)
    # static hazard pass on every compile (ISSUE 6): a missing
    # dependency edge is a lowering bug — fail here, not as silent
    # numeric corruption three replays later
    graph.check()
    n_cross = graph.n_cross_mesh
    n = len(recs)

    def _hook_for(r, idx, kind="exec"):
        reads, writes, kills = r["reads"], r["writes"], r["kills"]
        site = r.get("site")
        return OpHook(kind=kind, name=r["name"], node=idx,
                      mesh=r["mesh"], reads=reads, writes=writes,
                      kills=kills,
                      slots=tuple(sorted({*reads, *writes, *kills})),
                      fault_site=site,
                      fault_infos=(r["finfo"],) if site else (),
                      idempotent=r.get("idem", True),
                      precision=r.get("precision"),
                      equiv=r.get("equiv"),
                      members=(idx,))

    def _group_hook(mem_idx, kind="exec", label=None):
        # one hook for a batched same-edge group: union footprint, one
        # fault info per member (hit counts match the interpreter)
        mem = [recs[m] for m in mem_idx]
        first = mem[0]
        reads = tuple(m["ss"] for m in mem)
        writes = tuple(m["ds"] for m in mem)
        name = label or (f"RESHARD-GROUP x{len(mem)} "
                         f"{first['edge'][0]}->{first['edge'][1]}")
        return OpHook(kind=kind, name=name, node=mem_idx[0],
                      mesh=first["mesh"], reads=reads, writes=writes,
                      slots=tuple(sorted({*reads, *writes})),
                      fault_site="cross_mesh_send",
                      fault_infos=tuple(m["finfo"] for m in mem),
                      idempotent=True,
                      members=tuple(mem_idx))

    ops: List[Any] = []
    lines: List[str] = []
    meta: List[Tuple[str, str, str]] = []   # (span name, category, track)
    hooks: List[OpHook] = []                # per-op hook points (ISSUE 6)
    n_groups = 0
    n_free_hops = 0
    n_hoisted = 0
    n_launches = 0
    run_stats = {"transfer_busy_s": 0.0, "wait_blocked_s": 0.0}

    if mode == "registers":
        # ---- phase 2a: flat replay with extended same-edge coalescing ----
        # group-membership legality lives in ONE oracle shared with the
        # superopt fusion family (analysis/superopt.py, ISSUE 17);
        # superopt_max_group > 0 is the fission knob.  Lazy import:
        # analysis/ sits above the lowering layer.
        from alpa_tpu.analysis.superopt import reshard_group_extent
        i = 0
        while i < n:
            r = recs[i]
            if r["kind"] != "RESHARD":
                ops.append(r["op"])
                lines.append(r["line"])
                meta.append((r["name"], "instruction",
                             f"mesh {r['mesh']}"))
                hooks.append(_hook_for(r, i))
                i += 1
                continue
            edge = r["edge"]
            members, hopped, hops, j = reshard_group_extent(
                recs, i,
                max_members=global_config.superopt_max_group)
            n_free_hops += hops
            # trailing FREEs (after the last member) keep their original
            # relative position by being re-emitted after the group
            if len(members) == 1:
                m = recs[members[0]]
                ops.append(m["op"])
                lines.append(m["line"] + " edgegroup=1")
                meta.append((m["name"], "instruction",
                             f"mesh {m['mesh']}"))
                hooks.append(_hook_for(m, members[0]))
            else:
                n_groups += 1
                mem = [recs[m_] for m_ in members]
                ops.append(_make_reshard_group_op(
                    DirectTransferGroup([m["transfer"] for m in mem]),
                    tuple(m["ss"] for m in mem),
                    tuple(m["ds"] for m in mem)))
                for m in mem:
                    lines.append(m["line"] + f" edgegroup={len(mem)}")
                meta.append((
                    f"RESHARD-GROUP x{len(mem)} "
                    f"{edge[0]}->{edge[1]}", "instruction",
                    f"mesh {mem[0]['mesh']}"))
                hooks.append(_group_hook(members))
            for qi in hopped:
                q = recs[qi]
                ops.append(q["op"])
                lines.append(q["line"])
                meta.append((q["name"], "instruction",
                             f"mesh {q['mesh']}"))
                hooks.append(_hook_for(q, qi))
            i = j
    else:
        # ---- phase 2b: overlap replay of the dataflow graph ----
        window = max(1, min(int(overlap_window), max(1, n_cross)))
        plan, n_hoisted = schedule_overlap(graph, window)
        # merge consecutive same-edge launches into one batched group
        group_of: Dict[int, int] = {}
        group_members: Dict[int, List[int]] = {}
        k = 0
        while k < len(plan):
            kind, idx = plan[k]
            if kind != "launch" or not recs[idx].get("groupable", True):
                k += 1
                continue
            edge = recs[idx]["edge"]
            mem = [idx]
            k2 = k + 1
            while (k2 < len(plan) and plan[k2][0] == "launch" and
                   recs[plan[k2][1]].get("groupable", True) and
                   recs[plan[k2][1]]["edge"] == edge):
                mem.append(plan[k2][1])
                k2 += 1
            if len(mem) > 1:
                gid = len(group_members)
                group_members[gid] = mem
                for m_ in mem:
                    group_of[m_] = gid
            k = k2
        waited_groups: set = set()
        for kind, idx in plan:
            r = recs[idx]
            if kind == "exec":
                ops.append(r["op"])
                lines.append(r["line"])
                meta.append((r["name"], "instruction",
                             f"mesh {r['mesh']}"))
                hooks.append(_hook_for(r, idx))
            elif kind == "launch":
                gid = group_of.get(idx)
                if gid is None:
                    n_launches += 1
                    ops.append(_make_launch_op(
                        r["transfer"], r["ss"], r["ds"],
                        label=r["name"]))
                    lines.append(f"LAUNCH #{idx} " + r["line"])
                    meta.append((f"LAUNCH {r['name']}", "transfer",
                                 f"mesh {r['mesh']}"))
                    hooks.append(_hook_for(r, idx, kind="launch"))
                elif group_members[gid][0] == idx:
                    n_launches += 1
                    n_groups += 1
                    mem = group_members[gid]
                    ops.append(_make_launch_group_op(
                        DirectTransferGroup(
                            [recs[m]["transfer"] for m in mem]),
                        tuple(recs[m]["ss"] for m in mem),
                        tuple(recs[m]["ds"] for m in mem),
                        label=(f"{r['name']} x{len(mem)}")))
                    lines.append(
                        f"LAUNCH-GROUP #{mem} edge={r['edge']}")
                    meta.append((
                        f"LAUNCH-GROUP x{len(mem)} "
                        f"{r['edge'][0]}->{r['edge'][1]}", "transfer",
                        f"mesh {r['mesh']}"))
                    hooks.append(_group_hook(
                        mem, kind="launch",
                        label=(f"LAUNCH-GROUP x{len(mem)} "
                               f"{r['edge'][0]}->{r['edge'][1]}")))
                # non-leading group members were folded into the group op
            else:  # wait
                gid = group_of.get(idx)
                if gid is None:
                    ops.append(_make_wait_op(r["ds"], run_stats))
                    lines.append(f"WAIT #{idx} slot {r['ds']}")
                    meta.append((f"WAIT {r['name']}", "transfer",
                                 f"mesh {r['mesh']}"))
                    hooks.append(dataclasses.replace(
                        _hook_for(r, idx, kind="wait"),
                        name=f"WAIT {r['name']}",
                        fault_site=None, fault_infos=()))
                elif gid not in waited_groups:
                    waited_groups.add(gid)
                    mem = group_members[gid]
                    ops.append(_make_wait_group_op(
                        tuple(recs[m]["ds"] for m in mem), run_stats))
                    lines.append(f"WAIT-GROUP #{mem}")
                    meta.append((f"WAIT-GROUP x{len(mem)}", "transfer",
                                 f"mesh {r['mesh']}"))
                    hooks.append(dataclasses.replace(
                        _group_hook(mem, kind="wait",
                                    label=f"WAIT-GROUP x{len(mem)}"),
                        fault_site=None, fault_infos=()))
                # later member waits are satisfied by the group wait
        lines.append(f"MODE overlap window={window} hoisted={n_hoisted} "
                     f"launches={n_launches}")

    assert len(hooks) == len(ops) == len(meta), (
        "lowering emitted misaligned op/meta/hook lists")
    prog = RegisterFileProgram(num_slots=len(slot_of),
                               ops=ops,
                               n_instructions=n,
                               by_opcode=by_opcode,
                               slot_of=slot_of,
                               n_coalesced_groups=n_groups,
                               n_fixups=n_fixups,
                               text="\n".join(lines),
                               mode=mode,
                               graph=graph,
                               n_cross_mesh=n_cross,
                               n_hoisted=n_hoisted,
                               n_launches=n_launches,
                               n_free_hops=n_free_hops,
                               overlap_window=(window if mode == "overlap"
                                               else 0),
                               run_stats=run_stats,
                               op_meta=meta,
                               hooks=hooks)
    # static plan verification (ISSUE 8): typed abstract interpretation
    # + deadlock/liveness/structure analyses over the program just
    # built.  Runs once per compile (cached by plan fingerprint for
    # warm restarts), costs nothing at dispatch replay.  verify_plans:
    # "error" blocks compilation on findings, "warn" (default) logs,
    # "off" skips entirely.
    if getattr(global_config, "verify_plans", "warn") != "off":
        from alpa_tpu.analysis import plan_verifier
        prog.verdict = plan_verifier.verify_program(
            instructions, prog, preplaced_shardings, recs,
            protected_keys=protected_keys,
            opt_state_keys=opt_state_keys,
            provenance_keys=provenance_keys,
            reference=equiv_reference)
    return prog


def emit_free_instructions(instructions: List[PipelineInstruction],
                           protected_keys) -> List[PipelineInstruction]:
    """Insert FREE after the last use of each (var, inst, mesh) value
    (ref _compile_free, runtime_emitter.py:1087)."""
    last_use: Dict[Tuple[int, int, int], int] = {}
    defined: Dict[Tuple[int, int, int], int] = {}
    for i, inst in enumerate(instructions):
        if inst.opcode == PipelineInstType.RUN:
            mesh = inst.dst_mesh
            for k in inst.input_keys:
                last_use[(k[0], k[1], mesh)] = i
            for k in inst.output_keys:
                defined[(k[0], k[1], mesh)] = i
        elif inst.opcode == PipelineInstType.RESHARD:
            last_use[(inst.var_key[0], inst.var_key[1], inst.src_mesh)] = i
            defined[(inst.var_key[0], inst.var_key[1], inst.dst_mesh)] = i
    out: List[PipelineInstruction] = []
    frees_at: Dict[int, List[Tuple[int, int, int]]] = {}
    for key, i in last_use.items():
        if key in protected_keys:
            continue
        if key not in defined:
            continue  # inputs placed at launch are managed by the driver
        frees_at.setdefault(i, []).append(key)
    for i, inst in enumerate(instructions):
        out.append(inst)
        if i in frees_at:
            out.append(
                PipelineInstruction(PipelineInstType.FREE,
                                    free_keys=frees_at[i]))
    return out
