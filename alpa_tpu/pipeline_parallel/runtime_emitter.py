"""Pipeline instruction emitter.

Analog of ref ``alpa/pipeline_parallel/runtime_emitter.py`` (SURVEY.md
§2.4): walk the schedule tick by tick and compile it into a static
instruction list.  Single-controller simplifications vs the reference:

* ``SEND``/``RECV``/``BROADCAST`` collapse into one ``RESHARD`` instruction
  executed as ``jax.device_put`` (the jax runtime moves data between meshes
  over ICI/DCN; ref cross_mesh_resharding's NCCL P2P machinery becomes the
  runtime's transfer engine).
* There is one global instruction stream instead of per-host worker
  streams; jax's async dispatch provides cross-mesh overlap.
* ``FREE`` is emitted from liveness analysis like the reference
  (``_compile_free``, ref runtime_emitter.py:1087) and drops env references
  so buffers are reclaimed promptly.

Value identity: (var, instance) where instance = microbatch index for
per-microbatch values and -1 for microbatch-invariant ones (params, grad
accumulators, apply-grad results).
"""
import dataclasses
import enum
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

from jax.extend.core import Var

logger = logging.getLogger(__name__)


class PipelineInstType(enum.IntEnum):
    """(ref runtime_emitter.py:31)"""
    RUN = 0
    RESHARD = 1
    FREE = 2


@dataclasses.dataclass
class PipelineInstruction:
    """(ref runtime_emitter.py:47)"""
    opcode: PipelineInstType
    # RUN
    stage_id: Optional[int] = None
    micro_batch: Optional[int] = None
    input_keys: Optional[List[Tuple[int, int]]] = None   # (var_id, inst)
    output_keys: Optional[List[Tuple[int, int]]] = None
    # RESHARD
    var_key: Optional[Tuple[int, int]] = None
    src_mesh: Optional[int] = None
    dst_mesh: Optional[int] = None
    dst_sharding: Any = None
    # tile-level transfer plan (cross_mesh_resharding.ReshardingTaskSpec)
    plan: Any = None
    # cached executor for planned execution mode
    task: Any = None
    # FREE
    free_keys: Optional[List[Tuple[int, int, int]]] = None  # (var,inst,mesh)
    info: str = ""

    def __repr__(self):
        if self.opcode == PipelineInstType.RUN:
            return (f"RUN(stage={self.stage_id}, mb={self.micro_batch})")
        if self.opcode == PipelineInstType.RESHARD:
            return (f"RESHARD({self.var_key}, {self.src_mesh}->"
                    f"{self.dst_mesh})")
        return f"FREE({len(self.free_keys)})"


@dataclasses.dataclass
class PlacementSpecEntry:
    """Where a global input lives: list of (mesh_id, sharding)."""
    mesh_ids: List[int]
    shardings: List[Any]
    is_batch: bool = False


@dataclasses.dataclass
class PipeshardConfig:
    """The full compiled artifact (ref runtime_emitter.py:228)."""
    instructions: List[PipelineInstruction]
    # global invar index -> placement
    input_placements: List[PlacementSpecEntry]
    # accumulator allocations: (var_id, mesh_id, aval, sharding)
    acc_allocs: List[Tuple[int, int, Any, Any]]
    # flat output -> (var_id, inst, mesh_id)
    output_specs: List[Tuple[int, int, int]]
    num_micro_batches: int
    num_meshes: int
    var_ids: Dict[Var, int]
    # (var_id, inst) -> producing mesh (for debugging)
    schedule_text: str = ""


@dataclasses.dataclass
class InstructionStreams:
    """Per-mesh instruction streams with cross-stream dependencies — the
    single-controller analog of the reference's pre-pushed per-worker
    instruction lists (ref runtime_emitter.py:258 PipelineInstEmitter ->
    per-worker lists; pipeshard_executable.py:489 execute_on_worker).

    ``streams[m]`` is the ordered list of global instruction indices mesh
    ``m``'s worker executes; ``deps[i]`` is the set of global indices in
    OTHER streams instruction ``i`` must wait for.  Dependencies cover
    read-after-write (a consumer waits for its producer), plus
    write/kill-after-read anti-dependencies (donating or freeing a buffer
    waits for every earlier reader) — all edges point to earlier global
    indices, so stream workers that execute in-stream in order can never
    deadlock.
    """
    streams: List[List[int]]
    deps: Dict[int, set]
    stream_of: Dict[int, int]


def instruction_accesses(inst) -> List[Tuple[Tuple[int, int, int], str]]:
    """The (value key, access kind) pairs one instruction touches —
    kind "read" | "write" | "kill" (donation or FREE).  Shared by the
    stream partitioner (dependency edges) and the dispatch race checker
    (runtime conflict detection)."""
    acc = []
    if inst.opcode == PipelineInstType.RUN:
        ex = getattr(inst, "executable", None)
        donated = set(getattr(ex, "donate_idx", ()) or ())
        for pos, k in enumerate(inst.input_keys):
            kind = "kill" if pos in donated else "read"
            acc.append(((k[0], k[1], inst.dst_mesh), kind))
        for k in inst.output_keys:
            acc.append(((k[0], k[1], inst.dst_mesh), "write"))
    elif inst.opcode == PipelineInstType.RESHARD:
        acc.append(
            ((inst.var_key[0], inst.var_key[1], inst.src_mesh), "read"))
        acc.append(
            ((inst.var_key[0], inst.var_key[1], inst.dst_mesh), "write"))
    else:  # FREE
        for key in inst.free_keys:
            acc.append((tuple(key), "kill"))
    return acc


def partition_streams(instructions: List[PipelineInstruction],
                      num_meshes: int) -> InstructionStreams:
    """Split the global instruction list into per-mesh streams.

    Assignment: RUN executes on its ``dst_mesh``; RESHARD on its
    ``dst_mesh`` (the destination initiates the pull, matching the jax
    transfer model); FREE follows the stream of the preceding
    instruction — its last user, since emit_free_instructions places
    each FREE immediately after the last use (stream 0 if the list
    starts with a FREE).
    """
    streams: List[List[int]] = [[] for _ in range(num_meshes)]
    stream_of: Dict[int, int] = {}
    deps: Dict[int, set] = {}
    # key -> ordered access history: (global_idx, stream, kind)
    history: Dict[Tuple[int, int, int], List[Tuple[int, int, str]]] = {}

    prev_stream = 0
    for i, inst in enumerate(instructions):
        if inst.opcode == PipelineInstType.RUN:
            m = inst.dst_mesh
        elif inst.opcode == PipelineInstType.RESHARD:
            m = inst.dst_mesh
        else:
            m = prev_stream
        m = m if 0 <= m < num_meshes else 0
        streams[m].append(i)
        stream_of[i] = m
        prev_stream = m

        d = set()
        for key, kind in instruction_accesses(inst):
            hist = history.setdefault(key, [])
            if kind == "read":
                # wait for the latest write from another stream
                for j, sm, k in reversed(hist):
                    if k in ("write", "kill"):
                        if sm != m:
                            d.add(j)
                        break
            else:  # write or kill: wait for every earlier access
                for j, sm, k in hist:
                    if sm != m:
                        d.add(j)
            hist.append((i, m, kind))
        if d:
            deps[i] = d
    return InstructionStreams(streams=streams, deps=deps,
                              stream_of=stream_of)


class DispatchRaceChecker:
    """Runtime race detector for threaded per-mesh dispatch (SURVEY §5
    race detection — a capability the reference does not have).

    With ``global_config.debug_dispatch_races`` on, every worker reports
    its instruction's value accesses before executing and withdraws them
    after.  Two accesses CONFLICT when they touch the same (var,
    microbatch, mesh) key from different streams and at least one is a
    write or kill (donation/FREE).  A conflict observed live means the
    partitioner's dependency edges failed to serialize the pair — the
    exact bug class that would otherwise surface as silent numeric
    corruption or a use-after-donate crash far from its cause.
    """

    def __init__(self, instructions, stream_of):
        import threading
        self._stream_of = stream_of
        # instructions and streams are fixed for the executable's
        # lifetime: extract every access list once, not per step
        self._accs = [instruction_accesses(i) for i in instructions]
        self._lock = threading.Lock()
        # key -> {idx: kind} of instructions currently executing
        self._active: Dict[Tuple, Dict[int, str]] = {}
        self.violations: List[str] = []

    @staticmethod
    def _conflict(a: str, b: str) -> bool:
        return a != "read" or b != "read"

    def begin(self, idx: int):
        accs = self._accs[idx]
        me = self._stream_of[idx]
        with self._lock:
            for key, kind in accs:
                holders = self._active.setdefault(key, {})
                for other, okind in holders.items():
                    if self._stream_of[other] != me and \
                            self._conflict(kind, okind):
                        self.violations.append(
                            f"inst {idx} ({kind} {key}) raced inst "
                            f"{other} ({okind}) across streams "
                            f"{me}/{self._stream_of[other]}")
                holders[idx] = kind
        return accs

    def end(self, idx: int, accs):
        with self._lock:
            for key, _ in accs:
                holders = self._active.get(key)
                if holders is not None:
                    holders.pop(idx, None)
                    if not holders:
                        self._active.pop(key, None)

    def reset(self):
        """Clear violations AND in-flight accesses (an aborted launch can
        leave registrations behind); call at the start of every launch."""
        with self._lock:
            self._active = {}
            self.violations = []

    def check(self):
        if self.violations:
            raise RuntimeError(
                "threaded dispatch raced (stream dependency edges failed "
                "to serialize conflicting accesses):\n  " +
                "\n  ".join(self.violations[:10]))


########################################
# register-file lowering (replay fast path)
########################################


def _equiv_shardings(s1, s2, ndim) -> bool:
    if s1 is None or s2 is None:
        return True
    try:
        return s1.is_equivalent_to(s2, ndim)
    except Exception:  # pylint: disable=broad-except
        return s1 == s2


@dataclasses.dataclass
class RegisterFileProgram:
    """The instruction list lowered to a flat register file (ISSUE 2).

    Replay becomes ``for op in ops: op(regs)`` over ``regs = [None] *
    num_slots``: every ``(var, microbatch, mesh)`` key was resolved to an
    integer slot at build time, RUN inputs/outputs are precomputed index
    tuples closed over each op, FREE is slot clears, and RESHARD carries a
    pre-built :class:`~alpa_tpu.pipeline_parallel.cross_mesh_resharding.
    DirectTransfer` executor (adjacent same-edge transfers coalesced into
    one batched call) — no dict hashing, no sharding resolution, no
    per-call planning on the hot path.
    """
    num_slots: int
    ops: List[Any]                      # each: fn(regs) -> None
    n_instructions: int                 # original instruction count
    by_opcode: Dict[str, int]           # original counts per opcode
    slot_of: Dict[Tuple[Var, int, int], int]
    n_coalesced_groups: int
    n_fixups: int
    text: str                           # one line per op, for fingerprints

    def execute(self, regs: List[Any]):
        for op in self.ops:
            op(regs)

    def fingerprint(self) -> str:
        import hashlib
        return hashlib.sha256(self.text.encode()).hexdigest()


def _make_run_op(compiled, in_slots, out_slots, fixups):
    """RUN as a closure: gather args by slot index, call the compiled
    fast path, scatter outputs.  ``fixups`` carries the (rare) arg
    positions whose statically-tracked layout differs from the stage's
    expected sharding — the register-file analog of the interpreter's
    per-arg safety net, resolved at lowering instead of per call."""
    if fixups:

        def op(regs, _c=compiled, _i=in_slots, _o=out_slots, _f=fixups):
            import jax
            args = [regs[s] for s in _i]
            for pos, sh, ndim in _f:
                a = args[pos]
                if not a.sharding.is_equivalent_to(sh, ndim):
                    args[pos] = jax.device_put(a, sh)
            outs = _c(*args)
            for s, o in zip(_o, outs):
                regs[s] = o
    else:

        def op(regs, _c=compiled, _i=in_slots, _o=out_slots):
            outs = _c(*[regs[s] for s in _i])
            for s, o in zip(_o, outs):
                regs[s] = o

    return op


def _make_reshard_op(transfer, src_slot, dst_slot):
    def op(regs, _t=transfer, _s=src_slot, _d=dst_slot):
        regs[_d] = _t(regs[_s])

    return op


def _make_reshard_group_op(group, src_slots, dst_slots):
    def op(regs, _g=group, _s=src_slots, _d=dst_slots):
        outs = _g([regs[s] for s in _s])
        for d, o in zip(_d, outs):
            regs[d] = o

    return op


def _make_free_op(slots):
    def op(regs, _s=slots):
        for i in _s:
            regs[i] = None

    return op


def lower_to_register_file(
        instructions: List[PipelineInstruction],
        preplaced_shardings: Dict[Tuple[Var, int, int], Any]
) -> RegisterFileProgram:
    """Lower the emitted instruction list into a :class:`RegisterFileProgram`.

    ``preplaced_shardings`` seeds the static sharding model with the
    launch-placed values (global inputs, consts, zero accumulators):
    key ``(var, microbatch-instance, mesh)`` -> sharding.  The lowering
    walks the instructions in global order tracking the layout each slot
    holds, so RESHARD executors know their source sharding statically and
    RUN args that would need the interpreter's per-call relayout safety
    net become precomputed fixups.
    """
    from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
        DirectTransfer, DirectTransferGroup)

    slot_of: Dict[Tuple[Var, int, int], int] = {}

    def slot(key):
        s = slot_of.get(key)
        if s is None:
            s = slot_of[key] = len(slot_of)
        return s

    cur_sharding: Dict[int, Any] = {}
    for key, sh in preplaced_shardings.items():
        cur_sharding[slot(key)] = sh

    ops: List[Any] = []
    lines: List[str] = []
    by_opcode = {"RUN": 0, "RESHARD": 0, "FREE": 0}
    n_groups = 0
    n_fixups = 0

    i = 0
    n = len(instructions)
    while i < n:
        inst = instructions[i]
        if inst.opcode == PipelineInstType.RUN:
            by_opcode["RUN"] += 1
            ex = inst.executable
            in_slots, fixups = [], []
            for pos, k in enumerate(inst.input_keys):
                s = slot((k[0], k[1], inst.dst_mesh))
                in_slots.append(s)
                need = ex.in_shardings[pos]
                ndim = len(getattr(ex.invars[pos].aval, "shape", ()))
                if not _equiv_shardings(cur_sharding.get(s), need, ndim):
                    fixups.append((pos, need, ndim))
            out_slots = []
            for pos, k in enumerate(inst.output_keys):
                s = slot((k[0], k[1], inst.dst_mesh))
                out_slots.append(s)
                cur_sharding[s] = ex.out_shardings[pos]
            n_fixups += len(fixups)
            ops.append(
                _make_run_op(ex.compiled, tuple(in_slots), tuple(out_slots),
                             tuple(fixups)))
            lines.append(f"RUN {inst.info} mb={inst.micro_batch} "
                         f"in={in_slots} out={out_slots} "
                         f"fix={[(p, str(s)) for p, s, _ in fixups]}")
            i += 1
        elif inst.opcode == PipelineInstType.RESHARD:
            # coalesce the maximal run of globally-adjacent RESHARDs on
            # the same (src, dst) edge into one batched transfer
            edge = (inst.src_mesh, inst.dst_mesh)
            j = i
            group: List[PipelineInstruction] = []
            while (j < n and
                   instructions[j].opcode == PipelineInstType.RESHARD and
                   (instructions[j].src_mesh,
                    instructions[j].dst_mesh) == edge):
                group.append(instructions[j])
                j += 1
            src_slots, dst_slots, transfers = [], [], []
            for g in group:
                by_opcode["RESHARD"] += 1
                v = g.var_key[0]
                ss = slot((v, g.var_key[1], g.src_mesh))
                ds = slot((v, g.var_key[1], g.dst_mesh))
                t = DirectTransfer(v.aval, cur_sharding.get(ss),
                                   g.dst_sharding)
                src_slots.append(ss)
                dst_slots.append(ds)
                transfers.append(t)
                cur_sharding[ds] = g.dst_sharding
                lines.append(f"RESHARD {g.var_key} {g.src_mesh}->"
                             f"{g.dst_mesh} slot {ss}->{ds} "
                             f"fast={t.fast} edgegroup={len(group)}")
            if len(group) == 1:
                ops.append(
                    _make_reshard_op(transfers[0], src_slots[0],
                                     dst_slots[0]))
            else:
                n_groups += 1
                ops.append(
                    _make_reshard_group_op(DirectTransferGroup(transfers),
                                           tuple(src_slots),
                                           tuple(dst_slots)))
            i = j
        else:  # FREE
            by_opcode["FREE"] += 1
            slots = tuple(slot((k[0], k[1], k[2])) for k in inst.free_keys)
            ops.append(_make_free_op(slots))
            lines.append(f"FREE {list(slots)}")
            i += 1

    return RegisterFileProgram(num_slots=len(slot_of),
                               ops=ops,
                               n_instructions=n,
                               by_opcode=by_opcode,
                               slot_of=slot_of,
                               n_coalesced_groups=n_groups,
                               n_fixups=n_fixups,
                               text="\n".join(lines))


def emit_free_instructions(instructions: List[PipelineInstruction],
                           protected_keys) -> List[PipelineInstruction]:
    """Insert FREE after the last use of each (var, inst, mesh) value
    (ref _compile_free, runtime_emitter.py:1087)."""
    last_use: Dict[Tuple[int, int, int], int] = {}
    defined: Dict[Tuple[int, int, int], int] = {}
    for i, inst in enumerate(instructions):
        if inst.opcode == PipelineInstType.RUN:
            mesh = inst.dst_mesh
            for k in inst.input_keys:
                last_use[(k[0], k[1], mesh)] = i
            for k in inst.output_keys:
                defined[(k[0], k[1], mesh)] = i
        elif inst.opcode == PipelineInstType.RESHARD:
            last_use[(inst.var_key[0], inst.var_key[1], inst.src_mesh)] = i
            defined[(inst.var_key[0], inst.var_key[1], inst.dst_mesh)] = i
    out: List[PipelineInstruction] = []
    frees_at: Dict[int, List[Tuple[int, int, int]]] = {}
    for key, i in last_use.items():
        if key in protected_keys:
            continue
        if key not in defined:
            continue  # inputs placed at launch are managed by the driver
        frees_at.setdefault(i, []).append(key)
    for i, inst in enumerate(instructions):
        out.append(inst)
        if i in frees_at:
            out.append(
                PipelineInstruction(PipelineInstType.FREE,
                                    free_keys=frees_at[i]))
    return out
