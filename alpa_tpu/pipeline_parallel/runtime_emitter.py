"""Pipeline instruction emitter.

Analog of ref ``alpa/pipeline_parallel/runtime_emitter.py`` (SURVEY.md
§2.4): walk the schedule tick by tick and compile it into a static
instruction list.  Single-controller simplifications vs the reference:

* ``SEND``/``RECV``/``BROADCAST`` collapse into one ``RESHARD`` instruction
  executed as ``jax.device_put`` (the jax runtime moves data between meshes
  over ICI/DCN; ref cross_mesh_resharding's NCCL P2P machinery becomes the
  runtime's transfer engine).
* There is one global instruction stream instead of per-host worker
  streams; jax's async dispatch provides cross-mesh overlap.
* ``FREE`` is emitted from liveness analysis like the reference
  (``_compile_free``, ref runtime_emitter.py:1087) and drops env references
  so buffers are reclaimed promptly.

Value identity: (var, instance) where instance = microbatch index for
per-microbatch values and -1 for microbatch-invariant ones (params, grad
accumulators, apply-grad results).
"""
import dataclasses
import enum
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

from jax.extend.core import Var

logger = logging.getLogger(__name__)


class PipelineInstType(enum.IntEnum):
    """(ref runtime_emitter.py:31)"""
    RUN = 0
    RESHARD = 1
    FREE = 2


@dataclasses.dataclass
class PipelineInstruction:
    """(ref runtime_emitter.py:47)"""
    opcode: PipelineInstType
    # RUN
    stage_id: Optional[int] = None
    micro_batch: Optional[int] = None
    input_keys: Optional[List[Tuple[int, int]]] = None   # (var_id, inst)
    output_keys: Optional[List[Tuple[int, int]]] = None
    # RESHARD
    var_key: Optional[Tuple[int, int]] = None
    src_mesh: Optional[int] = None
    dst_mesh: Optional[int] = None
    dst_sharding: Any = None
    # tile-level transfer plan (cross_mesh_resharding.ReshardingTaskSpec)
    plan: Any = None
    # cached executor for planned execution mode
    task: Any = None
    # FREE
    free_keys: Optional[List[Tuple[int, int, int]]] = None  # (var,inst,mesh)
    info: str = ""

    def __repr__(self):
        if self.opcode == PipelineInstType.RUN:
            return (f"RUN(stage={self.stage_id}, mb={self.micro_batch})")
        if self.opcode == PipelineInstType.RESHARD:
            return (f"RESHARD({self.var_key}, {self.src_mesh}->"
                    f"{self.dst_mesh})")
        return f"FREE({len(self.free_keys)})"


@dataclasses.dataclass
class PlacementSpecEntry:
    """Where a global input lives: list of (mesh_id, sharding)."""
    mesh_ids: List[int]
    shardings: List[Any]
    is_batch: bool = False


@dataclasses.dataclass
class PipeshardConfig:
    """The full compiled artifact (ref runtime_emitter.py:228)."""
    instructions: List[PipelineInstruction]
    # global invar index -> placement
    input_placements: List[PlacementSpecEntry]
    # accumulator allocations: (var_id, mesh_id, aval, sharding)
    acc_allocs: List[Tuple[int, int, Any, Any]]
    # flat output -> (var_id, inst, mesh_id)
    output_specs: List[Tuple[int, int, int]]
    num_micro_batches: int
    num_meshes: int
    var_ids: Dict[Var, int]
    # (var_id, inst) -> producing mesh (for debugging)
    schedule_text: str = ""


def emit_free_instructions(instructions: List[PipelineInstruction],
                           protected_keys) -> List[PipelineInstruction]:
    """Insert FREE after the last use of each (var, inst, mesh) value
    (ref _compile_free, runtime_emitter.py:1087)."""
    last_use: Dict[Tuple[int, int, int], int] = {}
    defined: Dict[Tuple[int, int, int], int] = {}
    for i, inst in enumerate(instructions):
        if inst.opcode == PipelineInstType.RUN:
            mesh = inst.dst_mesh
            for k in inst.input_keys:
                last_use[(k[0], k[1], mesh)] = i
            for k in inst.output_keys:
                defined[(k[0], k[1], mesh)] = i
        elif inst.opcode == PipelineInstType.RESHARD:
            last_use[(inst.var_key[0], inst.var_key[1], inst.src_mesh)] = i
            defined[(inst.var_key[0], inst.var_key[1], inst.dst_mesh)] = i
    out: List[PipelineInstruction] = []
    frees_at: Dict[int, List[Tuple[int, int, int]]] = {}
    for key, i in last_use.items():
        if key in protected_keys:
            continue
        if key not in defined:
            continue  # inputs placed at launch are managed by the driver
        frees_at.setdefault(i, []).append(key)
    for i, inst in enumerate(instructions):
        out.append(inst)
        if i in frees_at:
            out.append(
                PipelineInstruction(PipelineInstType.FREE,
                                    free_keys=frees_at[i]))
    return out
