"""Stage construction: cluster layers into stages and assign submeshes.

Analog of ref ``alpa/pipeline_parallel/stage_construction.py`` (SURVEY.md
§2.4).  This module provides the option surface
(``UniformStageOption``/``ManualStageOption``/``AutoStageOption``), submesh
enumeration, and mesh slicing; the OSDI'22 auto DP algorithm lives in
``stage_dp.py`` (with a C++ native implementation) and is driven from here
when ``AutoStageOption`` is used.
"""
import dataclasses
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from alpa_tpu.device_mesh import VirtualPhysicalMesh
from alpa_tpu.telemetry import trace as _ttrace

logger = logging.getLogger(__name__)


def _cal_key_parts() -> List[str]:
    """Calibration-store fingerprint as extra cache-key parts (ISSUE 12):
    empty under ``replan_mode=off`` — keys stay byte-identical to a
    build without calibration — else one ``cal:<fingerprint>`` part, so
    a measured-cost re-solve caches separately and a warm restart with
    an unchanged store replays it with zero solves."""
    from alpa_tpu.telemetry.calibration import calibration_cache_token
    tok = calibration_cache_token()
    return [tok] if tok else []


@dataclasses.dataclass
class StageOption:
    """Base (ref stage_construction.py)."""


@dataclasses.dataclass
class UniformStageOption(StageOption):
    """Evenly assign layers to stages = meshes (ref :70)."""
    num_stages: Optional[int] = None


@dataclasses.dataclass
class ManualStageOption(StageOption):
    """Explicit layer->stage and stage->submesh assignment (ref :57)."""
    forward_stage_layer_ids: List[List[int]] = None
    submesh_physical_shapes: List[Sequence[int]] = None
    submesh_logical_shapes: List[Sequence[int]] = None
    submesh_autosharding_option_dicts: List[Dict] = None


@dataclasses.dataclass
class AutoStageOption(StageOption):
    """Search layer->stage clustering + submesh shapes with the OSDI'22 DP
    (ref :28)."""
    submesh_physical_shape_space: str = "power_of_two"
    # NOTE: logical-shape search within each submesh is delegated to the
    # per-stage intra-op planner's mesh-shape search; this field is kept
    # for reference API parity and logged if set to a non-default.
    submesh_logical_shape_space: str = "single_node_model_parallel"
    # Prune DP thresholds above tolerance * (best balanced stage cost).
    stage_imbalance_tolerance: float = np.inf
    # True (default): exact merged-span ILP comm costs for small search
    # spaces, additive per-layer ILP (prefix sums) for large ones.
    # False: exact merged-span ILP everywhere (slower; large merged spans
    # may hit the solver time limit).
    use_hlo_cost_model: bool = True
    profiling_database_filename: Optional[str] = None
    # "cost_model" (default) | "measured": compile + time the shortlisted
    # candidate stages on real devices (ref ProfileWorker path; SURVEY §7
    # hard part 2 — cost model default, real profiling opt-in)
    profiling_mode: str = "cost_model"
    # max candidates compiled+timed in "measured" mode
    measured_candidates_limit: int = 16
    # concurrent compile workers for "measured" mode (timing stays serial)
    measured_compile_workers: int = 4
    # Path to an .npz caching the (costs, mem_param, mem_act) tensors for
    # this model+mesh (the analog of ref compute-cost-<time>.npy,
    # stage_profiling.py:53).  Loaded when the content key matches;
    # recomputed and overwritten otherwise.
    cached_compute_cost: Optional[str] = None
    # Per-device memory budget in bytes (None = unconstrained).
    memory_budget_per_device: Optional[float] = None


def get_submesh_choices(num_hosts: int, num_devices_per_host: int,
                        space: str = "power_of_two"
                        ) -> List[Tuple[int, int]]:
    """Enumerate candidate submesh shapes (ref get_submesh_choices:414):
    (1, 2^k) within a host plus (k, full host) across hosts."""
    choices = []
    i = 1
    while i <= num_devices_per_host:
        choices.append((1, i))
        i *= 2
    assert choices[-1][1] == num_devices_per_host, (
        "num_devices_per_host must be a power of two")
    if space == "all":
        for k in range(2, num_hosts + 1):
            choices.append((k, num_devices_per_host))
    elif space == "power_of_two":
        k = 2
        while k <= num_hosts:
            choices.append((k, num_devices_per_host))
            k *= 2
    elif space == "small_power_of_two":
        k = 2
        while k <= min(num_hosts, 4):
            choices.append((k, num_devices_per_host))
            k *= 2
    else:
        raise ValueError(f"invalid submesh space: {space!r}")
    return choices


def get_sliced_virtual_submeshes(virtual_mesh: VirtualPhysicalMesh,
                                 submesh_shapes: List[Sequence[int]]
                                 ) -> List[VirtualPhysicalMesh]:
    """Carve the cluster into the requested submeshes
    (ref get_sliced_virtual_submeshes:529).

    Host-spanning submeshes take whole hosts; sub-host submeshes pack into
    hosts left to right.
    """
    num_hosts = virtual_mesh.num_hosts
    ndph = virtual_mesh.num_devices_per_host
    total_requested = sum(int(np.prod(s)) for s in submesh_shapes)
    assert total_requested <= virtual_mesh.num_devices, (
        f"requested {total_requested} devices > {virtual_mesh.num_devices}")
    # Pack largest-first (whole-host slices before sub-host fragments) so
    # fragments fill the gaps — mirrors ref stage_construction.py:536-539's
    # size-sorted packing; results are returned in the original order.
    order = sorted(range(len(submesh_shapes)),
                   key=lambda i: (-int(submesh_shapes[i][0]),
                                  -int(np.prod(submesh_shapes[i]))))
    submeshes = [None] * len(submesh_shapes)
    host_ptr = 0
    dev_ptr = 0
    for i in order:
        h, d = int(submesh_shapes[i][0]), int(submesh_shapes[i][1])
        if h > 1 or d == ndph:
            # whole-host slices
            if dev_ptr != 0:
                host_ptr += 1
                dev_ptr = 0
            assert host_ptr + h <= num_hosts, (
                f"not enough hosts packing submeshes {submesh_shapes}")
            sub = virtual_mesh.slice_2d(range(host_ptr, host_ptr + h),
                                        range(d))
            host_ptr += h
        else:
            if dev_ptr + d > ndph:
                host_ptr += 1
                dev_ptr = 0
            assert host_ptr < num_hosts, (
                f"not enough devices packing submeshes {submesh_shapes}")
            sub = virtual_mesh.slice_2d([host_ptr],
                                        range(dev_ptr, dev_ptr + d))
            dev_ptr += d
        submeshes[i] = sub
    return submeshes


def uniform_layer_to_stage(num_layers: int, num_stages: int
                           ) -> List[List[int]]:
    """Evenly group forward layers into stages."""
    base, rem = divmod(num_layers, num_stages)
    out, start = [], 0
    for i in range(num_stages):
        size = base + (1 if i < rem else 0)
        out.append(list(range(start, start + size)))
        start += size
    return out


def cluster_layers_and_slice_mesh(
        num_forward_layers: int,
        virtual_mesh: VirtualPhysicalMesh,
        stage_option: Optional[StageOption],
        layer_flops: Optional[Sequence[float]] = None,
        layer_comps=None,
        donation_mapping=None,
        num_micro_batches: int = 1,
        auto_sharding_option=None,
        objective: str = "training",
        schedule: str = "1f1b"):
    """Decide (forward_stage_layer_ids, submeshes, logical shapes, per-stage
    autosharding dicts) (ref cluster_layers_and_slice_mesh:571)."""
    stage_option = stage_option or UniformStageOption()

    if isinstance(stage_option, ManualStageOption):
        fwd_ids = stage_option.forward_stage_layer_ids
        phys_shapes = stage_option.submesh_physical_shapes
        logical_shapes = (stage_option.submesh_logical_shapes or
                          [None] * len(fwd_ids))
        as_dicts = (stage_option.submesh_autosharding_option_dicts or
                    [{}] * len(fwd_ids))
        submeshes = get_sliced_virtual_submeshes(virtual_mesh, phys_shapes)
        return fwd_ids, submeshes, logical_shapes, as_dicts

    if isinstance(stage_option, AutoStageOption):
        from alpa_tpu.compile_cache import cache_enabled, get_compile_cache
        from alpa_tpu.pipeline_parallel.stage_dp import auto_stage_dp

        # The DP decision is a pure function of the layer jaxprs, the
        # cluster extent, and the options — replay it from the compile
        # cache (submeshes are re-sliced from the live virtual mesh; only
        # their shapes are persisted).
        cache = key = None
        if cache_enabled():
            cache = get_compile_cache()
            comp_texts = [str(c.closed_jaxpr() if hasattr(c, "closed_jaxpr")
                              else c) for c in (layer_comps or [])]
            key = cache.make_key("stage_dp", [
                "cluster_layers_and_slice_mesh",
                repr(num_forward_layers),
                repr((virtual_mesh.num_hosts,
                      virtual_mesh.num_devices_per_host)),
                stage_option,
                repr(list(layer_flops) if layer_flops is not None else None),
                repr(num_micro_batches),
                auto_sharding_option if auto_sharding_option is not None
                else "no-as-option",
                objective,
                schedule,
            ] + comp_texts + _cal_key_parts())
            entry = cache.get("stage_dp", key)
            if entry is not None:
                try:
                    submeshes = get_sliced_virtual_submeshes(
                        virtual_mesh, entry["phys_shapes"])
                    cache.record_saved_seconds(
                        "stage_dp", entry.get("solve_seconds", 0.0))
                    _ttrace.instant("stage-dp-cache-hit", "compile")
                    return (entry["fwd_ids"], submeshes,
                            entry["logical_shapes"], entry["as_dicts"])
                except Exception:  # pylint: disable=broad-except
                    logger.warning("cached stage-DP decision failed to "
                                   "replay; re-solving", exc_info=True)

        import time
        tic = time.time()
        with _ttrace.span("stage-dp", "compile",
                          {"layers": num_forward_layers}
                          if _ttrace.enabled() else None):
            fwd_ids, submeshes, logical_shapes, as_dicts = auto_stage_dp(
                num_forward_layers, virtual_mesh, stage_option,
                layer_flops, layer_comps, num_micro_batches,
                auto_sharding_option, objective=objective,
                schedule=schedule)
        if cache is not None and key is not None:
            solve_seconds = time.time() - tic
            cache.record_solve_seconds("stage_dp", solve_seconds)
            cache.put("stage_dp", key, {
                "fwd_ids": [list(s) for s in fwd_ids],
                "phys_shapes": [(sub.num_hosts, sub.num_devices_per_host)
                                for sub in submeshes],
                "logical_shapes": list(logical_shapes),
                "as_dicts": list(as_dicts),
                "solve_seconds": solve_seconds,
            })
        return fwd_ids, submeshes, logical_shapes, as_dicts

    # Uniform: num_stages = num_hosts (or all devices as equal slices)
    num_stages = (stage_option.num_stages if isinstance(
        stage_option, UniformStageOption) and stage_option.num_stages else
        None)
    if num_stages is None:
        num_stages = (virtual_mesh.num_hosts if virtual_mesh.num_hosts > 1
                      else min(num_forward_layers,
                               virtual_mesh.num_devices_per_host))
    num_stages = min(num_stages, num_forward_layers)
    fwd_ids = uniform_layer_to_stage(num_forward_layers, num_stages)
    # split devices evenly
    if virtual_mesh.num_hosts >= num_stages and \
            virtual_mesh.num_hosts % num_stages == 0:
        hosts_per = virtual_mesh.num_hosts // num_stages
        phys_shapes = [(hosts_per, virtual_mesh.num_devices_per_host)
                       for _ in range(num_stages)]
    else:
        devs_per = virtual_mesh.num_devices // num_stages
        assert devs_per >= 1 and \
            virtual_mesh.num_devices % num_stages == 0, (
                f"cannot split {virtual_mesh.num_devices} devices into "
                f"{num_stages} equal pipeline stages; pass a stage_option "
                f"with num_stages dividing the device count")
        phys_shapes = [(1, devs_per) for _ in range(num_stages)]
    submeshes = get_sliced_virtual_submeshes(virtual_mesh, phys_shapes)
    logical_shapes = [None] * num_stages
    as_dicts = [{}] * num_stages
    return fwd_ids, submeshes, logical_shapes, as_dicts
