"""Cross-mesh resharding: tiling math + transfer planning.

Analog of ref ``alpa/pipeline_parallel/cross_mesh_resharding.py`` +
``resharding_tensor.py`` (SURVEY.md §2.4, hard part #1 in §7): when an
activation produced with sharding A on mesh X is consumed with sharding B
on mesh Y, plan the minimal set of tile transfers.

TPU redesign: the reference drives NCCL P2P per tile; here each planned
``TileSlice`` transfer executes as a ``jax.device_put`` of the source
shard slice to the destination devices (the jax runtime carries it over
ICI/DCN), and whole-array moves use a single device_put.  The value of the
planner is (a) minimal bytes on DCN — only the tiles a destination
actually needs move, with load-balanced source selection when a tile is
replicated on several sources (ref load-balancing solvers :1448-1884) —
and (b) the **local-allgather rewrite** (MLSys'23, ref
``_rewrite_allgather_spec:995``): when the destination sharding replicates
over some mesh axis, send each destination device only a 1/k slice and
all-gather inside the destination mesh over ICI instead of pulling full
tiles over DCN.
"""
import dataclasses
import itertools
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from alpa_tpu import fault
from alpa_tpu.telemetry import metrics as _tmetrics
from alpa_tpu.telemetry import trace as _ttrace

logger = logging.getLogger(__name__)


########################################
# tiling math (ref resharding_tensor.py)
########################################

Slice = Tuple[int, int]


@dataclasses.dataclass(frozen=True)
class Tile:
    """An axis-aligned hyper-rectangle of the global array
    (ref resharding_tensor.py:197)."""
    slices: Tuple[Slice, ...]

    @property
    def shape(self):
        return tuple(b - a for a, b in self.slices)

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.slices else 1

    def intersect(self, other: "Tile") -> Optional["Tile"]:
        out = []
        for (a1, b1), (a2, b2) in zip(self.slices, other.slices):
            lo, hi = max(a1, a2), min(b1, b2)
            if lo >= hi:
                return None
            out.append((lo, hi))
        return Tile(tuple(out))

    def offset_in(self, outer: "Tile") -> Tuple[Slice, ...]:
        """This tile's index range relative to ``outer``'s origin."""
        return tuple((a - oa, b - oa)
                     for (a, b), (oa, _ob) in zip(self.slices, outer.slices))


@dataclasses.dataclass
class TileSlice:
    """A piece of a source tile headed to one destination
    (ref resharding_tensor.py:234)."""
    tile: Tile                 # global coordinates of the moved piece
    src_shard_index: int       # which source shard holds it
    offset_in_src: Tuple[Slice, ...]


class VirtualDistributedArray:
    """Sharding-as-tiling view of one array on one mesh
    (ref resharding_tensor.py:25).

    ``shard_tiles``: per device-shard the global Tile it holds;
    replicated shardings produce identical tiles on several shards.
    """

    def __init__(self, shape: Tuple[int, ...], device_tiles: List[Tile],
                 device_ids: List[int]):
        self.shape = tuple(shape)
        self.device_tiles = device_tiles
        self.device_ids = device_ids

    @classmethod
    def from_sharding(cls, shape, sharding) -> "VirtualDistributedArray":
        """Build from a NamedSharding via its device index map."""
        index_map = sharding.devices_indices_map(tuple(shape))
        tiles, ids = [], []
        for dev, idx in index_map.items():
            sl = tuple(
                (s.start or 0, s.stop if s.stop is not None else dim)
                for s, dim in zip(idx, shape)) if len(shape) else ()
            tiles.append(Tile(sl))
            ids.append(dev.id)
        return cls(shape, tiles, ids)

    @property
    def unique_tiles(self) -> Dict[Tuple, List[int]]:
        """tile slices -> list of shard positions holding it."""
        out: Dict[Tuple, List[int]] = {}
        for i, t in enumerate(self.device_tiles):
            out.setdefault(t.slices, []).append(i)
        return out


########################################
# transfer plan (ref ReshardingTaskSpec:674)
########################################


@dataclasses.dataclass
class DstTileRequest:
    """One destination shard's needs: the tile slices covering it."""
    dst_shard_index: int
    dst_tile: Tile
    srcs: List[TileSlice]


@dataclasses.dataclass
class ReshardingTaskSpec:
    """Complete plan for one (array, src sharding, dst sharding) pair
    (ref cross_mesh_resharding.py:674)."""
    shape: Tuple[int, ...]
    requests: List[DstTileRequest]
    # total bytes crossing meshes under this plan
    transfer_bytes: float = 0.0
    # whether the local-allgather rewrite applies (dst replicated axes
    # served by intra-mesh collectives instead of repeated sends)
    allgather_rewrite: bool = False
    # device ids aligned with shard indexes (source / destination VDAs),
    # so an executor can route each planned TileSlice to real devices
    src_device_ids: Tuple[int, ...] = ()
    dst_device_ids: Tuple[int, ...] = ()
    # per source/destination shard, the FULL tile it holds; src_tiles lets
    # the executor verify the runtime array's layout matches the plan
    src_tiles: Tuple[Tile, ...] = ()
    dst_tiles: Tuple[Tile, ...] = ()
    # element size of the payload dtype (ISSUE 4 link accounting)
    itemsize: int = 1
    # bytes crossing under broadcast execution: each unique fetched tile
    # of each replica group crosses ONCE (vs transfer_bytes, which counts
    # the send_recv plan — once per requesting dst shard)
    broadcast_bytes: float = 0.0
    # planner objective (ISSUE 4, arXiv:2211.05322 load balancing): the
    # busiest single link — max over per-src-device egress bytes and
    # per-dst-device ingress bytes — under this plan's routing, and under
    # the naive routing (first-holder selection) for comparison
    max_link_bytes: float = 0.0
    max_link_bytes_naive: float = 0.0
    # same objective for broadcast execution (unique tiles routed across
    # replica-group members vs all to the group's first holder)
    max_link_bytes_broadcast: float = 0.0
    max_link_bytes_broadcast_naive: float = 0.0
    # greedy least-loaded-egress ordering of the plan's (request, src)
    # moves; empty = plan order (see plan_send_order)
    send_order: Tuple[Tuple[int, int], ...] = ()
    # whether load-balanced source selection / routing was applied
    loadbalanced: bool = True
    # ---- collective lowering (ISSUE 7) ----
    # chosen per-edge strategy for the executor path (one of
    # RESHARD_STRATEGIES); generalizes the allgather_rewrite boolean —
    # the tile ``requests`` above stay the interpreter/tiled-mode source
    # of truth, this field only drives the register/overlap executors
    strategy: str = "direct_p2p"
    # per-candidate cost-model estimates in seconds (reports / tooling)
    strategy_costs: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    # per-candidate cross-mesh link stats: candidate -> dict with
    # max_link_messages / max_link_bytes / total_bytes of the wire leg
    strategy_stats: Dict[str, Dict[str, float]] = dataclasses.field(
        default_factory=dict)
    # the CHOSEN strategy's busiest-link message count and total wire
    # bytes (feeds the "link" wire-emulation model and reports)
    wire_messages: int = 1
    wire_bytes: float = 0.0
    # whether the strategy decision came from the compile cache
    strategy_cached: bool = False

    def total_tiles(self):
        return sum(len(r.srcs) for r in self.requests)


def _cover_tile(dst_tile: Tile, src_vda: VirtualDistributedArray,
                load: Dict[int, float], itemsize: int,
                balance: bool = True) -> List[TileSlice]:
    """Cover ``dst_tile`` with pieces of source shards, choosing the least
    loaded source when a piece is replicated (ref load-balanced sender
    selection, cross_mesh_resharding.py:1448+).  ``balance=False`` always
    picks the first holder — the naive baseline the planner reports
    against."""
    pieces: List[TileSlice] = []
    # Collect candidate intersections per unique source tile.
    for tile_slices, holders in src_vda.unique_tiles.items():
        src_tile = Tile(tile_slices)
        inter = dst_tile.intersect(src_tile)
        if inter is None:
            continue
        if balance:
            # pick least-loaded holder
            best = min(holders, key=lambda i: load.get(i, 0.0))
        else:
            best = holders[0]
        load[best] = load.get(best, 0.0) + inter.size * itemsize
        pieces.append(
            TileSlice(inter, best, inter.offset_in(src_tile)))
    return pieces


def _build_requests(src_vda: VirtualDistributedArray,
                    dst_vda: VirtualDistributedArray,
                    itemsize: int, allgather_rewrite: bool,
                    balance: bool) -> Tuple[List[DstTileRequest], float]:
    """The tile-coverage core of :func:`plan_resharding`: one
    DstTileRequest per destination shard (or per replica-group split part
    under the allgather rewrite), plus the total planned cross bytes."""
    dst_unique = dst_vda.unique_tiles
    load: Dict[int, float] = {}
    requests: List[DstTileRequest] = []
    total = 0.0
    if allgather_rewrite:
        # fetch each unique tile once, split across its replica group
        for tile_slices, holders in dst_unique.items():
            dst_tile = Tile(tile_slices)
            k = len(holders)
            # split along the largest dim divisible by k (fallback: no
            # split, single fetch)
            dims = dst_tile.shape
            split_dim = None
            for d in np.argsort(dims)[::-1]:
                if dims[d] % k == 0 and dims[d] >= k:
                    split_dim = int(d)
                    break
            for gi, holder in enumerate(holders):
                if split_dim is None and gi > 0:
                    continue  # single member fetches; others gather
                if split_dim is None:
                    part = dst_tile
                else:
                    a, b = dst_tile.slices[split_dim]
                    step = (b - a) // k
                    sl = list(dst_tile.slices)
                    sl[split_dim] = (a + gi * step, a + (gi + 1) * step)
                    part = Tile(tuple(sl))
                srcs = _cover_tile(part, src_vda, load, itemsize, balance)
                requests.append(DstTileRequest(holder, part, srcs))
                total += sum(s.tile.size for s in srcs) * itemsize
    else:
        for i, dst_tile in enumerate(dst_vda.device_tiles):
            srcs = _cover_tile(dst_tile, src_vda, load, itemsize, balance)
            requests.append(DstTileRequest(i, dst_tile, srcs))
            total += sum(s.tile.size for s in srcs) * itemsize
    return requests, total


########################################
# link-load accounting + broadcast routing (ISSUE 4)
########################################


def route_broadcast(spec: ReshardingTaskSpec,
                    loadbalance: bool = True) -> Dict[Tuple, int]:
    """Route each unique fetched tile of each replica group to ONE group
    member for broadcast execution.

    Naive routing (``loadbalance=False``, the pre-ISSUE-4 behavior) sends
    every unique tile to the group's FIRST holder, concentrating the
    whole group's ingress on one device; balanced routing spreads unique
    tiles across the group by least accumulated ingress bytes, so a
    source tile fanning out to many destination devices loads each
    destination link evenly (arXiv:2211.05322 send-order balancing).  The
    intra-mesh assembly leg unions pieces across the whole group, so any
    member may receive any tile.

    Returns ``(group_tile_slices, tile_slices) -> dst shard index``.
    """
    groups = VirtualDistributedArray(
        spec.shape, list(spec.dst_tiles),
        list(spec.dst_device_ids)).unique_tiles
    itemsize = spec.itemsize or 1
    ingress: Dict[int, float] = {}
    routes: Dict[Tuple, int] = {}
    for req in spec.requests:
        gslices = spec.dst_tiles[req.dst_shard_index].slices
        holders = groups[gslices]
        for ts in req.srcs:
            key = (gslices, ts.tile.slices)
            if key in routes:
                continue
            if loadbalance:
                target = min(holders,
                             key=lambda h: (ingress.get(h, 0.0), h))
            else:
                target = holders[0]
            routes[key] = target
            ingress[target] = (ingress.get(target, 0.0) +
                               ts.tile.size * itemsize)
    return routes


def compute_link_loads(spec: ReshardingTaskSpec,
                       broadcast: bool = False,
                       loadbalance: bool = True) -> Dict[str, Any]:
    """Per-device link loads of one plan: egress bytes per source device,
    ingress bytes per destination device, their max (the planner's
    max-link objective), and the total bytes crossing.

    ``broadcast=True`` accounts broadcast execution — each unique fetched
    tile of a replica group crosses once, to the device
    :func:`route_broadcast` picks; otherwise every (request, src) move of
    the send_recv plan is counted."""
    itemsize = spec.itemsize or 1
    egress: Dict[int, float] = {}
    ingress: Dict[int, float] = {}
    total = 0.0
    if broadcast:
        routes = route_broadcast(spec, loadbalance)
        seen = set()
        for req in spec.requests:
            gslices = spec.dst_tiles[req.dst_shard_index].slices
            for ts in req.srcs:
                key = (gslices, ts.tile.slices)
                if key in seen:
                    continue
                seen.add(key)
                b = ts.tile.size * itemsize
                src_dev = spec.src_device_ids[ts.src_shard_index]
                dst_dev = spec.dst_device_ids[routes[key]]
                egress[src_dev] = egress.get(src_dev, 0.0) + b
                ingress[dst_dev] = ingress.get(dst_dev, 0.0) + b
                total += b
    else:
        for req in spec.requests:
            dst_dev = spec.dst_device_ids[req.dst_shard_index]
            for ts in req.srcs:
                b = ts.tile.size * itemsize
                src_dev = spec.src_device_ids[ts.src_shard_index]
                egress[src_dev] = egress.get(src_dev, 0.0) + b
                ingress[dst_dev] = ingress.get(dst_dev, 0.0) + b
                total += b
    links = list(egress.values()) + list(ingress.values())
    return {
        "egress": egress,
        "ingress": ingress,
        "total_bytes": total,
        "max_link_bytes": max(links) if links else 0.0,
    }


def plan_send_order(spec: ReshardingTaskSpec
                    ) -> Tuple[Tuple[int, int], ...]:
    """Greedy send ordering: repeatedly issue the pending (request, src)
    move whose SOURCE device has the least bytes already issued, so no
    single egress link runs far ahead of the others early in the step
    (the send-order half of arXiv:2211.05322's balancing; ties break by
    plan order for determinism)."""
    itemsize = spec.itemsize or 1
    pending = [(ri, si) for ri, req in enumerate(spec.requests)
               for si in range(len(req.srcs))]
    issued: Dict[int, float] = {}
    order: List[Tuple[int, int]] = []
    while pending:
        best = min(
            pending,
            key=lambda p: (issued.get(
                spec.src_device_ids[
                    spec.requests[p[0]].srcs[p[1]].src_shard_index],
                0.0), p))
        pending.remove(best)
        ts = spec.requests[best[0]].srcs[best[1]]
        dev = spec.src_device_ids[ts.src_shard_index]
        issued[dev] = issued.get(dev, 0.0) + ts.tile.size * itemsize
        order.append(best)
    return tuple(order)


# process-global planner counters, kept in the central metrics registry
# (ISSUE 5: exported on GET /metrics as alpa_resharding_*) and surfaced
# by monitoring.get_overlap_stats with the pre-telemetry dict shape.
_PLANNER_REG = _tmetrics.get_registry()
_PLANS = _PLANNER_REG.counter(
    "alpa_resharding_plans_total", "Resharding plans computed")
_PLAN_BYTES = _PLANNER_REG.counter(
    "alpa_resharding_planned_bytes_total",
    "Planned cross-mesh payload bytes (send_recv accounting)")
_PLAN_BCAST_BYTES = _PLANNER_REG.counter(
    "alpa_resharding_planned_broadcast_bytes_total",
    "Planned cross-mesh payload bytes under broadcast routing")
_PLAN_MAX_LINK = _PLANNER_REG.gauge(
    "alpa_resharding_max_link_bytes",
    "Max per-device link bytes over all plans, balanced routing")
_PLAN_MAX_LINK_NAIVE = _PLANNER_REG.gauge(
    "alpa_resharding_max_link_bytes_naive",
    "Max per-device link bytes over all plans, naive routing")


def _record_plan(spec: ReshardingTaskSpec):
    _PLANS.inc()
    _PLAN_BYTES.inc(spec.transfer_bytes)
    _PLAN_BCAST_BYTES.inc(spec.broadcast_bytes)
    _PLAN_MAX_LINK.set_max(max(spec.max_link_bytes,
                               spec.max_link_bytes_broadcast))
    _PLAN_MAX_LINK_NAIVE.set_max(max(spec.max_link_bytes_naive,
                                     spec.max_link_bytes_broadcast_naive))


def get_planner_stats() -> Dict[str, float]:
    """Snapshot of the resharding planner counters (plans made, planned
    total/broadcast bytes, max-link objective balanced vs naive) — a
    thin view over the metrics registry, same dict shape as before."""
    return {
        "plans": int(_PLANS.value),
        "total_bytes": _PLAN_BYTES.value,
        "broadcast_bytes": _PLAN_BCAST_BYTES.value,
        "max_link_bytes": _PLAN_MAX_LINK.value,
        "max_link_bytes_naive": _PLAN_MAX_LINK_NAIVE.value,
    }


def reset_planner_stats():
    for fam in (_PLANS, _PLAN_BYTES, _PLAN_BCAST_BYTES, _PLAN_MAX_LINK,
                _PLAN_MAX_LINK_NAIVE):
        fam.reset()


def plan_resharding(shape: Tuple[int, ...],
                    itemsize: int,
                    src_sharding,
                    dst_sharding,
                    allow_allgather_rewrite: bool = True,
                    loadbalance: Optional[bool] = None
                    ) -> ReshardingTaskSpec:
    """Compute the transfer plan for one cross-mesh value
    (ref CrossMeshCommunicator._compile_resharding_specs:935).

    ``loadbalance`` (default: from
    ``global_config.resharding_loadbalance_mode``) selects balanced
    source-holder selection, broadcast fan-out routing, and greedy send
    ordering; off = first-holder / plan-order naive baseline.  Both
    variants' max-link objectives are computed so reports can show the
    balancing win without re-planning."""
    if loadbalance is None:
        from alpa_tpu.global_env import global_config
        loadbalance = (getattr(global_config,
                               "resharding_loadbalance_mode",
                               "normal") != "no_loadbalance")
    tok = _ttrace.begin("plan_resharding", "resharding")
    src_vda = VirtualDistributedArray.from_sharding(shape, src_sharding)
    dst_vda = VirtualDistributedArray.from_sharding(shape, dst_sharding)

    # Local-allgather rewrite (MLSys'23): if several destination shards
    # request the SAME tile (dst replicates over some axis), fetching it
    # once per replica wastes DCN.  Rewrite: each replica group member
    # fetches a disjoint 1/k slice; the destination mesh all-gathers over
    # ICI.  We mark the spec; the executor realizes the gather with a
    # resharded device_put + with_sharding_constraint (XLA collective).
    dst_unique = dst_vda.unique_tiles
    replication = max(len(v) for v in dst_unique.values()) \
        if dst_unique else 1
    allgather_rewrite = allow_allgather_rewrite and replication > 1

    requests, total = _build_requests(src_vda, dst_vda, itemsize,
                                      allgather_rewrite, loadbalance)

    spec = ReshardingTaskSpec(tuple(shape), requests, total,
                              allgather_rewrite,
                              src_device_ids=tuple(src_vda.device_ids),
                              dst_device_ids=tuple(dst_vda.device_ids),
                              src_tiles=tuple(src_vda.device_tiles),
                              dst_tiles=tuple(dst_vda.device_tiles),
                              itemsize=itemsize,
                              loadbalanced=bool(loadbalance))

    # planner objective: max-link bytes under this plan's routing …
    loads = compute_link_loads(spec, broadcast=False)
    spec.max_link_bytes = loads["max_link_bytes"]
    bloads = compute_link_loads(spec, broadcast=True,
                                loadbalance=loadbalance)
    spec.broadcast_bytes = bloads["total_bytes"]
    spec.max_link_bytes_broadcast = bloads["max_link_bytes"]
    # … and under the naive baseline (first-holder selection + routing),
    # re-covered only when they can differ
    if loadbalance:
        nreq, _ = _build_requests(src_vda, dst_vda, itemsize,
                                  allgather_rewrite, balance=False)
        nspec = dataclasses.replace(spec, requests=nreq)
        spec.max_link_bytes_naive = compute_link_loads(
            nspec, broadcast=False)["max_link_bytes"]
        spec.max_link_bytes_broadcast_naive = compute_link_loads(
            nspec, broadcast=True, loadbalance=False)["max_link_bytes"]
        spec.send_order = plan_send_order(spec)
    else:
        spec.max_link_bytes_naive = spec.max_link_bytes
        spec.max_link_bytes_broadcast_naive = spec.max_link_bytes_broadcast
    # collective lowering (ISSUE 7): pick the per-edge strategy by the
    # cost model (cache-backed so warm restarts replay identically) and
    # record the decision for dump_debug_info / reshard_tool
    try:
        strat, costs, cached = resolve_strategy(shape, itemsize,
                                                src_sharding, dst_sharding)
        opts = collective_options(shape, itemsize, src_sharding,
                                  dst_sharding)
        spec.strategy = strat if strat in opts else "direct_p2p"
        spec.strategy_costs = costs
        spec.strategy_stats = {k: dict(o["stats"])
                               for k, o in opts.items()}
        st = opts[spec.strategy]["stats"]
        spec.wire_messages = int(st["max_link_messages"])
        spec.wire_bytes = float(st["total_bytes"])
        spec.strategy_cached = bool(cached)
        _STRATEGY_COUNT.labels(spec.strategy).inc()
        _RECENT_PLANS.append({
            "shape": tuple(shape),
            "itemsize": int(itemsize),
            "src": _sharding_key(src_sharding),
            "dst": _sharding_key(dst_sharding),
            "strategy": spec.strategy,
            "costs": dict(costs),
            "cached": bool(cached),
            "wire_messages": spec.wire_messages,
            "wire_bytes": spec.wire_bytes,
            "transfer_bytes": spec.transfer_bytes,
            "max_link_bytes": spec.max_link_bytes,
        })
    except Exception:  # pylint: disable=broad-except
        logger.warning("collective strategy planning failed; "
                       "keeping direct_p2p", exc_info=True)
    _record_plan(spec)
    _ttrace.end(tok)
    return spec


def naive_transfer_bytes(shape, itemsize, dst_sharding,
                         mode: str = "send_recv") -> float:
    """Bytes moved by the naive plan (no dedup/allgather) — for tests and
    reporting.

    ``mode="send_recv"``: the full per-shard need of every destination
    shard crosses (a replicated destination pays once PER REPLICA).
    ``mode="broadcast"``: each unique destination tile crosses exactly
    once regardless of replication — the correct baseline for
    broadcast-mode execution, where counting per replica overstates the
    wire bytes k-fold (ISSUE 4 accounting audit)."""
    vda = VirtualDistributedArray.from_sharding(shape, dst_sharding)
    if mode == "broadcast":
        return float(sum(Tile(sl).size
                         for sl in vda.unique_tiles)) * itemsize
    if mode != "send_recv":
        raise ValueError(f"unknown naive_transfer_bytes mode: {mode}")
    return float(sum(t.size for t in vda.device_tiles)) * itemsize


########################################
# collective strategy planning (ISSUE 7)
########################################

# Per-edge lowering strategies, generalizing the allgather_rewrite
# boolean ("Memory-efficient array redistribution through portable
# collective communication", PAPERS.md):
#
# * ``direct_p2p`` — today's path: one cross-mesh device_put straight to
#   the destination sharding.
# * ``slice_all_gather`` — destination replicates over some mesh axis:
#   each destination device receives only a disjoint 1/k slice
#   cross-mesh and the destination mesh all-gathers over its own links.
# * ``all_to_all`` — destination is a permuted/transposed layout of the
#   source: land source-shaped shards 1:1 (one message per link), then
#   re-lay inside the destination mesh with an all-to-all.
# * ``reduce_scatter_gather`` — source is replicated / partial-
#   reducible: pull disjoint scattered pieces from distinct source
#   replicas, then gather inside the destination mesh.
RESHARD_STRATEGIES = ("direct_p2p", "slice_all_gather", "all_to_all",
                      "reduce_scatter_gather")

# intra-destination-mesh collective each strategy's second leg emits,
# charged from mesh_profiling's per-kind (alpha, beta) calibration
_STRATEGY_COLLECTIVE_KIND = {
    "direct_p2p": None,
    "slice_all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "reduce_scatter_gather": "reduce_scatter",
}


def _sharding_key(sharding) -> str:
    """Device-id-free canonical form of a NamedSharding (cache keys and
    reports): mesh axis sizes + partition spec."""
    try:
        return f"{dict(sharding.mesh.shape)}|{sharding.spec}"
    except Exception:  # pylint: disable=broad-except
        return str(sharding)


def _spec_entries(sharding, ndim) -> Optional[Tuple]:
    """PartitionSpec as a length-``ndim`` tuple of None | axis name.
    None (whole) when the spec uses tuple entries — the conservative
    strategies below skip those edges."""
    try:
        entries = tuple(sharding.spec)
    except Exception:  # pylint: disable=broad-except
        return None
    entries = entries + (None,) * (ndim - len(entries))
    if any(isinstance(e, (tuple, list)) for e in entries):
        return None
    return entries


def _mesh_axis_sizes(sharding) -> Dict[str, int]:
    return dict(sharding.mesh.shape)


def _replication(sharding, shape) -> int:
    vda = VirtualDistributedArray.from_sharding(shape, sharding)
    uniq = vda.unique_tiles
    return max(len(v) for v in uniq.values()) if uniq else 1


def _scatter_sharding(dst_sharding, shape):
    """The 1/k "slice" landing layout: the destination spec with unused
    destination-mesh axes attached to the largest still-whole dims they
    divide.  The gather leg restores the true destination layout."""
    from jax.sharding import NamedSharding, PartitionSpec
    entries = _spec_entries(dst_sharding, len(shape))
    if entries is None:
        return None
    entries = list(entries)
    sizes = _mesh_axis_sizes(dst_sharding)
    used = {e for e in entries if e is not None}
    changed = False
    for ax, k in sizes.items():
        if ax in used or k <= 1:
            continue
        cands = [(shape[d], d) for d, e in enumerate(entries)
                 if e is None and shape[d] % k == 0 and shape[d] >= k]
        if not cands:
            continue
        # largest dim, lowest index on ties: aligns the scatter with the
        # leading-dim shardings sources usually carry (fewer wire msgs)
        best = max(sz for sz, _ in cands)
        entries[min(d for sz, d in cands if sz == best)] = ax
        changed = True
    if not changed:
        return None
    return NamedSharding(dst_sharding.mesh, PartitionSpec(*entries))


def _translate_spec(src_sharding, dst_sharding, shape):
    """The source layout re-expressed on the destination mesh (the
    all-to-all landing layout), or None when the meshes' axis structures
    do not line up (conservative: same-named equal-size axes only)."""
    from jax.sharding import NamedSharding, PartitionSpec
    entries = _spec_entries(src_sharding, len(shape))
    if entries is None:
        return None
    src_sizes = _mesh_axis_sizes(src_sharding)
    dst_sizes = _mesh_axis_sizes(dst_sharding)
    for e in entries:
        if e is not None and dst_sizes.get(e) != src_sizes.get(e):
            return None
    return NamedSharding(dst_sharding.mesh, PartitionSpec(*entries))


def _strategy_link_stats(shape, itemsize, src_sharding,
                         landing_sharding) -> Dict[str, float]:
    """Cross-mesh wire-leg stats when each landing-layout shard pulls its
    tile from (load-balanced) source holders: busiest-link message count,
    busiest-link bytes, and total bytes crossing."""
    src_vda = VirtualDistributedArray.from_sharding(shape, src_sharding)
    land_vda = VirtualDistributedArray.from_sharding(shape,
                                                     landing_sharding)
    load: Dict[int, float] = {}
    eg_m: Dict[int, int] = {}
    in_m: Dict[int, int] = {}
    eg_b: Dict[int, float] = {}
    in_b: Dict[int, float] = {}
    total = 0.0
    for i, dtile in enumerate(land_vda.device_tiles):
        ddev = land_vda.device_ids[i]
        for ts in _cover_tile(dtile, src_vda, load, itemsize, True):
            b = ts.tile.size * itemsize
            sdev = src_vda.device_ids[ts.src_shard_index]
            eg_m[sdev] = eg_m.get(sdev, 0) + 1
            in_m[ddev] = in_m.get(ddev, 0) + 1
            eg_b[sdev] = eg_b.get(sdev, 0.0) + b
            in_b[ddev] = in_b.get(ddev, 0.0) + b
            total += b
    msgs = list(eg_m.values()) + list(in_m.values())
    byts = list(eg_b.values()) + list(in_b.values())
    return {
        "max_link_messages": int(max(msgs)) if msgs else 0,
        "max_link_bytes": float(max(byts)) if byts else 0.0,
        "total_bytes": float(total),
    }


def collective_options(shape, itemsize, src_sharding, dst_sharding
                       ) -> Dict[str, Dict[str, Any]]:
    """Eligible strategies for one edge, in preference (tie-break)
    order: name -> {"landing": sharding the wire leg targets, "kind":
    intra-mesh collective kind (None for direct), "stats": wire-leg link
    stats}.  ``direct_p2p`` is always present."""
    opts: Dict[str, Dict[str, Any]] = {}

    def add(name, landing):
        opts[name] = {
            "landing": landing,
            "kind": _STRATEGY_COLLECTIVE_KIND[name],
            "stats": _strategy_link_stats(shape, itemsize, src_sharding,
                                          landing),
        }

    add("direct_p2p", dst_sharding)
    try:
        src_repl = _replication(src_sharding, shape)
        dst_repl = _replication(dst_sharding, shape)
    except Exception:  # pylint: disable=broad-except
        return opts
    dst_entries = _spec_entries(dst_sharding, len(shape))
    scattered = _scatter_sharding(dst_sharding, shape) \
        if dst_entries is not None else None
    if src_repl > 1 and scattered is not None:
        # distinct source replicas serve disjoint scattered pieces
        add("reduce_scatter_gather", scattered)
    if dst_repl > 1 and scattered is not None:
        add("slice_all_gather", scattered)
    if src_repl == 1 and dst_repl == 1:
        translated = _translate_spec(src_sharding, dst_sharding, shape)
        if (translated is not None and dst_entries is not None and
                _spec_entries(translated, len(shape)) != dst_entries):
            add("all_to_all", translated)
    return opts


def _strategy_cost(stats: Dict[str, float], kind: Optional[str],
                   nbytes: float, cal, lat: float, bw: float,
                   model: str, intra_us: Optional[float] = None) -> float:
    """Estimated edge seconds = cross-mesh wire leg (mirroring the
    active emulation model, so auto selection is honest about what it is
    timed against) + intra-destination collective leg from
    mesh_profiling's calibrated (alpha, beta) cost dicts.

    ``intra_us`` (ISSUE 12): a measured collective cost from the
    calibration store that supersedes the alpha-beta estimate for the
    intra leg."""
    if model == "link":
        cross = lat * stats["max_link_messages"]
    else:                       # "call": one idle per transfer call
        cross = lat
    if bw:
        cross += stats["max_link_bytes"] / bw
    intra = 0.0
    if intra_us is not None:
        intra = intra_us * 1e-6
    elif kind is not None and cal is not None:
        ab = cal.alpha_beta(kind)
        if ab is not None:
            intra = ab[0] + ab[1] * nbytes
    return cross + intra


def choose_strategy(shape, itemsize, src_sharding, dst_sharding
                    ) -> Tuple[str, Dict[str, float],
                               Dict[str, Dict[str, Any]]]:
    """Pick the cheapest eligible strategy for one cross-mesh edge
    (``global_config.reshard_strategy`` forces a specific one when not
    "auto"; ineligible forced strategies fall back to direct_p2p).
    Returns (strategy, per-candidate costs, candidate options).

    Under ``replan_mode != off`` (ISSUE 12) the calibration store
    supersedes the analytic price wherever it has enough measured
    samples: per-candidate wire cost by the edge signature (only the
    strategies that actually ran get measured overrides — the rest stay
    analytic, so a mispriced edge can flip the choice), and the intra
    collective leg by mesh_profiling-style (kind, byte-bucket) keys.
    The analytic prediction each override supersedes is recorded on the
    entry as the drift denominator."""
    from alpa_tpu.global_env import global_config
    from alpa_tpu.mesh_profiling import get_effective_calibration
    from alpa_tpu.telemetry import calibration as _calibration
    opts = collective_options(shape, itemsize, src_sharding, dst_sharding)
    try:
        cal = get_effective_calibration()
    except Exception:  # pylint: disable=broad-except
        cal = None
    lat = global_config.resharding_transfer_latency_s
    bw = getattr(global_config, "resharding_wire_bandwidth", 0.0)
    model = getattr(global_config, "resharding_wire_model", "call")
    nbytes = float(np.prod(shape, dtype=np.int64)) * itemsize \
        if shape else float(itemsize)
    store = _calibration.get_calibration_store() \
        if _calibration.replan_active() else None

    def _intra_us(kind):
        if store is None or kind is None:
            return None
        return store.measured_us(
            "collective", _calibration.collective_signature(kind, nbytes))

    costs = {name: _strategy_cost(o["stats"], o["kind"], nbytes, cal,
                                  lat, bw, model,
                                  intra_us=_intra_us(o["kind"]))
             for name, o in opts.items()}
    if store is not None:
        src_key = _sharding_key(src_sharding)
        dst_key = _sharding_key(dst_sharding)
        # Codec bucket (ISSUE 19): a quantized edge moves ~4x fewer
        # bytes, so its measured samples must not re-price the
        # full-precision signature.  Mirror the transfer factory's
        # eligibility (fp32/bf16 payload over the min-bytes floor).
        q_mode = getattr(global_config, "reshard_quantize", "off")
        q_min = getattr(global_config, "reshard_quantize_min_bytes",
                        65536)
        codec = q_mode if (q_mode != "off" and itemsize in (2, 4) and
                           nbytes >= q_min) else None
        for name in opts:
            sig = _calibration.wire_signature(shape, itemsize, src_key,
                                              dst_key, name, codec=codec)
            # attach the analytic price this entry would supersede
            # (drift denominator) before consulting it
            store.set_modeled("reshard_wire", sig, costs[name] * 1e6)
            measured = store.measured_us("reshard_wire", sig)
            if measured is not None:
                costs[name] = measured * 1e-6
    forced = getattr(global_config, "reshard_strategy", "auto")
    if forced != "auto":
        chosen = forced if forced in opts else "direct_p2p"
    else:
        order = list(opts)
        chosen = min(order, key=lambda n: (costs[n], order.index(n)))
    return chosen, costs, opts


def resolve_strategy(shape, itemsize, src_sharding, dst_sharding
                     ) -> Tuple[str, Dict[str, float], bool]:
    """Cache-backed :func:`choose_strategy`: per-edge decisions persist
    in the compile cache (namespace ``reshard_strategy``), so a warm
    restart replays the identical plan without re-costing.  The key
    covers the edge signature AND every knob the cost model reads —
    plus, when replanning is active, the calibration-store fingerprint
    (ISSUE 12): a calibrated re-solve caches like any other plan, an
    unchanged store replays it, and ``replan_mode=off`` keys stay
    byte-identical to a build without calibration.
    Returns (strategy, costs, from_cache)."""
    from alpa_tpu.compile_cache import cache_enabled, get_compile_cache
    from alpa_tpu.global_env import global_config
    from alpa_tpu.telemetry.calibration import calibration_cache_token
    tok = calibration_cache_token()
    parts = (tuple(shape), int(itemsize),
             _sharding_key(src_sharding), _sharding_key(dst_sharding),
             getattr(global_config, "reshard_strategy", "auto"),
             getattr(global_config, "resharding_wire_model", "call"),
             global_config.resharding_transfer_latency_s,
             getattr(global_config, "resharding_wire_bandwidth", 0.0)) \
        + ((tok,) if tok else ())
    cache = get_compile_cache() if cache_enabled() else None
    key = cache.make_key("reshard_strategy", parts) if cache else None
    if cache is not None:
        hit = cache.get("reshard_strategy", key)
        if isinstance(hit, dict) and hit.get("strategy") in \
                RESHARD_STRATEGIES:
            return hit["strategy"], dict(hit.get("costs", {})), True
    chosen, costs, _opts = choose_strategy(shape, itemsize, src_sharding,
                                           dst_sharding)
    if cache is not None:
        cache.put("reshard_strategy", key,
                  {"strategy": chosen, "costs": costs})
    return chosen, costs, False


# last-N per-edge strategy decisions, for dump_debug_info's
# resharding_plan.txt and scripts/reshard_tool.py
from collections import deque as _deque  # noqa: E402

_RECENT_PLANS: "_deque" = _deque(maxlen=128)

_STRATEGY_COUNT = _PLANNER_REG.counter(
    "alpa_reshard_strategy_total",
    "Cross-mesh resharding edges planned, per chosen strategy",
    labelnames=("kind",))


def strategy_plan_fingerprint() -> str:
    """Content hash over the recorded per-edge strategy decisions (in
    recording order): two runs that planned the same edges to the same
    strategies fingerprint identically — the warm-restart replay check
    in benchmark/resharding_bench.py."""
    import hashlib
    h = hashlib.sha256()
    for p in _RECENT_PLANS:
        h.update(f"{p['shape']}|{p['itemsize']}|{p['src']}|{p['dst']}|"
                 f"{p['strategy']}".encode())
    return h.hexdigest()


def reset_recent_plans():
    _RECENT_PLANS.clear()


def format_resharding_plan() -> str:
    """Human-readable per-edge strategy report (dump_debug_info's
    resharding_plan.txt; scripts/reshard_tool.py)."""
    if not _RECENT_PLANS:
        return "resharding plan: (no cross-mesh edges planned yet)"
    lines = [f"resharding plan ({len(_RECENT_PLANS)} most recent edges; "
             "strategy chosen by the collective cost model):"]
    for p in _RECENT_PLANS:
        costs = " ".join(f"{k}={v * 1e3:.3f}ms"
                         for k, v in sorted(p["costs"].items()))
        lines.append(
            f"  {p['shape']} x{p['itemsize']}B {p['src']} -> {p['dst']}")
        lines.append(
            f"    strategy={p['strategy']}"
            f"{' (cached)' if p['cached'] else ''} "
            f"wire_msgs={p['wire_messages']} "
            f"wire_bytes={p['wire_bytes']:.0f} "
            f"planned_bytes={p['transfer_bytes']:.0f} "
            f"max_link={p['max_link_bytes']:.0f}")
        if costs:
            lines.append(f"    est: {costs}")
    return "\n".join(lines)


########################################
# execution
########################################

_warned_fallback = False


def shard_structures_match(shape, src_sharding, dst_sharding) -> bool:
    """True when moving ``src_sharding -> dst_sharding`` is a pure 1:1
    shard move: each source shard maps onto the destination shard at the
    same position in the device-assignment order (same per-shard index
    maps).  That is exactly the case the runtime's batched C++ copy
    (``batched_copy_array_to_devices_with_sharding``) handles without any
    resharding logic; every other move needs the full device_put path."""
    try:
        src_map = src_sharding.devices_indices_map(tuple(shape))
        dst_map = dst_sharding.devices_indices_map(tuple(shape))
    except Exception:  # pylint: disable=broad-except
        return False
    return list(src_map.values()) == list(dst_map.values())


def _apply_sync_semantics(out, wire=None):
    """Blocking-transfer emulation (ISSUE 4 benchmark support).

    The CPU test backend's shard moves are asynchronous in-process
    memcpys, so a RESHARD never blocks the thread that issued it —
    unlike multi-host send/recv, which blocks for producer readiness
    plus wire latency.  With ``sync_resharding_transfers`` the calling
    thread blocks until the destination arrays materialize; with
    ``resharding_transfer_latency_s`` it additionally idles for the
    emulated wire time.  Both default off and cost one attribute read
    per transfer call.

    ``wire``, when given, is the transfer's ``(max_link_messages,
    max_link_bytes)`` from the planner's link stats.  Under
    ``resharding_wire_model == "link"`` the idle time scales with the
    busiest link — ``latency × messages + bytes / bandwidth`` — so a
    strategy that sends fewer, bigger messages per link actually runs
    faster under emulation, matching what the cost model charges it.
    The default ``"call"`` model keeps the legacy one-idle-per-call
    semantics regardless of ``wire``.
    """
    from alpa_tpu.global_env import global_config
    lat = global_config.resharding_transfer_latency_s
    bw = getattr(global_config, "resharding_wire_bandwidth", 0.0)
    if lat or bw or global_config.sync_resharding_transfers:
        import time as _time

        import jax
        jax.block_until_ready(out)
        idle = 0.0
        if (wire is not None and
                getattr(global_config, "resharding_wire_model",
                        "call") == "link"):
            msgs, link_bytes = wire
            idle = lat * max(1, int(msgs))
            if bw:
                idle += link_bytes / bw
        elif lat:
            idle = lat
        if idle:
            _time.sleep(idle)


class DirectTransfer:
    """Pre-resolved, reusable executor for one RESHARD edge (ISSUE 2:
    "plan once, replay as pre-resolved tasks", arXiv:2211.05322).

    Built once at instruction-lowering time from the emitter's static
    sharding model; ``__call__`` does no planning — the destination
    devices, sharding, and path choice are already resolved:

    * fast path: when the edge is a 1:1 shard-structure move (see
      :func:`shard_structures_match`) the transfer goes straight to the
      runtime's batched C++ copy, skipping device_put's sharding
      resolution (~3x cheaper on the 8-device CPU mesh);
    * fallback: ``jax.device_put`` with the pre-resolved dst sharding.

    A per-call guard (``is_equivalent_to``, ~2 us) confirms the runtime
    array still has the sharding the plan assumed; divergence silently
    takes the fallback, so the fast path can never assemble wrong values.
    """

    __slots__ = ("dst_sharding", "src_sharding", "ndim", "fast",
                 "nbytes", "wire", "_dst_devices", "_semantics")

    def __init__(self, aval, src_sharding, dst_sharding):
        self.dst_sharding = dst_sharding
        self.src_sharding = src_sharding
        # (max_link_messages, max_link_bytes) for the "link" wire model;
        # set by make_transfer from the planner's link stats
        self.wire = None
        self.ndim = len(getattr(aval, "shape", ()))
        shape = tuple(getattr(aval, "shape", ()))
        try:
            self.nbytes = int(np.prod(shape, dtype=np.int64) *
                              np.dtype(aval.dtype).itemsize)
        except Exception:  # pylint: disable=broad-except
            self.nbytes = 0
        self.fast = (src_sharding is not None and shard_structures_match(
            shape, src_sharding, dst_sharding))
        self._dst_devices = None
        self._semantics = None
        if self.fast:
            try:
                import jaxlib.xla_extension as xe
                self._dst_devices = list(
                    dst_sharding._addressable_device_assignment)
                self._semantics = xe.ArrayCopySemantics.ALWAYS_COPY
            except Exception:  # pylint: disable=broad-except
                self.fast = False

    def __call__(self, val):
        if _ttrace.enabled():
            # per-edge bytes + latency (the span's duration) on the
            # calling thread's track (driver or pool worker)
            with _ttrace.get_recorder().span(
                    "reshard.edge", "resharding",
                    {"bytes": self.nbytes, "fast": self.fast}):
                return self._transfer(val)
        return self._transfer(val)

    def _transfer(self, val):
        out = None
        if self.fast:
            try:
                if val.sharding.is_equivalent_to(self.src_sharding,
                                                 self.ndim):
                    import jaxlib.xla_extension as xe
                    out = xe.batched_copy_array_to_devices_with_sharding(
                        [val], [self._dst_devices], [self.dst_sharding],
                        [self._semantics])[0]
            except Exception:  # pylint: disable=broad-except
                out = None
        if out is None:
            import jax
            out = jax.device_put(val, self.dst_sharding)
        _apply_sync_semantics(out, wire=self.wire)
        return out


class DirectTransferGroup:
    """Several :class:`DirectTransfer` edges between the same mesh pair,
    coalesced into one call (adjacent same-edge transfers in the
    instruction stream).  All-fast groups go through one batched C++
    copy; mixed groups batch the fallback through a single
    ``jax.device_put`` call (one runtime round-trip instead of N)."""

    __slots__ = ("transfers", "all_fast")

    def __init__(self, transfers: Sequence[DirectTransfer]):
        self.transfers = list(transfers)
        self.all_fast = all(t.fast for t in self.transfers)

    def __len__(self):
        return len(self.transfers)

    def __call__(self, vals):
        if _ttrace.enabled():
            with _ttrace.get_recorder().span(
                    "reshard.edge-group", "resharding",
                    {"bytes": sum(t.nbytes for t in self.transfers),
                     "n": len(self.transfers),
                     "fast": self.all_fast}):
                return self._transfer(vals)
        return self._transfer(vals)

    def _transfer(self, vals):
        ts = self.transfers
        out = None
        if self.all_fast:
            try:
                if all(v.sharding.is_equivalent_to(t.src_sharding, t.ndim)
                       for v, t in zip(vals, ts)):
                    import jaxlib.xla_extension as xe
                    out = xe.batched_copy_array_to_devices_with_sharding(
                        list(vals), [t._dst_devices for t in ts],
                        [t.dst_sharding for t in ts],
                        [t._semantics for t in ts])
            except Exception:  # pylint: disable=broad-except
                out = None
        if out is None:
            import jax
            out = jax.device_put(list(vals), [t.dst_sharding for t in ts])
        # one emulated wire round-trip for the whole coalesced message;
        # under the link model, member messages on a link still queue
        wires = [t.wire for t in ts if t.wire is not None]
        wire = (sum(w[0] for w in wires),
                sum(w[1] for w in wires)) if wires else None
        _apply_sync_semantics(out, wire=wire)
        return out


class CollectiveTransfer:
    """Pre-resolved executor for one RESHARD edge lowered to a two-leg
    collective sequence (ISSUE 7; "Memory-efficient array redistribution
    through portable collective communication", PAPERS.md):

    1. **wire leg** — ``jax.device_put`` to the *landing* sharding on the
       destination mesh (the 1/k scattered layout for
       ``slice_all_gather`` / ``reduce_scatter_gather``, the translated
       source layout for ``all_to_all``), so only the strategy's reduced
       byte volume crosses meshes;
    2. **collective leg** — a cached identity ``jax.jit`` with
       ``out_shardings=dst_sharding``: XLA emits the intra-destination
       all-gather / all-to-all over the mesh's own links (the same
       lowering will emit real DCN collectives on multi-host, ROADMAP
       item 1).

    Both legs are pure data movement — no arithmetic — so every strategy
    here is bit-exact against ``direct_p2p``.  The emulated wire idle is
    applied to the wire leg only, scaled by this strategy's busiest-link
    message count under the ``"link"`` wire model.
    """

    __slots__ = ("strategy", "dst_sharding", "src_sharding",
                 "inter_sharding", "ndim", "nbytes", "wire", "fast",
                 "_relayout")

    def __init__(self, aval, src_sharding, dst_sharding, strategy,
                 inter_sharding, wire=None):
        self.strategy = strategy
        self.dst_sharding = dst_sharding
        self.src_sharding = src_sharding
        self.inter_sharding = inter_sharding
        self.ndim = len(getattr(aval, "shape", ()))
        self.fast = False   # never the batched-copy fast path
        shape = tuple(getattr(aval, "shape", ()))
        try:
            self.nbytes = int(np.prod(shape, dtype=np.int64) *
                              np.dtype(aval.dtype).itemsize)
        except Exception:  # pylint: disable=broad-except
            self.nbytes = 0
        self.wire = wire
        self._relayout = None

    def __call__(self, val):
        if _ttrace.enabled():
            with _ttrace.get_recorder().span(
                    "reshard.edge", "resharding",
                    {"bytes": self.nbytes, "strategy": self.strategy}):
                return self._transfer(val)
        return self._transfer(val)

    def _transfer(self, val):
        import jax
        staged = jax.device_put(val, self.inter_sharding)
        _apply_sync_semantics(staged, wire=self.wire)
        if self._relayout is None:
            self._relayout = jax.jit(lambda x: x,
                                     out_shardings=self.dst_sharding)
        return self._relayout(staged)


def make_transfer(aval, src_sharding, dst_sharding, cross=False,
                  plan=None, weight=False):
    """Executor factory for one RESHARD edge: DirectTransfer,
    CollectiveTransfer, or (opt-in) the quantized codec transfer.

    Same-mesh relayouts always stay direct.  Cross-mesh edges take the
    plan's strategy decision when a :class:`ReshardingTaskSpec` is given
    (so the emitter replays exactly what the planner chose and cached),
    else resolve it here.  The quantized codec
    (``global_config.reshard_quantize``) takes precedence for eligible
    activation edges but is NEVER applied when ``weight`` is True —
    microbatch-invariant values (parameters, optimizer state) must cross
    losslessly.  Any planning failure degrades to DirectTransfer."""
    if not cross or src_sharding is None:
        return DirectTransfer(aval, src_sharding, dst_sharding)
    from alpa_tpu.global_env import global_config
    shape = tuple(getattr(aval, "shape", ()))
    try:
        itemsize = int(np.dtype(aval.dtype).itemsize)
        qmode = getattr(global_config, "reshard_quantize", "off")
        if qmode != "off" and not weight:
            from alpa_tpu.pipeline_parallel import reshard_codec
            qt = reshard_codec.maybe_quantized_transfer(
                aval, src_sharding, dst_sharding, qmode)
            if qt is not None:
                return qt
        opts = collective_options(shape, itemsize, src_sharding,
                                  dst_sharding)
        if plan is not None and getattr(plan, "strategy", None) in opts:
            strat = plan.strategy
        else:
            strat, _costs, _cached = resolve_strategy(
                shape, itemsize, src_sharding, dst_sharding)
            if strat not in opts:
                strat = "direct_p2p"
        st = opts[strat]["stats"]
        wire = (st["max_link_messages"], st["max_link_bytes"])
        if strat == "direct_p2p":
            t = DirectTransfer(aval, src_sharding, dst_sharding)
            t.wire = wire
            return t
        return CollectiveTransfer(aval, src_sharding, dst_sharding,
                                  strat, opts[strat]["landing"],
                                  wire=wire)
    except Exception:  # pylint: disable=broad-except
        logger.warning("make_transfer: collective lowering failed; "
                       "using DirectTransfer", exc_info=True)
        return DirectTransfer(aval, src_sharding, dst_sharding)


def make_ingest_transfer(aval, dst_sharding):
    """Transfer executor landing a HOST-resident payload on the
    destination sharding — the arrival half of a cross-process edge
    whose source lives in another address space (the disaggregated
    KV handoff, serve.disagg: the prefill replica's payload arrives as
    numpy and must land exactly where the decode engine's resident
    caches live).  A plain :class:`DirectTransfer` with no source
    sharding: the fast copy path is off, ``device_put`` lands it, and
    the wire-emulation knobs (``resharding_transfer_latency_s``,
    ``resharding_wire_bandwidth``) still model the hop."""
    t = DirectTransfer(aval, None, dst_sharding)
    t.wire = (1, float(t.nbytes))
    return t


@dataclasses.dataclass
class ExecutionReport:
    """Bytes actually moved by one ``ReshardingTask.run`` call.

    ``cross_mesh_bytes`` is the inter-mesh traffic in planned (payload
    dtype) bytes — the DCN-class hop the planner minimizes;
    ``intra_mesh_bytes`` is destination-internal movement (the ICI-class
    all-gather/broadcast leg).  Tests assert ``cross_mesh_bytes ==
    spec.transfer_bytes``.  ``wire_bytes`` is the planned bytes widened to
    the psum work dtype the multiprocess leg actually packs tiles in
    (bf16/fp16 -> f32, bool -> i32) — up to 4x the planned bytes for
    sub-word payloads.  It is per-process payload size, not a total-DCN
    measurement (the collective also carries each non-owner process's
    zero slots), and only ``run_multiprocess`` sets it.
    ``max_link_bytes`` (ISSUE 4) is the busiest single link this run
    loaded — max over per-source-device egress and per-destination-device
    ingress bytes of the cross-mesh leg."""
    mode: str = "device_put"
    cross_mesh_bytes: float = 0.0
    intra_mesh_bytes: float = 0.0
    wire_bytes: float = 0.0
    n_tiles: int = 0
    max_link_bytes: float = 0.0


class ReshardingTask:
    """Executable resharding (ref SymbolicReshardingTask :418).

    Three execution modes:

    - ``device_put`` (default fast path): one ``jax.device_put`` — the jax
      runtime carries shard transfers over ICI/DCN itself.  The spec is
      used for accounting only.
    - ``tiled`` (ref send/recv mode :418): drives the plan literally —
      each planned ``TileSlice`` is sliced out *on its source device*,
      transferred to its destination device, and the destination tiles are
      assembled in place.  When the plan carries the local-allgather
      rewrite, each replica-group member receives only its 1/k part
      cross-mesh and the full tile is completed by intra-destination
      transfers (the ICI gather leg).
    - ``broadcast`` (ref broadcast mode :935): each unique destination
      tile crosses the mesh boundary exactly once (to its first holder),
      then fans out to the other replica holders inside the destination
      mesh.

    ``last_report`` records the bytes each leg actually moved so tests can
    hold execution to the plan's accounting.
    """

    def __init__(self, spec: ReshardingTaskSpec, dst_sharding,
                 mode: str = "device_put"):
        self.spec = spec
        self.dst_sharding = dst_sharding
        self.mode = mode
        self.last_report: Optional[ExecutionReport] = None

    def run(self, src_array, mode: Optional[str] = None):
        if _ttrace.enabled():
            with _ttrace.get_recorder().span(
                    "reshard.task", "resharding",
                    {"mode": mode or self.mode,
                     "bytes": self.spec.transfer_bytes}):
                return self._run(src_array, mode)
        return self._run(src_array, mode)

    def _run(self, src_array, mode: Optional[str] = None):
        import jax
        mode = mode or self.mode
        fault.fire("cross_mesh_recv", mode=mode,
                   n_requests=len(self.spec.requests))
        if mode == "device_put" or not self.spec.requests:
            self.last_report = ExecutionReport(mode="device_put")
            return jax.device_put(src_array, self.dst_sharding)
        if mode not in ("tiled", "broadcast"):
            raise ValueError(f"unknown resharding execution mode: {mode}")
        addressable_src = {s.device.id for s in src_array.addressable_shards}
        addressable_dst = {d.id
                           for d in self.dst_sharding.addressable_devices}
        if (not set(self.spec.src_device_ids) <= addressable_src or
                not set(self.spec.dst_device_ids) <= addressable_dst):
            # Planned modes drive transfers from the controller and need
            # every source/destination shard addressable; on a multi-host
            # run fall back to the runtime-carried transfer.
            return self._fallback(src_array,
                                  "needs all shards addressable from "
                                  "this process")
        if self.spec.src_tiles:
            # the array's ACTUAL layout must match the plan's source
            # sharding — the emit-model sharding can diverge from what a
            # stage executable really produced; slicing with the planned
            # offsets would then assemble wrong values
            actual = VirtualDistributedArray.from_sharding(
                self.spec.shape, src_array.sharding)
            if (tuple(actual.device_ids) != self.spec.src_device_ids or
                    tuple(actual.device_tiles) != self.spec.src_tiles):
                return self._fallback(src_array,
                                      "source layout diverged from plan")
        return self._run_planned(src_array, broadcast=(mode == "broadcast"))

    def run_multiprocess(self, src_array):
        """Cross-PROCESS tiled execution (multi-controller): only the
        packed unique planned tiles cross the process boundary — one
        global-device collective over a buffer of exactly the plan's
        bytes — and each process assembles its local destination shards
        from the packed buffer.

        This is the multi-controller analog of the reference's per-tile
        NCCL send/recv (ref SymbolicReshardingTask:418): DCN traffic is
        proportional to the PLANNED tiles instead of the full-array
        gather that ``put_global`` pays.

        COLLECTIVE: all processes execute the same instruction stream, so
        they reach this call in the same order with the same spec.
        """
        import jax
        import jax.numpy as jnp

        from alpa_tpu.distributed import (psum_work_dtype, put_global,
                                          sum_across_processes)

        # fires BEFORE the collective: every process injects (or not)
        # identically, so the lock-step instruction streams stay aligned
        fault.fire("cross_mesh_recv", mode="multiprocess",
                   n_requests=len(self.spec.requests))
        spec = self.spec
        if not spec.requests:
            self.last_report = ExecutionReport(mode="device_put")
            return put_global(src_array, self.dst_sharding)

        dtype = np.dtype(src_array.dtype)
        work = psum_work_dtype(dtype)
        report = ExecutionReport(mode="tiled")

        # unique planned tiles, packed in deterministic plan order
        order: List[TileSlice] = []
        offsets: Dict[Tuple, int] = {}
        total = 0
        for req in spec.requests:
            for ts in req.srcs:
                if ts.tile.slices in offsets:
                    continue
                offsets[ts.tile.slices] = total
                total += ts.tile.size
                order.append(ts)

        # cross-process leg: each tile is painted by the process owning
        # its (load-balanced, unique) planned source shard
        local_src = {s.device.id: np.asarray(s.data)
                     for s in src_array.addressable_shards}
        canvas = np.zeros(total, work)
        for ts in order:
            dev_id = spec.src_device_ids[ts.src_shard_index]
            shard = local_src.get(dev_id)
            if shard is not None:
                piece = shard[tuple(slice(a, b)
                                    for a, b in ts.offset_in_src)]
                off = offsets[ts.tile.slices]
                canvas[off:off + ts.tile.size] = \
                    piece.ravel().astype(work)
        packed = sum_across_processes(canvas)
        report.cross_mesh_bytes = float(total) * dtype.itemsize
        report.wire_bytes = float(total) * np.dtype(work).itemsize
        report.n_tiles = len(order)
        # busiest egress link: bytes painted per owning source device
        # (ingress is collective — every process receives the full pack)
        egress: Dict[int, float] = {}
        for ts in order:
            dev_id = spec.src_device_ids[ts.src_shard_index]
            egress[dev_id] = (egress.get(dev_id, 0.0) +
                              ts.tile.size * dtype.itemsize)
        report.max_link_bytes = max(egress.values()) if egress else 0.0

        # local assembly: every locally-addressable destination shard
        # fills its full tile from the intersecting packed tiles
        shard_of_dev = {d: i for i, d in enumerate(spec.dst_device_ids)}
        arrs = []
        for dev in sorted(self.dst_sharding.addressable_devices,
                          key=lambda d: d.id):
            full_tile = spec.dst_tiles[shard_of_dev[dev.id]]
            buf = np.zeros(full_tile.shape, work)
            for ts in order:
                inter = ts.tile.intersect(full_tile)
                if inter is None:
                    continue
                off = offsets[ts.tile.slices]
                tile_arr = packed[off:off + ts.tile.size].reshape(
                    ts.tile.shape)
                src_idx = tuple(slice(a, b)
                                for a, b in inter.offset_in(ts.tile))
                dst_idx = tuple(slice(a, b)
                                for a, b in inter.offset_in(full_tile))
                buf[dst_idx] = tile_arr[src_idx]
            arrs.append(jax.device_put(jnp.asarray(buf.astype(dtype)),
                                       dev))
        # no dtype kwarg: older jax rejects it; every arr already carries
        # the work dtype
        out = jax.make_array_from_single_device_arrays(
            spec.shape, self.dst_sharding, arrs)
        self.last_report = report
        return out

    def _fallback(self, src_array, why: str):
        import jax
        global _warned_fallback
        if not _warned_fallback:
            _warned_fallback = True
            logger.warning(
                "planned resharding execution %s; falling back to "
                "device_put (warned once)", why)
        self.last_report = ExecutionReport(mode="device_put")
        return jax.device_put(src_array, self.dst_sharding)

    # -- planned execution --------------------------------------------

    def _run_planned(self, src_array, broadcast: bool):
        import jax
        import jax.numpy as jnp
        from jax import lax

        spec = self.spec
        itemsize = src_array.dtype.itemsize
        report = ExecutionReport(mode="broadcast" if broadcast else "tiled")

        src_data = {s.device.id: s.data
                    for s in src_array.addressable_shards}
        dev_by_id = {d.id: d for d in self.dst_sharding.device_set}
        for d in getattr(src_array.sharding, "device_set", ()):
            dev_by_id.setdefault(d.id, d)

        # Replica groups: destination shards holding the same full tile.
        groups = VirtualDistributedArray(
            spec.shape, list(spec.dst_tiles),
            list(spec.dst_device_ids)).unique_tiles

        # 1) cross-mesh leg: move each planned TileSlice to one dst
        #    device, in the planner's balanced send order (ISSUE 4).
        #    Broadcast mode routes each replica group's unique tiles
        #    across the group members (route_broadcast) — naive routing
        #    piles the whole group's ingress on the first holder —
        #    and each unique piece still crosses exactly once; the other
        #    holders are served by intra-mesh fan-out below.
        #    landed[shard_index] = [(global_tile, piece_on_dst_device)]
        landed: Dict[int, List[Tuple[Tile, Any]]] = {}
        routes = route_broadcast(spec, spec.loadbalanced) \
            if broadcast else None
        seen: set = set()
        egress: Dict[int, float] = {}
        ingress: Dict[int, float] = {}
        order = spec.send_order or tuple(
            (ri, si) for ri, req in enumerate(spec.requests)
            for si in range(len(req.srcs)))
        for ri, si in order:
            req = spec.requests[ri]
            ts = req.srcs[si]
            if broadcast:
                gslices = spec.dst_tiles[req.dst_shard_index].slices
                key = (gslices, ts.tile.slices)
                if key in seen:
                    continue
                seen.add(key)
                target = routes[key]
            else:
                target = req.dst_shard_index
            dst_dev_id = spec.dst_device_ids[target]
            src_dev_id = spec.src_device_ids[ts.src_shard_index]
            shard = src_data[src_dev_id]
            piece = shard[tuple(slice(a, b)
                                for a, b in ts.offset_in_src)]
            moved = jax.device_put(piece, dev_by_id[dst_dev_id])
            nbytes = ts.tile.size * itemsize
            report.cross_mesh_bytes += nbytes
            egress[src_dev_id] = egress.get(src_dev_id, 0.0) + nbytes
            ingress[dst_dev_id] = ingress.get(dst_dev_id, 0.0) + nbytes
            report.n_tiles += 1
            landed.setdefault(target, []).append((ts.tile, moved))
        links = list(egress.values()) + list(ingress.values())
        report.max_link_bytes = max(links) if links else 0.0

        # 2) intra-mesh leg + assembly: every dst shard assembles its FULL
        #    tile; pieces that landed on a sibling replica are pulled over
        #    the destination mesh's own links (allgather / broadcast leg).
        out_arrays = []
        for shard_i, full_tile in enumerate(spec.dst_tiles):
            dst_dev = dev_by_id[spec.dst_device_ids[shard_i]]
            holders = groups[full_tile.slices]
            if spec.allgather_rewrite or broadcast:
                donors = holders          # union of the group's pieces
            else:
                donors = [shard_i]        # own fetches cover the tile
            pieces: List[Tuple[Tile, Any]] = []
            covered: Dict[Tuple, Any] = {}
            for d in donors:
                for tile, buf in landed.get(d, ()):
                    if tile.slices in covered:
                        continue
                    if d != shard_i:
                        buf = jax.device_put(buf, dst_dev)
                        report.intra_mesh_bytes += tile.size * itemsize
                    covered[tile.slices] = buf
                    pieces.append((tile, buf))
            if len(pieces) == 1 and pieces[0][0].slices == full_tile.slices:
                tile_arr = pieces[0][1]
            else:
                tile_arr = jax.device_put(
                    jnp.zeros(full_tile.shape, src_array.dtype), dst_dev)
                for tile, buf in pieces:
                    starts = tuple(a for a, _b in tile.offset_in(full_tile))
                    tile_arr = lax.dynamic_update_slice(
                        tile_arr, buf, starts)
            out_arrays.append(tile_arr)

        self.last_report = report
        return jax.make_array_from_single_device_arrays(
            spec.shape, self.dst_sharding, out_arrays)
