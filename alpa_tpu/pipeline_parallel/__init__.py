"""Inter-operator (pipeline) parallelization.

TPU-native analog of ref ``alpa/pipeline_parallel/`` (SURVEY.md §2.4): layer
clustering, stage construction, static schedules, a single-controller
multi-mesh pipeshard runtime, and cross-mesh resharding via the jax runtime
instead of NCCL p2p.
"""
