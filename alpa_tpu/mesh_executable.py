"""Mesh executables: compiled artifacts that run on one physical mesh.

TPU-native analog of the reference's ``alpa/mesh_executable.py`` (1195 LoC).
The driver/worker split collapses: there are no Ray workers, so each
``*MeshDriverExecutable``/``*MeshWorkerExecutable`` pair becomes a single
class wrapping a jit-compiled callable with explicit in/out shardings.

Key translations (SURVEY.md §2.5):
* ``NormalMeshDriverExecutable/NormalMeshWorkerExecutable``
  (ref mesh_executable.py:186/429) -> ``NormalMeshExecutable``.
* ``GradAccMeshDriverExecutable`` (ref :499) and its
  ``XLA_SKIP_NCCL_COLLECTIVE_IDS`` grad-sync-skip env hack (ref :855-894)
  -> ``GradAccMeshExecutable``: gradient accumulation is compiled *into* the
  program (shard_map local accumulation + one final reduction), since the TPU
  runtime cannot skip collectives dynamically (SURVEY.md §2.9).
* ``AllocZeroBufferDriverExecutable`` (ref :1018) -> zeros are created by XLA
  inside the compiled program; a helper remains for the pipeline runtime.
"""
import logging
import time
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from alpa_tpu.device_mesh import PhysicalDeviceMesh
from alpa_tpu.telemetry import metrics as _tmetrics
from alpa_tpu.util import benchmark_func

logger = logging.getLogger(__name__)

# dispatch (enqueue) latency of single-mesh executables — replaces the
# deprecated per-executable timers(f"exec-{uuid}-dispatch") bridge
_DISPATCH_SECONDS = _tmetrics.get_registry().histogram(
    "alpa_mesh_dispatch_seconds",
    "launch_on_driver enqueue latency per mesh executable call")

mesh_executable_counter = 0


def next_mesh_executable_uuid() -> int:
    global mesh_executable_counter
    mesh_executable_counter += 1
    return mesh_executable_counter


class MeshExecutable:
    """Base class (ref mesh_executable.py:108 MeshDriverExecutable)."""

    def __init__(self, physical_mesh: PhysicalDeviceMesh):
        self.physical_mesh = physical_mesh
        self.exec_uuid = next_mesh_executable_uuid()

    def launch_on_driver(self, *args):
        raise NotImplementedError

    def __call__(self, *args):
        return self.launch_on_driver(*args)

    # ---- introspection ----
    def get_hlo_text(self) -> str:
        raise NotImplementedError

    def get_total_allocation_size(self) -> int:
        raise NotImplementedError

    def profile_with_dummy_inputs(self, repeat=3, number=3) -> np.ndarray:
        raise NotImplementedError

    def sync(self):
        self.physical_mesh.sync_workers()


class NormalMeshExecutable(MeshExecutable):
    """A plain SPMD executable: one compiled XLA program over one mesh.

    ``compiled`` is the result of ``jax.jit(...).lower(...).compile()``;
    ``in_shardings``/``out_shardings`` are flat lists of NamedSharding;
    ``in_tree``/``out_tree`` handle pytree (un)flattening at the boundary
    (ref launch_on_driver mesh_executable.py:264: shard args -> execute ->
    wrap outs; here jax.jit does arg placement via committed shardings).
    """

    def __init__(self,
                 physical_mesh: PhysicalDeviceMesh,
                 compiled,
                 in_avals,
                 out_avals,
                 in_shardings,
                 out_shardings,
                 in_tree,
                 out_tree,
                 static_argnums: Sequence[int] = (),
                 donated_invars: Optional[Sequence[bool]] = None,
                 flop_count: Optional[float] = None):
        super().__init__(physical_mesh)
        self.compiled = compiled
        self.in_avals = in_avals
        self.out_avals = out_avals
        self.in_shardings = in_shardings
        self.out_shardings = out_shardings
        self.in_tree = in_tree
        self.out_tree = out_tree
        self.static_argnums = static_argnums
        self.donated_invars = donated_invars or (False,) * len(in_avals)
        self.flop_count = flop_count
        self.timer_name = f"exec-{self.exec_uuid}"

    def launch_on_driver(self, *flat_args):
        """Execute on flat (already tree-flattened) args.

        Dispatch is async (jax futures); the
        ``alpa_mesh_dispatch_seconds`` histogram measures enqueue
        latency only.  Use ``profile_with_dummy_inputs`` or block on the
        outputs for wall-clock execution time.
        """
        t0 = time.perf_counter()
        try:
            args = self._prepare_args(flat_args)
            return self.compiled(*args)
        finally:
            _DISPATCH_SECONDS.observe(time.perf_counter() - t0)

    def _prepare_args(self, flat_args):
        """Commit plain host arrays to the mesh per the input shardings.

        jax's compiled.call path requires committed, correctly-sharded
        inputs; this is the analog of the driver's ``shard_args_to_bufs``
        (ref device_mesh.py:1287).
        """
        out = []
        for a, s in zip(flat_args, self.in_shardings):
            if (isinstance(a, jax.Array) and a.committed and
                    a.sharding.is_equivalent_to(s, a.ndim)):
                out.append(a)
            elif not s.is_fully_addressable:
                # multi-process mesh: device_put rejects shardings with
                # non-addressable devices — build the global array from
                # this process's local shards instead (every process holds
                # the full host value here)
                arr = np.asarray(a)
                out.append(jax.make_array_from_callback(
                    arr.shape, s, lambda idx, _arr=arr: _arr[idx]))
            else:
                out.append(jax.device_put(a, s))
        return out

    def get_hlo_text(self) -> str:
        return self.compiled.as_text()

    def get_plan_fingerprint(self) -> str:
        """Content hash of this executable's parallel plan (mesh extent +
        input/output avals and shardings) — the shard-parallel analog of
        ``PipeshardDriverExecutable.get_plan_fingerprint``, consumed by
        ``checkpoint.CheckpointManager`` resume validation."""
        import hashlib
        parts = [repr(tuple(self.physical_mesh.shape))]
        parts.extend(str(a) for a in self.in_avals)
        parts.extend(str(a) for a in self.out_avals)
        parts.extend(str(s) for s in self.in_shardings)
        parts.extend(str(s) for s in self.out_shardings)
        return hashlib.sha256("\n".join(parts).encode()).hexdigest()

    def get_total_allocation_size(self) -> int:
        try:
            m = self.compiled.memory_analysis()
            return int(m.temp_size_in_bytes + m.argument_size_in_bytes +
                       m.output_size_in_bytes)
        except Exception:  # pylint: disable=broad-except
            return -1

    def profile_with_dummy_inputs(self, repeat=3, number=3) -> np.ndarray:
        """Time the executable on zero inputs (ref
        profile_with_dummy_inputs, mesh_executable.py).  Donated args are
        recreated every run since execution consumes their buffers."""
        make = lambda a, s: jax.device_put(jnp.zeros(a.shape, a.dtype), s)
        persistent = [
            None if d else make(a, s) for a, s, d in zip(
                self.in_avals, self.in_shardings, self.donated_invars)
        ]

        def run():
            args = [
                make(a, s) if p is None else p for a, s, p in zip(
                    self.in_avals, self.in_shardings, persistent)
            ]
            outs = self.compiled(*args)
            jax.block_until_ready(outs)

        return benchmark_func(run, warmup=1, repeat=repeat, number=number)


class GradAccMeshExecutable(NormalMeshExecutable):
    """Executable whose program internally loops over microbatches.

    The reference runs the accumulate-grad binary N times with grad-sync
    all-reduces skipped on all but the last microbatch via env-var runtime
    hooks (ref mesh_executable.py:855-894, §2.9 grad-sync skip).  Here the
    microbatch loop is a ``lax.scan`` compiled into the single program and —
    when the batch axis is a mesh axis — gradients accumulate *locally*
    inside a shard_map with one reduction at the end, which is the same
    communication volume without any runtime hook.
    """
    # Same execution surface as NormalMeshExecutable; the difference is in
    # how shard_parallel/compile_executable.py builds the traced function.
    pass


def alloc_zero_buffers(mesh: PhysicalDeviceMesh, avals, shardings):
    """Allocate zeroed arrays on a mesh (ref AllocZeroBufferExecutable
    mesh_executable.py:1018) — used by the pipeshard runtime for gradient
    accumulators."""
    zeros_fn = jax.jit(
        lambda: [jnp.zeros(a.shape, a.dtype) for a in avals],
        out_shardings=list(shardings))
    return zeros_fn()


def get_index_select_executable(mesh: PhysicalDeviceMesh, aval, sharding,
                                dim: int):
    """Compiled index_select used by serving for beam-search KV-cache reorder
    (ref mesh_executable.py:1168)."""

    def index_select(x, idx):
        return jnp.take(x, idx, axis=dim)

    idx_aval = jax.ShapeDtypeStruct((aval.shape[dim],), jnp.int32)
    return (jax.jit(index_select,
                    in_shardings=(sharding, NamedSharding(sharding.mesh,
                                                          PartitionSpec())),
                    out_shardings=sharding)
            .lower(jax.ShapeDtypeStruct(aval.shape, aval.dtype), idx_aval)
            .compile())
