"""Process-global checkpoint counters (exposed via
``alpa_tpu.monitoring.get_checkpoint_stats``).

Counters are plain add-only floats/ints behind one lock; timings are
accumulated seconds.  ``snapshot()`` returns a copy so callers can diff
before/after an operation without racing the background writer thread.
"""
import threading
from typing import Dict

_LOCK = threading.Lock()
_COUNTERS: Dict[str, float] = {}


def incr(name: str, value: float = 1) -> None:
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + value


def snapshot() -> Dict[str, float]:
    with _LOCK:
        return dict(_COUNTERS)


def reset() -> None:
    with _LOCK:
        _COUNTERS.clear()
