"""Process-global checkpoint counters (exposed via
``alpa_tpu.monitoring.get_checkpoint_stats``).

Since the unified telemetry layer (ISSUE 5) these live in the central
metrics registry as the labeled counter family
``alpa_checkpoint_stat_total{key=...}`` — so ``GET /metrics`` on the
serving controller exports checkpoint traffic for free.  The original
module API (``incr``/``snapshot``/``reset``) is preserved as a thin
view; ``snapshot()`` returns the same ``{name: value}`` dict shape as
before.
"""
from typing import Dict

from alpa_tpu.telemetry import metrics as _metrics

_FAMILY = _metrics.get_registry().counter(
    "alpa_checkpoint_stat_total",
    "Checkpoint traffic counters (saves, restores, staged/written "
    "bytes, accumulated staging/write/blocking seconds)",
    labelnames=("key",))


def incr(name: str, value: float = 1) -> None:
    _FAMILY.labels(name).inc(value)


def snapshot() -> Dict[str, float]:
    return {key[0]: child.value for key, child in _FAMILY.children()}


def reset() -> None:
    _FAMILY.reset()
