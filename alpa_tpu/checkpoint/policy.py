"""Checkpoint retention policies.

A policy decides which committed steps survive after each save;
:class:`~alpa_tpu.checkpoint.manager.CheckpointManager` deletes the
rest and garbage-collects unreferenced chunks.
"""
import dataclasses
from typing import List, Sequence


@dataclasses.dataclass(frozen=True)
class RetentionPolicy:
    """Keep-last-K plus keep-every-N.

    ``keep_last_k``: the newest K steps always survive (0 = keep all).
    ``keep_every_n``: additionally keep every step divisible by N
    (0 = none) — the long-horizon "milestone" ladder, so a run keeps
    e.g. its last 3 steps for crash recovery AND every 1000th for
    post-hoc evals, without the two goals fighting.
    """
    keep_last_k: int = 3
    keep_every_n: int = 0

    def __post_init__(self):
        if self.keep_last_k < 0 or self.keep_every_n < 0:
            raise ValueError("retention counts must be >= 0")

    def surviving(self, steps: Sequence[int]) -> List[int]:
        steps = sorted(steps)
        keep = set()
        if self.keep_last_k == 0:
            keep.update(steps)
        else:
            keep.update(steps[-self.keep_last_k:])
        if self.keep_every_n > 0:
            keep.update(s for s in steps if s % self.keep_every_n == 0)
        return sorted(keep)

    def to_delete(self, steps: Sequence[int]) -> List[int]:
        surviving = set(self.surviving(steps))
        return sorted(s for s in steps if s not in surviving)


#: Keep everything — the manager's default when no policy is given.
KEEP_ALL = RetentionPolicy(keep_last_k=0, keep_every_n=0)
