"""Zero-downtime serving weight swap.

Split into two phases so the serving hot path never waits on disk:

1. **Stage** (:func:`stage_weights_from_checkpoint`) — read a step from
   the content-addressed store, verifying every chunk hash, and place
   the new weights onto the SAME devices/shardings the replica's current
   params occupy.  Runs entirely in the background: requests keep
   flowing on the old weights.
2. **Swap** (``serve.controller._Replica.swap_weights``) — a drain
   barrier: the replica's batcher finishes its in-flight batch on the
   old weights, queued requests wait (they are never dropped), the
   params pointer + prefix KV swap, and the queue resumes on the new
   weights.  The streaming engine is drained and rebuilt lazily; a
   stream that outlives the drain window continues without error and
   finishes its remaining tokens on the new weights.

``POST /admin/reload`` on the serving controller drives both phases.
"""
import logging
import time
from typing import Any, Optional, Tuple

from alpa_tpu.checkpoint import metrics
from alpa_tpu.checkpoint.manager import CheckpointManager

logger = logging.getLogger(__name__)


def _as_manager(source) -> CheckpointManager:
    if isinstance(source, CheckpointManager):
        return source
    from alpa_tpu.checkpoint.store import ShardStore
    if isinstance(source, ShardStore):
        mgr = CheckpointManager(source.root)
        mgr.store = source
        return mgr
    return CheckpointManager(str(source))


def _shardings_like(params):
    """Pytree of shardings mirroring ``params``: device arrays keep
    their exact placement; host leaves restore host-side."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: x.sharding if isinstance(x, jax.Array) else None,
        params)


def stage_weights_from_checkpoint(source,
                                  target_params: Any,
                                  step: Optional[int] = None,
                                  verify: bool = True,
                                  expected_plan_fingerprint:
                                  Optional[str] = None) -> Tuple[Any, int]:
    """Background staging phase: load ``step`` (default latest) from
    ``source`` (a CheckpointManager, ShardStore, or store path) into a
    fresh pytree with ``target_params``'s structure and device
    placement.  Every chunk read is hash-verified (``verify=True``), so
    a truncated or bit-rotted checkpoint fails HERE — before any
    replica is touched.  Returns ``(new_params, step_loaded)``."""
    t0 = time.monotonic()
    mgr = _as_manager(source)
    if step is None:
        step = mgr.latest_step()
        if step is None:
            from alpa_tpu.checkpoint.store import CheckpointNotFoundError
            raise CheckpointNotFoundError(
                f"no committed checkpoint steps in {mgr.store.root}")
    new_params = mgr.restore(
        target_params, step=step, shardings=_shardings_like(target_params),
        expected_plan_fingerprint=expected_plan_fingerprint,
        verify=verify)
    staged = time.monotonic() - t0
    metrics.incr("hot_swap_staged")
    metrics.incr("hot_swap_stage_seconds", staged)
    logger.info("staged weights from step %d in %.3fs (hash-verified)",
                step, staged)
    return new_params, step


def drain_engine(engine, timeout: float = 30.0,
                 poll: float = 0.01) -> bool:
    """Wait until a ContinuousBatchingEngine has no active rows and an
    empty queue.  True when drained within ``timeout``; False when
    streams are still running (the caller then leaves the old engine
    alive — its stragglers finish on the swapped params rather than
    erroring)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with engine._cv:
            idle = (not engine._active.any()) and len(engine._queue) == 0
        if idle:
            return True
        time.sleep(poll)
    return False
