"""Async distributed checkpoint manager.

``CheckpointManager`` is the training-loop-facing API over
:class:`~alpa_tpu.checkpoint.store.ShardStore`:

* **Async double-buffered saves** — ``save(step, state)`` blocks only
  for (a) the previous step's disk write to finish (at most one write in
  flight: the double buffer) and (b) device→host staging of the new
  state.  Hashing + chunk writes + manifest commit + retention GC all
  run on a background thread, so train step N+1 overlaps the disk write
  of step N.  ``last_blocking_seconds`` records exactly how long the
  training loop was stalled — the number the <10%-of-sync acceptance
  test asserts on.
* **Save-failure surfacing** — a background write that fails is never
  silent: the first exception re-raises (wrapped in
  :class:`CheckpointSaveError`) from the next ``save()`` or ``wait()``.
  Store atomicity guarantees the failed step has no manifest, so
  ``latest_step()`` still points at the last good one.
* **Resume safety** — ``restore`` validates the manifest's recorded
  ``plan_fingerprint`` against the caller's (e.g.
  ``executable.get_plan_fingerprint()``), raising
  :class:`PlanFingerprintMismatch` instead of silently loading weights
  into a differently-parallelized program.
* **Resharding-on-read** — pass ``shardings`` (a pytree of shardings
  matching ``target``) and each device reads only the chunks
  overlapping its slice; the saving mesh shape is irrelevant.

``RecoveryCheckpointer`` plugs a manager into
:class:`alpa_tpu.fault.RecoveryManager`: quiesce → durable snapshot on
entry to RECOVERING, automatic restore of the last *verified* step when
recovery brings the mesh back.
"""
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from alpa_tpu.checkpoint import metrics
from alpa_tpu.checkpoint.policy import RetentionPolicy
from alpa_tpu.checkpoint.store import (CheckpointNotFoundError, ShardStore)
from alpa_tpu.telemetry import trace as _ttrace

logger = logging.getLogger(__name__)


class CheckpointSaveError(RuntimeError):
    """A background checkpoint write failed.  ``step`` is the step that
    was lost; the store holds no manifest for it."""

    def __init__(self, step: int, cause: BaseException):
        super().__init__(
            f"checkpoint save of step {step} failed: "
            f"{type(cause).__name__}: {cause}")
        self.step = step
        self.cause = cause


class PlanFingerprintMismatch(RuntimeError):
    """The checkpoint was saved under a different parallel plan than the
    one resuming — loading it would scatter weights into executables
    compiled for other shardings.  Re-solve or pass the saved plan."""


def _flatten_state_dict(target):
    from alpa_tpu.serialization import (_flatten_state_dict as _flat,
                                        _leaf_dirname)
    from flax.serialization import to_state_dict
    flat = _flat(to_state_dict(target))
    return {_leaf_dirname(path): (path, leaf)
            for path, leaf in flat.items()}


def _stage_leaf(leaf):
    """Device→host staging of one leaf: list of (global-index, ndarray)
    pieces.  The host copy is the only device-blocking part of a save."""
    import jax
    if isinstance(leaf, jax.Array):
        pieces = []
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue
            index = tuple(
                (s.start or 0, s.stop if s.stop is not None else dim)
                for s, dim in zip(shard.index, leaf.shape)) \
                if leaf.ndim else ()
            pieces.append((index, np.asarray(shard.data)))
        return list(leaf.shape), str(leaf.dtype), pieces
    arr = np.asarray(leaf)
    index = tuple((0, d) for d in arr.shape) if arr.ndim else ()
    return list(arr.shape), str(arr.dtype), [(index, arr)]


class CheckpointManager:
    """See module docstring.  Single-controller only: every shard must
    be addressable from this process (the tests' virtual CPU meshes and
    single-host TPU meshes qualify); multi-host runs keep using
    ``serialization.save_checkpoint``'s per-process index files until
    the manifest learns to merge per-process piece sets."""

    def __init__(self, root: str,
                 policy: Optional[RetentionPolicy] = None,
                 async_save: bool = True,
                 chunk_bytes: int = 64 * 1024 * 1024):
        self.store = ShardStore(root)
        self.policy = policy
        self.async_save = async_save
        self.chunk_bytes = chunk_bytes
        self._pending: Optional[threading.Thread] = None
        self._pending_step: Optional[int] = None
        self._errors: List[CheckpointSaveError] = []
        self._err_lock = threading.Lock()
        # stall accounting for the <10%-blocking acceptance criterion
        self.last_staging_seconds = 0.0
        self.last_write_seconds = 0.0
        self.last_blocking_seconds = 0.0

    # ---- save --------------------------------------------------------

    def save(self, step: int, state: Any,
             plan_fingerprint: Optional[str] = None,
             executable: Any = None,
             meta: Optional[Dict[str, Any]] = None,
             sync: Optional[bool] = None) -> None:
        """Checkpoint ``state`` (any flax-state-dict-able pytree) as
        ``step``.  ``executable`` (anything with
        ``get_plan_fingerprint()``) or ``plan_fingerprint`` stamps the
        manifest for resume validation.  ``sync=True`` forces the write
        inline (the benchmark baseline); default follows ``async_save``.
        """
        import jax
        if jax.process_count() > 1:
            raise NotImplementedError(
                "CheckpointManager is single-controller; multi-host "
                "saves go through serialization.save_checkpoint")
        self._raise_pending_error()
        if plan_fingerprint is None and executable is not None:
            plan_fingerprint = executable.get_plan_fingerprint()

        t0 = time.monotonic()
        save_span = _ttrace.begin(
            "checkpoint.save", "checkpoint",
            {"step": step} if _ttrace.enabled() else None)
        # double buffer: at most ONE write in flight — step N's write
        # must land (or fail) before step N+1's chunks hit the store,
        # which also keeps retention GC from racing fresh chunk files
        self._join_pending()
        t_joined = time.monotonic()

        flat = _flatten_state_dict(state)
        leaves: Dict[str, Dict[str, Any]] = {}
        staged_bytes = 0
        for name, (_path, leaf) in flat.items():
            shape, dtype, pieces = _stage_leaf(leaf)
            staged_bytes += sum(p.nbytes for _i, p in pieces)
            leaves[name] = {"shape": shape, "dtype": dtype,
                            "pieces": pieces}
        t_staged = time.monotonic()
        self.last_staging_seconds = t_staged - t_joined
        metrics.incr("staging_seconds", self.last_staging_seconds)
        metrics.incr("staged_bytes", staged_bytes)

        def write():
            w0 = time.monotonic()
            wtok = (_ttrace.begin("checkpoint.write", "checkpoint",
                                  {"step": step}, "ckpt-writer")
                    if _ttrace.enabled() else None)
            try:
                self.store.write_step(
                    step, leaves, plan_fingerprint=plan_fingerprint,
                    meta=meta, chunk_bytes=self.chunk_bytes)
                self._apply_retention()
            except BaseException as e:  # pylint: disable=broad-except
                logger.exception("async checkpoint write of step %d "
                                 "failed", step)
                with self._err_lock:
                    self._errors.append(CheckpointSaveError(step, e))
                metrics.incr("save_failures")
                return
            finally:
                self.last_write_seconds = time.monotonic() - w0
                metrics.incr("write_seconds", self.last_write_seconds)
                _ttrace.end(wtok)
            metrics.incr("saves")

        if sync if sync is not None else not self.async_save:
            write()
            self.last_blocking_seconds = time.monotonic() - t0
            self._raise_pending_error()
        else:
            t = threading.Thread(target=write, daemon=True,
                                 name=f"ckpt-write-{step}")
            self._pending = t
            self._pending_step = step
            t.start()
            self.last_blocking_seconds = time.monotonic() - t0
        metrics.incr("blocking_seconds", self.last_blocking_seconds)
        _ttrace.end(save_span)

    def _apply_retention(self):
        if self.policy is None:
            return
        doomed = self.policy.to_delete(self.store.all_steps())
        for s in doomed:
            self.store.delete_step(s)
        if doomed:
            self.store.gc()
            logger.info("retention dropped steps %s", doomed)

    def _join_pending(self):
        t = self._pending
        if t is not None:
            t.join()
            self._pending = None
            self._pending_step = None

    def _raise_pending_error(self):
        with self._err_lock:
            if self._errors:
                err = self._errors.pop(0)
                raise err

    def wait(self) -> None:
        """Block until the in-flight write lands; re-raise the first
        background failure (``CheckpointSaveError``)."""
        self._join_pending()
        self._raise_pending_error()

    # ---- introspection ----------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self.store.latest_step()

    def all_steps(self) -> List[int]:
        return self.store.all_steps()

    def last_verified_step(self) -> Optional[int]:
        return self.store.last_verified_step()

    # ---- restore -----------------------------------------------------

    def restore(self, target: Any, step: Optional[int] = None,
                shardings: Any = None,
                expected_plan_fingerprint: Optional[str] = None,
                executable: Any = None,
                verify: bool = True) -> Any:
        """Restore ``target``'s structure from ``step`` (default:
        latest committed).  ``shardings``: optional pytree matching
        ``target`` — each leaf is materialized directly into that
        sharding, reading only the covering chunks (resharding-on-read:
        the saving mesh shape does not matter).  ``verify`` re-hashes
        every chunk read (detects bit-rot/truncation up front)."""
        import jax
        from flax.serialization import from_state_dict, to_state_dict
        t0 = time.monotonic()
        restore_span = _ttrace.begin(
            "checkpoint.restore", "checkpoint",
            {"step": step} if _ttrace.enabled() else None)
        if expected_plan_fingerprint is None and executable is not None:
            expected_plan_fingerprint = executable.get_plan_fingerprint()
        manifest = self.store.read_manifest(step)
        saved_fp = manifest.get("plan_fingerprint")
        if expected_plan_fingerprint is not None:
            if saved_fp is None:
                logger.warning(
                    "checkpoint step %s carries no plan fingerprint; "
                    "cannot validate resume plan", manifest["step"])
            elif saved_fp != expected_plan_fingerprint:
                raise PlanFingerprintMismatch(
                    f"checkpoint step {manifest['step']} was saved under "
                    f"plan {saved_fp[:12]}… but this run compiled plan "
                    f"{expected_plan_fingerprint[:12]}…; restore with "
                    "the saved parallel plan (parallel_plan.plan_to_"
                    "method) or re-checkpoint under the new plan")

        flat = _flatten_state_dict(target)
        shard_flat = {}
        if shardings is not None:
            shard_flat = _flatten_state_dict(shardings)

        new_flat = {}
        for name, (path, _leaf) in flat.items():
            info = manifest["leaves"].get(name)
            if info is None:
                raise KeyError(
                    f"checkpoint step {manifest['step']} has no leaf "
                    f"{name!r}; saved leaves: "
                    f"{sorted(manifest['leaves'])[:8]}…")
            shape = tuple(info["shape"])
            dtype = np.dtype(info["dtype"])
            sharding = shard_flat.get(name, (None, None))[1]
            if sharding is not None:
                def cb(idx, _info=info, _shape=shape, _dtype=dtype):
                    index = tuple(
                        (s.start or 0,
                         s.stop if s.stop is not None else d)
                        for s, d in zip(idx, _shape)) if _shape else ()
                    return jax.numpy.asarray(
                        self.store.read_leaf_slice(_info, index,
                                                   verify=verify),
                        dtype=_dtype)
                new_flat[path] = jax.make_array_from_callback(
                    shape, sharding, cb)
            else:
                full = tuple((0, d) for d in shape) if shape else ()
                new_flat[path] = self.store.read_leaf_slice(
                    info, full, verify=verify)

        sd = to_state_dict(target)

        def rebuild(tree_path, node):
            if isinstance(node, dict):
                return {k: rebuild(tree_path + (k,), v)
                        for k, v in node.items()}
            return new_flat[tree_path]

        restored = from_state_dict(target, rebuild((), sd))
        metrics.incr("restores")
        metrics.incr("restore_seconds", time.monotonic() - t0)
        _ttrace.end(restore_span)
        return restored


class RecoveryCheckpointer:
    """Durable backend for :class:`alpa_tpu.fault.RecoveryManager`.

    * ``snapshot_hook`` — on entry to RECOVERING the recovery manager
      quiesces in-flight work, then this hook writes a SYNCHRONOUS
      (``wait()``-ed) snapshot: durability before the re-probe gamble.
    * restore-on-recover — when the state machine transitions
      RECOVERING/DEGRADED → HEALTHY, the last *verified* step is
      restored and handed to ``state_setter`` before the pre-existing
      resume hook runs: the quiesced in-flight state is gone, so the
      training/serving loop must restart from the snapshot.

    ``state_provider()`` returns the live state pytree to snapshot (and
    the restore target); ``step_provider()`` the step to save under
    (default: one past the newest committed step).  Pass
    ``plan_fingerprint``/``executable`` so resume refuses checkpoints
    from a differently-parallelized program.
    """

    def __init__(self, manager: CheckpointManager, recovery,
                 state_provider: Callable[[], Any],
                 state_setter: Optional[Callable[[Any], Any]] = None,
                 step_provider: Optional[Callable[[], int]] = None,
                 shardings_provider: Optional[Callable[[], Any]] = None,
                 plan_fingerprint: Optional[str] = None,
                 executable: Any = None):
        from alpa_tpu.fault import MeshHealth
        self.manager = manager
        self.recovery = recovery
        self.state_provider = state_provider
        self.state_setter = state_setter
        self.step_provider = step_provider or (
            lambda: (manager.latest_step() or 0) + 1)
        self.shardings_provider = shardings_provider
        if plan_fingerprint is None and executable is not None:
            plan_fingerprint = executable.get_plan_fingerprint()
        self.plan_fingerprint = plan_fingerprint
        self.snapshots_saved = 0
        self.restores_done = 0
        self._needs_restore = False
        self._mesh_health = MeshHealth

        recovery.snapshot_hook = self.snapshot
        self._chain_state_change()
        self._chain_resume()

    # -- wiring --------------------------------------------------------

    def _chain_state_change(self):
        prev = self.recovery.on_state_change
        health = self._mesh_health

        def on_state_change(old, new):
            if new is health.HEALTHY and old in (health.RECOVERING,
                                                 health.DEGRADED):
                self._needs_restore = True
            if prev is not None:
                prev(old, new)

        self.recovery.on_state_change = on_state_change

    def _chain_resume(self):
        prev = self.recovery.resume_hook

        def resume():
            if self._needs_restore:
                self._needs_restore = False
                self.restore_latest_verified()
            if prev is not None:
                prev()

        self.recovery.resume_hook = resume

    # -- hooks ---------------------------------------------------------

    def snapshot(self) -> Optional[int]:
        """Durable snapshot of the live state (RecoveryManager's
        ``snapshot_hook``): synchronous — recovery must not gamble on a
        write that has not landed."""
        step = self.step_provider()
        self.manager.save(step, self.state_provider(),
                          plan_fingerprint=self.plan_fingerprint,
                          meta={"reason": "recovery_snapshot"},
                          sync=True)
        self.manager.wait()
        self.snapshots_saved += 1
        logger.info("recovery snapshot committed as step %d", step)
        return step

    def restore_latest_verified(self) -> Optional[Any]:
        """Restore the newest step whose chunks all pass hash
        verification (a half-written or bit-rotted newest step falls
        back to the one before it)."""
        step = self.manager.last_verified_step()
        if step is None:
            logger.warning("recovery restore requested but the store "
                           "has no verified steps")
            return None
        shardings = (self.shardings_provider()
                     if self.shardings_provider else None)
        restored = self.manager.restore(
            self.state_provider(), step=step, shardings=shardings,
            expected_plan_fingerprint=self.plan_fingerprint)
        if self.state_setter is not None:
            self.state_setter(restored)
        self.restores_done += 1
        logger.info("recovery restored verified step %d", step)
        return restored


def get_checkpoint_stats() -> Dict[str, float]:
    """Process-global checkpoint counters (bytes, timings, failures) —
    surfaced by ``alpa_tpu.monitoring.get_checkpoint_stats``."""
    return metrics.snapshot()


# re-exported for callers that only import the manager module
__all__ = [
    "CheckpointManager", "CheckpointSaveError", "CheckpointNotFoundError",
    "PlanFingerprintMismatch", "RecoveryCheckpointer",
    "get_checkpoint_stats",
]
