"""Checkpoint 2.0: async distributed checkpointing (SURVEY §0 production
persistence; supersedes raw ``alpa_tpu.serialization`` use).

Modules:
  * :mod:`~alpa_tpu.checkpoint.store` — content-addressed chunked shard
    store: sha256-named chunks + one manifest per step carrying each
    leaf's shape/dtype/index-map and chunk hashes.  The manifest commit
    is atomic and LAST, so a ``kill -9`` mid-save can never produce a
    "complete" but corrupt step.
  * :mod:`~alpa_tpu.checkpoint.manager` — :class:`CheckpointManager`
    with async double-buffered device→host staging (step N+1 overlaps
    the disk write of step N), save-failure surfacing, plan-fingerprint
    validation on resume, and retention GC.
  * :mod:`~alpa_tpu.checkpoint.policy` — retention policies
    (keep-last-K + keep-every-N).
  * :mod:`~alpa_tpu.checkpoint.hot_swap` — zero-downtime serving weight
    swap: stage + hash-verify new weights in the background, then swap
    each replica under a drain barrier.

See docs/checkpointing.md for the on-disk layout and walkthroughs.
"""
from alpa_tpu.checkpoint.manager import (CheckpointManager,
                                         PlanFingerprintMismatch,
                                         RecoveryCheckpointer)
from alpa_tpu.checkpoint.policy import RetentionPolicy
from alpa_tpu.checkpoint.store import (ChunkCorruptionError,
                                       CheckpointNotFoundError,
                                       ShardStore)
from alpa_tpu.checkpoint.hot_swap import stage_weights_from_checkpoint

__all__ = [
    "CheckpointManager", "RecoveryCheckpointer", "PlanFingerprintMismatch",
    "RetentionPolicy", "ShardStore", "ChunkCorruptionError",
    "CheckpointNotFoundError", "stage_weights_from_checkpoint",
]
