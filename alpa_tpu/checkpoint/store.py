"""Content-addressed chunked shard store.

On-disk layout (one store holds EVERY step of one training run):

  root/
    chunks/<hh>/<sha256-hex>          # raw C-order bytes of one slice
    manifests/step_<%012d>.json       # one manifest per step

A *chunk* is one contiguous slice of one leaf's global array, named by
the sha256 of its bytes — identical slices across steps (frozen
embeddings, optimizer zeros) are stored once.  A *manifest* records,
per leaf, the global shape/dtype and the index-map: which global slice
each chunk covers and its hash.  "Memory-efficient array redistribution"
(PAPERS.md) motivates the slice-granular layout: restore reads only the
chunks overlapping each device's slice, so a checkpoint saved on one
mesh shape loads onto any other (resharding-on-read).

Crash atomicity: chunks are written tmp-then-rename, and the manifest is
committed (tmp + fsync + rename) strictly LAST — a ``kill -9`` at any
point mid-save leaves either no manifest for the step (the step simply
does not exist; ``latest_step()`` returns the prior one) or a fully
verifiable step.  There is no state in between.
"""
import hashlib
import json
import logging
import os
import re
import tempfile
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from alpa_tpu.checkpoint import metrics

logger = logging.getLogger(__name__)

FORMAT_VERSION = 1

_MANIFEST_RE = re.compile(r"^step_(\d{12})\.json$")


class CheckpointNotFoundError(FileNotFoundError):
    """No committed manifest for the requested step (or no steps at
    all).  A save that died before manifest commit lands here — by
    design it is indistinguishable from a save that never started."""


class ChunkCorruptionError(RuntimeError):
    """A chunk's bytes do not hash to its manifest-recorded name (or the
    chunk file is missing): the step failed verification."""


def _hash_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _norm_index(index) -> Tuple[Tuple[int, int], ...]:
    return tuple((int(a), int(b)) for a, b in index)


def _index_shape(index) -> Tuple[int, ...]:
    return tuple(b - a for a, b in index)


def _overlap(a, b):
    """Intersection of two index-maps (same rank); None if empty."""
    out = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


class ShardStore:
    """Content-addressed chunk + manifest store rooted at ``root``."""

    def __init__(self, root: str):
        self.root = str(root)
        self.chunk_root = os.path.join(self.root, "chunks")
        self.manifest_root = os.path.join(self.root, "manifests")
        os.makedirs(self.chunk_root, exist_ok=True)
        os.makedirs(self.manifest_root, exist_ok=True)
        self._lock = threading.Lock()

    # ---- chunks ------------------------------------------------------

    def chunk_path(self, h: str) -> str:
        return os.path.join(self.chunk_root, h[:2], h)

    def has_chunk(self, h: str) -> bool:
        return os.path.exists(self.chunk_path(h))

    def put_chunk(self, data: bytes) -> str:
        """Write ``data`` as a content-addressed chunk; returns its
        hash.  Idempotent: an existing chunk is never rewritten (the
        content address guarantees it is byte-identical), which is both
        the dedupe fast path and what makes retried saves safe."""
        h = _hash_bytes(data)
        path = self.chunk_path(h)
        if os.path.exists(path):
            metrics.incr("chunks_deduped")
            return h
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   prefix=".tmp_chunk_")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.rename(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        metrics.incr("chunks_written")
        metrics.incr("bytes_written", len(data))
        return h

    def read_chunk(self, h: str, verify: bool = True) -> bytes:
        path = self.chunk_path(h)
        if not os.path.exists(path):
            metrics.incr("verify_failures")
            raise ChunkCorruptionError(f"chunk {h} missing from {path}")
        with open(path, "rb") as f:
            data = f.read()
        metrics.incr("bytes_read", len(data))
        if verify and _hash_bytes(data) != h:
            metrics.incr("verify_failures")
            raise ChunkCorruptionError(
                f"chunk {h} failed hash verification ({path}): the file "
                "was truncated or bit-flipped on disk")
        return data

    # ---- manifests ---------------------------------------------------

    def manifest_path(self, step: int) -> str:
        return os.path.join(self.manifest_root, f"step_{step:012d}.json")

    def commit_manifest(self, step: int, manifest: Dict[str, Any]) -> str:
        """Atomically publish the manifest: this is THE commit point of
        a step.  tmp + fsync + rename; a crash before the rename leaves
        no manifest and therefore no step."""
        path = self.manifest_path(step)
        fd, tmp = tempfile.mkstemp(dir=self.manifest_root,
                                   prefix=".tmp_manifest_")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        return path

    def read_manifest(self, step: Optional[int] = None) -> Dict[str, Any]:
        if step is None:
            step = self.latest_step()
            if step is None:
                raise CheckpointNotFoundError(
                    f"no committed checkpoint steps in {self.root}")
        path = self.manifest_path(step)
        if not os.path.exists(path):
            raise CheckpointNotFoundError(
                f"no committed manifest for step {step} in {self.root} "
                f"(committed steps: {self.all_steps()})")
        with open(path, encoding="utf-8") as f:
            manifest = json.load(f)
        version = manifest.get("format_version")
        if version != FORMAT_VERSION:
            raise ValueError(
                f"manifest {path} has format_version {version}; this "
                f"build reads version {FORMAT_VERSION}")
        return manifest

    def all_steps(self) -> List[int]:
        """Committed steps only (ascending) — a mid-save crash's
        orphan chunks never surface here."""
        steps = []
        for name in os.listdir(self.manifest_root):
            m = _MANIFEST_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # ---- tree save ---------------------------------------------------

    def write_step(self, step: int,
                   leaves: Dict[str, Dict[str, Any]],
                   plan_fingerprint: Optional[str] = None,
                   meta: Optional[Dict[str, Any]] = None,
                   chunk_bytes: int = 64 * 1024 * 1024) -> Dict[str, Any]:
        """Write one step: all chunks first, manifest commit LAST.

        ``leaves``: ``{name: {"shape", "dtype", "pieces": [(index,
        ndarray), ...]}}`` where ``index`` is the global slice the piece
        covers (``()`` for scalars).  Pieces larger than ``chunk_bytes``
        are split along their first nontrivial axis so restore I/O and
        dedupe stay slice-granular.
        """
        manifest = {
            "format_version": FORMAT_VERSION,
            "step": int(step),
            "plan_fingerprint": plan_fingerprint,
            "meta": meta or {},
            "leaves": {},
        }
        for name, info in leaves.items():
            ents = []
            for index, arr in info["pieces"]:
                arr = np.ascontiguousarray(arr)
                for sub_index, sub in self._split(index, arr, chunk_bytes):
                    data = sub.tobytes()
                    h = self.put_chunk(data)
                    ents.append({"index": [list(x) for x in sub_index],
                                 "hash": h, "nbytes": len(data)})
            manifest["leaves"][name] = {
                "shape": [int(d) for d in info["shape"]],
                "dtype": str(info["dtype"]),
                "chunks": ents,
            }
        self.commit_manifest(step, manifest)
        metrics.incr("steps_committed")
        return manifest

    @staticmethod
    def _split(index, arr: np.ndarray, chunk_bytes: int):
        """Split one piece into <= chunk_bytes sub-slices along the
        first axis whose stride allows it (row-granular; never splits
        scalars or rows bigger than the target)."""
        index = _norm_index(index) if index else ()
        if arr.nbytes <= chunk_bytes or arr.ndim == 0 or arr.shape[0] <= 1:
            yield index, arr
            return
        row_bytes = arr.nbytes // arr.shape[0]
        rows = max(1, chunk_bytes // max(1, row_bytes))
        a0 = index[0][0]
        for start in range(0, arr.shape[0], rows):
            stop = min(arr.shape[0], start + rows)
            sub_index = ((a0 + start, a0 + stop),) + index[1:]
            yield sub_index, arr[start:stop]

    # ---- tree restore (resharding-on-read) ---------------------------

    def read_leaf_slice(self, leaf: Dict[str, Any], index,
                        verify: bool = True) -> np.ndarray:
        """Assemble one requested global slice of a leaf from every
        overlapping chunk — the resharding-on-read core: the requested
        slice need not match any slice the save wrote."""
        index = _norm_index(index) if index else ()
        dtype = np.dtype(leaf["dtype"])
        out = np.empty(_index_shape(index), dtype)
        filled = np.zeros(out.shape, bool) if out.ndim else None
        for ent in leaf["chunks"]:
            cidx = _norm_index(ent["index"]) if ent["index"] else ()
            if not index:
                # scalar leaf: the single chunk IS the value
                data = self.read_chunk(ent["hash"], verify)
                return np.frombuffer(data, dtype).reshape(())
            ov = _overlap(index, cidx)
            if ov is None:
                continue
            data = self.read_chunk(ent["hash"], verify)
            chunk = np.frombuffer(data, dtype).reshape(_index_shape(cidx))
            src = tuple(slice(lo - c0, hi - c0)
                        for (lo, hi), (c0, _c1) in zip(ov, cidx))
            dst = tuple(slice(lo - r0, hi - r0)
                        for (lo, hi), (r0, _r1) in zip(ov, index))
            out[dst] = chunk[src]
            filled[dst] = True
        if filled is not None and not filled.all():
            raise ChunkCorruptionError(
                "checkpoint does not cover the requested slice "
                f"{index}: the manifest's index-map has holes (truncated "
                "save or mismatched leaf)")
        return out

    # ---- verification / retention ------------------------------------

    def verify_step(self, step: Optional[int] = None) -> Dict[str, Any]:
        """Re-hash every chunk the step references.  Returns a report;
        ``report["ok"]`` is False when anything is missing/corrupt."""
        manifest = self.read_manifest(step)
        bad: List[Dict[str, str]] = []
        n_chunks = 0
        n_bytes = 0
        for name, leaf in manifest["leaves"].items():
            for ent in leaf["chunks"]:
                n_chunks += 1
                n_bytes += ent["nbytes"]
                try:
                    self.read_chunk(ent["hash"], verify=True)
                except ChunkCorruptionError as e:
                    bad.append({"leaf": name, "hash": ent["hash"],
                                "error": str(e)})
        return {"step": manifest["step"], "ok": not bad,
                "n_chunks": n_chunks, "n_bytes": n_bytes, "bad": bad}

    def last_verified_step(self) -> Optional[int]:
        """Newest step whose every chunk passes hash verification —
        the restore target after a crash or partial disk loss."""
        for step in reversed(self.all_steps()):
            try:
                if self.verify_step(step)["ok"]:
                    return step
            except (ValueError, OSError, json.JSONDecodeError):
                continue
        return None

    def delete_step(self, step: int) -> None:
        """Drop the step's manifest (its chunks stay until ``gc`` —
        other manifests may reference them)."""
        path = self.manifest_path(step)
        if os.path.exists(path):
            os.unlink(path)

    def referenced_hashes(self) -> set:
        refs = set()
        for step in self.all_steps():
            manifest = self.read_manifest(step)
            for leaf in manifest["leaves"].values():
                for ent in leaf["chunks"]:
                    refs.add(ent["hash"])
        return refs

    def gc(self) -> Dict[str, int]:
        """Delete every chunk not referenced by a surviving manifest
        (run after retention deletes manifests, or to reclaim a crashed
        save's orphans).  Concurrency note: the single-writer
        CheckpointManager serializes gc against saves; do not run an
        external gc while a save is in flight."""
        with self._lock:
            refs = self.referenced_hashes()
            removed = 0
            freed = 0
            for sub in os.listdir(self.chunk_root):
                subdir = os.path.join(self.chunk_root, sub)
                if not os.path.isdir(subdir):
                    continue
                for name in os.listdir(subdir):
                    if name.startswith(".tmp_"):
                        # abandoned tmp file from a crashed writer
                        pass
                    elif name in refs:
                        continue
                    path = os.path.join(subdir, name)
                    freed += os.path.getsize(path)
                    os.unlink(path)
                    removed += 1
        metrics.incr("gc_chunks_removed", removed)
        metrics.incr("gc_bytes_freed", freed)
        return {"chunks_removed": removed, "bytes_freed": freed}
