"""alpa_tpu: TPU-native automatic parallelization for jax programs.

A ground-up redesign of Alpa (alpa-projects/alpa) for TPU pods: automatic
inter-operator (pipeline) + intra-operator (sharding) parallelization on top
of stock jax/XLA — GSPMD over ICI for intra-mesh collectives, jax-runtime
DCN transfers for cross-mesh resharding, no forked jaxlib, no Ray.
See SURVEY.md for the design blueprint.
"""
from alpa_tpu import jax_compat
jax_compat.install()

from alpa_tpu.api import (clear_executable_cache, init, shutdown,
                          parallelize, grad, value_and_grad)
from alpa_tpu.device_mesh import (DeviceCluster, DistributedArray,
                                  DistributedPhysicalDeviceMesh,
                                  LocalPhysicalDeviceMesh, LogicalDeviceMesh,
                                  PhysicalDeviceMesh, PhysicalDeviceMeshGroup,
                                  VirtualPhysicalMesh,
                                  get_global_cluster,
                                  get_global_num_devices,
                                  get_global_physical_mesh,
                                  get_global_virtual_physical_mesh,
                                  prefetch,
                                  set_global_physical_mesh,
                                  set_global_virtual_physical_mesh, set_seed)
from alpa_tpu.global_env import global_config
from alpa_tpu.parallel_method import (DataParallel, LocalPipelineParallel,
                                      ParallelMethod, PipeshardParallel,
                                      ShardParallel, Zero2Parallel,
                                      Zero3Parallel, get_3d_parallel_method)
from alpa_tpu.create_state_parallel import CreateStateParallel
from alpa_tpu.data_loader import (DataLoader, DistributedDataLoader,
                                  MeshDriverDataLoader)
from alpa_tpu.follow_parallel import FollowParallel
from alpa_tpu.parallel_plan import (ParallelPlan, executable_to_plan,
                                    plan_to_method)
from alpa_tpu.mesh_profiling import ProfilingResultDatabase
from alpa_tpu.pipeline_parallel.layer_construction import (AutoLayerOption,
                                                           ManualLayerOption,
                                                           automatic_remat,
                                                           manual_remat)
from alpa_tpu.pipeline_parallel.primitive_def import (mark_pipeline_boundary)
from alpa_tpu.pipeline_parallel.stage_construction import (AutoStageOption,
                                                           ManualStageOption,
                                                           UniformStageOption)
from alpa_tpu import fault
from alpa_tpu.serialization import (restore_checkpoint, save_checkpoint)
from alpa_tpu.checkpoint import (CheckpointManager, RecoveryCheckpointer,
                                 RetentionPolicy)
from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption
from alpa_tpu.shard_parallel.manual_sharding import ManualShardingOption
from alpa_tpu.timer import timers, tracer

__version__ = "0.1.0"
