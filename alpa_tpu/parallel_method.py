"""Parallelization strategy objects.

Analog of ref ``alpa/parallel_method.py`` (SURVEY.md §2.1): a
``ParallelMethod`` owns compilation — it turns a traced function plus a mesh
into an executable.  The strategy surface is kept:
``ShardParallel``/``DataParallel``/``Zero2Parallel``/``Zero3Parallel``/
``PipeshardParallel``/``LocalPipelineParallel`` plus
``get_3d_parallel_method`` for manual DP x TP x PP.
"""
import logging
from typing import Any, Callable, Optional, Sequence, Tuple, Union

import numpy as np

from alpa_tpu.device_mesh import (LocalPhysicalDeviceMesh, PhysicalDeviceMesh,
                                  VirtualPhysicalMesh,
                                  get_global_physical_mesh,
                                  get_global_virtual_physical_mesh)
from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption
from alpa_tpu.shard_parallel.manual_sharding import ManualShardingOption

logger = logging.getLogger(__name__)


class ParallelMethod:
    """Base class (ref parallel_method.py:46)."""

    def compile_executable(self, fun, in_avals, in_tree, in_paths,
                           donated_invars, batch_invars):
        raise NotImplementedError


class ShardParallel(ParallelMethod):
    """Intra-op only: shard every operator over one device mesh, optionally
    with gradient accumulation (ref parallel_method.py:64)."""

    def __init__(self,
                 devices: Optional[Union[PhysicalDeviceMesh, Sequence]] = None,
                 num_micro_batches: Optional[int] = None,
                 auto_sharding_option: Optional[AutoShardingOption] = None,
                 manual_sharding_option: Optional[ManualShardingOption] = None):
        if devices is not None and not isinstance(devices, PhysicalDeviceMesh):
            devices = LocalPhysicalDeviceMesh(list(devices))
        self.devices = devices
        self.num_micro_batches = num_micro_batches
        self.as_option = auto_sharding_option or AutoShardingOption()
        self.ms_option = manual_sharding_option

    def _get_mesh(self) -> PhysicalDeviceMesh:
        if self.devices is not None:
            return self.devices
        mesh = get_global_physical_mesh(create_if_not_exist=True)
        return mesh

    def compile_executable(self, fun, in_avals, in_tree, in_paths,
                           donated_invars, batch_invars):
        from alpa_tpu.shard_parallel.compile_executable import (
            compile_shard_executable)
        return compile_shard_executable(fun, self._get_mesh(), in_avals,
                                        in_tree, in_paths, donated_invars,
                                        batch_invars, self.num_micro_batches,
                                        self.as_option, self.ms_option)


class DataParallel(ShardParallel):
    """Pure batch-dim data parallelism (ref parallel_method.py:115)."""

    def __init__(self, devices=None, num_micro_batches=None):
        super().__init__(
            devices, num_micro_batches,
            AutoShardingOption(enable_auto_sharding=False,
                               force_data_parallel=True,
                               force_batch_dim_to_mesh_dim=0))


class Zero2Parallel(ShardParallel):
    """DP + sharded optimizer state / reduce-scattered grads
    (ref parallel_method.py:130)."""

    def __init__(self, devices=None, num_micro_batches=None):
        super().__init__(
            devices, num_micro_batches,
            AutoShardingOption(enable_auto_sharding=False,
                               force_data_parallel=True,
                               prefer_reduce_scatter=True))


class Zero3Parallel(ShardParallel):
    """DP + sharded params/grads/optimizer state (ref parallel_method.py:146)."""

    def __init__(self, devices=None, num_micro_batches=None):
        super().__init__(
            devices, num_micro_batches,
            AutoShardingOption(enable_auto_sharding=False,
                               force_data_parallel=True,
                               prefer_reduce_scatter=True,
                               force_zero_stage_3=True))


class PipeshardParallel(ParallelMethod):
    """Inter-op (pipeline) + intra-op parallelism — the flagship method
    (ref parallel_method.py:160, compile path SURVEY.md §3.3)."""

    def __init__(self,
                 devices: Optional[VirtualPhysicalMesh] = None,
                 num_micro_batches: int = 1,
                 default_auto_sharding_option: Optional[AutoShardingOption] = None,
                 pipeline_schedule: str = "1f1b",
                 layer_option: Optional[Any] = None,
                 stage_option: Optional[Any] = None,
                 stage_input_shardings=None):
        self.devices = devices
        self.num_micro_batches = num_micro_batches
        self.as_option = default_auto_sharding_option or AutoShardingOption()
        self.pipeline_schedule = pipeline_schedule
        self.layer_option = layer_option
        self.stage_option = stage_option
        self.stage_input_shardings = stage_input_shardings

    def compile_executable(self, fun, in_avals, in_tree, in_paths,
                           donated_invars, batch_invars):
        from alpa_tpu.pipeline_parallel.compile_executable import (
            compile_pipeshard_executable)
        mesh = self.devices or get_global_virtual_physical_mesh()
        assert mesh is not None, (
            "No virtual mesh: call alpa_tpu.init() first")
        return compile_pipeshard_executable(
            fun, mesh, in_avals, in_tree, in_paths, donated_invars,
            batch_invars, self.num_micro_batches, self.as_option,
            self.pipeline_schedule, self.layer_option, self.stage_option)


class LocalPipelineParallel(ParallelMethod):
    """Single-device pipeline interpreter for debugging
    (ref parallel_method.py:317 / local_pipeline.py:16)."""

    def compile_executable(self, fun, in_avals, in_tree, in_paths,
                           donated_invars, batch_invars):
        from alpa_tpu.pipeline_parallel.local_pipeline import (
            compile_local_pipeline_executable)
        return compile_local_pipeline_executable(fun, in_avals, in_tree)


def get_3d_parallel_method(num_micro_batches: int,
                           data_parallel: int,
                           operator_parallel: int,
                           pipeline_parallel: int,
                           devices: Optional[VirtualPhysicalMesh] = None,
                           allow_degenerate_into_shard_parallel: bool = True):
    """Manual DP x TP x PP method (ref parallel_method.py:247).

    Slices the cluster into ``pipeline_parallel`` equal submeshes and forces a
    (dp, tp) logical mesh in each stage.
    """
    mesh = devices or get_global_virtual_physical_mesh()
    assert mesh is not None
    dp, op, pp = data_parallel, operator_parallel, pipeline_parallel
    num_devices = mesh.num_devices
    assert dp * op * pp == num_devices, (
        f"dp({dp}) * op({op}) * pp({pp}) != #devices({num_devices})")

    if pp == 1 and allow_degenerate_into_shard_parallel:
        return ShardParallel(
            num_micro_batches=num_micro_batches,
            auto_sharding_option=AutoShardingOption(
                enable_auto_sharding=False,
                force_data_parallel=(op == 1),
                logical_mesh_shape=(dp, op)))

    from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
    from alpa_tpu.pipeline_parallel.stage_construction import ManualStageOption

    # Build per-stage submesh shapes: pp stages, each dp*op devices.
    devices_per_stage = dp * op
    if devices_per_stage >= mesh.num_devices_per_host:
        hosts_per_stage = devices_per_stage // mesh.num_devices_per_host
        submesh = (hosts_per_stage, mesh.num_devices_per_host)
    else:
        submesh = (1, devices_per_stage)
    submeshes = [list(submesh) for _ in range(pp)]
    logical_shapes = [(dp, op) for _ in range(pp)]

    return PipeshardParallel(
        devices=mesh,
        num_micro_batches=num_micro_batches,
        default_auto_sharding_option=AutoShardingOption(
            enable_auto_sharding=False,
            force_data_parallel=(op == 1),
            logical_mesh_shape=(dp, op)),
        pipeline_schedule="1f1b",
        layer_option=AutoLayerOption(layer_num=pp),
        stage_option=ManualStageOption(
            forward_stage_layer_ids=[[i] for i in range(pp)],
            submesh_physical_shapes=submeshes,
            submesh_logical_shapes=logical_shapes,
            submesh_autosharding_option_dicts=[{} for _ in range(pp)]))
