"""Global configuration flags for alpa_tpu.

TPU-native analog of the reference's ``alpa/global_env.py:5-139`` GlobalConfig
singleton.  Unlike the reference there is no driver->Ray-worker snapshot sync
(``update_worker_config``): under jax.distributed every host process reads the
same environment, so flags are plain process-local state seeded from env vars.
"""
import os


def _env_bool(name: str, default: bool) -> bool:
    val = os.environ.get(name)
    if val is None:
        return default
    return val.lower() in ("1", "true", "yes", "on")


class GlobalConfig:
    """Process-global configuration object.

    Mirrors the flag surface of the reference GlobalConfig where the concept
    survives the TPU redesign; NCCL/Ray/cupy flags are intentionally absent.
    """

    def __init__(self):
        # ---------- backend ----------
        # "tpu" | "cpu" | "gpu".  Used to pick the jax platform for meshes.
        self.backend = os.environ.get("ALPA_TPU_BACKEND", None)  # None = jax default
        # Treated like the reference's has_cuda: whether real accelerators exist.
        self.debug_single_device = _env_bool("ALPA_TPU_DEBUG_SINGLE_DEVICE", False)

        # ---------- compilation ----------
        # Print compilation phase timings (ref: debug_compilation_time).
        self.print_compilation_time = _env_bool("ALPA_TPU_PRINT_COMPILATION_TIME", False)
        # Dump intermediate jaxprs / HLO to this dir if set.
        self.dump_debug_info_dir = os.environ.get("ALPA_TPU_DUMP_DIR", None)
        # Use static cost model instead of on-device profiling for auto stage
        # construction (ref: HloCostModelProfileWorker path).  Default True on
        # TPU: spinning up submeshes to profile is slow (SURVEY.md hard part 2).
        self.use_hlo_cost_model = _env_bool("ALPA_TPU_USE_HLO_COST_MODEL", True)
        # Path to a JSON ProfilingResultDatabase (mesh_profiling.profile_all).
        self.profiling_database_filename = os.environ.get(
            "ALPA_TPU_PROF_DATABASE", None)
        # Time limit (seconds) handed to the ILP solver.
        self.ilp_time_limit = int(os.environ.get("ALPA_TPU_ILP_TIME_LIMIT", "600"))
        # Seed used for deterministic compilation decisions.
        self.compile_seed = int(os.environ.get("ALPA_TPU_COMPILE_SEED", "42"))
        # Weight-update (ZeRO) sharding stage: "auto" lets the ILP choose
        # sharded optimizer state by cost (memory term vs all-gather
        # traffic), "0" disables it, "2" shards optimizer state over the
        # data-parallel axis, "3" also shards parameters.  Seeds
        # AutoShardingOption.zero_stage.
        self.zero_stage = os.environ.get("ALPA_TPU_ZERO_STAGE", "auto")

        # ---------- runtime ----------
        # Cross-mesh resharding strategy: "send_recv" | "broadcast".
        # (ref: global_config.resharding_mode)
        self.resharding_mode = os.environ.get("ALPA_TPU_RESHARDING_MODE", "send_recv")
        # How RESHARD instructions move data: "device_put" lets the jax
        # runtime carry the transfer; "planned" drives the tile plan
        # literally (per-tile routed transfers + local-allgather /
        # broadcast legs, ref SymbolicReshardingTask :418) with byte
        # accounting.  "planned" is the validating mode; device_put is the
        # production fast path.
        self.resharding_execution = os.environ.get(
            "ALPA_TPU_RESHARDING_EXEC", "device_put")
        # Load-balancing mode for resharding send selection:
        # "normal" | "no_loadbalance".
        self.resharding_loadbalance_mode = os.environ.get(
            "ALPA_TPU_RESHARDING_LOADBALANCE", "normal")
        # Pipeline instruction dispatch:
        # "auto" | "registers" | "overlap" | "sequential" | "threaded".
        # "registers" replays the build-time register-file lowering (flat
        # slot buffers + precomputed index tuples + cached resharding
        # executors — no dict hashing or sharding resolution per call);
        # "overlap" replays the lowering's instruction dataflow graph
        # with cross-mesh RESHARDs launched eagerly on a transfer pool
        # the moment their producers retire (bounded in-flight window);
        # "threaded" runs the emitter's per-mesh instruction streams on
        # worker threads (the per-host stream analog of ref
        # runtime_emitter's per-worker lists); "auto" picks overlap when
        # eligible (register-eligible AND multi-mesh with cross-mesh
        # RESHARDs AND overlap_resharding), else registers when eligible
        # (single process, device_put resharding), and falls back to the
        # interpreter otherwise.  Tracing, fault injection, and race
        # checking do NOT change the mode: they compile in as per-node
        # hooks on the graph executor (ISSUE 6), so instrumented runs
        # execute the same fast path.  Multi-process always dispatches
        # sequentially: collectives must be issued in the same order on
        # every process.
        self.pipeline_dispatch_mode = os.environ.get(
            "ALPA_TPU_PIPELINE_DISPATCH", "auto")
        # Runtime race detection: threaded dispatch reports every
        # worker's instruction accesses through DispatchRaceChecker;
        # register/overlap replay arms the SlotHazardChecker graph-node
        # hook (slot read/write/free conflicts against in-flight
        # transfers).  A detected race (a partitioner dependency bug)
        # raises instead of corrupting numerics.  Debug tool — adds
        # per-instruction bookkeeping.
        self.debug_dispatch_races = _env_bool(
            "ALPA_TPU_DEBUG_DISPATCH_RACES", False)
        # Collect per-instruction trace events on the dispatch hot loop
        # (any mode — recorded via the unified telemetry recorder and
        # exported by dump_stage_execution_trace).
        self.collect_trace = _env_bool("ALPA_TPU_COLLECT_TRACE", False)
        # Use dummy data for benchmarking (skip real input transfer).
        self.use_dummy_value_for_benchmarking = _env_bool(
            "ALPA_TPU_DUMMY_VALUES", False)
        # Shard the apply_grad computation over the pipeline meshes instead of
        # replicating (ref: grad accumulation + apply grad placement).
        self.pipeline_distributed_apply_grad = _env_bool(
            "ALPA_TPU_DISTRIBUTED_APPLY_GRAD", True)
        # Static plan verification (ISSUE 8): every lowered register-file
        # program runs the alpa_tpu.analysis.plan_verifier analyses (slot
        # typing, cross-mesh deadlock freedom, liveness/leaks, structural
        # invariants) at compile time.  "error" blocks compilation on any
        # finding; "warn" (default) logs and continues; "off" skips.
        # Zero dispatch-replay cost either way — the verifier never runs
        # on the hot path.
        self.verify_plans = os.environ.get(
            "ALPA_TPU_VERIFY_PLANS", "warn")
        # Fifth analysis (ISSUE 13): explicit-state model checking of
        # every lowered plan's stream interleavings under real
        # SEND/RECV FIFO channel semantics.  "all" model-checks every
        # plan; "fixture" (default) only plans small enough to finish
        # in well under a second (<= model_check.FIXTURE_MAX_OPS ops);
        # "off" skips the analysis.  Findings merge into the same
        # PlanVerdict and obey the verify_plans policy above.
        self.verify_plans_model_check = os.environ.get(
            "ALPA_TPU_VERIFY_MODEL_CHECK", "fixture")
        # DFS state budget for the model checker.  Exhaustion degrades
        # to partial coverage (reported as a model.budget-exhausted
        # note + the `partial` stat), never an error.
        self.model_check_state_budget = int(os.environ.get(
            "ALPA_TPU_MODEL_CHECK_BUDGET", "50000"))
        # Sixth analysis (ISSUE 14): numerics certification — a
        # precision-flow abstract interpretation composing the lossy
        # transfer codec's documented error bounds end to end.  "warn"
        # (default) reports findings through the verify_plans policy;
        # "error" blocks _launch with PlanVerificationError on any
        # numerics.* error finding even when verify_plans itself only
        # warns; "off" skips the analysis.
        self.verify_plans_numerics = os.environ.get(
            "ALPA_TPU_VERIFY_NUMERICS", "warn")
        # Per-tensor worst-case relative-error budget (fraction of the
        # codec's block max) the numerics analysis certifies every
        # value's composed bound against; crossing it raises a
        # numerics.budget-exceeded finding.
        self.numerics_error_budget = float(os.environ.get(
            "ALPA_TPU_NUMERICS_ERROR_BUDGET", "0.05"))
        # Seventh analysis (ISSUE 15): translation validation — prove
        # every lowered plan computes the source jaxpr by symbolic
        # execution over hash-consed opaque stage-application terms,
        # modulo the documented rewrite axioms (accumulation
        # reassociation/commutation, resharding identity).  "warn"
        # (default) reports findings through the verify_plans policy;
        # "error" blocks _launch with PlanVerificationError on any
        # equiv.* error finding even when verify_plans itself only
        # warns; "off" skips the analysis.
        self.verify_plans_equiv = os.environ.get(
            "ALPA_TPU_VERIFY_EQUIV", "warn")
        # Hash-consed term budget for the translation validation.
        # Exhaustion degrades to a partial verdict (an
        # equiv.budget-exhausted note + the `partial` stat), never an
        # error.
        self.equiv_term_budget = int(os.environ.get(
            "ALPA_TPU_EQUIV_TERM_BUDGET", "100000"))
        # Whether pipeshard runtime overlaps resharding with compute by
        # issuing transfers as soon as producers finish.  This is the
        # gate for the "overlap" dispatch mode under
        # pipeline_dispatch_mode="auto": set False to pin auto on the
        # synchronous register replay.
        self.overlap_resharding = _env_bool(
            "ALPA_TPU_OVERLAP_RESHARDING", True)
        # In-flight transfer window for overlap dispatch (caps how many
        # cross-mesh RESHARDs may be launched but unwaited, bounding
        # staging memory).  0 = auto: use the pipeline schedule's
        # overlap_window_hint().
        self.overlap_inflight_window = int(os.environ.get(
            "ALPA_TPU_OVERLAP_WINDOW", "0"))
        # Treat every cross-mesh transfer as synchronous: block until the
        # destination arrays have materialized before returning.  The CPU
        # test backend's copies are fully asynchronous, so RESHARD never
        # blocks the dispatching thread there; multi-host send/recv
        # backends do block.  This knob emulates that regime (used by
        # benchmark/bench_dispatch.py's reshard-dominated payload to
        # compare dispatch modes under blocking transfers).
        self.sync_resharding_transfers = _env_bool(
            "ALPA_TPU_SYNC_TRANSFERS", False)
        # Emulated wire latency per cross-mesh transfer call, in seconds
        # (implies synchronous semantics: the transfer materializes, then
        # the calling thread idles for the latency).  The CPU test
        # backend moves shards with an in-process memcpy, so the
        # send/recv wire time a real multi-host link adds is absent;
        # this knob reintroduces it so the dispatch-mode benchmark can
        # measure how much of that idle time each mode hides.  0 = off.
        self.resharding_transfer_latency_s = float(os.environ.get(
            "ALPA_TPU_TRANSFER_LATENCY", "0"))
        # How resharding_transfer_latency_s is charged (ISSUE 7):
        # "call" (legacy) idles once per transfer call regardless of the
        # transfer's link structure; "link" idles latency x the busiest
        # link's message count (plus bytes/bandwidth when
        # resharding_wire_bandwidth is set), so collective strategies
        # that cut per-link messages show their wall-clock win under
        # emulation.  The strategy cost model mirrors whichever model is
        # active, keeping auto selection honest about what it is timed
        # against.
        self.resharding_wire_model = os.environ.get(
            "ALPA_TPU_WIRE_MODEL", "call")
        # Emulated per-link wire bandwidth in bytes/s for the "link"
        # model; 0 = latency-only emulation.
        self.resharding_wire_bandwidth = float(os.environ.get(
            "ALPA_TPU_WIRE_BANDWIDTH", "0"))
        # Cross-mesh RESHARD lowering strategy (ISSUE 7): "auto" picks
        # per edge by the collective cost model (wire-emulation cross
        # leg + mesh_profiling intra-mesh collective leg); forcing
        # "direct_p2p" | "slice_all_gather" | "all_to_all" |
        # "reduce_scatter_gather" pins every edge where the strategy is
        # eligible (ineligible edges fall back to direct_p2p).
        self.reshard_strategy = os.environ.get(
            "ALPA_TPU_RESHARD_STRATEGY", "auto")
        # Lossy transfer codec for cross-mesh ACTIVATION edges (ISSUE 7):
        # "off" | "int8" | "fp8".  Opt-in; applies only to fp32/bf16
        # payloads at least reshard_quantize_min_bytes large, and never
        # to microbatch-invariant values (weights, consts, grad
        # accumulators).  Error bounds: pipeline_parallel/reshard_codec.
        self.reshard_quantize = os.environ.get(
            "ALPA_TPU_RESHARD_QUANTIZE", "off")
        # Minimum payload bytes before the transfer codec applies.
        self.reshard_quantize_min_bytes = int(os.environ.get(
            "ALPA_TPU_RESHARD_QUANTIZE_MIN_BYTES", "65536"))
        # Quantized GRADIENT collectives (ISSUE 19; EQuARX-style):
        # "off" | "int8" | "fp8".  Opt-in: the auto-sharding ILP prices
        # quantized vs full-precision gradient all-reduce /
        # reduce-scatter per tensor and the numerics certifier composes
        # the codec's stochastic-rounding ERROR_BOUND into the
        # end-to-end budget.  "off" produces byte-identical plans,
        # fingerprints, and cache keys.
        self.grad_quantize = os.environ.get(
            "ALPA_TPU_GRAD_QUANTIZE", "off")
        # Minimum gradient tensor bytes before the gradient codec
        # applies; smaller tensors aren't bandwidth-bound and keep the
        # full-precision collective.
        self.grad_quantize_min_bytes = int(os.environ.get(
            "ALPA_TPU_GRAD_QUANTIZE_MIN_BYTES", "65536"))
        # Error feedback for quantized gradients: carry the
        # quantization residual into the next step's quantization so
        # cumulative error stays at the single-shot bound (the numerics
        # analysis amortizes the bound accordingly).  On by default
        # whenever grad_quantize is enabled.
        self.grad_error_feedback = os.environ.get(
            "ALPA_TPU_GRAD_ERROR_FEEDBACK", "on") != "off"

        # ---------- profile-guided replanning (ISSUE 12) ----------
        # Close the loop from measured step performance back into the
        # planners (telemetry/calibration.py): "off" plans from the
        # analytic cost models exactly as before (byte-identical plans,
        # unchanged cache keys); "suggest" consults the measured-cost
        # calibration store and logs the predicted critical-path delta
        # of a replan without applying it; "auto" re-solves with
        # measured costs and hot-swaps the new plan through the compile
        # cache + plan-fingerprint machinery (the static plan verifier
        # re-runs on the swapped plan).
        self.replan_mode = os.environ.get("ALPA_TPU_REPLAN_MODE", "off")
        # Minimum ingested samples before a calibrated entry overrides
        # its analytic prediction; below this the planners fall back to
        # the analytic model.
        self.calibration_min_samples = int(os.environ.get(
            "ALPA_TPU_CALIBRATION_MIN_SAMPLES", "3"))
        # On-disk tier of the calibration store (one JSON file per
        # entry, atomic writes, content-addressed like the compile
        # cache).  Unset = memory-only: measurements calibrate this
        # process but do not persist across restarts.
        self.calibration_dir = os.environ.get(
            "ALPA_TPU_CALIBRATION_DIR", None)

        # ---------- certified plan superoptimization (ISSUE 17) ------
        # Post-lowering rewrite engine over RegisterFileProgram
        # (analysis/superopt.py): instruction re-scheduling, FREE
        # sinking/hoisting, transfer fusion/fission, recompute flips —
        # scored by simulate_dag over calibrated costs and accepted
        # only when the seven-analysis verdict introduces no new
        # (analysis, code) finding vs the baseline.  "off" skips the
        # engine entirely (byte-identical plans); "suggest" searches
        # and reports (superopt.txt, alpa_superopt_* metrics) without
        # applying; "auto" swaps the accepted rewritten program in.
        self.superopt_mode = os.environ.get(
            "ALPA_TPU_SUPEROPT_MODE", "off")
        # Beam width of the greedy rewrite search.
        self.superopt_beam_width = int(os.environ.get(
            "ALPA_TPU_SUPEROPT_BEAM", "4"))
        # Rewrite-step budget: total candidates the search may score.
        self.superopt_step_budget = int(os.environ.get(
            "ALPA_TPU_SUPEROPT_STEPS", "32"))
        # Max candidate lowerings the verdict gate may run per compile
        # (each gate check re-lowers + re-verifies one candidate).
        self.superopt_verify_budget = int(os.environ.get(
            "ALPA_TPU_SUPEROPT_VERIFY_BUDGET", "2"))
        # Transfer-fission cap: max members per batched same-edge
        # RESHARD group (0 = unlimited, the historical coalescer
        # behavior).  Oversized groups serialize behind the
        # overlap_inflight_window; capping lets the search split them.
        self.superopt_max_group = int(os.environ.get(
            "ALPA_TPU_SUPEROPT_MAX_GROUP", "0"))

        # ---------- elastic training (ISSUE 16) ----------
        # ElasticSupervisor budgets (alpa_tpu/elastic.py; see
        # docs/fault_tolerance.md#elastic-training).  Step budget: max
        # committed steps an episode may lose (checkpoint cadence must
        # keep the replay distance under this); exceeding it is recorded
        # in alpa_elastic_budget_violations_total, it never blocks the
        # resume itself.
        self.elastic_step_budget = int(os.environ.get(
            "ALPA_TPU_ELASTIC_STEP_BUDGET", "4"))
        # Wall-clock budget (seconds) for one detect -> resume episode.
        self.elastic_time_budget_s = float(os.environ.get(
            "ALPA_TPU_ELASTIC_TIME_BUDGET", "300"))
        # Preemption grace window (seconds): on a preemption *notice*
        # the supervisor snapshots synchronously and must land the write
        # inside this window for the snapshot to count as before-kill.
        self.elastic_grace_period_s = float(os.environ.get(
            "ALPA_TPU_ELASTIC_GRACE", "30"))
        # How long quiesce() may wait for in-flight pipeshard launches
        # to drain before the episode proceeds with a torn step (the
        # restore path makes that safe — resume replays from the last
        # verified checkpoint either way).
        self.elastic_quiesce_timeout_s = float(os.environ.get(
            "ALPA_TPU_ELASTIC_QUIESCE_TIMEOUT", "60"))
        # Checkpoint every N successful steps while supervised (1 =
        # every step; the replay distance after a failure is at most
        # this interval, so keep it <= elastic_step_budget).
        self.elastic_snapshot_interval = int(os.environ.get(
            "ALPA_TPU_ELASTIC_SNAPSHOT_INTERVAL", "1"))
        # WedgeDetector probe timeout (seconds) — the runbook's
        # ``timeout 120`` leg discipline (scripts/chip_recovery_runbook
        # .sh): a probe that neither answers nor errors inside this
        # window classifies the device as wedged, not dead.
        self.wedge_probe_timeout_s = float(os.environ.get(
            "ALPA_TPU_WEDGE_PROBE_TIMEOUT", "120"))

        # ---------- compile cache ----------
        # On-disk tier of the persistent compile cache (ILP auto-sharding
        # solutions, stage-DP decisions, parallel_plan artifacts — see
        # alpa_tpu/compile_cache.py).  Unset = memory-only cache; set a
        # directory to make warm restarts skip the solvers.
        self.compile_cache_dir = os.environ.get("ALPA_TPU_CACHE_DIR", None)
        # Master switch for the compile cache (both tiers).
        self.compile_cache_enabled = _env_bool(
            "ALPA_TPU_COMPILE_CACHE", True)
        # In-memory LRU capacity (entries) of the compile cache.
        self.compile_cache_memory_entries = int(os.environ.get(
            "ALPA_TPU_COMPILE_CACHE_MEM_ENTRIES", "128"))

        # ---------- telemetry ----------
        # Span tracing master switch (alpa_tpu/telemetry/trace.py).
        # Checked as a module-level flag before any allocation: the
        # register-replay hot path stays within 2% of the no-telemetry
        # baseline when this is off (guarded in tier-1).
        self.telemetry_enabled = _env_bool("ALPA_TPU_TRACE", False)
        # Where scripts/trace_tool.py and instrumented entry points drop
        # Chrome-trace JSON files.  None = caller chooses.
        self.telemetry_trace_dir = os.environ.get(
            "ALPA_TPU_TRACE_DIR", None)
        # Cap on buffered events per TraceRecorder store (spans /
        # instants / counters each); overflow increments a drop counter
        # in the exported trace instead of growing without bound.
        self.telemetry_max_events = int(os.environ.get(
            "ALPA_TPU_TRACE_MAX_EVENTS", "200000"))
        # Flight recorder (alpa_tpu/telemetry/flight.py): fixed-size
        # lock-free ring of the last N instruction events, auto-dumped
        # when a step raises, a fault site fires, or the watchdog
        # declares a mesh SUSPECT.  Cheap enough to leave on in
        # production (one counter bump + one tuple store per
        # instruction), hence default True.
        self.flight_recorder = _env_bool("ALPA_TPU_FLIGHT", True)
        # Ring capacity (instruction events retained); rounded up to a
        # power of two.
        self.flight_recorder_capacity = int(os.environ.get(
            "ALPA_TPU_FLIGHT_CAPACITY", "4096"))
        # Where auto-dumps land.  None = dump_debug_info_dir, else the
        # system temp dir.
        self.flight_dump_dir = os.environ.get("ALPA_TPU_FLIGHT_DIR", None)
        # Chip peak bf16 TFLOPS used by the MFU attribution
        # (telemetry/perf.py — the single formula bench.py and
        # scripts/mfu_breakdown.py also ride).  0 = auto-detect from the
        # TPU generation via mesh_profiling.TPU_GENERATION_SPECS; set
        # explicitly for CPU/emulated runs so stage-MFU numbers stay
        # meaningful.
        self.device_peak_tflops = float(os.environ.get(
            "ALPA_TPU_DEVICE_PEAK_TFLOPS", "0"))

        # ---------- serving: paged KV cache + router (ISSUE 11) ------
        # Master switch: controller replicas build their streaming
        # engines over a serve.kv_cache.KVBlockPool (fixed-size token
        # blocks, refcounted block tables, upfront reservation).  Decode
        # stays bit-exact vs the unpaged engine.
        self.kv_paged = _env_bool("ALPA_TPU_KV_PAGED", False)
        # Tokens per KV block; must divide the model's seq_len.
        self.kv_block_size = int(os.environ.get(
            "ALPA_TPU_KV_BLOCK_SIZE", "16"))
        # Pool capacity in blocks; 0 = auto (two engine batches' worth:
        # one for live sequences, one of headroom for cached prefixes).
        self.kv_cache_blocks = int(os.environ.get(
            "ALPA_TPU_KV_CACHE_BLOCKS", "0"))
        # Cross-request prefix reuse: full prompt/output blocks are
        # published to a hash-chain index (LRU-evicted under pressure);
        # admissions sharing a token prefix skip recomputing those
        # blocks.  Off keeps paging but recomputes every prompt, and
        # preserves the legacy one-static-PrefixHandle register_model
        # semantics (docs/serving.md).
        self.kv_prefix_reuse = _env_bool("ALPA_TPU_KV_PREFIX_REUSE", True)
        # serve.router placement policy: "least_loaded" scores replicas
        # by queue depth + in-flight + tokens; "round_robin" rotates.
        self.router_policy = os.environ.get(
            "ALPA_TPU_ROUTER_POLICY", "least_loaded")
        # Per-replica saturation: a replica whose request p99 exceeds
        # this (milliseconds) is routed around; 0 disables the check.
        self.router_shed_ttft_ms = float(os.environ.get(
            "ALPA_TPU_ROUTER_SHED_TTFT_MS", "0"))
        # Per-replica saturation: queue depth above which a replica is
        # routed around; requests shed (503) only when EVERY healthy
        # replica is saturated.  0 disables.
        self.router_shed_queue_depth = int(os.environ.get(
            "ALPA_TPU_ROUTER_SHED_QUEUE_DEPTH", "64"))
        # Consecutive failed /healthz probes before a replica is
        # dropped from rotation (one clean probe restores it).
        self.router_health_fail_threshold = int(os.environ.get(
            "ALPA_TPU_ROUTER_HEALTH_FAILS", "3"))
        # Autoscale hooks: sliding evaluation window (seconds) over
        # aggregate queue depth...
        self.router_autoscale_window_s = float(os.environ.get(
            "ALPA_TPU_ROUTER_AUTOSCALE_WINDOW", "30"))
        # ...sustained above hi fires on_want_more, sustained below lo
        # fires on_want_fewer (per-replica averages).
        self.router_autoscale_hi_queue = float(os.environ.get(
            "ALPA_TPU_ROUTER_AUTOSCALE_HI_QUEUE", "8"))
        self.router_autoscale_lo_queue = float(os.environ.get(
            "ALPA_TPU_ROUTER_AUTOSCALE_LO_QUEUE", "1"))

        # ---------- serving: disaggregated prefill/decode (ISSUE 18) -
        # Phase-split serving (serve.disagg): "off" keeps the monolithic
        # path byte-identical; "auto" splits whenever the router has at
        # least one prefill-phase AND one decode-phase replica; "forced"
        # requires both pools and sheds (503) when either is missing.
        self.disagg_mode = os.environ.get("ALPA_TPU_DISAGG_MODE", "off")
        # KV handoff payload codec over the wire: "off" ships the block
        # bytes verbatim (bit-exact decode, the default); "int8"/"fp8"
        # ride the reshard_codec blockwise quantizer (lossy within its
        # ERROR_BOUND — docs/serving.md#disaggregated-prefilldecode).
        self.disagg_codec = os.environ.get("ALPA_TPU_DISAGG_CODEC", "off")
        # Decode-pool backpressure: when the decode pool's aggregate
        # depth (queued + in-flight) exceeds this, NEW prefill
        # admissions shed (503) — handoffs already produced are never
        # dropped.  0 disables.
        self.disagg_backpressure_depth = int(os.environ.get(
            "ALPA_TPU_DISAGG_BACKPRESSURE_DEPTH", "0"))
        # Prefill-pool SLO: route around a prefill replica whose
        # router-measured TTFT p99 exceeds this (ms).  0 disables.
        self.disagg_ttft_slo_ms = float(os.environ.get(
            "ALPA_TPU_DISAGG_TTFT_SLO_MS", "0"))
        # Decode-pool SLO: route around a decode replica whose
        # inter-token p99 exceeds this (ms).  0 disables.
        self.disagg_itl_slo_ms = float(os.environ.get(
            "ALPA_TPU_DISAGG_ITL_SLO_MS", "0"))
        # Handoff artifacts retained per prefill engine for corrupt-
        # artifact re-fetch / decode-replica re-ingest (LRU once full;
        # the router acks artifacts as streams finish).
        self.disagg_retain_artifacts = int(os.environ.get(
            "ALPA_TPU_DISAGG_RETAIN_ARTIFACTS", "64"))

        # ---------- checkpointing ----------
        # Local cache dir drained asynchronously to the shared FS
        # (ref: DaemonMoveWorker).
        self.checkpoint_cache_dir = os.environ.get("ALPA_TPU_CKPT_CACHE", None)

        # ---------- testing ----------
        # Replace heavy compile paths with fast ones in unit tests.
        self.testing_mode = _env_bool("ALPA_TPU_TESTING", False)

    def show(self):
        return {k: v for k, v in self.__dict__.items()}


global_config = GlobalConfig()

# Flags appended to XLA_FLAGS at import, mirroring the reference's
# global_env.py:144-146.  Kept minimal: libtpu picks good defaults.
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_tpu_spmd_threshold_for_allgather_cse" not in _xla_flags:
    pass  # placeholder: no forced flags; users own XLA_FLAGS.
