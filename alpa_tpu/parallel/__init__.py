"""Single-program (SPMD) parallelism building blocks.

Unlike ``pipeline_parallel/`` (the Alpa-style multi-executable pipeshard
runtime), these express pipeline/sequence/expert parallelism as collective
programs inside ONE jit — the idiomatic TPU formulation where XLA sees the
whole step and overlaps collectives with compute.
"""
