"""Single-program SPMD pipeline parallelism (GPipe over a mesh axis).

The reference has no analog: its pipeline is a multi-binary Ray runtime
(SURVEY.md §2.4).  On TPU, a pipeline can instead be compiled into ONE XLA
program: stage weights are stacked along a leading axis sharded over the
``pp`` mesh axis; a ``lax.scan`` over clock ticks runs every stage each
tick on its in-flight microbatch, and activations move to the next stage
with ``ppermute`` over ICI.  XLA overlaps the permute with compute, there
is no per-tick host dispatch, and the whole fwd+bwd step differentiates
through the scan (the transpose of ``ppermute`` is the reverse permute, so
the backward pass pipelines in reverse automatically).

Composition: the surrounding jit handles dp/tp via GSPMD shardings
(``shard_map(..., axis_names={'pp'})`` leaves other mesh axes automatic).
"""
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_pytrees(trees):
    """Stack a list of identical pytrees along a new leading axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def spmd_pipeline(stage_fn: Callable,
                  stage_params: Any,
                  microbatches: jnp.ndarray,
                  *,
                  mesh: Mesh,
                  pp_axis: str = "pp",
                  extra_args: Any = None):
    """Run a GPipe pipeline over the ``pp_axis`` of ``mesh`` in one program.

    Args:
      stage_fn: ``(params_slice, x, extra) -> y`` for one pipeline stage;
        ``x`` and ``y`` must have identical shape/dtype.  Called inside a
        partial-manual shard_map: dp/tp axes remain automatic inside.
      stage_params: pytree whose leaves have leading dim ``S`` (= pp size),
        entry s holding stage s's weights.  Sharded over ``pp_axis``.
      microbatches: ``[n_mb, ...]`` stacked microbatch activations.
      extra_args: broadcast pytree passed to every stage (e.g. masks).

    Returns:
      ``[n_mb, ...]`` stacked outputs of the last stage (valid on every
      device; materialized with a masked psum over ``pp_axis``).
    """
    S = mesh.shape[pp_axis]
    n_mb = microbatches.shape[0]
    T = n_mb + S - 1

    def pipelined(params, mbs, extra):
        # leaves arrive with leading dim 1 (this rank's stage); drop it.
        params = jax.tree_util.tree_map(lambda x: x[0], params)
        rank = lax.axis_index(pp_axis)
        is_first = rank == 0
        is_last = rank == S - 1

        fwd_perm = [(i, i + 1) for i in range(S - 1)]

        def tick(carry, t):
            recv, outputs = carry
            mb_idx = jnp.clip(t - 0, 0, n_mb - 1)
            first_in = lax.dynamic_index_in_dim(mbs, mb_idx, axis=0,
                                                keepdims=False)
            x = jnp.where(is_first, first_in, recv)
            y = stage_fn(params, x, extra)
            # shift activations to the next stage
            nxt = lax.ppermute(y, pp_axis, fwd_perm)
            out_idx = jnp.clip(t - (S - 1), 0, n_mb - 1)
            write = jnp.logical_and(is_last, t >= S - 1)
            outputs = lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(write,
                          y.astype(outputs.dtype),
                          lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                                   keepdims=False)),
                out_idx, 0)
            return (nxt, outputs), None

        recv0 = jnp.zeros_like(microbatches[0])
        outputs0 = jnp.zeros_like(mbs)
        (recv, outputs), _ = lax.scan(tick, (recv0, outputs0),
                                      jnp.arange(T))
        # only the last rank holds real outputs; share them over pp
        outputs = jnp.where(is_last, outputs, jnp.zeros_like(outputs))
        outputs = lax.psum(outputs, pp_axis)
        return outputs

    sm = jax.shard_map(pipelined,
                       mesh=mesh,
                       in_specs=(P(pp_axis), P(), P()),
                       out_specs=P(),
                       axis_names={pp_axis},
                       check_vma=False)
    return sm(stage_params, microbatches, extra_args)


def pipeline_train_step_builder(embed_fn: Callable,
                                stage_fn: Callable,
                                head_loss_fn: Callable,
                                *,
                                mesh: Mesh,
                                pp_axis: str = "pp",
                                num_micro_batches: int = 1):
    """Build a full pipelined train-step loss:

      loss(params, batch) = head_loss(pipeline(stages, embed(batch)))

    ``params`` = (embed_params, stacked_stage_params, head_params).
    embed/head run outside the shard_map (replicated over pp; dp/tp by
    GSPMD); the block stack is pipelined.
    """

    def loss_fn(params, batch):
        embed_params, stage_params, head_params = params
        x = embed_fn(embed_params, batch)  # [B, ...]
        B = x.shape[0]
        assert B % num_micro_batches == 0
        mbs = x.reshape((num_micro_batches, B // num_micro_batches) +
                        x.shape[1:])
        y = spmd_pipeline(stage_fn, stage_params, mbs, mesh=mesh,
                          pp_axis=pp_axis)
        y = y.reshape((B,) + y.shape[2:])
        return head_loss_fn(head_params, y, batch)

    return loss_fn
