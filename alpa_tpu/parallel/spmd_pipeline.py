"""Single-program SPMD pipeline parallelism (GPipe over a mesh axis).

The reference has no analog: its pipeline is a multi-binary Ray runtime
(SURVEY.md §2.4).  On TPU, a pipeline can instead be compiled into ONE XLA
program: stage weights are stacked along a leading axis sharded over the
``pp`` mesh axis; a ``lax.scan`` over clock ticks runs every stage each
tick on its in-flight microbatch, and activations move to the next stage
with ``ppermute`` over ICI.  XLA overlaps the permute with compute, there
is no per-tick host dispatch, and the whole fwd+bwd step differentiates
through the scan (the transpose of ``ppermute`` is the reverse permute, so
the backward pass pipelines in reverse automatically).

Composition: the surrounding jit handles dp/tp via GSPMD shardings
(``shard_map(..., axis_names={'pp'})`` leaves other mesh axes automatic).
"""
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_pytrees(trees):
    """Stack a list of identical pytrees along a new leading axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def spmd_pipeline(stage_fn: Callable,
                  stage_params: Any,
                  microbatches: jnp.ndarray,
                  *,
                  mesh: Mesh,
                  pp_axis: str = "pp",
                  extra_args: Any = None):
    """Run a GPipe pipeline over the ``pp_axis`` of ``mesh`` in one program.

    Args:
      stage_fn: ``(params_slice, x, extra) -> y`` for one pipeline stage;
        ``x`` and ``y`` must have identical shape/dtype.  Called inside a
        partial-manual shard_map: dp/tp axes remain automatic inside.
      stage_params: pytree whose leaves have leading dim ``S`` (= pp size),
        entry s holding stage s's weights.  Sharded over ``pp_axis``.
      microbatches: ``[n_mb, ...]`` stacked microbatch activations.
      extra_args: broadcast pytree passed to every stage (e.g. masks).

    Returns:
      ``[n_mb, ...]`` stacked outputs of the last stage (valid on every
      device; materialized with a masked psum over ``pp_axis``).
    """
    S = mesh.shape[pp_axis]
    n_mb = microbatches.shape[0]
    T = n_mb + S - 1

    def pipelined(params, mbs, extra):
        # leaves arrive with leading dim 1 (this rank's stage); drop it.
        params = jax.tree_util.tree_map(lambda x: x[0], params)
        rank = lax.axis_index(pp_axis)
        is_first = rank == 0
        is_last = rank == S - 1

        fwd_perm = [(i, i + 1) for i in range(S - 1)]

        def tick(carry, t):
            recv, outputs = carry
            mb_idx = jnp.clip(t - 0, 0, n_mb - 1)
            first_in = lax.dynamic_index_in_dim(mbs, mb_idx, axis=0,
                                                keepdims=False)
            x = jnp.where(is_first, first_in, recv)
            y = stage_fn(params, x, extra)
            # shift activations to the next stage
            nxt = lax.ppermute(y, pp_axis, fwd_perm)
            out_idx = jnp.clip(t - (S - 1), 0, n_mb - 1)
            write = jnp.logical_and(is_last, t >= S - 1)
            outputs = lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(write,
                          y.astype(outputs.dtype),
                          lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                                   keepdims=False)),
                out_idx, 0)
            return (nxt, outputs), None

        recv0 = jnp.zeros_like(microbatches[0])
        outputs0 = jnp.zeros_like(mbs)
        (recv, outputs), _ = lax.scan(tick, (recv0, outputs0),
                                      jnp.arange(T))
        # only the last rank holds real outputs; share them over pp
        outputs = jnp.where(is_last, outputs, jnp.zeros_like(outputs))
        outputs = lax.psum(outputs, pp_axis)
        return outputs

    sm = jax.shard_map(pipelined,
                       mesh=mesh,
                       in_specs=(P(pp_axis), P(), P()),
                       out_specs=P(),
                       axis_names={pp_axis},
                       check_vma=False)
    return sm(stage_params, microbatches, extra_args)


def spmd_pipeline_1f1b(stage_fn: Callable,
                       last_stage_loss_fn: Callable,
                       stage_params: Any,
                       microbatches: jnp.ndarray,
                       mb_labels: Any,
                       *,
                       mesh: Mesh,
                       pp_axis: str = "pp",
                       extra_args: Any = None):
    """Single-program 1F1B pipeline: fwd and bwd interleaved in ONE scan.

    GPipe-via-autodiff (``spmd_pipeline`` + ``jax.grad``) keeps every
    microbatch's activations alive across the forward scan — O(n_mb)
    memory.  Here each global tick runs one forward AND one backward unit
    per rank (classic 1F1B: a microbatch's backward starts as soon as its
    forward reaches the last stage), so at most ``2S-1`` microbatch
    activations are in flight — O(S) memory — and only the stage INPUT is
    stored (the stage body recomputes inside ``jax.vjp`` at its backward
    tick: per-stage remat).  Activations flow to the next rank and
    cotangents to the previous rank with ``ppermute`` over ICI each tick;
    XLA overlaps both with compute.  Semantic target: the multi-mesh
    runtime's 1F1B order (ref alpa/pipeline_parallel/schedules.py:271);
    no reference analog exists for the single-program form.

    Schedule (rank r of S, microbatch m of M, tick t of M + 2S - 2):
      forward  of m at rank r:  t = m + r
      backward of m at rank r:  t = m + 2(S-1) - r
    On the last rank both land on the same tick: forward, loss, and the
    seed cotangent happen together and backward starts immediately.

    Args:
      stage_fn: ``(params_slice, x, extra) -> y``, same contract as
        :func:`spmd_pipeline`.
      last_stage_loss_fn: ``(y, label_slice) -> scalar`` mean-per-
        microbatch loss applied to the LAST stage's output; its VJP seeds
        the backward pass on-pipeline.
      stage_params: pytree, leaves ``[S, ...]``, sharded over ``pp_axis``.
      microbatches: ``[M, mb, ...]`` stacked first-stage inputs.
      mb_labels: pytree of ``[M, ...]`` per-microbatch labels.

    Returns:
      (mean_loss, stage_grads, d_microbatches): loss averaged over
      microbatches; grads with the same ``[S, ...]`` layout as
      ``stage_params``; cotangents of ``microbatches`` for chaining into
      an embedding backward.
    """
    S = mesh.shape[pp_axis]
    M = microbatches.shape[0]
    T = M + 2 * S - 2
    n_slots = 2 * S  # > max in-flight (2S-1)

    def pipelined(params, mbs, labels, extra):
        params = jax.tree_util.tree_map(lambda x: x[0], params)
        rank = lax.axis_index(pp_axis)
        is_first = rank == 0
        is_last = rank == S - 1
        fwd_perm = [(i, i + 1) for i in range(S - 1)]
        bwd_perm = [(i + 1, i) for i in range(S - 1)]

        def tick(carry, t):
            xbuf, recv_y, recv_dy, wgrad, loss_acc, dx_out = carry

            # ---------------- forward unit ----------------
            m_f = t - rank
            do_f = jnp.logical_and(m_f >= 0, m_f < M)
            m_f_c = jnp.clip(m_f, 0, M - 1)
            x_in = jnp.where(is_first,
                             lax.dynamic_index_in_dim(mbs, m_f_c, 0,
                                                      keepdims=False),
                             recv_y)
            y = stage_fn(params, x_in, extra)
            slot_f = m_f_c % n_slots
            old = lax.dynamic_index_in_dim(xbuf, slot_f, 0, keepdims=False)
            xbuf = lax.dynamic_update_index_in_dim(
                xbuf, jnp.where(do_f, x_in, old), slot_f, 0)

            # ---------------- backward unit ----------------
            m_b = t - 2 * (S - 1) + rank
            do_b = jnp.logical_and(m_b >= 0, m_b < M)
            m_b_c = jnp.clip(m_b, 0, M - 1)
            x_saved = lax.dynamic_index_in_dim(xbuf, m_b_c % n_slots, 0,
                                               keepdims=False)
            lbl = jax.tree_util.tree_map(
                lambda l: lax.dynamic_index_in_dim(l, m_b_c, 0,
                                                   keepdims=False), labels)
            # ONE recomputed-fwd VJP serves both cases via a masked
            # surrogate: the last rank differentiates loss/M (seeding the
            # pipeline backward), other ranks differentiate <y, recv_dy>
            # (i.e. the VJP against the received cotangent).  jnp.where
            # routes the cotangent, so the unselected branch contributes
            # zero gradient.
            def surrogate(p, x):
                y = stage_fn(p, x, extra)
                loss = last_stage_loss_fn(y, lbl)
                pulled = jnp.sum(y.astype(jnp.float32) *
                                 recv_dy.astype(jnp.float32))
                return jnp.where(is_last, loss / M, pulled), loss

            (dp, dx), loss_m = jax.grad(
                surrogate, argnums=(0, 1), has_aux=True)(params, x_saved)
            wgrad = jax.tree_util.tree_map(
                lambda w, g: w + jnp.where(do_b, g, jnp.zeros_like(g)),
                wgrad, dp)
            loss_acc = loss_acc + jnp.where(
                jnp.logical_and(is_last, do_b), loss_m / M, 0.0)
            dx_first = jnp.where(
                jnp.logical_and(is_first, do_b), dx,
                jnp.zeros_like(dx))
            dx_out = lax.dynamic_update_index_in_dim(
                dx_out,
                dx_first + lax.dynamic_index_in_dim(dx_out, m_b_c, 0,
                                                    keepdims=False),
                m_b_c, 0)

            # ---------------- communicate ----------------
            nxt_y = lax.ppermute(y, pp_axis, fwd_perm)
            nxt_dy = lax.ppermute(dx, pp_axis, bwd_perm)
            return (xbuf, nxt_y, nxt_dy, wgrad, loss_acc, dx_out), None

        mb_shape = microbatches.shape[1:]
        xbuf0 = jnp.zeros((n_slots,) + mb_shape, microbatches.dtype)
        recv0 = jnp.zeros(mb_shape, microbatches.dtype)
        wgrad0 = jax.tree_util.tree_map(jnp.zeros_like, params)
        dx_out0 = jnp.zeros_like(mbs)
        carry0 = (xbuf0, recv0, recv0, wgrad0, jnp.zeros(()), dx_out0)
        (xbuf, _, _, wgrad, loss_acc, dx_out), _ = lax.scan(
            tick, carry0, jnp.arange(T))

        # loss lives on the last rank, dx_out on the first: share over pp
        loss = lax.psum(jnp.where(is_last, loss_acc, 0.0), pp_axis)
        dx_out = lax.psum(
            jnp.where(is_first, dx_out, jnp.zeros_like(dx_out)), pp_axis)
        # re-attach the leading stage dim for the [S, ...] grads layout
        wgrad = jax.tree_util.tree_map(lambda g: g[None], wgrad)
        return loss, wgrad, dx_out

    sm = jax.shard_map(pipelined,
                       mesh=mesh,
                       in_specs=(P(pp_axis), P(), P(), P()),
                       out_specs=(P(), P(pp_axis), P()),
                       axis_names={pp_axis},
                       check_vma=False)
    return sm(stage_params, microbatches, mb_labels, extra_args)


def pipeline_train_step_builder(embed_fn: Callable,
                                stage_fn: Callable,
                                head_loss_fn: Callable,
                                *,
                                mesh: Mesh,
                                pp_axis: str = "pp",
                                num_micro_batches: int = 1):
    """Build a full pipelined train-step loss:

      loss(params, batch) = head_loss(pipeline(stages, embed(batch)))

    ``params`` = (embed_params, stacked_stage_params, head_params).
    embed/head run outside the shard_map (replicated over pp; dp/tp by
    GSPMD); the block stack is pipelined.
    """

    def loss_fn(params, batch):
        embed_params, stage_params, head_params = params
        x = embed_fn(embed_params, batch)  # [B, ...]
        B = x.shape[0]
        assert B % num_micro_batches == 0
        mbs = x.reshape((num_micro_batches, B // num_micro_batches) +
                        x.shape[1:])
        y = spmd_pipeline(stage_fn, stage_params, mbs, mesh=mesh,
                          pp_axis=pp_axis)
        y = y.reshape((B,) + y.shape[2:])
        return head_loss_fn(head_params, y, batch)

    return loss_fn
