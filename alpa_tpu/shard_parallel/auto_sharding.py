"""Auto-sharding: decide a sharding for every input/output/intermediate.

Reference architecture (SURVEY.md §2.3): a forked-XLA C++ ``AutoSharding``
pass builds per-instruction strategy vectors, an ILP picks one per op
(``alpa/shard_parallel/auto_sharding.py:617-872``), and GSPMD partitions the
annotated module.  TPU-native redesign: the strategy enumeration and ILP run
in Python over the *jaxpr* (see ``solver.py``), and the chosen strategies are
emitted as pjit ``in_shardings``/``out_shardings`` plus
``with_sharding_constraint`` on intermediate values; stock libtpu's GSPMD
partitioner does the rest.

``AutoShardingOption`` keeps the reference's option surface
(ref auto_sharding.py:48-79) where it still means something on TPU.
"""
import dataclasses
import logging
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from alpa_tpu.device_mesh import LogicalDeviceMesh
from alpa_tpu.global_env import global_config

logger = logging.getLogger(__name__)

# Mesh axis names used by shard-parallel compiled programs.
MESH_AXIS_NAMES = ("mesh0", "mesh1")

########################################
# pytree-path classification (weight-update sharding)
########################################

# keystr paths look like ``[0].opt_state[0].mu['Dense_0']['kernel']``;
# split them into identifier components so e.g. the component ``nu``
# never matches a param named ``num_embeddings`` (ISSUE 10 satellite
# bugfix — the old substring test did).
_PATH_COMPONENT_RE = re.compile(r"[A-Za-z0-9_]+")

# pytree components that mark an optimizer-state leaf: the optax/flax
# ``opt_state`` subtree, Adam moments, SGD momentum, RMSProp trace.
_OPT_STATE_COMPONENTS = frozenset(
    ("opt_state", "mu", "nu", "momentum", "trace"))


def path_components(path: str) -> Tuple[str, ...]:
    """Identifier components of a ``jax.tree_util.keystr`` path."""
    return tuple(_PATH_COMPONENT_RE.findall(path or ""))


def is_opt_state_path(path: str) -> bool:
    """True when a flat-invar path names an optimizer-state leaf.

    ``opt_state`` anywhere in the path wins; outside an ``opt_state``
    subtree, a ``params`` component wins (a parameter literally named
    ``mu`` is still a parameter); bare moment/momentum components are
    recognized for optimizer states passed outside a TrainState.
    """
    comps = set(path_components(path))
    if "opt_state" in comps:
        return True
    if "params" in comps:
        return False
    return bool(comps & _OPT_STATE_COMPONENTS)


def is_param_path(path: str) -> bool:
    """True when a flat-invar path names a parameter leaf (and not an
    optimizer-state mirror of one)."""
    comps = set(path_components(path))
    return "params" in comps and "opt_state" not in comps


def resolved_zero_stage(option: "AutoShardingOption") -> int:
    """Resolve the ``zero_stage`` knob plus the legacy forcing flags to
    one of ``0`` (off), ``2``, ``3`` (forced), or ``-1`` (auto: the
    solver weighs the memory term against all-gather traffic)."""
    z = str(getattr(option, "zero_stage", "auto") or "auto")
    if z == "auto":
        if option.force_zero_stage_3:
            return 3
        if option.prefer_reduce_scatter:
            return 2
        return -1
    if z not in ("0", "2", "3"):
        raise ValueError(
            f"zero_stage must be one of auto|0|2|3, got {z!r} "
            "(set via AutoShardingOption.zero_stage or "
            "ALPA_TPU_ZERO_STAGE)")
    return int(z)


@dataclasses.dataclass
class AutoShardingOption:
    """Options controlling the auto-sharding planner
    (ref alpa/shard_parallel/auto_sharding.py:48)."""
    # Search over sharding strategies with the ILP (False = rule-based).
    enable_auto_sharding: bool = True
    # Force all parallelism to be batch-dim data parallelism.
    force_data_parallel: bool = False
    # Prefer reduce-scatter + sharded optimizer state (ZeRO-2).
    prefer_reduce_scatter: bool = False
    # Shard parameters too (ZeRO-3).
    force_zero_stage_3: bool = False
    # Threshold (bytes) above which ZeRO-3 keeps params sharded.
    force_zero_stage_3_all_gather_threshold: int = 1 << 26
    # Map the batch dim onto this logical mesh dim (None = solver decides).
    force_batch_dim_to_mesh_dim: Optional[int] = None
    # Allow all-to-all (expert-parallel style) strategies.
    allow_all_to_all: bool = True
    # Allow all-gather strategies.
    allow_all_gather: bool = True
    # Also consider 1-D logical mesh shapes (ref allow_mixed_mesh_shape).
    allow_mixed_mesh_shape: bool = False
    # Memory budget per device in bytes (None = unlimited).
    memory_budget_per_device: Optional[int] = None
    # ILP: abort if solve takes longer than this many seconds.
    solver_timeout: int = 600
    # Logical mesh shape override, e.g. (2, 4).  None = physical shape.
    logical_mesh_shape: Optional[Tuple[int, ...]] = None
    # Insert with_sharding_constraint on solved dot outputs so GSPMD
    # follows the ILP exactly (auto-disabled when remat is present).
    emit_sharding_constraints: bool = True
    # Outputs smaller than this many elements are left to propagation
    # (pinning tiny tensors can force costly GSPMD transitions).  Set 0 to
    # constrain everything.
    constrain_min_elements: int = 1 << 16
    mesh_shape_search: bool = False
    # Weight-update (ZeRO) sharding stage: "auto" enumerates sharded
    # optimizer-state strategies and lets the ILP pick them by cost
    # (memory term vs all-gather traffic); "0" disables weight-update
    # sharding entirely; "2" forces optimizer-state sharding over the
    # dp axis (reduce-scattered grads); "3" also shards parameters.
    # Seeded from global_config.zero_stage (env ALPA_TPU_ZERO_STAGE).
    zero_stage: str = dataclasses.field(
        default_factory=lambda: global_config.zero_stage)

    def copy(self):
        return dataclasses.replace(self)


def replicated(mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_dim(mesh, dim: int, axis_name: str, ndim: int) -> NamedSharding:
    spec = [None] * ndim
    spec[dim] = axis_name
    return NamedSharding(mesh, PartitionSpec(*spec))


def _largest_divisible_dim(shape, size: int) -> Optional[int]:
    """Pick the largest dim divisible by ``size`` (prefer later dims on
    ties, which tend to be feature dims laid out well for TPU tiling)."""
    best, best_len = None, 0
    for i, s in enumerate(shape):
        if s % size == 0 and s >= best_len and s >= size:
            best, best_len = i, s
    return best


def plan_rule_based(jax_mesh,
                    avals: Sequence[Any],
                    in_paths: Sequence[str],
                    batch_flat_idx: Sequence[int],
                    option: AutoShardingOption):
    """Rule-based sharding plan (no search).

    Realizes DataParallel / Zero2Parallel / Zero3Parallel
    (ref alpa/parallel_method.py:115-159) as explicit NamedShardings:

    * batch args: dim 0 sharded over mesh axis 0 -> pure DP; gradient
      all-reduce is inserted by GSPMD.
    * ZeRO-2 (prefer_reduce_scatter): optimizer-state leaves sharded over the
      dp axis; XLA converts grad all-reduce + dynamic-slice into
      reduce-scatter (the ref achieves this inside the ILP,
      auto_sharding.py:69,290).
    * ZeRO-3 (force_zero_stage_3): parameter leaves sharded too; GSPMD
      inserts param all-gathers at use sites.
    """
    dp_axis = MESH_AXIS_NAMES[0]
    dp_size = int(np.prod([jax_mesh.shape[a] for a in jax_mesh.axis_names]))
    zero = resolved_zero_stage(option)
    in_shardings = []
    batch_set = set(batch_flat_idx)
    for i, (aval, path) in enumerate(zip(avals, in_paths)):
        ndim = len(aval.shape)
        if i in batch_set and ndim >= 1 and aval.shape[0] % dp_size == 0:
            spec = [None] * ndim
            spec[0] = tuple(jax_mesh.axis_names)  # batch over all axes
            in_shardings.append(NamedSharding(jax_mesh, PartitionSpec(*spec)))
            continue
        is_opt_state = is_opt_state_path(path)
        is_param = is_param_path(path)
        shard_it = ((zero in (2, 3) and is_opt_state) or
                    (zero == 3 and (is_opt_state or is_param)))
        if shard_it:
            d = _largest_divisible_dim(aval.shape, jax_mesh.shape[dp_axis])
            if d is not None:
                in_shardings.append(
                    shard_dim(jax_mesh, d, dp_axis, ndim))
                continue
        in_shardings.append(replicated(jax_mesh))
    return in_shardings


def input_sharding_to_spec(sharding: NamedSharding) -> PartitionSpec:
    return sharding.spec
