"""Auto-sharding driver: mesh-shape search + strategy graph + ILP.

Replaces the reference's ``run_auto_sharding_pass``
(``alpa/shard_parallel/auto_sharding.py:172-370``, which drives the forked
C++ AutoSharding pass): traces the flat function, builds the jaxpr-level
strategy graph (strategy.py), solves the one-hot ILP (ilp.py) for every
candidate logical mesh shape (the analog of the reference's logical-shape
enumeration in stage_construction.py:456-526), and emits the winning
assignment as pjit ``in_shardings``.

GSPMD sharding propagation in stock libtpu then plays the role of the
reference's SPMD partitioner pass: with all inputs optimally sharded,
propagation reproduces the intra-op plan (column/row-parallel dots, ZeRO
layouts) without any custom XLA pass.
"""
import logging
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding

from alpa_tpu.compile_cache import cache_enabled, get_compile_cache
from alpa_tpu.device_mesh import PhysicalDeviceMesh
from alpa_tpu.global_env import global_config
from alpa_tpu.shard_parallel.auto_sharding import (AutoShardingOption,
                                                  MESH_AXIS_NAMES)
from alpa_tpu.shard_parallel.ilp import (InfeasibleMemoryBudget,
                                         solution_cost, solve_strategy_graph)
from alpa_tpu.shard_parallel.sharding_spec import spec_to_partition_spec
from alpa_tpu.shard_parallel.strategy import build_strategy_graph
from alpa_tpu.telemetry import trace as _ttrace

logger = logging.getLogger(__name__)


def candidate_mesh_shapes(num_devices: int,
                          option: AutoShardingOption,
                          symmetric_axes: bool = False
                          ) -> List[Tuple[int, int]]:
    """2-D logical shapes to search (ref stage_construction.py:456-526)."""
    if option.logical_mesh_shape is not None:
        return [tuple(option.logical_mesh_shape)]
    shapes = []
    d = 1
    while d <= num_devices:
        if num_devices % d == 0:
            shapes.append((d, num_devices // d))
        d *= 2
    if symmetric_axes:
        # On a single host the two axes have identical alpha/beta, so
        # (d, n/d) and (n/d, d) build isomorphic graphs — search one.
        shapes = [s for s in shapes if s[0] <= s[1]] or shapes[:1]
    return shapes


def _grad_quantize_cache_token() -> Optional[str]:
    """ILP cache-key token for the quantized-gradient knobs (ISSUE 19).
    None at ``grad_quantize=off`` so default-mode keys stay
    byte-identical with plans solved before this feature existed."""
    mode = getattr(global_config, "grad_quantize", "off")
    if mode == "off":
        return None
    return "gq:{}:{}:{}".format(
        mode,
        int(getattr(global_config, "grad_quantize_min_bytes", 65536)),
        1 if getattr(global_config, "grad_error_feedback", True) else 0)


def _note_grad_quantized_choices(graph, choice) -> None:
    """Export plan-time metrics for gradient tensors the ILP routed
    through the codec (the byte math is static, so counting happens
    here rather than inside the jitted step)."""
    for node, s in zip(graph.nodes, choice):
        if node.kind != "invar":
            continue
        st = node.strategies[s]
        codec = getattr(st, "codec", None)
        if not codec:
            continue
        from alpa_tpu.pipeline_parallel import reshard_codec as _codec
        shape = tuple(getattr(node.aval, "shape", ()))
        itemsize = int(np.dtype(node.aval.dtype).itemsize)
        full = int(np.prod(shape, dtype=np.int64) if shape else 1) * itemsize
        _codec.note_grad_quantized(
            codec, full, _codec.grad_wire_bytes(shape, itemsize, codec))


def plan_auto_sharding(fun: Callable,
                       in_avals: Sequence[Any],
                       in_paths: Sequence[str],
                       batch_flat_idx: Sequence[int],
                       physical_mesh: PhysicalDeviceMesh,
                       option: AutoShardingOption,
                       return_graph: bool = False):
    """Search logical mesh shapes; returns
    (jax_mesh, flat in_shardings, constraint_fn or None, chosen_shape);
    with ``return_graph`` also (graph, choice) of the winning solve —
    used by fidelity tests comparing the ILP solution to compiled HLO."""
    closed_jaxpr = jax.make_jaxpr(fun)(*in_avals)

    # The winning (shape, choice) is a pure function of the jaxpr, the
    # physical mesh extent, and the option — replay it from the compile
    # cache instead of re-running the ILP over every candidate shape.
    # ``return_graph`` callers are fidelity tests validating the solver
    # itself, so they always solve fresh.
    cache = key = None
    if not return_graph and cache_enabled():
        cache = get_compile_cache()
        from alpa_tpu.telemetry.calibration import calibration_cache_token
        cal_tok = calibration_cache_token()
        gq_tok = _grad_quantize_cache_token()
        key = cache.make_key("ilp", [
            "plan_auto_sharding",
            str(closed_jaxpr),
            repr([str(a) for a in in_avals]),
            repr(tuple(in_paths)),
            repr(tuple(batch_flat_idx)),
            repr((physical_mesh.num_hosts, physical_mesh.num_devices)),
            option,
            # calibration fingerprint (ISSUE 12): absent when
            # replan_mode=off so off-mode keys stay byte-identical;
            # grad-quantize token (ISSUE 19): same contract — absent at
            # grad_quantize=off
        ] + ([cal_tok] if cal_tok else [])
          + ([gq_tok] if gq_tok else []))
        entry = cache.get("ilp", key)
        if entry is not None:
            with _ttrace.span("ilp-cache-replay", "compile",
                              {"cache": "hit"} if _ttrace.enabled()
                              else None):
                replayed = _replay_cached_solution(
                    closed_jaxpr, in_avals, in_paths, batch_flat_idx,
                    physical_mesh, option, entry)
            if replayed is not None:
                cache.record_saved_seconds(
                    "ilp", entry.get("solve_seconds", 0.0))
                shape, logical_mesh, graph, choice = replayed
                return _assemble_plan(closed_jaxpr, in_avals, in_paths,
                                      batch_flat_idx, option, shape,
                                      logical_mesh, graph, choice,
                                      return_graph)

    solve_span = _ttrace.begin(
        "ilp-solve", "compile",
        {"cache": "miss" if cache is not None else "off"}
        if _ttrace.enabled() else None)
    best = None
    tic = time.time()
    infeasible = None
    for shape in candidate_mesh_shapes(physical_mesh.num_devices, option,
                                       physical_mesh.num_hosts == 1):
        logical_mesh = physical_mesh.get_logical_mesh(shape)
        graph = build_strategy_graph(closed_jaxpr, in_avals, logical_mesh,
                                     batch_flat_idx, option,
                                     in_paths=in_paths)
        try:
            with _ttrace.span("ilp-solve-shape", "compile",
                              {"shape": str(shape)} if _ttrace.enabled()
                              else None):
                choice = solve_strategy_graph(
                    graph, option.solver_timeout,
                    option.memory_budget_per_device)
        except InfeasibleMemoryBudget as e:
            # e.g. a (1, n) shape cannot shard a dim this shape could;
            # another candidate may still fit the budget
            logger.debug("mesh shape %s infeasible under memory budget: %s",
                         shape, e)
            infeasible = e
            continue
        cost = solution_cost(graph, choice)
        logger.debug("mesh shape %s: cost %.4f (%s)", shape, cost,
                     graph.stats())
        if best is None or cost < best[0]:
            best = (cost, shape, logical_mesh, graph, choice)
    if best is None:
        _ttrace.end(solve_span)
        raise infeasible
    cost, shape, logical_mesh, graph, choice = best
    solve_seconds = time.time() - tic
    _ttrace.end(solve_span)
    if global_config.print_compilation_time:
        logger.warning("auto-sharding search took %.2f s; picked %s "
                       "(cost %.4f)", solve_seconds, shape, cost)
    if cache is not None and key is not None:
        cache.record_solve_seconds("ilp", solve_seconds)
        cache.put("ilp", key, {
            "shape": tuple(shape),
            "choice": [int(s) for s in choice],
            "cost": float(cost),
            "solve_seconds": solve_seconds,
        })

    return _assemble_plan(closed_jaxpr, in_avals, in_paths, batch_flat_idx,
                          option, shape, logical_mesh, graph, choice,
                          return_graph)


def _replay_cached_solution(closed_jaxpr, in_avals, in_paths,
                            batch_flat_idx, physical_mesh, option, entry):
    """Rebuild (shape, logical_mesh, graph, choice) from a cached ILP
    solution, or None if the entry no longer fits the strategy graph
    (e.g. strategy enumeration changed without a format-version bump)."""
    try:
        shape = tuple(entry["shape"])
        choice = entry["choice"]
        if shape not in candidate_mesh_shapes(physical_mesh.num_devices,
                                              option,
                                              physical_mesh.num_hosts == 1):
            return None
        logical_mesh = physical_mesh.get_logical_mesh(shape)
        graph = build_strategy_graph(closed_jaxpr, in_avals, logical_mesh,
                                     batch_flat_idx, option,
                                     in_paths=in_paths)
        if len(choice) != len(graph.nodes):
            return None
        for node, s in zip(graph.nodes, choice):
            if not 0 <= s < len(node.strategies):
                return None
    except Exception:  # pylint: disable=broad-except
        logger.warning("cached ILP solution failed to replay; re-solving",
                       exc_info=True)
        return None
    return shape, logical_mesh, graph, choice


def _assemble_plan(closed_jaxpr, in_avals, in_paths, batch_flat_idx, option,
                   shape, logical_mesh, graph, choice, return_graph):
    """Turn a solved (graph, choice) into the plan_auto_sharding result
    tuple.  Shared by the fresh-solve path and the cache-replay path."""
    _note_grad_quantized_choices(graph, choice)
    axis_names = MESH_AXIS_NAMES[:len(shape)]
    jax_mesh = logical_mesh.get_jax_mesh(axis_names)

    # Assemble invar shardings from the solved assignment.
    in_shardings: List[Optional[NamedSharding]] = [None] * len(in_avals)
    for node, s in zip(graph.nodes, choice):
        if node.kind == "invar" and node.invar_idx is not None:
            spec = node.strategies[s].out_spec
            in_shardings[node.invar_idx] = NamedSharding(
                jax_mesh, spec_to_partition_spec(spec, axis_names))
    for i, s in enumerate(in_shardings):
        if s is None:
            in_shardings[i] = NamedSharding(
                jax_mesh, spec_to_partition_spec((), axis_names))

    # Forced ZeRO stages guarantee sharded weight-update leaves on top of
    # the ILP plan (the reference folds these into ILP forcing flags,
    # auto_sharding.py:225-299).  Under ``zero_stage=auto`` the strategy
    # graph itself enumerated costed sharded candidates, so whatever the
    # solver chose stands; under 2/3 any leaf the solver left replicated
    # (e.g. because a consumer edge charged the all-gather) is sharded
    # anyway — that is the contract of forcing.
    from alpa_tpu.shard_parallel.auto_sharding import (
        _largest_divisible_dim, is_opt_state_path, is_param_path,
        resolved_zero_stage, shard_dim)
    zero = resolved_zero_stage(option)
    if zero in (2, 3):
        # The dp axis is whichever axis the ILP put the batch dim on;
        # fall back to the largest non-trivial axis.
        dp_axis_name = None
        for node, s in zip(graph.nodes, choice):
            if (node.kind == "invar" and node.invar_idx in batch_flat_idx and
                    node.strategies[s].out_spec and
                    node.strategies[s].out_spec[0]):
                dp_axis_name = axis_names[node.strategies[s].out_spec[0][0]]
                break
        if dp_axis_name is None:
            dp_axis_name = axis_names[int(np.argmax(shape))]
        dp = dict(jax_mesh.shape)[dp_axis_name]
        if dp > 1:
            for i, path in enumerate(in_paths):
                is_opt = is_opt_state_path(path)
                is_param = is_param_path(path)
                if is_opt or (zero == 3 and is_param):
                    aval = in_avals[i]
                    d = _largest_divisible_dim(aval.shape, dp)
                    if d is not None and in_shardings[i].spec == \
                            spec_to_partition_spec((), axis_names):
                        in_shardings[i] = shard_dim(jax_mesh, d, dp_axis_name,
                                                    len(aval.shape))

    # Emit with_sharding_constraint on solved dot outputs so GSPMD realizes
    # the ILP's intra-op plan exactly.  The constrained function re-wraps
    # remat/checkpoint bodies in jax.checkpoint, so rematerialization is
    # preserved (constraints land inside the checkpointed body).
    constraint_fn = None
    if option.emit_sharding_constraints:
        from alpa_tpu.shard_parallel.strategy import make_constrained_fun
        constraint_fn = make_constrained_fun(
            graph, choice, jax_mesh, axis_names, closed_jaxpr.consts,
            min_elements=option.constrain_min_elements)

    if return_graph:
        return jax_mesh, in_shardings, constraint_fn, shape, (graph, choice)
    return jax_mesh, in_shardings, constraint_fn, shape
