"""Auto-sharding strategy search (jaxpr-level ILP).

Replaces the reference's C++ AutoSharding pass + PuLP ILP callback
(ref alpa/shard_parallel/auto_sharding.py:617-872, playground/
auto_sharding_solver/).  Strategy vectors are enumerated per jaxpr equation,
costs come from the LogicalDeviceMesh alpha-beta model, and the one-hot
selection problem is solved with scipy's MILP (HiGHS).  The chosen strategies
become pjit in_shardings + with_sharding_constraint on intermediates.

This module currently implements the planner skeleton with a rule-based
fallback; the full per-equation ILP lands in strategy.py/ilp.py.
"""
from typing import Any, Callable, Optional, Sequence, Tuple

from alpa_tpu.shard_parallel.auto_sharding import (AutoShardingOption,
                                                  plan_rule_based)


def plan_auto_sharding(fun: Callable,
                       in_avals: Sequence[Any],
                       in_paths: Sequence[str],
                       batch_flat_idx: Sequence[int],
                       logical_mesh,
                       jax_mesh,
                       option: AutoShardingOption
                       ) -> Tuple[list, Optional[Callable]]:
    """Return (flat in_shardings, optional wrapped fun with internal
    sharding constraints)."""
    try:
        from alpa_tpu.shard_parallel.strategy import plan_with_ilp
        return plan_with_ilp(fun, in_avals, in_paths, batch_flat_idx,
                             logical_mesh, jax_mesh, option)
    except ImportError:
        shardings = plan_rule_based(jax_mesh, in_avals, in_paths,
                                    batch_flat_idx, option)
        return shardings, None
