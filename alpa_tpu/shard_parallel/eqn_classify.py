"""Jaxpr-level precision classification for stage executables
(ISSUE 14; consumed by :mod:`alpa_tpu.analysis.numerics`).

Walks a stage's closed jaxpr (recursing into sub-jaxprs carried in eqn
params — ``remat``, ``scan``, ``cond``, ``pjit`` bodies) and types the
operations that decide numerical fate: contractions
(``dot_general`` / ``conv_general_dilated``), reductions
(``reduce_sum`` / ``reduce_prod`` / ``add_any`` / ``cumsum`` /
``reduce_window_sum``), and dtype casts (``convert_element_type``).
The result is a small deterministic JSON-able dict the plan verifier
attaches to each RUN op (``OpModel.precision``) — notably
``min_accum`` (the narrowest accumulation dtype any contraction or
reduction in the stage uses) and ``below_fp32_accum`` (True when a
reduction accumulates below fp32, the
``numerics.bf16-accumulation`` trigger per "Mixed Precision Training",
Micikevicius et al., PAPERS.md: partial sums need fp32 even when
storage is bf16/fp16).
"""
from typing import Any, Dict, Optional

__all__ = ["classify_stage_precision", "classify_jaxpr_precision"]

# wider-is-better rank for accumulation dtypes; unknown dtypes (ints,
# bools, tokens) don't participate in min_accum
_DTYPE_RANK = {
    "float64": 4,
    "float32": 3,
    "bfloat16": 2,
    "float16": 2,
    "float8_e4m3fn": 1,
    "float8_e5m2": 1,
    "float8_e4m3": 1,
}

_CONTRACTIONS = ("dot_general", "conv_general_dilated")
_REDUCTIONS = ("reduce_sum", "reduce_prod", "add_any", "cumsum",
               "reduce_window_sum")
_CASTS = ("convert_element_type",)


def _rank(dtype: str) -> Optional[int]:
    return _DTYPE_RANK.get(str(dtype))


def _out_dtype(eqn) -> str:
    try:
        return str(eqn.outvars[0].aval.dtype)
    except Exception:  # pylint: disable=broad-except
        return ""


def _accum_dtype(eqn) -> str:
    """The dtype an eqn accumulates in: an explicit
    ``preferred_element_type`` when the contraction declares one, else
    the output dtype (XLA accumulates reductions in the result type
    unless told otherwise)."""
    pet = eqn.params.get("preferred_element_type") \
        if hasattr(eqn, "params") else None
    if pet is not None:
        return str(pet)
    return _out_dtype(eqn)


def _walk(jaxpr, acc: Dict[str, Any]) -> None:
    for eqn in jaxpr.eqns:
        prim = getattr(eqn.primitive, "name", str(eqn.primitive))
        if prim in _CONTRACTIONS:
            acc["n_matmul"] += 1
            _fold_accum(acc, _accum_dtype(eqn), reduction=False)
        elif prim in _REDUCTIONS:
            acc["n_reduce"] += 1
            _fold_accum(acc, _accum_dtype(eqn), reduction=True)
        elif prim in _CASTS:
            acc["n_cast"] += 1
        # recurse into sub-jaxprs (remat/scan/cond/pjit bodies)
        for v in getattr(eqn, "params", {}).values():
            for sub in _sub_jaxprs(v):
                _walk(sub, acc)


def _sub_jaxprs(param):
    out = []
    stack = [param]
    while stack:
        v = stack.pop()
        if isinstance(v, (list, tuple)):
            stack.extend(v)
            continue
        inner = getattr(v, "jaxpr", None)
        if inner is not None and hasattr(inner, "eqns"):
            out.append(inner)           # ClosedJaxpr
        elif hasattr(v, "eqns"):
            out.append(v)               # bare Jaxpr
    return out


def _fold_accum(acc: Dict[str, Any], dtype: str,
                reduction: bool) -> None:
    r = _rank(dtype)
    if r is None:
        return
    cur = _rank(acc["min_accum"]) if acc["min_accum"] else None
    if cur is None or r < cur:
        acc["min_accum"] = str(dtype)
    if reduction and r < _DTYPE_RANK["float32"]:
        acc["below_fp32_accum"] = True


def classify_jaxpr_precision(closed_jaxpr) -> Dict[str, Any]:
    """Classify one closed jaxpr's precision-relevant eqn population.
    Deterministic and JSON-able (it joins the cached plan verdict)."""
    acc: Dict[str, Any] = {
        "n_matmul": 0, "n_reduce": 0, "n_cast": 0,
        "min_accum": "", "below_fp32_accum": False,
    }
    _walk(closed_jaxpr.jaxpr, acc)
    return acc


def classify_stage_precision(ex) -> Optional[Dict[str, Any]]:
    """:func:`classify_jaxpr_precision` over a
    :class:`~alpa_tpu.pipeline_parallel.pipeshard_executable.StageExecutable`'s
    computation; None when the executable carries no recoverable jaxpr
    (synthetic test stages) — the numerics analysis then skips the
    accumulation checks for that RUN."""
    try:
        comp = getattr(ex, "comp", None)
        if comp is None:
            return None
        return classify_jaxpr_precision(comp.closed_jaxpr())
    except Exception:  # pylint: disable=broad-except
        return None
