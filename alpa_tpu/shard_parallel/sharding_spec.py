"""Sharding spec algebra for the auto-sharding planner.

A ``Spec`` is a tuple over tensor dims; each element is a tuple of logical
mesh axes (ints) that dim is sharded over (usually 0 or 1 axes, possibly 2
for fully-2D sharding of one dim).  Replicated = all elements empty.

This plays the role of the HloSharding/ShardingSpec conversions in ref
``alpa/shard_parallel/auto_sharding.py:490-588``, but stays in
jax-PartitionSpec land: ``spec_to_partition_spec`` maps a Spec to
``jax.sharding.PartitionSpec`` over named mesh axes.
"""
from typing import Optional, Sequence, Tuple

import numpy as np
from jax.sharding import PartitionSpec

Spec = Tuple[Tuple[int, ...], ...]


def replicated_spec(ndim: int) -> Spec:
    return tuple(() for _ in range(ndim))


def is_replicated(spec: Spec) -> bool:
    return all(not axes for axes in spec)


def used_axes(spec: Spec) -> Tuple[int, ...]:
    out = []
    for axes in spec:
        out.extend(axes)
    return tuple(sorted(out))


def make_spec(ndim: int, assignment: dict) -> Spec:
    """assignment: {tensor_dim: mesh_axis or tuple(mesh_axes)}"""
    spec = [() for _ in range(ndim)]
    for d, a in assignment.items():
        spec[d] = (a,) if isinstance(a, int) else tuple(a)
    return tuple(spec)


def num_shards(spec: Spec, mesh_shape: Sequence[int]) -> int:
    n = 1
    for a in used_axes(spec):
        n *= mesh_shape[a]
    return n


def sharded_bytes(aval, spec: Spec, mesh_shape: Sequence[int]) -> float:
    size = float(np.prod(aval.shape)) if aval.shape else 1.0
    return size * aval.dtype.itemsize / num_shards(spec, mesh_shape)


def spec_valid(aval, spec: Spec, mesh_shape: Sequence[int]) -> bool:
    """Every sharded dim must be divisible by its axis product."""
    if len(spec) != len(aval.shape):
        return False
    for d, axes in enumerate(spec):
        if not axes:
            continue
        p = int(np.prod([mesh_shape[a] for a in axes]))
        if p > 1 and (aval.shape[d] % p != 0 or aval.shape[d] < p):
            return False
    return True


def spec_to_partition_spec(spec: Spec,
                           axis_names: Sequence[str]) -> PartitionSpec:
    parts = []
    for axes in spec:
        if not axes:
            parts.append(None)
        elif len(axes) == 1:
            parts.append(axis_names[axes[0]])
        else:
            parts.append(tuple(axis_names[a] for a in axes))
    # Trim trailing Nones for canonical form.
    while parts and parts[-1] is None:
        parts.pop()
    return PartitionSpec(*parts)


def enumerate_var_specs(aval, mesh_shape: Sequence[int],
                        max_axes: int = 2) -> Tuple[Spec, ...]:
    """All valid specs for a tensor: replicated, one dim on one axis, and
    two dims on the two axes (or one dim on both axes)."""
    ndim = len(aval.shape)
    nontrivial = [a for a, s in enumerate(mesh_shape) if s > 1]
    out = [replicated_spec(ndim)]
    # one axis on one dim
    for a in nontrivial:
        for d in range(ndim):
            s = make_spec(ndim, {d: a})
            if spec_valid(aval, s, mesh_shape):
                out.append(s)
    if len(nontrivial) >= 2 and max_axes >= 2:
        a0, a1 = nontrivial[0], nontrivial[1]
        for d0 in range(ndim):
            for d1 in range(ndim):
                if d0 == d1:
                    s = make_spec(ndim, {d0: (a0, a1)})
                else:
                    s = make_spec(ndim, {d0: a0, d1: a1})
                if spec_valid(aval, s, mesh_shape):
                    out.append(s)
    # dedup, keep order
    seen, uniq = set(), []
    for s in out:
        if s not in seen:
            seen.add(s)
            uniq.append(s)
    return tuple(uniq)


def resharding_cost(aval, src: Spec, dst: Spec, logical_mesh) -> float:
    """Alpha-beta cost of transforming src-sharded tensor to dst sharding.

    Coarse model mirroring the role of the reference's resharding cost
    entries in the ILP (ref auto_sharding.py edge costs): per mesh axis,
    gathering pays all-gather; slicing is free; moving an axis between dims
    pays an all-to-all.
    """
    if src == dst:
        return 0.0
    mesh_shape = logical_mesh.shape
    size_bytes = float(np.prod(aval.shape) if aval.shape else 1) * \
        aval.dtype.itemsize
    cost = 0.0
    src_axis_dim = {a: d for d, axes in enumerate(src) for a in axes}
    dst_axis_dim = {a: d for d, axes in enumerate(dst) for a in axes}
    for a, d in src_axis_dim.items():
        if a not in dst_axis_dim:
            # gather this axis; bytes gathered = full size / shards kept
            cost += logical_mesh.all_gather_cost(size_bytes, a)
        elif dst_axis_dim[a] != d:
            cost += logical_mesh.all_to_all_cost(size_bytes, a)
    # axes newly introduced in dst: local slice, free.
    return cost
