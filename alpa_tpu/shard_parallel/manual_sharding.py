"""Manual (pjit-style) sharding constraints on inputs/outputs.

Analog of ref ``alpa/shard_parallel/manual_sharding.py`` (SURVEY.md §2.3):
``ManualShardingOption`` carries user PartitionSpecs that override the
planner's choice for specific args/outputs.
"""
import dataclasses
from typing import Any, Optional, Sequence, Tuple

from jax.sharding import NamedSharding, PartitionSpec


@dataclasses.dataclass
class ManualShardingOption:
    """User-specified in/out PartitionSpecs (pytree prefixes allowed).

    ``mesh_axis_names`` names the logical mesh dims the specs refer to.
    (ref manual_sharding.py:19 ManualShardingOption)
    """
    mesh_axis_names: Optional[Tuple[str, ...]] = None
    in_axis_resources: Any = None   # pytree of PartitionSpec or None
    out_axis_resources: Any = None  # pytree of PartitionSpec or None


def flat_specs_from_tree(tree_specs, in_tree, num_leaves) -> Optional[list]:
    """Flatten a (possibly prefix) pytree of PartitionSpecs to a flat list."""
    if tree_specs is None:
        return None
    import jax
    from jax.api_util import flatten_axes
    return list(
        flatten_axes("manual_sharding specs", in_tree, tree_specs))


def apply_manual_shardings(mesh, flat_shardings, manual_specs_flat):
    """Override planner shardings with user-provided specs where given."""
    out = []
    for auto, spec in zip(flat_shardings, manual_specs_flat):
        if spec is None:
            out.append(auto)
        else:
            out.append(NamedSharding(mesh, spec))
    return out
