"""Intra-operator (sharding) parallelization.

TPU-native analog of the reference ``alpa/shard_parallel/`` (SURVEY.md §2.3):
the forked-XLA C++ AutoSharding pass + Python ILP callback is replaced by a
pure-Python planner over the jaxpr that emits ``jax.sharding.NamedSharding``
constraints consumed by pjit/GSPMD in stock libtpu.
"""
from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption
from alpa_tpu.shard_parallel.manual_sharding import ManualShardingOption
