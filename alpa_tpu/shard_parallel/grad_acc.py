"""Gradient accumulation by jaxpr rewriting.

Analog of ref ``alpa/shard_parallel/compile_executable.py:159-429``
(``shard_parallel_internal_gradient_accumulation`` +
``add_gradient_accumulation``): the traced train step is split at the
gradient marker (inserted by ``alpa_tpu.grad``/``value_and_grad``) into a
*compute_grad* section and an *apply_grad* section.

TPU-native difference: the reference compiles two XLA binaries and skips the
grad-sync all-reduce on all but the last microbatch with a runtime env-var
hook (ref mesh_executable.py:855-894) — impossible on TPU where collectives
are compiled in.  Here the microbatch loop is a ``lax.scan`` *inside one
program*: XLA keeps the per-microbatch gradient partial sums local and the
cross-replica reduction happens once where the accumulated gradient is
consumed, which is the same communication volume (one all-reduce per step).
"""
import logging
from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax._src.core import jaxpr_as_fun
from jax.extend.core import ClosedJaxpr, Var

from alpa_tpu.pipeline_parallel.primitive_def import is_marker
from alpa_tpu.util import clone_jaxpr

logger = logging.getLogger(__name__)


def split_jaxpr_at_grad_marker(closed_jaxpr: ClosedJaxpr):
    """Split a jaxpr's eqns at the (single) gradient marker.

    Returns (compute_eqns, marker_eqn, apply_eqns).  Mirrors ref
    ``split_compute_grad_and_apply_grad`` (pipeline_parallel/apply_grad.py:351)
    but at shard-parallel level.
    """
    jaxpr = closed_jaxpr.jaxpr
    marker_idx = [
        i for i, eqn in enumerate(jaxpr.eqns) if is_marker(eqn, "grad")
    ]
    if not marker_idx:
        return None
    if len(marker_idx) > 1:
        raise ValueError(
            "Gradient accumulation requires exactly one alpa_tpu.grad / "
            f"value_and_grad call; found {len(marker_idx)} gradient markers.")
    i = marker_idx[0]
    return jaxpr.eqns[:i], jaxpr.eqns[i], jaxpr.eqns[i + 1:]


def rewrite_for_grad_accumulation(fun: Callable,
                                  in_avals: Sequence[Any],
                                  batch_flat_idx: Sequence[int],
                                  num_micro_batches: int
                                  ) -> Tuple[Callable, Sequence[Any]]:
    """Rewrite ``fun`` (flat signature, full-batch avals) into a
    microbatch-scanning equivalent.

    The rewritten function takes the SAME full-batch avals; internally it
    reshapes each batch arg to ``(num_micro_batches, B/num_micro_batches,
    ...)``, scans the compute_grad section accumulating every
    gradient-marked value, divides by ``num_micro_batches`` (mean-loss
    semantics, ref ``apply_grad_get_mean`` apply_grad.py:650), and runs the
    apply_grad section once.
    """
    batch_set = set(batch_flat_idx)
    micro_avals = []
    for i, aval in enumerate(in_avals):
        if i in batch_set:
            b = aval.shape[0]
            if b % num_micro_batches != 0:
                raise ValueError(
                    f"Batch size {b} of arg {i} is not divisible by "
                    f"num_micro_batches={num_micro_batches}")
            micro_avals.append(
                jax.ShapeDtypeStruct((b // num_micro_batches,) +
                                     tuple(aval.shape[1:]), aval.dtype))
        else:
            micro_avals.append(aval)

    closed_jaxpr = jax.make_jaxpr(fun)(*micro_avals)
    split = split_jaxpr_at_grad_marker(closed_jaxpr)
    if split is None:
        raise ValueError(
            "num_micro_batches > 1 requires using alpa_tpu.grad or "
            "alpa_tpu.value_and_grad inside the parallelized function so the "
            "gradient boundary can be found.")
    compute_eqns, marker_eqn, apply_eqns = split
    jaxpr = closed_jaxpr.jaxpr
    invars = list(jaxpr.invars)
    invar_pos = {v: i for i, v in enumerate(invars)}

    # Values accumulated across microbatches: the marker's inputs.
    acc_invars = [v for v in marker_eqn.invars if isinstance(v, Var)]
    acc_avals = [v.aval for v in acc_invars]

    # --- compute_grad sub-jaxpr: invars -> marker inputs ---
    compute_cj = clone_jaxpr(closed_jaxpr,
                             invars=invars,
                             outvars=acc_invars,
                             eqns=list(compute_eqns))

    # --- apply_grad sub-jaxpr: (invars, marker outputs) -> outputs ---
    # Validate that nothing besides marker outputs / invars / constvars
    # crosses the boundary.
    defined_before = set()
    for eqn in compute_eqns:
        defined_before.update(eqn.outvars)
    marker_outs = list(marker_eqn.outvars)
    allowed = set(invars) | set(marker_outs) | set(jaxpr.constvars)
    for eqn in apply_eqns:
        for v in eqn.invars:
            if isinstance(v, Var) and v in defined_before and v not in allowed:
                raise ValueError(
                    "A value computed before alpa_tpu.grad is used after it "
                    f"without passing through the gradient marker: {v}. "
                    "Return it through the loss/aux outputs instead.")
    for v in jaxpr.outvars:
        if isinstance(v, Var) and v in defined_before and v not in allowed:
            raise ValueError(
                "A function output bypasses the gradient marker; with "
                "num_micro_batches > 1 every output must be derived from "
                "marked values or inputs.")

    # Batch args must not be consumed after the gradient marker: apply_grad
    # runs once on full-batch args while the jaxpr was traced at microbatch
    # shape.
    batch_vars = {invars[i] for i in batch_set if i < len(invars)}
    for eqn in apply_eqns:
        for v in eqn.invars:
            if isinstance(v, Var) and v in batch_vars:
                raise ValueError(
                    "A batch argument is used after alpa_tpu.grad; with "
                    "num_micro_batches > 1 the apply-gradient section may "
                    "only consume state and gradient-marked values.")

    apply_cj = clone_jaxpr(closed_jaxpr,
                           invars=invars + marker_outs,
                           outvars=list(jaxpr.outvars),
                           eqns=list(apply_eqns))

    num_args = len(in_avals)
    batch_list = sorted(batch_set)
    compute_fn = jaxpr_as_fun(compute_cj)
    apply_fn = jaxpr_as_fun(apply_cj)

    # Quantized gradient sync (ISSUE 19): when the knob is on, each
    # microbatch's gradient contribution goes through the blockwise
    # stochastic-rounding codec before accumulation — emulating the
    # per-sync quantized collective — with the error-feedback residual
    # threaded through the scan carry alongside the accumulators, so
    # what one hop fails to transmit the next hop carries.  At the
    # default ``grad_quantize=off`` the original body/scan is traced
    # unchanged (byte-identical jaxpr and compiled HLO).
    from alpa_tpu.global_env import global_config
    gq_mode = getattr(global_config, "grad_quantize", "off")
    q_set = set()
    if gq_mode != "off":
        from alpa_tpu.pipeline_parallel import reshard_codec as _codec
        q_set = {
            j for j, a in enumerate(acc_avals)
            if _codec.grad_eligible(
                tuple(a.shape), a.dtype, gq_mode,
                getattr(global_config, "grad_quantize_min_bytes", 65536))
        }
    use_ef = bool(q_set) and getattr(global_config, "grad_error_feedback",
                                     True)

    def grad_acc_fun(*full_args):
        assert len(full_args) == num_args
        # Reshape batch args to (num_micro_batches, micro, ...).
        stacked = []
        for i in batch_list:
            a = full_args[i]
            stacked.append(
                a.reshape((num_micro_batches, a.shape[0] // num_micro_batches)
                          + a.shape[1:]))

        if not q_set:
            def body(acc, mb_slices):
                args = list(full_args)
                for i, s in zip(batch_list, mb_slices):
                    args[i] = s
                vals = compute_fn(*args)
                new_acc = [a + v for a, v in zip(acc, vals)]
                return new_acc, None

            acc0 = [jnp.zeros(a.shape, a.dtype) for a in acc_avals]
            acc, _ = lax.scan(body, acc0, stacked, length=num_micro_batches)
        else:
            from alpa_tpu.pipeline_parallel import reshard_codec as _codec

            def body(carry, xs):
                acc, res = carry
                mb_slices, key = xs
                args = list(full_args)
                for i, s in zip(batch_list, mb_slices):
                    args[i] = s
                vals = compute_fn(*args)
                new_acc, new_res = [], []
                for j, (a, v) in enumerate(zip(acc, vals)):
                    if j in q_set:
                        kj = jax.random.fold_in(key, j)
                        v_hat, r_new = _codec.grad_compress(
                            v, gq_mode, kj, res[j] if use_ef else None)
                        new_acc.append(a + v_hat)
                        new_res.append(r_new if use_ef else res[j])
                    else:
                        new_acc.append(a + v)
                        new_res.append(res[j])
                return (new_acc, new_res), None

            keys = jax.random.split(jax.random.PRNGKey(0),
                                    num_micro_batches)
            acc0 = [jnp.zeros(a.shape, a.dtype) for a in acc_avals]
            res0 = [jnp.zeros(a.shape, a.dtype) for a in acc_avals]
            (acc, _res), _ = lax.scan(body, (acc0, res0), (stacked, keys),
                                      length=num_micro_batches)
        acc = [a / num_micro_batches for a in acc]
        return apply_fn(*full_args, *acc)

    return grad_acc_fun, list(in_avals)
