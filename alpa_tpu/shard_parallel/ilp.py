"""ILP solver for the strategy graph.

Re-architecture of ref ``_call_solver_serialized_args``
(``alpa/shard_parallel/auto_sharding.py:617-872``): the same one-hot
selection formulation — node vars s_i, edge vars e_ij with row/column
consistency, objective = node comm cost + edge resharding cost — but solved
with scipy's MILP (HiGHS) instead of PuLP/CBC, and fed from the jaxpr-level
strategy graph instead of C++-serialized protobufs.

A greedy topo-order fallback handles solver timeouts/infeasibility.
"""
import logging
import time
from typing import Dict, List, Tuple

import numpy as np

from alpa_tpu.global_env import global_config
from alpa_tpu.shard_parallel.strategy import StrategyGraph

logger = logging.getLogger(__name__)


def solve_strategy_graph(graph: StrategyGraph,
                         time_limit: float = None,
                         memory_budget: float = None) -> List[int]:
    """Pick one strategy per node minimizing total cost.

    ``memory_budget``: optional per-device byte cap — adds the constraint
    sum(mem_bytes[i, s] * x[i, s]) <= budget over invar nodes (the analog
    of ref auto_sharding's memory_budget_per_device).  Returns chosen
    strategy index per node.
    """
    time_limit = time_limit or global_config.ilp_time_limit
    n_nodes = len(graph.nodes)
    sizes = [len(n.strategies) for n in graph.nodes]

    # Trivial case: everything has one strategy.
    if all(s == 1 for s in sizes) and not memory_budget:
        return [0] * n_nodes

    try:
        return _solve_milp(graph, sizes, time_limit, memory_budget)
    except Exception as e:  # pylint: disable=broad-except
        if memory_budget:
            logger.warning(
                "MILP solve failed (%s); greedy fallback enforces the "
                "memory budget only greedily — the %d-byte cap may be "
                "exceeded", e, int(memory_budget))
        else:
            logger.warning("MILP solve failed (%s); using greedy fallback",
                           e)
        return _solve_greedy(graph, sizes, memory_budget)


def _solve_milp(graph: StrategyGraph, sizes: List[int],
                time_limit: float,
                memory_budget: float = None) -> List[int]:
    from scipy.optimize import Bounds, LinearConstraint, milp
    from scipy.sparse import lil_matrix

    # Variable layout: [node strategy vars..., edge vars...]
    node_off = []
    off = 0
    for s in sizes:
        node_off.append(off)
        off += s
    n_node_vars = off
    edge_off = []
    for e in graph.edges:
        edge_off.append(off)
        off += e.cost.size
    n_vars = off

    c = np.zeros(n_vars)
    for n, o in zip(graph.nodes, node_off):
        for s, st in enumerate(n.strategies):
            c[o + s] = st.comm_cost
    for e, o in zip(graph.edges, edge_off):
        c[o:o + e.cost.size] = e.cost.reshape(-1)
    # Normalize for solver conditioning.
    scale = max(1.0, np.abs(c).max() / 1e4)
    c = c / scale

    has_mem = bool(memory_budget)
    n_cons = len(graph.nodes) + sum(
        sizes[e.src] + sizes[e.dst] for e in graph.edges) + (
            1 if has_mem else 0)
    A = lil_matrix((n_cons, n_vars))
    lb = np.zeros(n_cons)
    ub = np.zeros(n_cons)
    row = 0
    if has_mem:
        # sum over invar nodes of per-strategy resident bytes <= budget
        for n, o in zip(graph.nodes, node_off):
            if n.kind != "invar":
                continue
            for s, st in enumerate(n.strategies):
                A[row, o + s] = st.mem_bytes
        lb[row] = -np.inf
        ub[row] = float(memory_budget)
        row += 1
    # sum_s x[i,s] = 1
    for i, o in enumerate(node_off):
        A[row, o:o + sizes[i]] = 1.0
        lb[row] = ub[row] = 1.0
        row += 1
    # edge consistency: sum_j e[si,:] = x_src[si]; sum_i e[:,sj] = x_dst[sj]
    for e, o in zip(graph.edges, edge_off):
        ns, nd = sizes[e.src], sizes[e.dst]
        for si in range(ns):
            A[row, o + si * nd:o + (si + 1) * nd] = 1.0
            A[row, node_off[e.src] + si] = -1.0
            lb[row] = ub[row] = 0.0
            row += 1
        for sj in range(nd):
            for si in range(ns):
                A[row, o + si * nd + sj] = 1.0
            A[row, node_off[e.dst] + sj] = -1.0
            lb[row] = ub[row] = 0.0
            row += 1

    integrality = np.zeros(n_vars)
    integrality[:n_node_vars] = 1  # node vars binary; edge vars relax to LP
    bounds = Bounds(np.zeros(n_vars), np.ones(n_vars))
    cons = LinearConstraint(A.tocsr(), lb, ub)
    tic = time.time()
    res = milp(c=c,
               constraints=cons,
               integrality=integrality,
               bounds=bounds,
               options={"time_limit": time_limit, "presolve": True})
    # status 0 = optimal; status 1 = time/iteration limit hit, but scipy
    # still returns the best incumbent in res.x — use it rather than
    # falling back to greedy.
    if res.x is None or res.status not in (0, 1):
        raise RuntimeError(f"milp status={res.status} {res.message}")
    logger.debug("ILP solved in %.2fs obj=%.3f (%s)",
                 time.time() - tic, res.fun * scale, graph.stats())
    choice = []
    for i, o in enumerate(node_off):
        choice.append(int(np.argmax(res.x[o:o + sizes[i]])))
    return choice


def _solve_greedy(graph: StrategyGraph, sizes: List[int],
                  memory_budget: float = None) -> List[int]:
    """Greedy: process nodes in index order (invars first, then ops in
    program order), choosing the strategy with minimal marginal cost against
    already-decided neighbors; then one refinement sweep.

    ``memory_budget``: soft enforcement — a per-byte penalty is charged on
    invar strategies once the running resident total exceeds the budget,
    pushing further choices toward sharded layouts (best effort, unlike the
    MILP's hard constraint)."""
    choice = [0] * len(graph.nodes)
    mem_used = [0.0]
    decided = [False] * len(graph.nodes)
    in_edges: Dict[int, List] = {}
    out_edges: Dict[int, List] = {}
    for e in graph.edges:
        in_edges.setdefault(e.dst, []).append(e)
        out_edges.setdefault(e.src, []).append(e)

    def marginal(i, s):
        st = graph.nodes[i].strategies[s]
        cost = st.comm_cost
        if memory_budget and graph.nodes[i].kind == "invar":
            over = max(0.0, mem_used[0] + st.mem_bytes - memory_budget)
            cost += over * 1e3  # strongly prefer staying under budget
        for e in in_edges.get(i, ()):
            if decided[e.src]:
                cost += e.cost[choice[e.src], s]
        for e in out_edges.get(i, ()):
            if decided[e.dst]:
                cost += e.cost[s, choice[e.dst]]
        return cost

    order = sorted(range(len(graph.nodes)),
                   key=lambda i: (graph.nodes[i].kind == "invar", i))
    for i in order:
        costs = [marginal(i, s) for s in range(sizes[i])]
        choice[i] = int(np.argmin(costs))
        decided[i] = True
        if memory_budget and graph.nodes[i].kind == "invar":
            mem_used[0] += graph.nodes[i].strategies[choice[i]].mem_bytes
    # refinement sweep
    for _ in range(2):
        for i in range(len(graph.nodes)):
            costs = [marginal(i, s) for s in range(sizes[i])]
            choice[i] = int(np.argmin(costs))
    return choice


def solution_cost(graph: StrategyGraph, choice: List[int]) -> float:
    cost = 0.0
    for n, s in zip(graph.nodes, choice):
        cost += n.strategies[s].comm_cost
    for e in graph.edges:
        cost += e.cost[choice[e.src], choice[e.dst]]
    return cost
