"""ILP solver for the strategy graph.

Re-architecture of ref ``_call_solver_serialized_args``
(``alpa/shard_parallel/auto_sharding.py:617-872``): the same one-hot
selection formulation — node vars s_i, edge vars e_ij with row/column
consistency, objective = node comm cost + edge resharding cost — but solved
with scipy's MILP (HiGHS) instead of PuLP/CBC, and fed from the jaxpr-level
strategy graph instead of C++-serialized protobufs.

A greedy topo-order fallback handles solver timeouts/infeasibility.
"""
import logging
import time
from typing import Dict, List, Tuple

import numpy as np

from alpa_tpu.global_env import global_config
from alpa_tpu.shard_parallel.strategy import StrategyGraph

logger = logging.getLogger(__name__)


class InfeasibleMemoryBudget(RuntimeError):
    """No strategy assignment fits memory_budget_per_device — even the
    minimum-footprint (fully sharded) layout exceeds the cap."""


def solve_strategy_graph(graph: StrategyGraph,
                         time_limit: float = None,
                         memory_budget: float = None) -> List[int]:
    """Pick one strategy per node minimizing total cost.

    ``memory_budget``: optional per-device byte cap — adds the constraint
    sum(mem_bytes[i, s] * x[i, s]) <= budget over invar nodes (the analog
    of ref auto_sharding's memory_budget_per_device).  Returns chosen
    strategy index per node.
    """
    time_limit = time_limit or global_config.ilp_time_limit
    n_nodes = len(graph.nodes)
    sizes = [len(n.strategies) for n in graph.nodes]

    # Trivial case: everything has one strategy.
    if all(s == 1 for s in sizes) and not memory_budget:
        return [0] * n_nodes

    try:
        return _solve_milp(graph, sizes, time_limit, memory_budget)
    except InfeasibleMemoryBudget:
        raise
    except Exception as e:  # pylint: disable=broad-except
        logger.warning("MILP solve failed (%s); using greedy fallback", e)
        return _solve_greedy(graph, sizes, memory_budget)


def _solve_milp(graph: StrategyGraph, sizes: List[int],
                time_limit: float,
                memory_budget: float = None) -> List[int]:
    from scipy.optimize import Bounds, LinearConstraint, milp
    from scipy.sparse import lil_matrix

    # Variable layout: [node strategy vars..., edge vars...]
    node_off = []
    off = 0
    for s in sizes:
        node_off.append(off)
        off += s
    n_node_vars = off
    edge_off = []
    for e in graph.edges:
        edge_off.append(off)
        off += e.cost.size
    n_vars = off

    c = np.zeros(n_vars)
    biased = []
    for n, o in zip(graph.nodes, node_off):
        for s, st in enumerate(n.strategies):
            c[o + s] = st.comm_cost
            if getattr(st, "tie_bias", 0.0) > 0:
                biased.append(o + s)
    for e, o in zip(graph.edges, edge_off):
        c[o:o + e.cost.size] = e.cost.reshape(-1)
    # Normalize for solver conditioning.
    scale = max(1.0, np.abs(c).max() / 1e4)
    c = c / scale
    # tie_bias steers genuinely-tied choices (e.g. conv batch vs
    # out-channel sharding) without entering comm accounting.  Applied
    # AFTER normalization and sized from the smallest real (normalized)
    # cost so the summed bias can never flip a real cost difference,
    # while each individual bias stays well above solver tolerance.
    if biased:
        pos = c[c > 1e-12]
        eps = ((pos.min() if pos.size else 1.0) * 1e-3 /
               max(1, len(biased)))
        c[np.asarray(biased)] += eps

    has_mem = bool(memory_budget)
    n_cons = len(graph.nodes) + sum(
        sizes[e.src] + sizes[e.dst] for e in graph.edges) + (
            1 if has_mem else 0)
    A = lil_matrix((n_cons, n_vars))
    lb = np.zeros(n_cons)
    ub = np.zeros(n_cons)
    row = 0
    if has_mem:
        # sum over resident values of per-strategy bytes <= budget.
        # invar nodes always participate (params / optimizer state live
        # for the whole step — the term the costed ZeRO strategies
        # shrink by 1/dp); op nodes participate when a strategy declares
        # nonzero mem_bytes.
        for n, o in zip(graph.nodes, node_off):
            if n.kind != "invar" and not any(
                    st.mem_bytes for st in n.strategies):
                continue
            for s, st in enumerate(n.strategies):
                A[row, o + s] = st.mem_bytes
        lb[row] = -np.inf
        ub[row] = float(memory_budget)
        row += 1
    # sum_s x[i,s] = 1
    for i, o in enumerate(node_off):
        A[row, o:o + sizes[i]] = 1.0
        lb[row] = ub[row] = 1.0
        row += 1
    # edge consistency: sum_j e[si,:] = x_src[si]; sum_i e[:,sj] = x_dst[sj]
    for e, o in zip(graph.edges, edge_off):
        ns, nd = sizes[e.src], sizes[e.dst]
        for si in range(ns):
            A[row, o + si * nd:o + (si + 1) * nd] = 1.0
            A[row, node_off[e.src] + si] = -1.0
            lb[row] = ub[row] = 0.0
            row += 1
        for sj in range(nd):
            for si in range(ns):
                A[row, o + si * nd + sj] = 1.0
            A[row, node_off[e.dst] + sj] = -1.0
            lb[row] = ub[row] = 0.0
            row += 1

    integrality = np.zeros(n_vars)
    integrality[:n_node_vars] = 1  # node vars binary; edge vars relax to LP
    bounds = Bounds(np.zeros(n_vars), np.ones(n_vars))
    cons = LinearConstraint(A.tocsr(), lb, ub)
    tic = time.time()
    res = milp(c=c,
               constraints=cons,
               integrality=integrality,
               bounds=bounds,
               options={"time_limit": time_limit, "presolve": True,
                        # tight gap so tie_bias-scale terms are honored
                        "mip_rel_gap": 1e-9})
    # status 0 = optimal; status 1 = time/iteration limit hit, but scipy
    # still returns the best incumbent in res.x — use it rather than
    # falling back to greedy.
    if res.x is None or res.status not in (0, 1):
        raise RuntimeError(f"milp status={res.status} {res.message}")
    logger.debug("ILP solved in %.2fs obj=%.3f (%s)",
                 time.time() - tic, res.fun * scale, graph.stats())
    choice = []
    for i, o in enumerate(node_off):
        choice.append(int(np.argmax(res.x[o:o + sizes[i]])))
    return choice


def _solve_greedy(graph: StrategyGraph, sizes: List[int],
                  memory_budget: float = None) -> List[int]:
    """Greedy: process ops first in program order, then invars (which
    align to their consumers' decisions under the budget), choosing the
    strategy with minimal marginal cost against already-decided neighbors;
    then refinement sweeps.

    ``memory_budget`` is enforced HARD, like the MILP's constraint: a
    strategy is only eligible if the running invar-resident total plus the
    minimum possible footprint of the still-undecided invars fits the
    budget (so feasibility is never painted into a corner).  Raises
    :class:`InfeasibleMemoryBudget` when even the minimum-footprint layout
    exceeds the cap."""
    nodes = graph.nodes
    choice = [0] * len(nodes)
    decided = [False] * len(nodes)
    in_edges: Dict[int, List] = {}
    out_edges: Dict[int, List] = {}
    for e in graph.edges:
        in_edges.setdefault(e.dst, []).append(e)
        out_edges.setdefault(e.src, []).append(e)

    invar_idx = [i for i, n in enumerate(nodes) if n.kind == "invar"]
    min_mem = {
        i: min(st.mem_bytes for st in nodes[i].strategies)
        for i in invar_idx
    }
    if memory_budget and sum(min_mem.values()) > memory_budget:
        raise InfeasibleMemoryBudget(
            f"minimum resident footprint {sum(min_mem.values()):.3e} B "
            f"exceeds memory_budget_per_device {memory_budget:.3e} B")
    mem_used = [0.0]
    remaining_min = [sum(min_mem.values())]

    def marginal(i, s):
        st = nodes[i].strategies[s]
        cost = st.comm_cost + getattr(st, "tie_bias", 0.0)
        for e in in_edges.get(i, ()):
            if decided[e.src]:
                cost += e.cost[choice[e.src], s]
        for e in out_edges.get(i, ()):
            if decided[e.dst]:
                cost += e.cost[s, choice[e.dst]]
        return cost

    def feasible_set(i):
        if not memory_budget or nodes[i].kind != "invar":
            return range(sizes[i])
        headroom = memory_budget - mem_used[0] - (remaining_min[0] -
                                                  min_mem[i])
        ok = [s for s in range(sizes[i])
              if nodes[i].strategies[s].mem_bytes <= headroom]
        # min-mem strategy always fits (global feasibility checked above);
        # guard float round-off anyway
        return ok or [int(np.argmin(
            [st.mem_bytes for st in nodes[i].strategies]))]

    order = sorted(range(len(nodes)),
                   key=lambda i: (nodes[i].kind == "invar", i))
    for i in order:
        cand = feasible_set(i)
        choice[i] = min(cand, key=lambda s: marginal(i, s))
        decided[i] = True
        if memory_budget and nodes[i].kind == "invar":
            mem_used[0] += nodes[i].strategies[choice[i]].mem_bytes
            remaining_min[0] -= min_mem[i]
    # refinement sweeps: re-choose each node; invar flips must keep the
    # (now fully decided) resident total within budget
    for _ in range(2):
        for i in range(len(nodes)):
            if memory_budget and nodes[i].kind == "invar":
                cur = nodes[i].strategies[choice[i]].mem_bytes
                headroom = memory_budget - (mem_used[0] - cur)
                cand = [s for s in range(sizes[i])
                        if nodes[i].strategies[s].mem_bytes <= headroom]
            else:
                cand = range(sizes[i])
            new = min(cand, key=lambda s: marginal(i, s))
            if memory_budget and nodes[i].kind == "invar":
                mem_used[0] += (nodes[i].strategies[new].mem_bytes -
                                nodes[i].strategies[choice[i]].mem_bytes)
            choice[i] = new
    return choice


def solution_cost(graph: StrategyGraph, choice: List[int]) -> float:
    cost = 0.0
    for n, s in zip(graph.nodes, choice):
        cost += n.strategies[s].comm_cost
    for e in graph.edges:
        cost += e.cost[choice[e.src], choice[e.dst]]
    return cost
