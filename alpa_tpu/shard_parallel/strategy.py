"""Per-equation sharding strategy enumeration + graph construction.

The jaxpr-level re-architecture of the reference's C++ AutoSharding pass
(``auto_sharding.cc``/``auto_sharding_dot_handler.cc``, reconstructed in
SURVEY.md §2.9; readable Python spec in ref ``playground/
auto_sharding_solver/``).  We build a *strategy graph*:

* **nodes** — decision points: graph invars and "heavy" equations
  (dot_general, conv, reduce, unmappable reshapes, unknown ops).  Each node
  has a finite list of strategies; a strategy fixes the node's output Spec,
  a node communication cost (e.g. the all-reduce of a contracted-dim-sharded
  matmul), and required operand Specs.
* **follow chains** — cheap ops (elementwise, transpose, broadcast,
  mappable reshape, convert) don't get nodes; they reuse their lead
  operand's decision through a dim-mapping (the analog of the reference's
  strategy "following").
* **edges** — (producer node, consumer node) pairs with a dense resharding
  cost matrix C[s_src, s_dst].

The ILP (ilp.py) picks one strategy per node minimizing node + edge costs;
invar decisions become pjit in_shardings, and ``make_constrained_fun``
re-interprets the jaxpr inserting ``with_sharding_constraint`` on every
solved op output (via Node.outvar) so GSPMD realizes the ILP's plan even
where propagation would disagree.
"""
import dataclasses
import functools
import itertools
import logging
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.extend.core import ClosedJaxpr, Jaxpr, Literal, Var

from alpa_tpu.shard_parallel.sharding_spec import (Spec, enumerate_var_specs,
                                                   is_replicated, make_spec,
                                                   num_shards,
                                                   replicated_spec,
                                                   resharding_cost,
                                                   spec_valid, used_axes)

logger = logging.getLogger(__name__)

# Ops followed through without creating a decision node.
ELEMENTWISE_PRIMS = frozenset({
    "add", "add_any", "sub", "mul", "div", "max", "min", "pow", "rem",
    "atan2",
    "and", "or", "xor", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "nextafter",
    "exp", "log", "log1p", "expm1", "tanh", "sin", "cos", "tan", "asin",
    "acos", "atan", "sinh", "cosh", "asinh", "acosh", "atanh", "sqrt",
    "rsqrt", "cbrt", "logistic", "erf", "erfc", "erf_inv", "abs", "neg",
    "sign", "floor", "ceil", "round", "is_finite", "not", "integer_pow",
    "exp2", "square",
    "eq", "ne", "ge", "gt", "le", "lt", "select_n", "clamp",
    "convert_element_type", "bitcast_convert_type", "stop_gradient",
    "copy", "real", "imag", "conj",
})

# Sub-jaxpr-carrying ops we inline for analysis.
INLINE_PRIMS = frozenset({
    "jit", "pjit", "closed_call", "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr", "remat", "checkpoint",
    "remat2", "custom_vjp_call_custom_transpose", "custom_lin",
})

DimMap = Tuple[Optional[int], ...]  # var dim -> node dim (None = fresh dim)


def identity_dimmap(ndim: int) -> DimMap:
    return tuple(range(ndim))


def compose_dimmap(outer: DimMap, inner: DimMap) -> DimMap:
    """outer: var<-mid, inner: mid<-node  =>  var<-node."""
    return tuple(inner[m] if m is not None else None for m in outer)


def map_spec(node_spec: Spec, dimmap: DimMap, ndim: int) -> Tuple[Spec, Tuple[int, ...]]:
    """Map a node's spec through a dim-mapping.

    Returns (mapped_spec, dropped_axes): mesh axes sharding node dims that
    the mapping does not carry (they must be all-gathered to realize the
    follow, charged on the edge).
    """
    mapped = [() for _ in range(ndim)]
    used_node_dims = set()
    for d, nd in enumerate(dimmap):
        if nd is not None and nd < len(node_spec):
            mapped[d] = node_spec[nd]
            used_node_dims.add(nd)
    dropped = []
    for nd, axes in enumerate(node_spec):
        if nd not in used_node_dims:
            dropped.extend(axes)
    return tuple(mapped), tuple(dropped)


@dataclasses.dataclass
class Strategy:
    name: str
    out_spec: Spec
    comm_cost: float
    # required operand specs, parallel to the node's operand list
    operand_specs: Tuple[Spec, ...] = ()
    # resident bytes per device under this strategy (invar nodes: the
    # sharded parameter bytes; used by the ILP memory constraint)
    mem_bytes: float = 0.0
    # collective realizing comm_cost in the compiled HLO: "all_reduce"
    # (contracted-dim sharding) or "ppermute" (spatial halo exchange) —
    # structural tests match the planned kind against the HLO op counts
    comm_kind: str = "all_reduce"
    # tiny objective nudge for breaking genuine cost ties (e.g. prefer
    # batch over out-channel conv sharding, the reference's data-parallel
    # bias); excluded from comm accounting and solution_cost
    tie_bias: float = 0.0
    # gradient-collective codec realizing comm_cost (ISSUE 19): None =
    # full precision; "int8"/"fp8" = the blockwise stochastic-rounding
    # codec (reshard_codec), priced by the *_cost_quantized twins.  Only
    # ever set when global_config.grad_quantize != "off", so default
    # plans stay byte-identical.
    codec: Optional[str] = None


@dataclasses.dataclass
class Node:
    idx: int
    kind: str  # 'invar' | 'op'
    aval: Any
    strategies: List[Strategy]
    label: str = ""
    # invar nodes: which flat invar index they represent
    invar_idx: Optional[int] = None
    # op nodes: the eqn's primary outvar (for constraint emission)
    outvar: Optional[Var] = None


@dataclasses.dataclass
class Edge:
    src: int
    dst: int
    # cost[s_src, s_dst]
    cost: np.ndarray


@dataclasses.dataclass
class StrategyGraph:
    nodes: List[Node]
    edges: List[Edge]
    logical_mesh: Any

    def stats(self):
        nvars = sum(len(n.strategies) for n in self.nodes)
        nevars = sum(e.cost.size for e in self.edges)
        return (f"{len(self.nodes)} nodes / {nvars} strategy vars / "
                f"{len(self.edges)} edges / {nevars} edge vars")


########################################
# jaxpr flattening (inline sub-jaxprs)
########################################


def _inline_site(eqn, depth: int):
    """Resolve an inlinable call site: ``(sub_jaxpr, consts)`` or None.

    Single source of truth for INLINE_PRIMS membership, the depth cap and
    the param-key lookup.  The flatten traversal, ``_check_evaluable`` and
    the constrained re-interpreter MUST agree on this (constraints attach
    by position in the flattened eqn order), so they all call here.
    """
    if eqn.primitive.name not in INLINE_PRIMS or depth >= 6:
        return None
    sub = (eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr") or
           eqn.params.get("fun_jaxpr"))
    if sub is None:
        return None
    if isinstance(sub, ClosedJaxpr):
        return sub.jaxpr, sub.consts
    return sub, []


def _align_call_args(outer: list, inner_invars) -> list:
    """pjit-style calls line invars up 1:1; custom_jvp has extra prefix
    args — align from the end.  Pads (with None) when there are fewer
    outer args than inner invars (such sites are not re-evaluable; see
    ``_check_evaluable``)."""
    if len(outer) >= len(inner_invars):
        return outer[len(outer) - len(inner_invars):]
    return list(outer) + [None] * (len(inner_invars) - len(outer))


def _subst(v, env):
    if isinstance(v, Literal):
        return v
    seen = 0
    while v in env and env[v] is not v and seen < 100:
        nxt = env[v]
        if isinstance(nxt, Literal):
            return nxt
        v = nxt
        seen += 1
    return v


def flatten_jaxpr_eqns(jaxpr: Jaxpr, env: Optional[dict] = None,
                       depth: int = 0, info: Optional[dict] = None) -> List:
    """Inline pjit/custom-call/remat sub-jaxprs, returning a flat eqn list
    over substituted vars.  Scan/while/cond are left opaque (barriers).

    ``info`` (optional dict) collects side data for re-evaluation:
    ``captured_consts`` (inner constvar -> value) and ``env`` (the
    substitution, for resolving outer outvars of inlined calls).
    """
    env = env if env is not None else {}
    if info is not None:
        info.setdefault("captured_consts", {})
        if depth == 0:
            # only the top-level substitution maps outer outvars; inner
            # envs must not clobber it
            info["env"] = env
    out = []
    for eqn in jaxpr.eqns:
        site = _inline_site(eqn, depth)
        if site is not None:
            sub_jaxpr, consts = site
            inner_env = {}
            outer_in = [_subst(v, env) for v in eqn.invars]
            inner_invars = list(sub_jaxpr.invars)
            aligned = _align_call_args(outer_in, inner_invars)
            for iv, ov in zip(inner_invars, aligned):
                if ov is not None:
                    inner_env[iv] = ov
            for ci, cv in enumerate(sub_jaxpr.constvars):
                # consts become opaque leaf vars (replicated barriers);
                # record their values for re-evaluation
                inner_env[cv] = cv
                if info is not None and ci < len(consts):
                    info["captured_consts"][cv] = consts[ci]
            inner_eqns = flatten_jaxpr_eqns(sub_jaxpr, inner_env, depth + 1,
                                            info)
            # Freshen every var DEFINED inside this inline site: jax caches
            # traced sub-jaxprs, so two calls of the same function share
            # inner Var objects — without freshening, the second site's
            # eqns would collide with (and overwrite) the first's.
            from alpa_tpu.util import gensym_var
            fresh = {}

            def _fresh(v):
                if isinstance(v, Literal):
                    return v
                return fresh.get(v, v)

            freshened = []
            for ie in inner_eqns:
                new_outs = []
                for ov2 in ie.outvars:
                    nv = gensym_var(ov2.aval)
                    fresh[ov2] = nv
                    new_outs.append(nv)
                freshened.append(
                    ie.replace(invars=[_fresh(v) for v in ie.invars],
                               outvars=new_outs))
            out.extend(freshened)
            # map eqn outvars to (freshened) inner outvars
            for ov, inner_ov in zip(eqn.outvars, sub_jaxpr.outvars):
                if isinstance(inner_ov, Literal):
                    env[ov] = inner_ov
                else:
                    env[ov] = _fresh(_subst(inner_ov, inner_env))
        else:
            out.append(eqn.replace(
                invars=[_subst(v, env) for v in eqn.invars],
                outvars=list(eqn.outvars)))
            # resolve substitutions lazily for later eqns
    # Second pass: apply env to all invars (outvars of inlined eqns may map)
    fixed = []
    for eqn in out:
        fixed.append(eqn.replace(invars=[_subst(v, env) for v in eqn.invars]))
    return fixed


########################################
# dot_general strategy enumeration
########################################


def _dot_semantic_dims(eqn):
    """Classify output dims of a dot_general as (batch, lhs_free, rhs_free)
    and locate contracting dims on the operands."""
    (lhs_c, rhs_c), (lhs_b, rhs_b) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    lhs_free = [d for d in range(len(lhs.shape))
                if d not in lhs_c and d not in lhs_b]
    rhs_free = [d for d in range(len(rhs.shape))
                if d not in rhs_c and d not in rhs_b]
    # out dims: batch..., lhs_free..., rhs_free...
    return (list(lhs_b), list(rhs_b), list(lhs_c), list(rhs_c), lhs_free,
            rhs_free)


def enumerate_dot_strategies(eqn, logical_mesh) -> List[Strategy]:
    """The dot handler (analog of ref ``auto_sharding_dot_handler.cc``).

    Enumerates assignments of each non-trivial mesh axis to one semantic
    role: a batch dim (Sb), an lhs free dim (Si), an rhs free dim (Sj), or
    a contracting dim (Sk -> all-reduce of the output on that axis).
    """
    mesh_shape = logical_mesh.shape
    lhs_av, rhs_av = eqn.invars[0].aval, eqn.invars[1].aval
    out_av = eqn.outvars[0].aval
    lhs_b, rhs_b, lhs_c, rhs_c, lhs_free, rhs_free = _dot_semantic_dims(eqn)
    nb = len(lhs_b)
    out_ndim = len(out_av.shape)

    nontrivial = [a for a, s in enumerate(mesh_shape) if s > 1]
    if not nontrivial:
        return [Strategy("R", replicated_spec(out_ndim), 0.0,
                         (replicated_spec(len(lhs_av.shape)),
                          replicated_spec(len(rhs_av.shape))))]

    # Role choices per mesh axis: ('b', i) / ('i', i) / ('j', i) / ('k', i)
    role_choices = []
    for bi in range(nb):
        role_choices.append(("b", bi))
    for i_pos, _ in enumerate(lhs_free):
        role_choices.append(("i", i_pos))
    for j_pos, _ in enumerate(rhs_free):
        role_choices.append(("j", j_pos))
    for k_pos, _ in enumerate(lhs_c):
        role_choices.append(("k", k_pos))

    strategies = []
    seen = set()
    for assignment in itertools.product(role_choices, repeat=len(nontrivial)):
        # each (role, pos) may appear at most once across axes
        if len(set(assignment)) != len(assignment):
            continue
        lhs_map, rhs_map, out_map = {}, {}, {}
        ar_axes = []
        ok = True
        for axis, (role, pos) in zip(nontrivial, assignment):
            if role == "b":
                lhs_map[lhs_b[pos]] = axis
                rhs_map[rhs_b[pos]] = axis
                out_map[pos] = axis
            elif role == "i":
                lhs_map[lhs_free[pos]] = axis
                out_map[nb + pos] = axis
            elif role == "j":
                rhs_map[rhs_free[pos]] = axis
                out_map[nb + len(lhs_free) + pos] = axis
            else:  # k
                lhs_map[lhs_c[pos]] = axis
                rhs_map[rhs_c[pos]] = axis
                ar_axes.append(axis)
        lhs_spec = make_spec(len(lhs_av.shape), lhs_map)
        rhs_spec = make_spec(len(rhs_av.shape), rhs_map)
        out_spec = make_spec(out_ndim, out_map)
        if not (spec_valid(lhs_av, lhs_spec, mesh_shape) and
                spec_valid(rhs_av, rhs_spec, mesh_shape) and
                spec_valid(out_av, out_spec, mesh_shape)):
            ok = False
        if not ok:
            continue
        out_bytes = (float(np.prod(out_av.shape)) * out_av.dtype.itemsize /
                     num_shards(out_spec, mesh_shape))
        cost = sum(logical_mesh.all_reduce_cost(out_bytes, a)
                   for a in ar_axes)
        name = "".join(f"{r}{p}@{a}" for a, (r, p) in
                       zip(nontrivial, assignment))
        key = (lhs_spec, rhs_spec, out_spec)
        if key in seen:
            continue
        seen.add(key)
        strategies.append(Strategy(name, out_spec, cost,
                                   (lhs_spec, rhs_spec)))
    if not strategies:
        strategies.append(Strategy("R", replicated_spec(out_ndim), 0.0,
                                   (replicated_spec(len(lhs_av.shape)),
                                    replicated_spec(len(rhs_av.shape)))))
    return strategies


def enumerate_conv_strategies(eqn, logical_mesh) -> List[Strategy]:
    """Conv handler (analog of the reference dot/conv strategy vectors):
    each non-trivial mesh axis takes one role —

      'b': shard the batch dim (lhs batch <-> out batch),
      'o': shard output channels (rhs O <-> out feature),
      'i': shard input channels (lhs C + rhs I contracted -> all-reduce),
      'g': shard channel groups (grouped/depthwise convs: lhs C, rhs O
           and out F all sharded along the group axis, no collective),
      's': shard the first spatial dim (GSPMD inserts the halo exchange;
           costed as one neighbor ppermute of the halo ring).
    """
    mesh_shape = logical_mesh.shape
    dn = eqn.params["dimension_numbers"]
    lhs_spec, rhs_spec, out_spec_dims = (dn.lhs_spec, dn.rhs_spec,
                                         dn.out_spec)
    lhs_av, rhs_av = eqn.invars[0].aval, eqn.invars[1].aval
    out_av = eqn.outvars[0].aval
    feature_group_count = eqn.params.get("feature_group_count", 1)
    batch_group_count = eqn.params.get("batch_group_count", 1)
    lhs_b, lhs_c = lhs_spec[0], lhs_spec[1]
    rhs_o, rhs_i = rhs_spec[0], rhs_spec[1]
    out_b, out_f = out_spec_dims[0], out_spec_dims[1]
    # first spatial dim triple + its kernel extent (for the halo size)
    lhs_s0, rhs_s0, out_s0 = lhs_spec[2], rhs_spec[2], out_spec_dims[2]
    kernel0 = int(rhs_av.shape[rhs_s0])

    nontrivial = [a for a, s in enumerate(mesh_shape) if s > 1]
    if not nontrivial:
        return [Strategy("R", replicated_spec(len(out_av.shape)), 0.0,
                         (replicated_spec(len(lhs_av.shape)),
                          replicated_spec(len(rhs_av.shape))))]

    roles = ["b", "o", "s"]
    # contracting input channels is only valid without feature groups;
    # with groups, the group dim itself is shardable instead
    if feature_group_count == 1:
        roles.append("i")
    else:
        roles.append("g")

    # Like the dot handler: every non-trivial axis must take a role —
    # the strategy space has no fully-replicated entry (with no compute
    # cost in the model, replication would otherwise always win).
    strategies = []
    seen = set()
    for assignment in itertools.product(roles, repeat=len(nontrivial)):
        lhs_map, rhs_map, out_map = {}, {}, {}
        ar_axes, halo_axes = [], []
        for axis, role in zip(nontrivial, assignment):
            if role == "b":
                if lhs_b in lhs_map:
                    break
                # batch groups must stay intact on each shard
                if (batch_group_count > 1 and
                        batch_group_count % mesh_shape[axis] != 0):
                    break
                lhs_map[lhs_b] = axis
                out_map[out_b] = axis
            elif role == "o":
                if rhs_o in rhs_map:
                    break
                if (feature_group_count > 1 and
                        feature_group_count % mesh_shape[axis] != 0):
                    break
                rhs_map[rhs_o] = axis
                out_map[out_f] = axis
            elif role == "i":
                if lhs_c in lhs_map or rhs_i in rhs_map:
                    break
                lhs_map[lhs_c] = axis
                rhs_map[rhs_i] = axis
                ar_axes.append(axis)
            elif role == "g":
                # grouped conv: whole groups split across the axis; lhs
                # channels, rhs out-channels and out features shard
                # together, no collective needed
                if (lhs_c in lhs_map or rhs_o in rhs_map or
                        feature_group_count % mesh_shape[axis] != 0):
                    break
                lhs_map[lhs_c] = axis
                rhs_map[rhs_o] = axis
                out_map[out_f] = axis
            else:  # 's': spatial sharding, halo exchange
                if lhs_s0 in lhs_map:
                    break
                lhs_map[lhs_s0] = axis
                out_map[out_s0] = axis
                halo_axes.append(axis)
        else:
            lhs_s = make_spec(len(lhs_av.shape), lhs_map)
            rhs_s = make_spec(len(rhs_av.shape), rhs_map)
            out_s = make_spec(len(out_av.shape), out_map)
            if not (spec_valid(lhs_av, lhs_s, mesh_shape) and
                    spec_valid(rhs_av, rhs_s, mesh_shape) and
                    spec_valid(out_av, out_s, mesh_shape)):
                continue
            key = (lhs_s, rhs_s, out_s)
            if key in seen:
                continue
            seen.add(key)
            out_bytes = (float(np.prod(out_av.shape)) *
                         out_av.dtype.itemsize /
                         num_shards(out_s, mesh_shape))
            cost = sum(logical_mesh.all_reduce_cost(out_bytes, a)
                       for a in ar_axes)
            # halo ring: (kernel-1) rows of the per-shard input
            # cross-section move to each neighbor (GSPMD's exchange)
            for a in halo_axes:
                shard_elems = (float(np.prod(lhs_av.shape)) /
                               num_shards(lhs_s, mesh_shape))
                spatial_len = max(int(lhs_av.shape[lhs_s0]) //
                                  mesh_shape[a], 1)
                halo_bytes = (shard_elems / spatial_len *
                              max(kernel0 - 1, 0) * lhs_av.dtype.itemsize)
                cost += logical_mesh.ppermute_cost(halo_bytes, a)
            strategies.append(
                Strategy("conv" + str(assignment), out_s, cost,
                         (lhs_s, rhs_s),
                         comm_kind=("ppermute" if halo_axes and
                                    not ar_axes else "all_reduce"),
                         tie_bias=0.0 if "b" in assignment else 1e-6))
    if not strategies:
        strategies.append(
            Strategy("R", replicated_spec(len(out_av.shape)), 0.0,
                     (replicated_spec(len(lhs_av.shape)),
                      replicated_spec(len(rhs_av.shape)))))
    return strategies


def enumerate_reduce_strategies(eqn, logical_mesh) -> List[Strategy]:
    """reduce_sum/reduce_max/...: strategies indexed by the operand spec;
    sharded reduced dims pay an all-reduce on the output."""
    mesh_shape = logical_mesh.shape
    in_av = eqn.invars[0].aval
    out_av = eqn.outvars[0].aval
    red_dims = set(eqn.params.get("axes", ()))
    kept = [d for d in range(len(in_av.shape)) if d not in red_dims]
    strategies = []
    for in_spec in enumerate_var_specs(in_av, mesh_shape):
        out_map = {}
        ar_axes = []
        for d, axes in enumerate(in_spec):
            if not axes:
                continue
            if d in red_dims:
                ar_axes.extend(axes)
            else:
                out_map[kept.index(d)] = tuple(axes) if len(axes) > 1 \
                    else axes[0]
        out_spec = make_spec(len(out_av.shape), out_map)
        if not spec_valid(out_av, out_spec, mesh_shape):
            continue
        out_bytes = (float(np.prod(out_av.shape) if out_av.shape else 1) *
                     out_av.dtype.itemsize / num_shards(out_spec, mesh_shape))
        # Reduction over sharded dims realizes as an all-reduce of the
        # output for every reduction kind (sum/max/min/...).
        cost = sum(logical_mesh.all_reduce_cost(out_bytes, a)
                   for a in ar_axes)
        strategies.append(Strategy(f"red{in_spec}", out_spec, cost,
                                   (in_spec,)))
    return strategies or [
        Strategy("R", replicated_spec(len(out_av.shape)), 0.0,
                 (replicated_spec(len(in_av.shape)),))
    ]


def enumerate_gather_strategies(eqn, logical_mesh) -> Optional[List[Strategy]]:
    """Gather handler (the reference's C++ pass enumerates strategies for
    the full HLO instruction set incl. gather — ref
    playground/auto_sharding_solver/solver.py; absent here until r3).

    Embedding lookups (``jnp.take(table, ids)``) are the headline case.
    Each non-trivial mesh axis takes one role:

      ('ib', k): shard the k-th indices batch dim — the matching output
                 batch dim shards with it, no collective;
      ('pt', d): shard a fully-sliced (passthrough) operand dim — e.g. the
                 embedding feature dim; output offset dim shards, free;
      ('ix', d): shard an indexed operand dim — vocab-parallel embedding:
                 each shard gathers its local rows (GSPMD masks out-of-
                 shard ids) and the partial outputs all-reduce.

    Returns None (fall back to the generic barrier) for exotic forms
    (batching dims, non-trailing index vector dim).
    """
    dn = eqn.params["dimension_numbers"]
    if dn.operand_batching_dims or dn.start_indices_batching_dims:
        return None
    op_av, idx_av = eqn.invars[0].aval, eqn.invars[1].aval
    out_av = eqn.outvars[0].aval
    slice_sizes = eqn.params["slice_sizes"]
    mesh_shape = logical_mesh.shape
    op_ndim, idx_ndim, out_ndim = (len(op_av.shape), len(idx_av.shape),
                                   len(out_av.shape))

    offset_dims = list(dn.offset_dims)
    batch_out_dims = [d for d in range(out_ndim) if d not in set(offset_dims)]
    idx_batch_dims = list(range(idx_ndim - 1))  # index vector dim is last
    if len(batch_out_dims) != len(idx_batch_dims):
        return None
    # operand dims surviving into the output, in order -> offset positions
    passthrough = [d for d in range(op_ndim)
                   if d not in set(dn.collapsed_slice_dims)]
    if len(passthrough) != len(offset_dims):
        return None
    full_passthrough = [d for d in passthrough
                        if slice_sizes[d] == op_av.shape[d]]
    indexed = list(dn.start_index_map)

    nontrivial = [a for a, s in enumerate(mesh_shape) if s > 1]
    if not nontrivial:
        return [Strategy("R", replicated_spec(out_ndim), 0.0,
                         (replicated_spec(op_ndim),
                          replicated_spec(idx_ndim)))]

    role_choices = ([("ib", k) for k in range(len(idx_batch_dims))] +
                    [("pt", d) for d in full_passthrough] +
                    [("ix", d) for d in indexed])
    strategies = []
    seen = set()
    for assignment in itertools.product(role_choices,
                                        repeat=len(nontrivial)):
        if len(set(assignment)) != len(assignment):
            continue
        op_map, idx_map, out_map = {}, {}, {}
        ar_axes = []
        for axis, (role, pos) in zip(nontrivial, assignment):
            if role == "ib":
                idx_map[idx_batch_dims[pos]] = axis
                out_map[batch_out_dims[pos]] = axis
            elif role == "pt":
                op_map[pos] = axis
                out_map[offset_dims[passthrough.index(pos)]] = axis
            else:  # 'ix': vocab-parallel
                op_map[pos] = axis
                ar_axes.append(axis)
        op_spec = make_spec(op_ndim, op_map)
        idx_spec = make_spec(idx_ndim, idx_map)
        out_spec = make_spec(out_ndim, out_map)
        if not (spec_valid(op_av, op_spec, mesh_shape) and
                spec_valid(idx_av, idx_spec, mesh_shape) and
                spec_valid(out_av, out_spec, mesh_shape)):
            continue
        key = (op_spec, idx_spec, out_spec)
        if key in seen:
            continue
        seen.add(key)
        out_bytes = (float(np.prod(out_av.shape) or 1) *
                     out_av.dtype.itemsize / num_shards(out_spec, mesh_shape))
        cost = sum(logical_mesh.all_reduce_cost(out_bytes, a)
                   for a in ar_axes)
        name = "g" + "".join(f"{r}{p}@{a}" for a, (r, p) in
                             zip(nontrivial, assignment))
        strategies.append(Strategy(name, out_spec, cost,
                                   (op_spec, idx_spec)))
    if not strategies:
        strategies.append(Strategy("R", replicated_spec(out_ndim), 0.0,
                                   (replicated_spec(op_ndim),
                                    replicated_spec(idx_ndim))))
    return strategies


SCATTER_PRIMS = frozenset({"scatter", "scatter-add", "scatter-mul",
                           "scatter-min", "scatter-max"})


def enumerate_scatter_strategies(eqn, logical_mesh) -> Optional[List[Strategy]]:
    """Scatter handler — the transpose of gather (embedding-gradient
    ``scatter-add`` is the headline case; KV-cache writes that lower to
    scatter take the same roles).  Output has the operand's shape.

      ('w', d):  shard a window (passthrough) operand dim — updates shard
                 along with it, no collective;
      ('sc', d): shard a scattered operand dim — vocab-parallel table:
                 each shard applies the updates landing in its rows
                 (GSPMD masks the rest), updates replicated, free;
      ('ub', k): shard the k-th updates batch dim — each shard scatters
                 its slice of updates, the operand-shaped partials
                 all-reduce (grad-accumulation pattern).
    """
    dn = eqn.params["dimension_numbers"]
    if dn.operand_batching_dims or dn.scatter_indices_batching_dims:
        return None
    op_av, idx_av, upd_av = (eqn.invars[0].aval, eqn.invars[1].aval,
                             eqn.invars[2].aval)
    out_av = eqn.outvars[0].aval
    mesh_shape = logical_mesh.shape
    op_ndim, idx_ndim, upd_ndim = (len(op_av.shape), len(idx_av.shape),
                                   len(upd_av.shape))

    window_dims = list(dn.update_window_dims)  # positions in updates
    upd_batch_dims = [d for d in range(upd_ndim)
                      if d not in set(window_dims)]
    idx_batch_dims = list(range(idx_ndim - 1))
    if len(upd_batch_dims) != len(idx_batch_dims):
        return None
    # operand window dims (not inserted), in order -> update window positions
    op_window = [d for d in range(op_ndim)
                 if d not in set(dn.inserted_window_dims)]
    if len(op_window) != len(window_dims):
        return None
    full_window = [d for d in op_window
                   if upd_av.shape[window_dims[op_window.index(d)]] ==
                   op_av.shape[d]]
    scattered = list(dn.scatter_dims_to_operand_dims)

    nontrivial = [a for a, s in enumerate(mesh_shape) if s > 1]
    if not nontrivial:
        return [Strategy("R", replicated_spec(op_ndim), 0.0,
                         (replicated_spec(op_ndim), replicated_spec(idx_ndim),
                          replicated_spec(upd_ndim)))]

    role_choices = ([("w", d) for d in full_window] +
                    [("sc", d) for d in scattered] +
                    [("ub", k) for k in range(len(upd_batch_dims))])
    strategies = []
    seen = set()
    for assignment in itertools.product(role_choices,
                                        repeat=len(nontrivial)):
        if len(set(assignment)) != len(assignment):
            continue
        op_map, idx_map, upd_map = {}, {}, {}
        ar_axes = []
        for axis, (role, pos) in zip(nontrivial, assignment):
            if role == "w":
                op_map[pos] = axis
                upd_map[window_dims[op_window.index(pos)]] = axis
            elif role == "sc":
                op_map[pos] = axis
            else:  # 'ub'
                upd_map[upd_batch_dims[pos]] = axis
                idx_map[idx_batch_dims[pos]] = axis
                ar_axes.append(axis)
        op_spec = make_spec(op_ndim, op_map)
        idx_spec = make_spec(idx_ndim, idx_map)
        upd_spec = make_spec(upd_ndim, upd_map)
        if not (spec_valid(op_av, op_spec, mesh_shape) and
                spec_valid(idx_av, idx_spec, mesh_shape) and
                spec_valid(upd_av, upd_spec, mesh_shape)):
            continue
        key = (op_spec, idx_spec, upd_spec)
        if key in seen:
            continue
        seen.add(key)
        out_bytes = (float(np.prod(out_av.shape) or 1) *
                     out_av.dtype.itemsize / num_shards(op_spec, mesh_shape))
        cost = sum(logical_mesh.all_reduce_cost(out_bytes, a)
                   for a in ar_axes)
        name = "s" + "".join(f"{r}{p}@{a}" for a, (r, p) in
                             zip(nontrivial, assignment))
        # out spec == operand spec (scatter writes in place)
        strategies.append(Strategy(name, op_spec, cost,
                                   (op_spec, idx_spec, upd_spec)))
    if not strategies:
        strategies.append(Strategy("R", replicated_spec(op_ndim), 0.0,
                                   (replicated_spec(op_ndim),
                                    replicated_spec(idx_ndim),
                                    replicated_spec(upd_ndim))))
    return strategies


########################################
# follow-through dim mappings
########################################


def follow_dimmap(eqn, operand_idx: int) -> Optional[DimMap]:
    """If eqn's output can follow operand ``operand_idx``'s sharding via a
    pure dim-mapping, return out_dim -> operand_dim, else None."""
    prim = eqn.primitive.name
    if not eqn.outvars or not hasattr(eqn.outvars[0], "aval"):
        return None
    out_shape = eqn.outvars[0].aval.shape
    in_av = eqn.invars[operand_idx].aval if hasattr(
        eqn.invars[operand_idx], "aval") else None
    if in_av is None:
        return None
    in_shape = in_av.shape

    if prim in ELEMENTWISE_PRIMS:
        if in_shape == out_shape:
            return identity_dimmap(len(out_shape))
        # right-aligned broadcasting
        if len(in_shape) <= len(out_shape):
            off = len(out_shape) - len(in_shape)
            dm = []
            for d in range(len(out_shape)):
                if d < off:
                    dm.append(None)
                else:
                    ind = d - off
                    dm.append(ind if in_shape[ind] == out_shape[d] else None)
            return tuple(dm)
        return None
    if prim == "transpose":
        perm = eqn.params["permutation"]
        return tuple(perm)
    if prim == "broadcast_in_dim":
        bdims = eqn.params["broadcast_dimensions"]
        inv = {od: id_ for id_, od in enumerate(bdims)}
        dm = []
        for d in range(len(out_shape)):
            src = inv.get(d)
            if src is not None and in_shape[src] == out_shape[d]:
                dm.append(src)
            else:
                dm.append(None)
        return tuple(dm)
    if prim in ("reshape",):
        # mappable iff the >1-sized dims correspond 1:1 in order
        in_nt = [(d, s) for d, s in enumerate(in_shape) if s > 1]
        out_nt = [(d, s) for d, s in enumerate(out_shape) if s > 1]
        if [s for _, s in in_nt] == [s for _, s in out_nt]:
            dm = [None] * len(out_shape)
            for (od, _), (id_, _) in zip(out_nt, in_nt):
                dm[od] = id_
            return tuple(dm)
        # partial: a preserved leading-dim prefix keeps its sharding
        # (covers dim-split/merge tails like GroupNorm's
        # (N,H,W,C) <-> (N,H,W,G,C/G))
        dm = [None] * len(out_shape)
        for d in range(min(len(in_shape), len(out_shape))):
            if in_shape[d] != out_shape[d]:
                break
            dm[d] = d
        if any(x is not None for x in dm):
            return tuple(dm)
        return None
    if prim in ("squeeze",):
        dims = set(eqn.params["dimensions"])
        kept = [d for d in range(len(in_shape)) if d not in dims]
        return tuple(kept)
    if prim in ("expand_dims",):
        dims = set(eqn.params["dimensions"])
        dm = []
        src = 0
        for d in range(len(out_shape)):
            if d in dims:
                dm.append(None)
            else:
                dm.append(src)
                src += 1
        return tuple(dm)
    if prim in ("rev", "cumsum", "cumprod", "cummax", "cummin",
                "sort", "argsort"):
        if in_shape == out_shape:
            return identity_dimmap(len(out_shape))
        return None
    if prim in ("reduce_window_max", "reduce_window_min",
                "reduce_window_sum", "reduce_window", "select_and_scatter",
                "select_and_scatter_add"):
        # windowed ops keep dim correspondence (spatial sizes shrink but
        # batch/feature shardings carry through; spatial sharding costs
        # are approximated — execution correctness is GSPMD's job)
        if len(in_shape) == len(out_shape):
            return identity_dimmap(len(out_shape))
        return None
    if prim in ("pad", "slice", "dynamic_slice"):
        if len(in_shape) == len(out_shape):
            return identity_dimmap(len(out_shape))
        return None
    if prim == "dynamic_update_slice":
        # KV-cache writes: the output follows the cache operand dim-for-dim
        # (and, for the update operand, on every dim whose extent matches —
        # the updated dim stays unmapped so its sharding isn't forced onto
        # the smaller update).  GSPMD executes the sharded in-place update.
        if len(in_shape) == len(out_shape):
            return tuple(d if in_shape[d] == out_shape[d] else None
                         for d in range(len(out_shape)))
        return None
    return None


def pick_lead_operand(eqn) -> Optional[int]:
    """Choose the operand to follow: the largest non-literal one."""
    best, best_size = None, -1
    for i, v in enumerate(eqn.invars):
        if isinstance(v, Literal):
            continue
        if not hasattr(v, "aval") or not hasattr(v.aval, "shape"):
            continue
        size = int(np.prod(v.aval.shape)) if v.aval.shape else 1
        if size > best_size:
            best, best_size = i, size
    return best


########################################
# graph construction
########################################


def build_strategy_graph(closed_jaxpr: ClosedJaxpr,
                         in_avals: Sequence[Any],
                         logical_mesh,
                         batch_flat_idx: Sequence[int],
                         option,
                         in_paths: Sequence[str] = ()) -> StrategyGraph:
    jaxpr = closed_jaxpr.jaxpr
    mesh_shape = logical_mesh.shape
    nodes: List[Node] = []
    edges: List[Edge] = []
    # var -> (node_idx, dimmap var<-node)
    var_node: Dict[Var, Tuple[int, DimMap]] = {}

    def new_node(kind, aval, strategies, label="", invar_idx=None,
                 outvar=None):
        n = Node(len(nodes), kind, aval, strategies, label, invar_idx, outvar)
        nodes.append(n)
        return n

    def barrier_node(aval, label):
        nd = len(aval.shape) if hasattr(aval, "shape") else 0
        return new_node("op", aval,
                        [Strategy("R", replicated_spec(nd), 0.0)], label)

    # --- invar nodes ---
    from alpa_tpu.shard_parallel.auto_sharding import (
        is_opt_state_path, is_param_path, resolved_zero_stage)
    zero = resolved_zero_stage(option)
    batch_set = set(batch_flat_idx)
    for i, (v, aval) in enumerate(zip(jaxpr.invars, in_avals)):
        specs = enumerate_var_specs(aval, mesh_shape)
        if i in batch_set and option.force_batch_dim_to_mesh_dim is not None:
            a = option.force_batch_dim_to_mesh_dim
            forced = make_spec(len(aval.shape), {0: a}) \
                if len(aval.shape) and mesh_shape[a] > 1 else \
                replicated_spec(len(aval.shape))
            if spec_valid(aval, forced, mesh_shape):
                specs = (forced,)
        from alpa_tpu.shard_parallel.sharding_spec import sharded_bytes
        # Weight-update (ZeRO) sharding: optimizer-state leaves (and
        # param leaves under stage 3) get reduce-scatter-aware costed
        # strategies instead of the replication tie preference.
        path = in_paths[i] if i < len(in_paths) else ""
        zero_leaf = (zero != 0 and i not in batch_set and bool(path) and
                     (is_opt_state_path(path) or
                      (zero == 3 and is_param_path(path))))
        if zero_leaf and zero in (2, 3):
            # Forced stages: restrict to sharded layouts when any exist.
            sharded = tuple(s for s in specs if any(bool(d) for d in s))
            if sharded:
                specs = sharded
        if zero_leaf:
            nbytes = (float(np.prod(aval.shape) if aval.shape else 1) *
                      aval.dtype.itemsize)
            strategies = []
            for s in specs:
                axes = [a for dim_axes in s for a in dim_axes]
                if axes:
                    # Sharding a weight-update leaf trades the grad
                    # all-reduce for reduce-scatter (credit) but must
                    # all-gather the updated value back (charge); under
                    # the ring model the traffic terms cancel and the
                    # residual is the collective latency — the memory
                    # term (mem_bytes, 1/dp of the leaf) then decides.
                    charge = sum(logical_mesh.all_gather_cost(nbytes, a)
                                 for a in axes)
                    credit = sum(
                        logical_mesh.all_reduce_cost(nbytes, a) -
                        logical_mesh.reduce_scatter_cost(nbytes, a)
                        for a in axes)
                    strategies.append(Strategy(
                        f"zero{str(s)}", s, max(0.0, charge - credit),
                        mem_bytes=sharded_bytes(aval, s, mesh_shape),
                        comm_kind="reduce_scatter"))
                    # Quantized gradient reduce-scatter twin (ISSUE 19):
                    # same layout, but the gradient sync runs through
                    # the blockwise stochastic-rounding codec — the
                    # credit prices the *quantized* reduce-scatter, so
                    # the ILP flips per tensor exactly when the wire
                    # saving beats the encode/decode charge.  Only
                    # enumerated when the knob is on and the leaf is
                    # eligible (dtype + grad_quantize_min_bytes), so
                    # grad_quantize=off plans are byte-identical.
                    from alpa_tpu.global_env import global_config
                    gq_mode = getattr(global_config, "grad_quantize",
                                      "off")
                    if gq_mode != "off":
                        from alpa_tpu.pipeline_parallel import (
                            reshard_codec as _codec)
                        if _codec.grad_eligible(
                                aval.shape, aval.dtype, gq_mode,
                                getattr(global_config,
                                        "grad_quantize_min_bytes",
                                        65536)):
                            itemsize = int(aval.dtype.itemsize)
                            credit_q = sum(
                                logical_mesh.all_reduce_cost(nbytes, a) -
                                logical_mesh.reduce_scatter_cost_quantized(
                                    nbytes, a, itemsize)
                                for a in axes)
                            strategies.append(Strategy(
                                f"zero{str(s)}_q{gq_mode}", s,
                                max(0.0, charge - credit_q),
                                mem_bytes=sharded_bytes(
                                    aval, s, mesh_shape),
                                comm_kind="reduce_scatter",
                                codec=gq_mode))
                else:
                    # Replication keeps the full leaf resident; carry the
                    # tie penalty so equal-cost solutions prefer the
                    # sharded (memory-saving) layout.
                    strategies.append(Strategy(
                        str(s), s, 0.0, mem_bytes=sharded_bytes(
                            aval, s, mesh_shape), tie_bias=1e-6))
        else:
            strategies = [
                Strategy(str(s), s, 0.0,
                         mem_bytes=sharded_bytes(aval, s, mesh_shape),
                         # Reference-aligned tie preferences, epsilon-sized
                         # so any real cost difference still dominates:
                         # batch invars prefer a sharded leading (batch)
                         # dim; other invars (params) prefer replication
                         # (the reference's allow_replicated_parameters
                         # default).  Together the ties resolve toward
                         # data parallelism.
                         tie_bias=(1e-6 if (
                             (i in batch_set and len(aval.shape) and
                              (not s or not s[0])) or
                             (i not in batch_set and
                              any(bool(d) for d in s))) else 0.0))
                for s in specs
            ]
        n = new_node("invar", aval, strategies, f"invar{i}", invar_idx=i)
        var_node[v] = (n.idx, identity_dimmap(len(aval.shape)))

    # constvars: replicated barriers
    for v in jaxpr.constvars:
        nd = len(v.aval.shape) if hasattr(v.aval, "shape") else 0
        n = new_node("op", v.aval,
                     [Strategy("R", replicated_spec(nd), 0.0)], "const")
        var_node[v] = (n.idx, identity_dimmap(nd))

    def edge_cost_matrix(src_node: Node, dimmap: DimMap, aval,
                         required: List[Spec]) -> np.ndarray:
        """cost[s_src, s_req] of delivering src's value (viewed through
        dimmap) as each required operand spec."""
        ndim = len(aval.shape) if hasattr(aval, "shape") else 0
        C = np.zeros((len(src_node.strategies), len(required)))
        for si, st in enumerate(src_node.strategies):
            mapped, dropped = map_spec(st.out_spec, dimmap, ndim)
            size_bytes = (float(np.prod(aval.shape) if aval.shape else 1) *
                          aval.dtype.itemsize)
            drop_cost = sum(logical_mesh.all_gather_cost(size_bytes, a)
                            for a in dropped)
            for ri, req in enumerate(required):
                C[si, ri] = drop_cost + resharding_cost(
                    aval, mapped, req, logical_mesh)
        return C

    def get_source(v):
        """Node+dimmap for a var, creating a replicated barrier for unknown
        sources (e.g. scan outputs)."""
        if isinstance(v, Literal):
            return None
        if v not in var_node:
            n = barrier_node(v.aval, "opaque")
            var_node[v] = (n.idx, identity_dimmap(
                len(v.aval.shape) if hasattr(v.aval, "shape") else 0))
        return var_node[v]

    flatten_info: Dict = {}
    flat_eqns = flatten_jaxpr_eqns(jaxpr, info=flatten_info)

    for eqn in flat_eqns:
        prim = eqn.primitive.name

        if prim == "pipeline":  # markers: identity pass-through
            for iv, ov in zip(eqn.invars, eqn.outvars):
                if isinstance(iv, Literal):
                    continue
                src = get_source(iv)
                if src is not None:
                    var_node[ov] = src
            continue

        if prim in ("dot_general", "conv_general_dilated"):
            if prim == "dot_general":
                strategies = enumerate_dot_strategies(eqn, logical_mesh)
            else:
                strategies = enumerate_conv_strategies(eqn, logical_mesh)
            out_av = eqn.outvars[0].aval
            n = new_node("op", out_av, strategies,
                         f"{prim.split('_')[0]}:{out_av.shape}",
                         outvar=eqn.outvars[0])
            for oi in range(2):
                v = eqn.invars[oi]
                src = get_source(v)
                if src is None:
                    continue
                src_idx, dimmap = src
                req = [st.operand_specs[oi] for st in strategies]
                C = edge_cost_matrix(nodes[src_idx], dimmap, v.aval, req)
                edges.append(Edge(src_idx, n.idx, C))
            var_node[eqn.outvars[0]] = (n.idx,
                                        identity_dimmap(len(out_av.shape)))
            continue

        if prim in ("reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
                    "reduce_and", "reduce_or", "argmax", "argmin"):
            strategies = enumerate_reduce_strategies(eqn, logical_mesh)
            out_av = eqn.outvars[0].aval
            n = new_node("op", out_av, strategies, f"{prim}", outvar=None)
            v = eqn.invars[0]
            src = get_source(v)
            if src is not None:
                src_idx, dimmap = src
                req = [st.operand_specs[0] for st in strategies]
                C = edge_cost_matrix(nodes[src_idx], dimmap, v.aval, req)
                edges.append(Edge(src_idx, n.idx, C))
            var_node[eqn.outvars[0]] = (n.idx,
                                        identity_dimmap(len(out_av.shape)))
            continue

        if prim == "gather" or prim in SCATTER_PRIMS:
            if prim == "gather":
                strategies = enumerate_gather_strategies(eqn, logical_mesh)
            else:
                strategies = enumerate_scatter_strategies(eqn, logical_mesh)
            if strategies is not None:
                out_av = eqn.outvars[0].aval
                n = new_node("op", out_av, strategies,
                             f"{prim}:{out_av.shape}", outvar=eqn.outvars[0])
                n_operands = len(strategies[0].operand_specs)
                for oi in range(n_operands):
                    v = eqn.invars[oi]
                    if isinstance(v, Literal):
                        continue
                    src = get_source(v)
                    if src is None:
                        continue
                    src_idx, dimmap = src
                    req = [st.operand_specs[oi] for st in strategies]
                    C = edge_cost_matrix(nodes[src_idx], dimmap, v.aval, req)
                    edges.append(Edge(src_idx, n.idx, C))
                var_node[eqn.outvars[0]] = (
                    n.idx, identity_dimmap(len(out_av.shape)))
                continue
            # exotic gather/scatter forms fall through to the barrier

        # Free nodes: ops whose inputs are all literals/scalars (constant
        # broadcasts, iota, zeros_like chains).  Materializing any sharding
        # of them is free, so they get the full spec space at zero cost and
        # the ILP aligns them with their consumers via consistency edges.
        def _scalar_or_lit(v):
            if isinstance(v, Literal):
                return True
            if not hasattr(v, "aval") or not hasattr(v.aval, "shape"):
                return True
            return (int(np.prod(v.aval.shape)) if v.aval.shape else 1) == 1

        if (eqn.outvars and hasattr(eqn.outvars[0], "aval") and
                getattr(eqn.outvars[0].aval, "shape", None) and
                all(_scalar_or_lit(v) for v in eqn.invars)):
            out_av = eqn.outvars[0].aval
            specs = enumerate_var_specs(out_av, mesh_shape)
            n = new_node("op", out_av,
                         [Strategy(str(s), s, 0.0) for s in specs],
                         f"free:{prim}")
            var_node[eqn.outvars[0]] = (n.idx,
                                        identity_dimmap(len(out_av.shape)))
            for ov in eqn.outvars[1:]:
                if hasattr(ov, "aval") and hasattr(ov.aval, "shape"):
                    bn = barrier_node(ov.aval, f"barrier:{prim}")
                    var_node[ov] = (bn.idx,
                                    identity_dimmap(len(ov.aval.shape)))
            continue

        # follow-through attempt
        lead = pick_lead_operand(eqn)
        dm = follow_dimmap(eqn, lead) if lead is not None else None
        if dm is not None:
            src = get_source(eqn.invars[lead])
            if src is not None:
                src_idx, src_dm = src
                composed = compose_dimmap(dm, src_dm)
                var_node[eqn.outvars[0]] = (src_idx, composed)
                # Side operands (bias adds, residual joins): they must match
                # the followed spec on their (right-aligned broadcast) dims.
                # Add a consistency edge (side node <-> lead node) whose cost
                # is the resharding of the *side* tensor to the lead's spec
                # viewed in output-dim space.
                out_ndim = len(eqn.outvars[0].aval.shape)
                lead_av = eqn.invars[lead].aval
                lead_size = float(np.prod(lead_av.shape) or 1)
                for oi, v in enumerate(eqn.invars):
                    if oi == lead or isinstance(v, Literal):
                        continue
                    if not (hasattr(v, "aval") and hasattr(v.aval, "shape")):
                        continue
                    side_size = float(np.prod(v.aval.shape) or 1)
                    if side_size * 8 < lead_size:
                        # small operands (biases, scalars): GSPMD replicates
                        # or reshards them cheaply; ignore in the model.
                        continue
                    osrc = get_source(v)
                    if osrc is None:
                        continue
                    o_idx, o_dm = osrc
                    if o_idx == src_idx:
                        continue
                    side_dm = follow_dimmap(eqn, oi)
                    if side_dm is None:
                        side_dm = (None,) * out_ndim
                    o_comp = compose_dimmap(side_dm, o_dm)
                    src_node_, o_node_ = nodes[src_idx], nodes[o_idx]
                    C = np.zeros((len(o_node_.strategies),
                                  len(src_node_.strategies)))
                    for si, st_o in enumerate(o_node_.strategies):
                        o_spec, o_drop = map_spec(st_o.out_spec, o_comp,
                                                  out_ndim)
                        sb = side_size * v.aval.dtype.itemsize
                        drop_cost = sum(
                            logical_mesh.all_gather_cost(sb, a)
                            for a in o_drop)
                        for li, st_l in enumerate(src_node_.strategies):
                            l_spec, _ = map_spec(st_l.out_spec, composed,
                                                 out_ndim)
                            C[si, li] = drop_cost + resharding_cost(
                                v.aval if len(v.aval.shape) == out_ndim
                                else eqn.outvars[0].aval,
                                o_spec, l_spec, logical_mesh)
                    edges.append(Edge(o_idx, src_idx, C))
                continue

        # barrier: unknown op -> replicated node per output
        for ov in eqn.outvars:
            if hasattr(ov, "aval") and hasattr(ov.aval, "shape"):
                n = barrier_node(ov.aval, f"barrier:{prim}")
                var_node[ov] = (n.idx, identity_dimmap(len(ov.aval.shape)))
                # charge gathering of inputs into the barrier
                for v in eqn.invars:
                    if isinstance(v, Literal) or not hasattr(v, "aval"):
                        continue
                    if not hasattr(v.aval, "shape"):
                        continue
                    src = get_source(v)
                    if src is None:
                        continue
                    src_idx, dimmap = src
                    req = [replicated_spec(len(v.aval.shape))]
                    C = edge_cost_matrix(nodes[src_idx], dimmap, v.aval, req)
                    edges.append(Edge(src_idx, n.idx, C))

    graph = StrategyGraph(nodes, edges, logical_mesh)
    graph.closed_jaxpr = closed_jaxpr
    graph.flat_eqns = flat_eqns
    graph.invars = list(jaxpr.invars)
    graph.constvars = list(jaxpr.constvars)
    sub_env = flatten_info.get("env", {})
    graph.outvars = [_subst(v, sub_env) for v in jaxpr.outvars]
    graph.captured_consts = flatten_info.get("captured_consts", {})
    return graph


def _check_evaluable(jaxpr: Jaxpr, depth: int = 0) -> bool:
    """Mirror of the flatten traversal: True iff every inline site can be
    re-evaluated (enough outer args to bind the inner jaxpr's invars)."""
    for eqn in jaxpr.eqns:
        site = _inline_site(eqn, depth)
        if site is None:
            continue
        sub_jaxpr, _ = site
        if len(eqn.invars) < len(sub_jaxpr.invars):
            return False
        if not _check_evaluable(sub_jaxpr, depth + 1):
            return False
    return True


def make_constrained_fun(graph: StrategyGraph, choice, jax_mesh,
                         axis_names, consts, min_elements: int = 1 << 16):
    """Build a function re-evaluating the ORIGINAL jaxpr with
    ``with_sharding_constraint`` inserted on every solved dot output — so
    GSPMD realizes exactly the ILP's intra-op plan instead of relying on
    propagation (the fidelity upgrade promised by this module's header).

    The interpreter recurses into the same call primitives the analysis
    flattening inlines, in the same order, so the ILP's decisions (keyed by
    position in ``graph.flat_eqns``) attach to the right ``bind`` even
    though flattening freshens variable identities.  remat/checkpoint
    bodies are re-wrapped in ``jax.checkpoint`` (same policy/prevent_cse),
    preserving rematerialization — the constraint lands INSIDE the
    checkpointed body.
    """
    import jax as _jax
    from alpa_tpu.shard_parallel.sharding_spec import (is_replicated,
                                                       spec_to_partition_spec)

    from jax.sharding import NamedSharding

    def _sharding(spec):
        return NamedSharding(jax_mesh,
                             spec_to_partition_spec(spec, axis_names))

    def _too_small(aval):
        return (min_elements and getattr(aval, "shape", None) and
                int(np.prod(aval.shape)) < min_elements)

    # Solved op node -> constraints on its outvar AND its operands.
    # Pinning only the output is not enough for fidelity: for a
    # contracting-dim (k) dot strategy GSPMD is free to all-gather the
    # operands and compute the full dot locally unless the operands'
    # chosen shardings are pinned too (the reference C++ pass annotates
    # operand shardings for the same reason).  Tensors below
    # ``min_elements`` (AutoShardingOption.constrain_min_elements) are
    # left to propagation: pinning tiny tensors can force GSPMD
    # transitions that cost more than the constraint is worth.
    var_pos = {}
    for ei, e in enumerate(graph.flat_eqns):
        for oi, ov in enumerate(e.outvars):
            if isinstance(ov, Var):
                var_pos[ov] = (ei, oi)
    flat_eqns = graph.flat_eqns
    out_cons = {}   # (eqn_pos, out_idx) -> NamedSharding
    in_cons = {}    # (eqn_pos, operand_idx) -> NamedSharding
    for node, s in zip(graph.nodes, choice):
        if node.kind != "op" or node.outvar is None:
            continue
        if node.outvar not in var_pos:
            continue
        pos, oi = var_pos[node.outvar]
        strat = node.strategies[s]
        if not is_replicated(strat.out_spec) and not _too_small(
                node.outvar.aval):
            out_cons[(pos, oi)] = _sharding(strat.out_spec)
        eqn = flat_eqns[pos]
        for ii, op_spec in enumerate(strat.operand_specs):
            if ii >= len(eqn.invars) or is_replicated(op_spec):
                continue
            v = eqn.invars[ii]
            if isinstance(v, Literal) or _too_small(v.aval):
                continue
            in_cons[(pos, ii)] = _sharding(op_spec)
    if not out_cons and not in_cons:
        return None

    root = graph.closed_jaxpr
    if root is None or not _check_evaluable(root.jaxpr):
        logger.warning(
            "skipping sharding-constraint emission: an inlined call site "
            "cannot be re-evaluated (fewer outer args than inner invars)")
        return None

    def constrained(*args):
        counter = [0]  # position in the flattened eqn order

        def eval_jaxpr(jaxpr, jconsts, jargs, depth):
            env = {}
            for v, c in zip(jaxpr.constvars, jconsts):
                env[v] = c
            for v, a in zip(jaxpr.invars, jargs):
                env[v] = a

            def read(v):
                if isinstance(v, Literal):
                    return v.val
                return env[v]

            for eqn in jaxpr.eqns:
                prim = eqn.primitive.name
                site = _inline_site(eqn, depth)
                if site is not None:
                    sub_jaxpr, sub_consts = site
                    outer_in = [read(v) for v in eqn.invars]
                    aligned = _align_call_args(outer_in, sub_jaxpr.invars)
                    if prim in ("remat", "checkpoint", "remat2"):
                        fn = functools.partial(
                            _remat_body, eval_jaxpr, sub_jaxpr, sub_consts,
                            depth)
                        fn = _jax.checkpoint(
                            fn,
                            policy=eqn.params.get("policy"),
                            prevent_cse=eqn.params.get("prevent_cse", True))
                        ans = fn(*aligned)
                    else:
                        ans = eval_jaxpr(sub_jaxpr, sub_consts, aligned,
                                         depth + 1)
                    for ov, a in zip(eqn.outvars, ans):
                        env[ov] = a
                    continue
                if prim == "pipeline":
                    # boundary marker: identity passthrough (one flat slot)
                    counter[0] += 1
                    for iv, ov in zip(eqn.invars, eqn.outvars):
                        env[ov] = read(iv)
                    continue
                pos = counter[0]
                counter[0] += 1
                vals = [read(v) for v in eqn.invars]
                for ii in range(len(vals)):
                    sh = in_cons.get((pos, ii))
                    if sh is not None:
                        vals[ii] = _jax.lax.with_sharding_constraint(
                            vals[ii], sh)
                ans = eqn.primitive.bind(*vals, **eqn.params)
                if not eqn.primitive.multiple_results:
                    ans = [ans]
                for oi, (ov, a) in enumerate(zip(eqn.outvars, ans)):
                    sh = out_cons.get((pos, oi))
                    if sh is not None:
                        a = _jax.lax.with_sharding_constraint(a, sh)
                    env[ov] = a
            return [read(v) for v in jaxpr.outvars]

        return eval_jaxpr(root.jaxpr, consts, args, 0)

    return constrained


def _remat_body(eval_jaxpr, sub_jaxpr, sub_consts, depth, *args):
    return eval_jaxpr(sub_jaxpr, sub_consts, list(args), depth + 1)
