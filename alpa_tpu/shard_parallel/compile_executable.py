"""Compile a function into a sharded single-mesh executable.

Analog of ref ``alpa/shard_parallel/compile_executable.py`` (SURVEY.md §3.2):
trace -> plan shardings -> (optionally rewrite for gradient accumulation) ->
jit with NamedShardings -> compile on the mesh.  The reference's two-binary
grad-accumulation design with runtime all-reduce skipping
(ref compile_executable.py:159 + mesh_executable.py:855-894) is replaced by a
single program whose microbatch loop is a ``lax.scan`` (see grad_acc.py).
"""
import logging
import time
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from alpa_tpu.device_mesh import LogicalDeviceMesh, PhysicalDeviceMesh
from alpa_tpu.global_env import global_config
from alpa_tpu.mesh_executable import GradAccMeshExecutable, NormalMeshExecutable
from alpa_tpu.shard_parallel.auto_sharding import (AutoShardingOption,
                                                  MESH_AXIS_NAMES,
                                                  plan_rule_based, replicated)
from alpa_tpu.shard_parallel.manual_sharding import (ManualShardingOption,
                                                     apply_manual_shardings,
                                                     flat_specs_from_tree)

logger = logging.getLogger(__name__)


def _logical_mesh_for(physical_mesh: PhysicalDeviceMesh,
                      option: AutoShardingOption) -> LogicalDeviceMesh:
    shape = option.logical_mesh_shape
    if shape is None:
        # Default: 1-D mesh over all devices; the solver may search 2-D
        # shapes itself (mesh_shape_search).
        shape = (physical_mesh.num_devices, 1)
    return physical_mesh.get_logical_mesh(shape)


def _pin_state_out_shardings(in_avals, in_shardings, batch_invars,
                             out_shapes):
    """Greedy in-order (shape, dtype) matching of output leaves to non-batch
    input leaves; matched outputs inherit the input sharding, others stay
    unspecified (inferred by GSPMD).  In-order matching aligns structurally
    identical state trees (params->new params, mu->new mu, ...)."""
    flat_outs = jax.tree_util.tree_leaves(out_shapes)
    unclaimed = {}
    for i, (aval, is_batch) in enumerate(zip(in_avals, batch_invars)):
        if not is_batch:
            unclaimed.setdefault((tuple(aval.shape), np.dtype(aval.dtype)),
                                 []).append(i)
    out_shardings = []
    for o in flat_outs:
        key = (tuple(o.shape), np.dtype(o.dtype))
        if unclaimed.get(key):
            i = unclaimed[key].pop(0)
            out_shardings.append(in_shardings[i])
        else:
            out_shardings.append(None)
    return out_shardings


def compile_shard_executable(
        fun: Callable,
        physical_mesh: PhysicalDeviceMesh,
        in_avals: Sequence[Any],
        in_tree,
        in_paths: Sequence[str],
        donated_invars: Sequence[bool],
        batch_invars: Sequence[bool],
        num_micro_batches: Optional[int],
        as_option: AutoShardingOption,
        manual_sharding_option: Optional[ManualShardingOption] = None):
    """Compile ``fun`` (flat signature) into a mesh executable.

    ``fun`` takes flat args and returns flat outputs (the caller handles
    pytrees).  Mirrors ref compile_shard_executable
    (shard_parallel/compile_executable.py:54).
    """
    tic = time.time()
    batch_flat_idx = [i for i, b in enumerate(batch_invars) if b]

    # ---- plan input shardings (on the original, scan-free function) ----
    if as_option.enable_auto_sharding and not as_option.force_data_parallel:
        from alpa_tpu.shard_parallel.solver import plan_auto_sharding
        jax_mesh, in_shardings, constraint_fn, _shape = plan_auto_sharding(
            fun, in_avals, in_paths, batch_flat_idx, physical_mesh,
            as_option)
        # The constraint function re-evaluates eqns traced at *these*
        # avals; the grad-accumulation rewrite retraces at microbatch
        # shapes, so the two do not compose — prefer plain in_shardings +
        # propagation there.
        if constraint_fn is not None and not (num_micro_batches and
                                              num_micro_batches > 1):
            fun = constraint_fn
    else:
        logical_mesh = _logical_mesh_for(physical_mesh, as_option)
        jax_mesh = logical_mesh.get_jax_mesh(
            MESH_AXIS_NAMES[:len(logical_mesh.shape)])
        in_shardings = plan_rule_based(jax_mesh, in_avals, in_paths,
                                       batch_flat_idx, as_option)

    # ---- rewrite for gradient accumulation (after planning: the planner
    # sees the scan-free full-batch program; shardings carry over since the
    # rewritten function keeps the same flat signature) ----
    if num_micro_batches is not None and num_micro_batches > 1:
        from alpa_tpu.shard_parallel.grad_acc import (
            rewrite_for_grad_accumulation)
        fun, in_avals = rewrite_for_grad_accumulation(
            fun, in_avals, batch_flat_idx, num_micro_batches)
        executable_cls = GradAccMeshExecutable
    else:
        executable_cls = NormalMeshExecutable

    if manual_sharding_option is not None:
        manual_flat = flat_specs_from_tree(
            manual_sharding_option.in_axis_resources, in_tree, len(in_avals))
        if manual_flat is not None:
            in_shardings = apply_manual_shardings(jax_mesh, in_shardings,
                                                  manual_flat)

    donate_idx = tuple(i for i, d in enumerate(donated_invars) if d)

    # Pin outputs that structurally correspond to inputs (state -> new state)
    # to the input's sharding: keeps the state layout stable across steps so
    # AOT executables can be re-invoked and donation can alias buffers.
    out_shapes = getattr(fun, "out_shapes", None)
    if out_shapes is None:
        out_shapes = jax.eval_shape(fun, *in_avals)
    out_shardings = _pin_state_out_shardings(in_avals, in_shardings,
                                             batch_invars, out_shapes)

    jitted = jax.jit(fun,
                     in_shardings=tuple(in_shardings),
                     out_shardings=out_shardings,
                     donate_argnums=donate_idx)
    lowered = jitted.lower(*in_avals)
    compiled = lowered.compile()
    out_avals = [
        jax.ShapeDtypeStruct(s.shape, s.dtype) for s in lowered.out_info
    ] if hasattr(lowered, "out_info") else None

    if global_config.print_compilation_time:
        logger.warning("shard-parallel compile took %.2f s", time.time() - tic)

    return executable_cls(
        physical_mesh,
        compiled,
        in_avals=in_avals,
        out_avals=out_avals,
        in_shardings=in_shardings,
        out_shardings=list(compiled.output_shardings),
        in_tree=in_tree,
        out_tree=None,  # set by the caller
        donated_invars=donated_invars,
        flop_count=None,
    )
