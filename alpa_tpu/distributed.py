"""Multi-host (TPU pod) bring-up helpers.

Analog of the reference's distributed XLA runtime bootstrap (SURVEY.md
§2.9: ``get_distributed_runtime_service/client`` + per-host Ray workers,
device_mesh.py:1057-1148).  On TPU pods the runtime is jax's own:
``jax.distributed.initialize`` connects every host process to the
coordinator, after which ``jax.devices()`` is the global pod view and all
of alpa_tpu's meshes/compile paths work unchanged — intra-mesh collectives
ride ICI, cross-mesh transfers ride DCN.

Typical pod usage (same script on every host):

    import alpa_tpu.distributed as dist
    dist.initialize()                    # TPU pods: args auto-detected
    alpa_tpu.init(cluster="distributed")
"""
import logging
import os
from typing import Optional, Sequence

import jax

from alpa_tpu import fault

logger = logging.getLogger(__name__)

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None):
    """Connect this host to the pod (idempotent).

    On Cloud TPU all arguments are auto-detected from the metadata server;
    elsewhere pass them explicitly or via the standard env vars
    (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``,
    ``JAX_PROCESS_ID``).
    """
    global _initialized
    if _initialized:
        return
    # NOTE: do not probe jax.process_count() here — it would initialize
    # the backend, after which jax.distributed.initialize cannot run.
    kwargs = {}
    if coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        kwargs["coordinator_address"] = (
            coordinator_address or os.environ["JAX_COORDINATOR_ADDRESS"])
    if num_processes or os.environ.get("JAX_NUM_PROCESSES"):
        kwargs["num_processes"] = int(
            num_processes or os.environ["JAX_NUM_PROCESSES"])
    if process_id is not None or os.environ.get("JAX_PROCESS_ID"):
        kwargs["process_id"] = int(
            process_id if process_id is not None else
            os.environ["JAX_PROCESS_ID"])
    if local_device_ids is not None:
        kwargs["local_device_ids"] = list(local_device_ids)
    if jax.config.jax_platforms == "cpu" or \
            os.environ.get("JAX_PLATFORMS") == "cpu":
        # cross-process computations on the CPU backend need the gloo
        # collectives client; without it XLA rejects multi-node programs
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except AttributeError:
            pass  # older jax: single collectives impl, nothing to select

    def connect():
        fault.fire("distributed_init", kwargs=sorted(kwargs))
        jax.distributed.initialize(**kwargs)

    try:
        # the coordinator may come up later than the workers: retry the
        # connection with backoff (site "distributed_init", no-retry by
        # default) before concluding we are single-process
        fault.call_with_retry(connect, site="distributed_init",
                              retry_on=(RuntimeError, ConnectionError,
                                        fault.InjectedFault))
        _initialized = True
        logger.info("jax.distributed initialized: process %d/%d, %d local "
                    "of %d global devices", jax.process_index(),
                    jax.process_count(), jax.local_device_count(),
                    jax.device_count())
    except Exception as e:
        # single-process runs (tests, one host) are fine without it
        logger.info("jax.distributed.initialize skipped: %s", e)


def shutdown():
    global _initialized
    if _initialized:
        try:
            jax.distributed.shutdown()
        except Exception:  # pylint: disable=broad-except
            pass
        _initialized = False


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    return jax.process_index() == 0


def sync_global_devices(tag: str = "barrier"):
    """Cross-host barrier (analog of the reference's sync RPCs)."""
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(tag)


def broadcast_from_coordinator(pytree):
    """Make host-0's values visible on every host."""
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(pytree)


########################################
# cross-process array movement
########################################
# The multi-controller analog of the reference's driver-side fetch +
# NCCL send/recv (ref device_mesh.py:1175 fetch, cross_mesh NCCL groups):
# jax cannot device_put an existing array onto devices of another process,
# so cross-mesh transfers that cross a process boundary are host-mediated
# — every process reconstructs the full value (one psum-style collective
# over all global devices), then re-places its own shards.  Correct for
# any sharding pair; the DCN cost is one full-array broadcast, which is
# acceptable for the validation path (production cross-slice transfers
# ride the compiled device_put fast path inside one process, or a
# dedicated interconnect transfer library).


def psum_work_dtype(dtype) -> "np.dtype":
    """psum-safe working dtype: widen sub-word types; keep word-size and
    wider types exact (an int64/float64 array can only exist with x64
    enabled, in which case psum carries it losslessly)."""
    import numpy as np
    dtype = np.dtype(dtype)
    if dtype == np.bool_:
        return np.dtype(np.int32)
    if dtype.itemsize < 4:
        return (np.dtype(np.int32) if dtype.kind in "iu"
                else np.dtype(np.float32))
    return dtype


def sum_across_processes(canvas: "np.ndarray") -> "np.ndarray":
    """Element-wise sum of every process's host ``canvas``, materialized
    identically on all processes — ONE global-device collective.

    COLLECTIVE: every process must call it with a same-shape/dtype canvas
    in the same order.  Each process's canvas rides in its first local
    device's slot of a global stack (other local slots carry zeros), one
    jitted sum reduces over the process axis.
    """
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    shape, work = canvas.shape, canvas.dtype
    devs = jax.devices()
    gmesh = Mesh(np.array(devs), ("p",))
    slot_sh = NamedSharding(gmesh, P("p"))
    # make_array skips the cross-process value-consistency check that
    # device_put(host, ...) enforces
    first_local = min(jax.local_devices(), key=lambda d: d.id)
    zeros = np.zeros((1,) + tuple(shape), work)
    shards = [
        jax.device_put(
            jnp.asarray(canvas[None] if d == first_local else zeros), d)
        for d in jax.local_devices()
    ]
    # no dtype kwarg: inferred from the (always non-empty) shards, and
    # older jax does not accept it
    stacked = jax.make_array_from_single_device_arrays(
        (len(devs),) + tuple(shape), slot_sh, shards)
    summed = jax.jit(lambda a: a.sum(0),
                     out_shardings=NamedSharding(gmesh, P()))(stacked)
    return np.asarray(summed.addressable_shards[0].data)


def host_gather(arr) -> "np.ndarray":
    """Full value of a (possibly non-fully-addressable) global jax.Array,
    materialized identically on every process.

    Multi-process semantics: this is a COLLECTIVE — every process must
    call it for the same array in the same order (the usual SPMD
    contract), even processes that could read the value locally.  The
    decision to take the collective path depends only on process_count,
    never on per-process addressability, so the collective sequence is
    identical everywhere.  Each process paints its replica-0 shards onto
    a zero canvas and one global-device sum reconstructs the full value
    on all hosts.
    """
    import numpy as np

    if jax.process_count() <= 1:
        return np.asarray(arr)

    dtype = np.dtype(arr.dtype)
    work = psum_work_dtype(dtype)
    canvas = np.zeros(arr.shape, work)
    for s in arr.addressable_shards:
        if s.replica_id == 0:
            canvas[s.index] = np.asarray(s.data).astype(work)
    full = sum_across_processes(canvas)
    if dtype == np.bool_:
        return full != 0
    return full.astype(dtype)


def is_process_local(arr) -> bool:
    """True for arrays that are this process's own (uncommitted results
    of local computation, or explicitly placed on one local device) as
    opposed to global arrays whose sharding metadata is identical on all
    processes.  Process-local arrays follow the SPMD host-input contract:
    every process passes its own identical copy."""
    from jax.sharding import SingleDeviceSharding
    committed = getattr(arr, "committed", getattr(arr, "_committed", True))
    return (not committed) or isinstance(arr.sharding,
                                         SingleDeviceSharding)


def ghost_array(shape, sharding, dtype):
    """A global array handle with only this process's shards materialized
    (zero-filled); processes owning no devices of ``sharding`` get a pure
    metadata handle.  The multi-controller stand-in for 'this value lives
    on another host'."""
    import numpy as np
    import jax.numpy as jnp

    idx_map = sharding.addressable_devices_indices_map(tuple(shape))
    arrs = []
    for d, idx in idx_map.items():
        shard_shape = tuple(
            len(range(*sl.indices(dim))) for sl, dim in
            zip(idx, shape)) if idx is not None and len(shape) else ()
        arrs.append(jax.device_put(jnp.zeros(shard_shape, dtype), d))
    return jax.make_array_from_single_device_arrays(
        tuple(shape), sharding, arrs, dtype=np.dtype(dtype))


def put_global(value, sharding):
    """``jax.device_put`` that survives process boundaries.

    Multi-process semantics: COLLECTIVE when ``value`` is a jax.Array
    whose devices are not confined to a single process identical to the
    destination's — every process must call it in the same order.  The
    path choice depends only on global metadata (sharding device sets),
    never on per-process addressability, so all processes stay aligned:

    - host values: plain device_put (places local shards; identical
      value on every process by the SPMD input contract);
    - array whose src+dst devices live on one process: that process
      device_puts locally, the others build a ghost handle (no
      collective);
    - anything else (a transfer that crosses a process boundary):
      host-mediated — a host_gather collective, then local placement.

    Single-process behavior is exactly ``jax.device_put``.
    """
    if jax.process_count() <= 1 or not isinstance(value, jax.Array):
        return jax.device_put(value, sharding)
    if is_process_local(value):
        # each process holds its own (identical, by the SPMD input
        # contract) copy: treat as a host value — its device metadata
        # differs per process and must not steer the branch below
        import numpy as np
        return jax.device_put(np.asarray(value), sharding)
    src_procs = {d.process_index for d in value.sharding.device_set}
    dst_procs = {d.process_index for d in sharding.device_set}
    me = jax.process_index()
    if len(src_procs) == 1 and src_procs == dst_procs:
        owner = next(iter(src_procs))
        if owner == me:
            return jax.device_put(value, sharding)
        return ghost_array(value.shape, sharding, value.dtype)
    # crosses a process boundary: host-mediated (collective)
    return jax.device_put(host_gather(value), sharding)
