"""Multi-host (TPU pod) bring-up helpers.

Analog of the reference's distributed XLA runtime bootstrap (SURVEY.md
§2.9: ``get_distributed_runtime_service/client`` + per-host Ray workers,
device_mesh.py:1057-1148).  On TPU pods the runtime is jax's own:
``jax.distributed.initialize`` connects every host process to the
coordinator, after which ``jax.devices()`` is the global pod view and all
of alpa_tpu's meshes/compile paths work unchanged — intra-mesh collectives
ride ICI, cross-mesh transfers ride DCN.

Typical pod usage (same script on every host):

    import alpa_tpu.distributed as dist
    dist.initialize()                    # TPU pods: args auto-detected
    alpa_tpu.init(cluster="distributed")
"""
import logging
import os
from typing import Optional, Sequence

import jax

logger = logging.getLogger(__name__)

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None):
    """Connect this host to the pod (idempotent).

    On Cloud TPU all arguments are auto-detected from the metadata server;
    elsewhere pass them explicitly or via the standard env vars
    (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``,
    ``JAX_PROCESS_ID``).
    """
    global _initialized
    if _initialized or jax.process_count() > 1:
        _initialized = True
        return
    kwargs = {}
    if coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        kwargs["coordinator_address"] = (
            coordinator_address or os.environ["JAX_COORDINATOR_ADDRESS"])
    if num_processes or os.environ.get("JAX_NUM_PROCESSES"):
        kwargs["num_processes"] = int(
            num_processes or os.environ["JAX_NUM_PROCESSES"])
    if process_id is not None or os.environ.get("JAX_PROCESS_ID"):
        kwargs["process_id"] = int(
            process_id if process_id is not None else
            os.environ["JAX_PROCESS_ID"])
    if local_device_ids is not None:
        kwargs["local_device_ids"] = list(local_device_ids)
    try:
        jax.distributed.initialize(**kwargs)
        _initialized = True
        logger.info("jax.distributed initialized: process %d/%d, %d local "
                    "of %d global devices", jax.process_index(),
                    jax.process_count(), jax.local_device_count(),
                    jax.device_count())
    except Exception as e:
        # single-process runs (tests, one host) are fine without it
        logger.info("jax.distributed.initialize skipped: %s", e)


def shutdown():
    global _initialized
    if _initialized:
        try:
            jax.distributed.shutdown()
        except Exception:  # pylint: disable=broad-except
            pass
        _initialized = False


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    return jax.process_index() == 0


def sync_global_devices(tag: str = "barrier"):
    """Cross-host barrier (analog of the reference's sync RPCs)."""
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(tag)


def broadcast_from_coordinator(pytree):
    """Make host-0's values visible on every host."""
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(pytree)
