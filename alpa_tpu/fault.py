"""Fault-tolerance layer: fault injection, retry/backoff, recovery.

The reference runtime only has passive failure *detection*
(``check_alive`` no-op RPC + ``exception_shutdown``, SURVEY.md §5); a
serving deployment needs detect-AND-recover.  This module is the shared
substrate for that, used across the stack:

1. **Fault injection** (``FaultPlan`` / ``FaultSpec``): deterministic,
   context-managed injection of hangs, errors, and slowdowns at named
   *sites* so every recovery path is testable on CPU.  Production code
   calls ``fault.fire("<site>", **info)`` at instrumented points; with
   no active plan this is a near-zero-cost no-op.  Instrumented sites:

   =====================  ==================================================
   site                   where
   =====================  ==================================================
   ``probe``              ``monitoring.check_alive``'s device probe
   ``stage_launch``       pipeshard RUN instruction dispatch
   ``cross_mesh_send``    pipeshard RESHARD instruction dispatch
   ``cross_mesh_recv``    ``ReshardingTask.run`` / ``run_multiprocess`` entry
   ``scheduler_take``     ``serve.controller.RequestBatcher`` batch formation
   ``scheduler_tick``     ``serve.engine.ContinuousBatchingEngine`` decode
                          tick
   ``distributed_init``   ``distributed.initialize`` bring-up
   ``worker_lost``        ``elastic.ElasticSupervisor`` step-boundary poll
                          (a mesh's workers died; re-solve for survivors)
   ``preemption_notice``  ``elastic.ElasticSupervisor`` step-boundary poll
                          (eviction warning; snapshot inside the grace
                          window before the kill lands)
   ``wedge_detected``     ``elastic.WedgeDetector.check`` probe sweep (a
                          device answers nothing — not even an error)
   =====================  ==================================================

   Recovery re-probes fire at sites ``probe`` and ``recovery_probe``.
   The three elastic sites (``ELASTIC_SITES``) additionally escalate:
   retry exhaustion there routes into the installed
   ``RecoveryManager`` (``set_escalation_manager``) instead of
   propagating a raw ``RetryExhaustedError`` — worker loss is a
   lifecycle event to recover from, not an RPC error to re-raise.

2. **Retry policy** (``RetryPolicy`` + ``call_with_retry``): jittered
   exponential backoff with deadline budgets and per-site overrides,
   threaded through ``check_alive``, pipeshard stage launch, and
   cross-mesh resharding transfers.  ``InjectedFault`` errors are always
   retry-safe; real errors are retried only when the caller declares the
   operation idempotent (cross-mesh transfers are; a donated-buffer
   stage execution is not).

3. **Recovery state machine** (``MeshHealth`` / ``RecoveryManager``):
   HEALTHY -> SUSPECT -> RECOVERING -> DEGRADED with bounded re-probe
   retries, in-flight-work quiescing, and driver-state snapshotting
   hooks.  ``monitoring.FailureWatchdog`` drives it periodically; the
   serving stack registers degrade/recover callbacks so a dead mesh
   sheds load (503-style rejections) instead of crashing the batcher.
"""
import dataclasses
import enum
import logging
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from alpa_tpu.telemetry import metrics as _tmetrics

logger = logging.getLogger(__name__)

_RETRIES_TOTAL = _tmetrics.get_registry().counter(
    "alpa_fault_retries_total",
    "Total extra retry attempts per instrumented site",
    labelnames=("site",))
_HEALTH_STATE = _tmetrics.get_registry().gauge(
    "alpa_fault_health_state",
    "Recovery state machine position "
    "(0=healthy 1=suspect 2=recovering 3=degraded)")
_STATE_TRANSITIONS = _tmetrics.get_registry().counter(
    "alpa_fault_state_transitions_total",
    "Recovery state machine transitions by destination state",
    labelnames=("to",))

__all__ = [
    "FaultSpec", "FaultPlan", "InjectedFault", "fire", "active_plan",
    "KNOWN_SITES", "ELASTIC_SITES",
    "set_escalation_manager", "get_escalation_manager",
    "RetryPolicy", "RetryExhaustedError", "call_with_retry",
    "set_retry_policy", "get_retry_policy", "retry_stats",
    "install_retry_classification", "get_retry_classification",
    "MeshHealth", "RecoveryManager", "ServiceDegradedError",
    "make_snapshotter",
]

#: Registry of instrumented fault sites (the table in the module
#: docstring, machine-readable).  The repo lint checks every
#: ``fault.fire(...)`` / ``site=...`` literal against this set, so a
#: typo'd site name fails tier-1 instead of silently never firing.
#: Adding a site = instrument the call point, add it here AND to the
#: docstring table above.
KNOWN_SITES = frozenset({
    "probe", "stage_launch", "cross_mesh_send", "cross_mesh_recv",
    "scheduler_take", "scheduler_tick", "distributed_init",
    "recovery_probe",
    "worker_lost", "preemption_notice", "wedge_detected",
})

#: Elastic-lifecycle sites (ISSUE 16): failures here are cluster
#: membership events, not transient RPC errors.  ``call_with_retry``
#: exhaustion at these sites escalates into the installed
#: RecoveryManager (``set_escalation_manager``) rather than propagating
#: a raw ``RetryExhaustedError`` to the caller.
ELASTIC_SITES = frozenset({
    "worker_lost", "preemption_notice", "wedge_detected",
})


class InjectedFault(RuntimeError):
    """Error raised by an ``error``-kind FaultSpec.  Retry wrappers treat
    these as always safe to retry (the injection fired *before* the real
    operation ran), which lets tests exercise retry loops around
    non-idempotent operations without risking double execution."""


class ServiceDegradedError(RuntimeError):
    """Load-shed rejection: the serving stack is in DEGRADED mode and
    refuses new work instead of crashing on it (mapped to HTTP 503 by
    ``serve.controller``)."""


########################################
# fault injection
########################################


@dataclasses.dataclass
class FaultSpec:
    """One injected fault at a named site.

    ``kind``:
      * ``"error"`` — raise (``exc`` factory, default ``InjectedFault``).
      * ``"hang"``  — sleep ``delay`` seconds (simulates a wedged device:
        make it longer than the caller's timeout).
      * ``"slow"``  — sleep ``delay`` seconds, then continue normally.

    ``times``: how many matching hits fire this spec (-1 = every hit).
    ``after``: skip the first N matching hits (fire on hit N+1 onward) —
    lets a test fail the first attempt and let the retry succeed.
    ``match``: optional predicate over the site's keyword info (e.g.
    ``lambda info: info.get("mesh_id") == 1``) to target one mesh/stage.
    """
    site: str
    kind: str = "error"
    times: int = 1
    after: int = 0
    delay: float = 0.0
    exc: Optional[Callable[[], BaseException]] = None
    match: Optional[Callable[[Dict[str, Any]], bool]] = None

    def __post_init__(self):
        if self.kind not in ("error", "hang", "slow"):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.kind in ("hang", "slow") and self.delay <= 0.0:
            raise ValueError(f"{self.kind} fault needs a positive delay")


class _SpecState:
    """Mutable firing counters for one FaultSpec inside one plan."""

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self.hits = 0       # matching fire() calls seen
        self.fired = 0      # times the fault actually triggered


class FaultPlan:
    """Context manager installing a set of FaultSpecs for the duration
    of a ``with`` block (process-global; nested plans stack, innermost
    consulted first).  Thread-safe: ``fire`` may be called from worker
    threads while the plan is active.

    Introspection for tests:
      * ``plan.events`` — every triggered fault as ``(site, kind, info)``.
      * ``plan.hits(site)`` — matching ``fire`` calls (triggered or not).
      * ``plan.retries`` — per-site retry-attempt counts recorded by
        ``call_with_retry`` while this plan was active.
    """

    def __init__(self, *specs: FaultSpec):
        self._states = [_SpecState(s) for s in specs]
        self._lock = threading.Lock()
        self.events: List[Tuple[str, str, Dict[str, Any]]] = []
        self.retries: Dict[str, int] = {}
        self.backoffs: Dict[str, List[float]] = {}

    # -- context management -------------------------------------------

    def __enter__(self):
        with _PLANS_LOCK:
            _ACTIVE_PLANS.append(self)
        return self

    def __exit__(self, *exc_info):
        with _PLANS_LOCK:
            if self in _ACTIVE_PLANS:
                _ACTIVE_PLANS.remove(self)
        return False

    # -- firing --------------------------------------------------------

    def hits(self, site: str) -> int:
        with self._lock:
            return sum(st.hits for st in self._states
                       if st.spec.site == site)

    def fired(self, site: str) -> int:
        with self._lock:
            return sum(st.fired for st in self._states
                       if st.spec.site == site)

    def _consume(self, site: str, info: Dict[str, Any]):
        """Return the FaultSpec to trigger for this hit, if any."""
        with self._lock:
            for st in self._states:
                spec = st.spec
                if spec.site != site:
                    continue
                if spec.match is not None and not spec.match(info):
                    continue
                st.hits += 1
                if st.hits <= spec.after:
                    continue
                if spec.times >= 0 and st.fired >= spec.times:
                    continue
                st.fired += 1
                self.events.append((site, spec.kind, dict(info)))
                return spec
        return None

    def _record_retry(self, site: str, attempts: int,
                      delays: Sequence[float]):
        with self._lock:
            self.retries[site] = self.retries.get(site, 0) + attempts
            self.backoffs.setdefault(site, []).extend(delays)


_ACTIVE_PLANS: List[FaultPlan] = []
_PLANS_LOCK = threading.Lock()


def active_plan() -> Optional[FaultPlan]:
    """Innermost active plan (None outside any ``with FaultPlan(...)``)."""
    with _PLANS_LOCK:
        return _ACTIVE_PLANS[-1] if _ACTIVE_PLANS else None


def instrumented() -> bool:
    """True when any fault plan or retry policy is installed.  Hot
    dispatch paths may skip their retry-wrapper overhead when False —
    with nothing installed the wrapper could only ever make one
    attempt anyway."""
    return bool(_ACTIVE_PLANS or _SITE_POLICIES
                or _DEFAULT_POLICY is not None)


def fire(site: str, **info):
    """Fault-injection hook: no-op unless an active FaultPlan has a
    matching spec.  Call at every instrumented site; the fast path is a
    single list check."""
    if not _ACTIVE_PLANS:  # fast path: no plan installed
        return
    with _PLANS_LOCK:
        plans = list(reversed(_ACTIVE_PLANS))
    for plan in plans:
        spec = plan._consume(site, info)
        if spec is None:
            continue
        # a firing site is one of the flight recorder's auto-dump
        # triggers (ISSUE 6): capture the instruction timeline leading
        # up to the injection before the failure propagates.  Lazy
        # import: fault.py must stay importable without telemetry.
        from alpa_tpu.telemetry import flight as _flight
        _flight.auto_dump(f"fault site fired: {site} ({spec.kind})")
        if spec.kind == "error":
            exc = spec.exc() if spec.exc is not None else InjectedFault(
                f"injected fault at {site} ({info})")
            raise exc
        # hang / slow both sleep; "hang" is expected to exceed the
        # caller's timeout, "slow" to stay under it
        time.sleep(spec.delay)
        return


########################################
# retry / timeout / backoff
########################################


class RetryExhaustedError(RuntimeError):
    """All retry attempts failed.  ``last`` is the final exception;
    ``attempts`` the number of calls made."""

    def __init__(self, site: str, attempts: int, last: BaseException):
        super().__init__(
            f"{site}: {attempts} attempt(s) failed; last error: "
            f"{type(last).__name__}: {last}")
        self.site = site
        self.attempts = attempts
        self.last = last


@dataclasses.dataclass
class RetryPolicy:
    """Jittered exponential backoff with a deadline budget.

    ``max_attempts`` total calls (1 = no retry); sleep between attempts
    is ``min(max_delay, base_delay * multiplier**k)`` plus uniform
    jitter of up to ``jitter`` fraction of the delay.  ``deadline``
    (seconds, measured from the first attempt) bounds the whole loop:
    no retry is started once the budget is spent.  ``site_overrides``
    maps site names to replacement policies — one policy object can be
    threaded through the stack and still treat probes differently from
    transfers.
    """
    max_attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.1
    deadline: Optional[float] = None
    site_overrides: Dict[str, "RetryPolicy"] = dataclasses.field(
        default_factory=dict)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.base_delay < 0 or self.max_delay < 0 or self.jitter < 0:
            raise ValueError("delays and jitter must be non-negative")
        if self.multiplier < 1.0:
            raise ValueError("multiplier must be >= 1")

    def for_site(self, site: Optional[str]) -> "RetryPolicy":
        if site is not None and site in self.site_overrides:
            return self.site_overrides[site]
        return self

    def backoff(self, attempt: int,
                rng: Optional[random.Random] = None) -> float:
        """Sleep before attempt ``attempt`` (attempt 1 is the second
        call).  Deterministic when ``jitter == 0``."""
        base = min(self.max_delay,
                   self.base_delay * self.multiplier ** (attempt - 1))
        if self.jitter > 0:
            base += (rng or random).uniform(0, self.jitter * base)
        return base


#: No-retry default: production paths pay zero behavior change unless a
#: policy is installed (``set_retry_policy``) or passed explicitly.
NO_RETRY = RetryPolicy(max_attempts=1)

_SITE_POLICIES: Dict[str, RetryPolicy] = {}
_DEFAULT_POLICY: Optional[RetryPolicy] = None
_POLICY_LOCK = threading.Lock()

#: Process-global retry accounting: site -> total extra attempts.
retry_stats: Dict[str, int] = {}

#: Static retry-safety classification per site, installed by the plan
#: model checker (ISSUE 13, alpa_tpu.analysis.model_check) for the most
#: recently verified plan: site -> {"classification":
#: "safe" | "unsafe" | "unreachable", "reasons": [...]}.  Consulted by
#: call_with_retry under ``global_config.verify_plans == "error"``.
_RETRY_CLASSIFICATION: Dict[str, Dict[str, Any]] = {}


def install_retry_classification(
        sites: Optional[Dict[str, Dict[str, Any]]]) -> None:
    """Install (or with ``None``/``{}``, clear) the model checker's
    per-site retry-safety classification.  Called by
    ``plan_verifier.verify_program`` on every verified compile — cache
    hits included, so warm restarts replay identical refusals."""
    with _POLICY_LOCK:
        _RETRY_CLASSIFICATION.clear()
        if sites:
            _RETRY_CLASSIFICATION.update(
                {s: dict(e) for s, e in sites.items()})


def get_retry_classification() -> Dict[str, Dict[str, Any]]:
    """The currently installed static retry classification (a copy)."""
    with _POLICY_LOCK:
        return {s: dict(e) for s, e in _RETRY_CLASSIFICATION.items()}


#: Process-global escalation target for ELASTIC_SITES retry exhaustion:
#: a RecoveryManager (or anything with ``escalate(site, error)``).
_ESCALATION_MANAGER: Optional[Any] = None


def set_escalation_manager(manager: Optional[Any]) -> Optional[Any]:
    """Install (``None`` clears) the RecoveryManager that absorbs retry
    exhaustion at ``ELASTIC_SITES``.  Returns the previous target so
    tests and nested supervisors can restore it."""
    global _ESCALATION_MANAGER
    with _POLICY_LOCK:
        prev = _ESCALATION_MANAGER
        _ESCALATION_MANAGER = manager
    return prev


def get_escalation_manager() -> Optional[Any]:
    with _POLICY_LOCK:
        return _ESCALATION_MANAGER


def _escalate_exhaustion(site: str, attempts: int,
                         error: BaseException) -> bool:
    """Route elastic-site retry exhaustion into the recovery state
    machine.  True when a manager absorbed it (the caller then raises
    ``ServiceDegradedError`` instead of the raw error)."""
    if site not in ELASTIC_SITES:
        return False
    manager = get_escalation_manager()
    if manager is None:
        return False
    try:
        manager.escalate(site, error)
        return True
    except Exception:  # pylint: disable=broad-except
        logger.exception("elastic escalation of %s failed", site)
        return False


def _refuse_statically_unsafe(site: str) -> bool:
    """True when the model checker proved retrying ``site`` unsafe for
    the verified plan AND the operator runs with verify_plans=error —
    the strict mode where static proofs override caller-declared
    idempotency."""
    with _POLICY_LOCK:
        ent = _RETRY_CLASSIFICATION.get(site)
    if not ent or ent.get("classification") != "unsafe":
        return False
    try:
        from alpa_tpu.global_env import global_config
        return getattr(global_config, "verify_plans", "warn") == "error"
    except Exception:  # pylint: disable=broad-except
        return False


def set_retry_policy(policy: Optional[RetryPolicy],
                     site: Optional[str] = None):
    """Install ``policy`` for ``site`` (or as the process default when
    site is None).  ``None`` removes the entry."""
    global _DEFAULT_POLICY
    with _POLICY_LOCK:
        if site is None:
            _DEFAULT_POLICY = policy
        elif policy is None:
            _SITE_POLICIES.pop(site, None)
        else:
            _SITE_POLICIES[site] = policy


def get_retry_policy(site: Optional[str] = None) -> RetryPolicy:
    """Effective policy for a site: explicit site entry, else the
    process default's ``for_site`` view, else NO_RETRY."""
    with _POLICY_LOCK:
        if site is not None and site in _SITE_POLICIES:
            return _SITE_POLICIES[site]
        if _DEFAULT_POLICY is not None:
            return _DEFAULT_POLICY.for_site(site)
    return NO_RETRY


def call_with_retry(fn: Callable[[], Any],
                    policy: Optional[RetryPolicy] = None,
                    site: str = "call",
                    retry_on: Tuple = (Exception,),
                    idempotent: bool = True,
                    on_retry: Optional[Callable[[int, BaseException],
                                                Any]] = None,
                    rng: Optional[random.Random] = None) -> Any:
    """Run ``fn()`` under ``policy`` (default: the installed policy for
    ``site``).

    * ``InjectedFault`` is always retryable (the injection preempted the
      real operation); other ``retry_on`` errors are retried only when
      ``idempotent`` — re-running a donated-buffer execution would read
      freed inputs, so non-idempotent callers get detection + the
      original error, never a blind re-run.
    * Exhaustion re-raises the LAST error (callers' existing error paths
      keep working); wrap in ``RetryExhaustedError`` only when asked via
      ``policy.deadline``-style introspection — attempts are recorded in
      ``retry_stats`` and the active ``FaultPlan`` either way.
    """
    pol = (policy or get_retry_policy(site)).for_site(site)
    start = time.monotonic()
    attempts = 0
    delays: List[float] = []
    while True:
        attempts += 1
        try:
            result = fn()
            break
        except retry_on as e:  # pylint: disable=broad-except
            retryable = idempotent or isinstance(e, InjectedFault)
            if retryable and not isinstance(e, InjectedFault) and \
                    _refuse_statically_unsafe(site):
                # the model checker proved a real mid-op failure at
                # this site cannot be retried without double-applying
                # state (donation / partial group / FIFO reorder);
                # under verify_plans=error that proof wins over the
                # caller's idempotent flag
                logger.warning(
                    "%s: retry refused — statically classified unsafe "
                    "by the plan model checker (%s) under "
                    "verify_plans=error", site,
                    ",".join(get_retry_classification()
                             .get(site, {}).get("reasons", ())))
                retryable = False
            out_of_attempts = attempts >= pol.max_attempts
            out_of_budget = (
                pol.deadline is not None and
                time.monotonic() - start >= pol.deadline)
            if not retryable or out_of_attempts or out_of_budget:
                _account_retries(site, attempts - 1, delays)
                if _escalate_exhaustion(site, attempts, e):
                    # elastic lifecycle event: the recovery manager now
                    # owns it (quiesce/snapshot/degrade); callers see a
                    # typed degradation signal, never the raw
                    # RetryExhaustedError / transport error
                    raise ServiceDegradedError(
                        f"{site}: {attempts} attempt(s) failed; "
                        "escalated to the recovery manager "
                        f"(last error: {type(e).__name__}: {e})") from e
                raise
            delay = pol.backoff(attempts, rng)
            if pol.deadline is not None:
                delay = min(delay, max(
                    0.0, pol.deadline - (time.monotonic() - start)))
            delays.append(delay)
            if on_retry is not None:
                try:
                    on_retry(attempts, e)
                except Exception:  # pylint: disable=broad-except
                    logger.exception("on_retry callback failed")
            logger.warning("%s failed (attempt %d/%d): %s — retrying "
                           "in %.3fs", site, attempts, pol.max_attempts,
                           e, delay)
            if delay > 0:
                time.sleep(delay)
    _account_retries(site, attempts - 1, delays)
    return result


def _account_retries(site: str, extra_attempts: int,
                     delays: Sequence[float]):
    if extra_attempts <= 0:
        return
    with _POLICY_LOCK:
        retry_stats[site] = retry_stats.get(site, 0) + extra_attempts
    _RETRIES_TOTAL.labels(site).inc(extra_attempts)
    plan = active_plan()
    if plan is not None:
        plan._record_retry(site, extra_attempts, delays)


########################################
# recovery state machine
########################################


class MeshHealth(enum.Enum):
    HEALTHY = "healthy"
    SUSPECT = "suspect"
    RECOVERING = "recovering"
    DEGRADED = "degraded"


#: numeric encoding for the alpa_fault_health_state gauge
_HEALTH_LEVEL = {
    MeshHealth.HEALTHY: 0,
    MeshHealth.SUSPECT: 1,
    MeshHealth.RECOVERING: 2,
    MeshHealth.DEGRADED: 3,
}


class RecoveryManager:
    """Watchdog-driven recovery: HEALTHY -> SUSPECT -> RECOVERING ->
    (HEALTHY | DEGRADED).

    Transitions (driven by ``observe(alive)`` per watchdog round):

    * HEALTHY, probe fails        -> SUSPECT (one immediate re-probe
      round with the retry policy — transient blips recover here).
    * SUSPECT, re-probe succeeds  -> HEALTHY.
    * SUSPECT, re-probe fails     -> RECOVERING: ``quiesce()`` in-flight
      pipeshard work, ``snapshot()`` driver-side state (serialization
      hooks), then re-probe with bounded retries.
    * RECOVERING, probe succeeds  -> HEALTHY (``on_recover`` fires;
      load-shedding lifts).
    * RECOVERING, retries exhaust -> DEGRADED (``on_degrade`` fires;
      the serving stack sheds load with 503s instead of crashing).
    * DEGRADED, probe succeeds    -> HEALTHY (meshes un-wedge on their
      own; see bench.py's probe-and-wait discipline).

    All callbacks are best-effort: a raising hook is logged, never
    allowed to kill the watchdog thread.
    """

    def __init__(self, mesh_group=None,
                 retry_policy: Optional[RetryPolicy] = None,
                 probe: Optional[Callable[[Any], bool]] = None,
                 quiesce: Optional[Callable[[], Any]] = None,
                 resume: Optional[Callable[[], Any]] = None,
                 snapshot: Optional[Callable[[], Any]] = None,
                 on_degrade: Optional[Callable[[str], Any]] = None,
                 on_recover: Optional[Callable[[], Any]] = None,
                 on_state_change: Optional[
                     Callable[[MeshHealth, MeshHealth], Any]] = None,
                 probe_timeout: float = 10.0):
        if probe is None:
            from alpa_tpu.monitoring import check_alive

            def probe(mesh, _t=probe_timeout):
                return check_alive(mesh, timeout=_t)

        self.mesh_group = mesh_group
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=3, base_delay=0.05, max_delay=1.0)
        self._probe = probe
        # public, reassignable after construction (e.g.
        # Controller.attach_recovery rebinds the degrade/recover hooks)
        self.quiesce_hook = quiesce
        self.resume_hook = resume
        self.snapshot_hook = snapshot
        self.on_degrade = on_degrade
        self.on_recover = on_recover
        self.on_state_change = on_state_change
        self._lock = threading.Lock()
        self._state = MeshHealth.HEALTHY
        #: every transition as (old, new, reason) — test introspection
        self.transitions: List[Tuple[MeshHealth, MeshHealth, str]] = []
        self.snapshots_taken = 0
        self.last_dead: List[int] = []

    # -- state ---------------------------------------------------------

    @property
    def state(self) -> MeshHealth:
        with self._lock:
            return self._state

    def _transition(self, new: MeshHealth, reason: str):
        with self._lock:
            old = self._state
            if old is new:
                return
            self._state = new
            self.transitions.append((old, new, reason))
        _HEALTH_STATE.set(_HEALTH_LEVEL[new])
        _STATE_TRANSITIONS.labels(new.value).inc()
        logger.warning("mesh health: %s -> %s (%s)", old.value,
                       new.value, reason)
        if new is MeshHealth.SUSPECT:
            # watchdog declared a mesh SUSPECT: dump the flight ring —
            # the last instructions dispatched before liveness broke are
            # exactly the post-mortem a hang needs (ISSUE 6)
            from alpa_tpu.telemetry import flight as _flight
            _flight.auto_dump(f"mesh SUSPECT: {reason}")
        self._call(self.on_state_change, old, new)

    @staticmethod
    def _call(hook, *args):
        if hook is None:
            return None
        try:
            return hook(*args)
        except Exception:  # pylint: disable=broad-except
            logger.exception("recovery hook %r failed", hook)
            return None

    # -- probing -------------------------------------------------------

    def _probe_all(self) -> List[int]:
        """Indices of dead meshes (empty list = all healthy)."""
        if self.mesh_group is None:
            return []
        dead = []
        for i, mesh in enumerate(self.mesh_group):
            ok = False
            try:
                ok = bool(self._probe(mesh))
            except Exception:  # pylint: disable=broad-except
                logger.exception("probe of mesh %d raised", i)
            if not ok:
                dead.append(i)
        return dead

    def _reprobe_with_retries(self, site: str) -> bool:
        """Bounded re-probe loop: True once every mesh answers."""

        def attempt():
            dead = self._probe_all()
            if dead:
                self.last_dead = dead
                raise InjectedFault(f"meshes still dead: {dead}")
            return True

        try:
            return bool(call_with_retry(
                attempt, policy=self.retry_policy, site=site))
        except Exception:  # pylint: disable=broad-except
            return False

    # -- the state machine ---------------------------------------------

    def observe(self, dead: Sequence[int]) -> MeshHealth:
        """One watchdog round's verdict: ``dead`` mesh indices (empty =
        all probes passed).  Drives the state machine; returns the state
        after handling.  Callable from FailureWatchdog's thread or
        directly from tests."""
        dead = list(dead)
        state = self.state
        if not dead:
            if state is not MeshHealth.HEALTHY:
                self._recover(f"probe clean from {state.value}")
            return self.state

        self.last_dead = dead
        if state is MeshHealth.HEALTHY:
            self._transition(MeshHealth.SUSPECT,
                             f"probe failed for meshes {dead}")
            # one immediate retried re-probe: transient blips end here
            if self._reprobe_with_retries("probe"):
                self._recover("re-probe clean")
                return self.state
            self._begin_recovery()
        elif state is MeshHealth.SUSPECT:
            self._begin_recovery()
        elif state is MeshHealth.RECOVERING:
            self._transition(MeshHealth.DEGRADED,
                             f"still dead in recovery: {dead}")
            self._call(self.on_degrade,
                       f"meshes {dead} unrecovered")
        # DEGRADED + dead: stay degraded (watchdog keeps probing; a
        # clean round recovers via the branch above)
        return self.state

    def _begin_recovery(self):
        self._transition(MeshHealth.RECOVERING,
                         f"quiescing; dead meshes {self.last_dead}")
        self._call(self.quiesce_hook)
        if self.snapshot_hook is not None:
            self._call(self.snapshot_hook)
            self.snapshots_taken += 1
        if self._reprobe_with_retries("recovery_probe"):
            self._recover("recovered after quiesce")
        else:
            self._transition(
                MeshHealth.DEGRADED,
                f"recovery retries exhausted; dead {self.last_dead}")
            self._call(self.on_degrade,
                       f"meshes {self.last_dead} unrecovered")

    def _recover(self, reason: str):
        was_degraded = self.state is MeshHealth.DEGRADED
        self._transition(MeshHealth.HEALTHY, reason)
        self._call(self.resume_hook)
        self._call(self.on_recover)
        if was_degraded:
            logger.warning("mesh group recovered from DEGRADED (%s)",
                           reason)

    def escalate(self, site: str, error: BaseException) -> MeshHealth:
        """Absorb an elastic-site retry exhaustion (``worker_lost`` /
        ``preemption_notice`` / ``wedge_detected``; see
        ``set_escalation_manager``): the failure is treated as a failed
        watchdog round — SUSPECT, then the quiesce → snapshot →
        re-probe recovery path — instead of propagating to the caller.
        """
        logger.warning("elastic site %s exhausted retries (%s: %s); "
                       "escalating into recovery", site,
                       type(error).__name__, error)
        state = self.state
        if state is MeshHealth.HEALTHY:
            self._transition(MeshHealth.SUSPECT,
                             f"elastic escalation from {site}")
            self._begin_recovery()
        elif state is MeshHealth.SUSPECT:
            self._begin_recovery()
        # RECOVERING / DEGRADED: recovery already owns the failure
        return self.state

    def tick(self) -> MeshHealth:
        """Probe every mesh once and feed the result to the state
        machine (the watchdog's per-interval body)."""
        return self.observe(self._probe_all())


def make_snapshotter(snapshot_dir: str,
                     state_provider: Callable[[], Any],
                     step: int = 0) -> Callable[[], str]:
    """Driver-side state snapshot hook for ``RecoveryManager``: dumps
    ``state_provider()`` (a pytree of arrays) via
    ``serialization.save_checkpoint`` and blocks until the write lands —
    a recovery that later fails over to a fresh cluster restores from
    here.

    Prefer :class:`alpa_tpu.checkpoint.RecoveryCheckpointer` for new
    code: it snapshots into the content-addressed store (verifiable,
    retained, atomically committed) AND auto-restores the last verified
    step when recovery brings the mesh back; this helper remains for
    flat-directory snapshots with no retention."""

    def snapshot():
        from alpa_tpu.serialization import checkpoint_wait, save_checkpoint
        target = state_provider()
        save_checkpoint(snapshot_dir, target, step=step)
        checkpoint_wait()
        logger.info("driver state snapshot written to %s", snapshot_dir)
        return snapshot_dir

    return snapshot
