"""Health checking, failure detection, recovery, and debug dumps.

Analog of ref SURVEY.md §5 failure detection: ``check_alive`` no-op RPC
(ref device_mesh.py:616) + ``PipeshardDriverExecutable._check_alive``
(ref pipeshard_executable.py:417) + ``exception_shutdown``
(ref device_mesh.py:2099), re-expressed for the single-controller runtime:
liveness = a tiny device program completing within a timeout per mesh;
debug dumps collect every IR the compiler produced
(ref dump_debug_info, pipeshard_executable.py:357).

Beyond the reference's passive detection, ``FailureWatchdog`` drives the
``fault.RecoveryManager`` state machine (HEALTHY -> SUSPECT ->
RECOVERING -> DEGRADED): on mesh failure it quiesces in-flight pipeshard
work, snapshots driver-side state, and either re-probes back to HEALTHY
or fails the serving stack over to load-shedding degraded mode.  See
docs/fault_tolerance.md.
"""
import concurrent.futures
import logging
import os
import time
from typing import List, Optional

import jax
import jax.numpy as jnp

from alpa_tpu import fault
from alpa_tpu.telemetry import metrics as _tmetrics
from alpa_tpu.telemetry import trace as _ttrace

logger = logging.getLogger(__name__)

_WATCHDOG_LAST_OK = _tmetrics.get_registry().gauge(
    "alpa_watchdog_last_ok_timestamp",
    "Unix time of each mesh's last successful liveness probe",
    labelnames=("mesh",))
_WATCHDOG_FAILS = _tmetrics.get_registry().gauge(
    "alpa_watchdog_consecutive_failures",
    "Consecutive failed liveness probes per mesh",
    labelnames=("mesh",))


def check_alive(mesh, timeout: float = 10.0,
                retry_policy: Optional["fault.RetryPolicy"] = None) -> bool:
    """True iff every device of the mesh completes a trivial program within
    ``timeout`` seconds (ref check_alive no-op RPC).

    ``retry_policy`` (default: the installed policy for site ``probe``,
    no-retry out of the box) re-probes with jittered backoff before
    declaring the mesh dead — one slow tick must not trip recovery.
    """

    def probe():
        fault.fire("probe", mesh=mesh)
        vals = [
            jax.device_put(jnp.zeros(()), d) + 1
            for d in mesh.flat_devices
        ]
        jax.block_until_ready(vals)
        return True

    def probe_once():
        # No context manager: with a genuinely hung device the probe
        # thread never finishes, and pool.__exit__ would join it forever
        # — exactly the case this function must detect.  The daemon
        # thread is abandoned.
        pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        fut = pool.submit(probe)
        try:
            return bool(fut.result(timeout=timeout))
        finally:
            pool.shutdown(wait=False)

    policy = retry_policy or fault.get_retry_policy("probe")
    try:
        return bool(fault.call_with_retry(
            probe_once, policy=policy, site="probe",
            retry_on=(concurrent.futures.TimeoutError, Exception)))
    except concurrent.futures.TimeoutError:
        logger.error("mesh %s failed liveness probe (%.1fs timeout)",
                     mesh, timeout)
        return False
    except Exception as e:  # pylint: disable=broad-except
        logger.error("mesh %s liveness probe raised: %s", mesh, e)
        return False


def check_mesh_group_alive(mesh_group, timeout: float = 10.0) -> List[bool]:
    return [check_alive(m, timeout) for m in mesh_group]


class FailureWatchdog:
    """Periodic liveness checking driving the recovery state machine
    (the elastic-recovery hook the reference lacks, SURVEY.md §5).

    Backward-compatible surface: ``on_failure(dead_indices)`` still
    fires on every failed probe round.  New surface: pass ``recovery=``
    a :class:`alpa_tpu.fault.RecoveryManager` (or let the watchdog build
    a plain one) and each round's verdict drives HEALTHY -> SUSPECT ->
    RECOVERING -> DEGRADED with quiesce/snapshot/degrade hooks; the
    current state is readable via ``watchdog.state``.
    """

    def __init__(self, mesh_group, interval: float = 60.0,
                 on_failure=None, recovery: Optional[
                     "fault.RecoveryManager"] = None,
                 probe_timeout: float = 10.0):
        import threading
        self.mesh_group = mesh_group
        self.interval = interval
        self.on_failure = on_failure or (lambda dead: None)
        self.probe_timeout = probe_timeout
        if recovery is None:
            recovery = fault.RecoveryManager(mesh_group,
                                             probe_timeout=probe_timeout)
        elif recovery.mesh_group is None:
            recovery.mesh_group = mesh_group
        self.recovery = recovery
        self._stop = threading.Event()
        self._thread = None

    @property
    def state(self) -> "fault.MeshHealth":
        return self.recovery.state

    def start(self):
        import threading
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            alive = check_mesh_group_alive(self.mesh_group,
                                           self.probe_timeout)
            if self._stop.is_set():
                return  # stopped during the probe: don't fire callbacks
            dead = [i for i, a in enumerate(alive) if not a]
            now = time.time()
            for i, ok in enumerate(alive):
                if ok:
                    _WATCHDOG_LAST_OK.labels(str(i)).set(now)
                    _WATCHDOG_FAILS.labels(str(i)).set(0)
                else:
                    _WATCHDOG_FAILS.labels(str(i)).inc()
            if dead:
                try:
                    self.on_failure(dead)
                except Exception:  # pylint: disable=broad-except
                    logger.exception("on_failure callback failed")
            try:
                self.recovery.observe(dead)
            except Exception:  # pylint: disable=broad-except
                logger.exception("recovery state machine raised")
            self._stop.wait(self.interval)

    def stop(self):
        """Takes effect immediately: the loop wakes from its wait and no
        further probes or callbacks run."""
        self._stop.set()


def dump_debug_info(executable, dump_dir: str):
    """Dump every IR/plan of a compiled executable
    (ref dump_debug_info, pipeshard_executable.py:357)."""
    os.makedirs(dump_dir, exist_ok=True)

    def write(name, text):
        with open(os.path.join(dump_dir, name), "w",
                  encoding="utf-8") as f:
            f.write(text)

    if hasattr(executable, "get_hlo_text"):
        write("compiled_hlo.txt", executable.get_hlo_text())
    if hasattr(executable, "get_schedule_text"):
        write("schedule.txt", executable.get_schedule_text())
    if hasattr(executable, "get_instruction_text"):
        write("instructions.txt", executable.get_instruction_text())
    if hasattr(executable, "get_resharding_report"):
        write("resharding.txt", executable.get_resharding_report())
    # static plan verifier verdict (ISSUE 8): typing / deadlock /
    # liveness / structure findings plus peak-live-bytes stats
    if hasattr(executable, "get_plan_verdict_text"):
        write("plan_verdict.txt", executable.get_plan_verdict_text())
    # explicit-state model checker (ISSUE 13): interleaving coverage,
    # channel-semantics verdicts, retry-site classification
    if hasattr(executable, "get_model_check_text"):
        write("model_check.txt", executable.get_model_check_text())
    # numerics certification (ISSUE 14): per-output composed error
    # bounds, lossy-hop enumeration, budget verdicts
    if hasattr(executable, "get_numerics_text"):
        write("numerics.txt", executable.get_numerics_text())
    # translation validation (ISSUE 15): per-output proof statuses,
    # axioms used, term-diff witnesses on mismatch
    if hasattr(executable, "get_equiv_text"):
        write("equiv.txt", executable.get_equiv_text())
    # certified superoptimization (ISSUE 17): rewrite decision, before/
    # after simulated critical path + peak bytes, gate rejections
    if hasattr(executable, "get_superopt_text"):
        write("superopt.txt", executable.get_superopt_text())
    # post-step perf analysis (ISSUE 9): critical path, bubbles, MFU
    if hasattr(executable, "get_perf_report_text"):
        write("perf_report.txt", executable.get_perf_report_text())
    # per-edge collective strategy decisions (ISSUE 7); also printable
    # standalone via `scripts/reshard_tool.py plan`
    from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
        format_resharding_plan)
    write("resharding_plan.txt", format_resharding_plan())
    # measured-cost calibration store + model drift (ISSUE 12); also
    # printable standalone via `scripts/perf_tool.py drift`
    from alpa_tpu.telemetry.calibration import format_calibration_report
    write("calibration.txt", format_calibration_report())
    write("compile_cache.txt", format_compile_cache_report())
    write("checkpoint.txt", format_checkpoint_report())
    write("overlap.txt", format_overlap_report())
    write("metrics.txt", _tmetrics.get_registry().to_prometheus_text())
    if _ttrace.enabled():
        rec = _ttrace.get_recorder()
        if rec.n_events:
            rec.save(os.path.join(dump_dir, "trace.json"))
    # flight recorder ring (ISSUE 6): the last N instruction events —
    # the post-mortem timeline `scripts/trace_tool.py flight` reads
    from alpa_tpu.telemetry import flight as _flight
    if _flight.enabled():
        frec = _flight.get_recorder()
        if frec.n_events:
            frec.dump(os.path.join(dump_dir, "flight.json"),
                      reason="dump_debug_info")
    logger.info("debug info dumped to %s", dump_dir)


def get_compile_cache_stats() -> dict:
    """Hit/miss/solve-time counters of the persistent compile cache
    (ISSUE 2), per namespace (``ilp`` / ``stage_dp`` / ``parallel_plan``).
    See alpa_tpu/compile_cache.py."""
    from alpa_tpu.compile_cache import get_compile_cache
    return get_compile_cache().stats()


def get_checkpoint_stats() -> dict:
    """Process-global checkpoint counters (ISSUE 3): save/restore
    latency and byte totals, chunk dedupe, verify failures, hot-swap
    staging.  See alpa_tpu/checkpoint/metrics.py."""
    from alpa_tpu.checkpoint import metrics
    return metrics.snapshot()


def format_checkpoint_report() -> str:
    """Human-readable checkpoint counter report (scripts/ckpt_tool.py
    ``stat`` and debug dumps)."""
    stats = get_checkpoint_stats()
    if not stats:
        return "checkpoint: (no traffic yet)"
    lines = ["checkpoint counters:"]
    for key in sorted(stats):
        v = stats[key]
        val = f"{v:.4f}" if v != int(v) else str(int(v))
        lines.append(f"  {key:<24} {val}")
    return "\n".join(lines)


def get_overlap_stats() -> dict:
    """Process-global overlap-dispatch counters (ISSUE 4): per-step
    transfer pool busy/blocked time, hoisted-launch counts, the last
    step's overlap fraction, plus the resharding planner's link-load
    aggregates (total / broadcast / max-link bytes over all plans)."""
    from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
        get_planner_stats)
    from alpa_tpu.pipeline_parallel.runtime_emitter import (
        get_overlap_runtime_stats)
    stats = {"runtime": get_overlap_runtime_stats(),
             "planner": get_planner_stats()}
    return stats


def format_overlap_report() -> str:
    """Human-readable overlap-dispatch report (debug dumps)."""
    stats = get_overlap_stats()
    rt, pl = stats["runtime"], stats["planner"]
    lines = ["overlap dispatch (runtime):"]
    if rt["steps"] == 0:
        lines.append("  (no overlap-mode steps yet)")
    else:
        busy, blocked = rt["transfer_busy_s"], rt["wait_blocked_s"]
        lines.append(f"  steps={rt['steps']} launches={rt['n_launches']} "
                     f"hoisted={rt['n_hoisted']} "
                     f"window={rt['last_window']}")
        lines.append(f"  transfer_busy={busy:.4f}s "
                     f"wait_blocked={blocked:.4f}s "
                     f"last_overlap_fraction="
                     f"{rt['last_overlap_fraction']:.3f}")
    lines.append("resharding planner (link loads):")
    if pl["plans"] == 0:
        lines.append("  (no plans yet)")
    else:
        lines.append(f"  plans={pl['plans']} "
                     f"total_bytes={pl['total_bytes']:.0f} "
                     f"broadcast_bytes={pl['broadcast_bytes']:.0f}")
        lines.append(f"  max_link_bytes={pl['max_link_bytes']:.0f} "
                     f"(naive {pl['max_link_bytes_naive']:.0f})")
    return "\n".join(lines)


def format_compile_cache_report() -> str:
    """Human-readable one-namespace-per-line cache report (used by
    scripts/cache_tool.py stat and debug dumps)."""
    stats = get_compile_cache_stats()
    lines = [f"compile cache dir: {stats['cache_dir'] or '(memory only)'}",
             f"memory entries: {stats['memory_entries']}"]
    for ns, s in stats["namespaces"].items():
        lines.append(
            f"  {ns:<14} hits={s['hits']} (disk={s['disk_hits']}) "
            f"misses={s['misses']} puts={s['puts']} "
            f"solve={s['solve_seconds']}s saved={s['saved_seconds']}s")
    if not stats["namespaces"]:
        lines.append("  (no cache traffic yet)")
    return "\n".join(lines)
