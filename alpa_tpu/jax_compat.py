"""Additive shims for jax API renames (installed from ``alpa_tpu/__init__``).

The codebase targets the modern spellings (``jax.set_mesh``,
``jax.shard_map``); older jax (0.4.x) ships the same functionality under
different names.  Each shim is installed only when the modern name is
absent, so on current jax this module is a no-op.
"""
import jax


def _set_mesh_compat(mesh):
    # Mesh is itself a context manager on older jax, so returning it makes
    # ``with jax.set_mesh(mesh):`` equivalent to ``with mesh:``
    return mesh


def _shard_map_compat(f, *, mesh, in_specs, out_specs, axis_names=None,
                      check_vma=True):
    from jax.experimental.shard_map import shard_map as _shard_map

    # modern axis_names lists the MANUAL axes; the old API takes the
    # complement (``auto`` = axes left automatic inside the body)
    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    if auto:
        # partial-automatic shard_map on old jax miscompiles (XLA
        # PartitionId errors, hard aborts on CPU) — refuse up front so
        # callers get a diagnosable error instead of a process abort
        raise NotImplementedError(
            f"partial-automatic shard_map (auto axes {sorted(auto)}) "
            "requires a newer jax than this environment provides")
    return _shard_map(f, mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma, auto=auto)


def _get_abstract_mesh_compat():
    # the ambient mesh on older jax is whatever ``with mesh:`` entered
    # (which is what _set_mesh_compat resolves to)
    from jax._src import mesh as mesh_lib
    return mesh_lib.thread_resources.env.physical_mesh


def _axis_size_compat(axis_name):
    # psum of a python scalar is computed statically: the classic
    # pre-jax.lax.axis_size spelling of "size of this mapped axis"
    return jax.lax.psum(1, axis_name)


def install():
    if not hasattr(jax, "set_mesh"):
        jax.set_mesh = _set_mesh_compat
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_compat
    if not hasattr(jax.sharding, "get_abstract_mesh"):
        jax.sharding.get_abstract_mesh = _get_abstract_mesh_compat
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = _axis_size_compat
