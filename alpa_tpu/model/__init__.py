"""Model zoo: flax models used by tests, benchmarks, and serving.

TPU-native analog of ref ``alpa/model/`` (SURVEY.md §2.8): GPT/BERT
transformers, MoE, WideResNet, plus TrainState utilities.  Models are
written mesh-agnostic: parallelization comes entirely from
``@alpa_tpu.parallelize``; optional ``mark_pipeline_boundary`` calls and a
pluggable attention implementation (jnp reference / pallas flash / ring)
are the only parallelism-aware hooks.
"""
