"""Train state and optimizer utilities.

Analog of ref ``alpa/model/model_util.py`` (TrainState, optimizers incl.
dynamic loss scale).  Built on flax/optax; the dynamic-scale logic follows
the standard flax DynamicScale pattern re-expressed so the scale update is
part of the train step (jit-compatible, no host sync).
"""
from typing import Any, Callable, Optional

import flax
import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax import struct
from flax.training import train_state


class TrainState(train_state.TrainState):
    """TrainState with optional dynamic loss scaling state and master-copy
    support (ref model_util.py TrainState)."""
    dynamic_scale: Optional[Any] = None

    @classmethod
    def create_with_scale(cls, *, apply_fn, params, tx, use_dynamic_scale=False,
                          **kwargs):
        ds = DynamicScaleState.create() if use_dynamic_scale else None
        return cls.create(apply_fn=apply_fn, params=params, tx=tx,
                          dynamic_scale=ds, **kwargs)


class DynamicScaleState(struct.PyTreeNode):
    """Loss-scale state for mixed-precision training."""
    scale: jnp.ndarray
    growth_interval: int = struct.field(pytree_node=False, default=2000)
    growth_factor: float = struct.field(pytree_node=False, default=2.0)
    backoff_factor: float = struct.field(pytree_node=False, default=0.5)
    fine_count: jnp.ndarray = None

    @classmethod
    def create(cls, init_scale: float = 2.0**15):
        return cls(scale=jnp.float32(init_scale),
                   fine_count=jnp.zeros((), jnp.int32))

    def update(self, grads_finite: jnp.ndarray) -> "DynamicScaleState":
        grow = (self.fine_count + 1) >= self.growth_interval
        new_scale = jnp.where(
            grads_finite,
            jnp.where(grow, self.scale * self.growth_factor, self.scale),
            jnp.maximum(self.scale * self.backoff_factor, 1.0))
        new_count = jnp.where(grads_finite & ~grow, self.fine_count + 1,
                              jnp.zeros((), jnp.int32))
        return self.replace(scale=new_scale, fine_count=new_count)


def all_finite(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.bool_(True)
    return jnp.all(
        jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]))


def create_adamw(learning_rate=1e-3, weight_decay=0.01, b1=0.9, b2=0.999,
                 grad_clip: Optional[float] = 1.0):
    chain = []
    if grad_clip:
        chain.append(optax.clip_by_global_norm(grad_clip))
    chain.append(optax.adamw(learning_rate, b1=b1, b2=b2,
                             weight_decay=weight_decay))
    return optax.chain(*chain)


def gpt_lm_loss(apply_fn, params, batch, chunked=False):
    """LM loss for a GPT-family model with tied embeddings: dense fp32
    CE, or the fused/chunked lm-head + CE that never materializes the
    full logits tensor (shared by bench.py and scripts/bench_sweep.py so
    the measured loss formulation cannot drift between them)."""
    if chunked:
        hidden = apply_fn(params, batch["input_ids"], return_hidden=True)
        emb = params["params"]["wte"]["embedding"]
        return chunked_cross_entropy_loss(hidden, emb, batch["labels"])
    logits = apply_fn(params, batch["input_ids"])
    return cross_entropy_loss(logits.astype(jnp.float32), batch["labels"])


def cross_entropy_loss(logits, labels, label_mask=None, vocab_size=None):
    """Mean token cross-entropy with optional mask."""
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    if label_mask is not None:
        return (loss * label_mask).sum() / jnp.maximum(label_mask.sum(), 1)
    return loss.mean()


def chunked_cross_entropy_loss(hidden, embedding, labels, chunk_size=512):
    """Fused lm-head + mean cross-entropy without materializing the full
    logits tensor.

    ``hidden``: (B, S, H) final hidden states; ``embedding``: (V, H) tied
    lm-head weights; ``labels``: (B, S) int.  Token rows are processed in
    ``chunk_size`` chunks under ``jax.checkpoint``: the lm-head matmul
    runs in the embedding's dtype (bf16 on the MXU path, matching the
    unchunked ``tok_emb.attend``), only lse/loss math is fp32.  Peak
    logits memory is O(chunk * V) instead of O(B * S * V) — for GPT's
    51200 vocab at bs8/seq1024, ~50 MB bf16 per chunk vs a 1.6 GB fp32
    buffer (+ its saved backward residuals).
    """
    b, s, h = hidden.shape
    x = hidden.reshape(-1, h).astype(embedding.dtype)
    y = labels.reshape(-1)
    n = x.shape[0]
    n_chunks = max(1, -(-n // chunk_size))
    pad = n_chunks * chunk_size - n
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, h), x.dtype)])
        y = jnp.concatenate([y, jnp.zeros((pad,), y.dtype)])
    x = x.reshape(n_chunks, chunk_size, h)
    y = y.reshape(n_chunks, chunk_size)

    @jax.checkpoint
    def one_chunk(args):
        xc, yc = args
        logits = (xc @ embedding.T).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, yc[:, None], axis=-1)[:, 0]
        return lse - gold

    losses = jax.lax.map(one_chunk, (x, y)).reshape(-1)
    if pad:
        mask = jnp.arange(losses.shape[0]) < n
        return (losses * mask).sum() / n
    return losses.mean()
