"""Train state and optimizer utilities.

Analog of ref ``alpa/model/model_util.py`` (TrainState, optimizers incl.
dynamic loss scale).  Built on flax/optax; the dynamic-scale logic follows
the standard flax DynamicScale pattern re-expressed so the scale update is
part of the train step (jit-compatible, no host sync).
"""
from typing import Any, Callable, Optional

import flax
import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax import struct
from flax.training import train_state


class TrainState(train_state.TrainState):
    """TrainState with optional dynamic loss scaling state and master-copy
    support (ref model_util.py TrainState)."""
    dynamic_scale: Optional[Any] = None

    @classmethod
    def create_with_scale(cls, *, apply_fn, params, tx, use_dynamic_scale=False,
                          **kwargs):
        ds = DynamicScaleState.create() if use_dynamic_scale else None
        return cls.create(apply_fn=apply_fn, params=params, tx=tx,
                          dynamic_scale=ds, **kwargs)


class DynamicScaleState(struct.PyTreeNode):
    """Loss-scale state for mixed-precision training."""
    scale: jnp.ndarray
    growth_interval: int = struct.field(pytree_node=False, default=2000)
    growth_factor: float = struct.field(pytree_node=False, default=2.0)
    backoff_factor: float = struct.field(pytree_node=False, default=0.5)
    fine_count: jnp.ndarray = None

    @classmethod
    def create(cls, init_scale: float = 2.0**15):
        return cls(scale=jnp.float32(init_scale),
                   fine_count=jnp.zeros((), jnp.int32))

    def update(self, grads_finite: jnp.ndarray) -> "DynamicScaleState":
        grow = (self.fine_count + 1) >= self.growth_interval
        new_scale = jnp.where(
            grads_finite,
            jnp.where(grow, self.scale * self.growth_factor, self.scale),
            jnp.maximum(self.scale * self.backoff_factor, 1.0))
        new_count = jnp.where(grads_finite & ~grow, self.fine_count + 1,
                              jnp.zeros((), jnp.int32))
        return self.replace(scale=new_scale, fine_count=new_count)


def all_finite(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.bool_(True)
    return jnp.all(
        jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]))


def create_adamw(learning_rate=1e-3, weight_decay=0.01, b1=0.9, b2=0.999,
                 grad_clip: Optional[float] = 1.0):
    chain = []
    if grad_clip:
        chain.append(optax.clip_by_global_norm(grad_clip))
    chain.append(optax.adamw(learning_rate, b1=b1, b2=b2,
                             weight_decay=weight_decay))
    return optax.chain(*chain)


def cross_entropy_loss(logits, labels, label_mask=None, vocab_size=None):
    """Mean token cross-entropy with optional mask."""
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    if label_mask is not None:
        return (loss * label_mask).sum() / jnp.maximum(label_mask.sum(), 1)
    return loss.mean()
