"""BERT-style bidirectional encoder with MLM / NSP / classification heads.

Analog of ref ``alpa/model/bert_model.py`` (884 LoC flax BERT incl.
``FlaxBertForPreTrainingModule``).  Reuses the shared transformer blocks
(gpt_model) with ``causal=False`` — the reference inverts this
relationship (its GPT wraps BERT with a causal mask, ref gpt_model.py:151);
either way one block implementation serves both.

Coverage vs the reference heads:

* ``BertModel`` — trunk: word/position/segment embeddings + encoder +
  pooler (ref FlaxBertModule:557), with attention-mask support
  (padding masks threaded as an additive fp32 score bias).
* ``BertForPreTraining`` — MLM + NSP heads over one trunk, decoder
  optionally tied to the word-embedding table
  (ref FlaxBertForPreTrainingModule:609, FlaxBertPreTrainingHeads:541,
  tied decoder FlaxBertLMPredictionHead:486).
* ``BertForMaskedLM`` (ref :665), ``BertForSequenceClassification``
  (ref :718).
* ``bert_pretraining_loss`` — masked-LM + NSP loss with label weights.
"""
import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from alpa_tpu.model.gpt_model import GPTConfig, TransformerBlock


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    seq_len: int = 512
    type_vocab_size: int = 2
    mlp_ratio: int = 4
    dtype: Any = jnp.float32
    layer_norm_eps: float = 1e-12   # HF BERT default
    tie_word_embeddings: bool = True

    def gpt(self) -> GPTConfig:
        return GPTConfig(vocab_size=self.vocab_size,
                         hidden_size=self.hidden_size,
                         num_layers=self.num_layers,
                         num_heads=self.num_heads,
                         seq_len=self.seq_len,
                         mlp_ratio=self.mlp_ratio,
                         dtype=self.dtype,
                         layer_norm_eps=self.layer_norm_eps,
                         causal=False)


def attention_mask_to_bias(attention_mask) -> jnp.ndarray:
    """(B, S) 1/0 padding mask -> (B, 1, 1, S) additive fp32 score bias
    (ref FlaxBertSelfAttention mask handling, bert_model.py:142)."""
    bias = jnp.where(attention_mask > 0, 0.0, -1e9)
    return bias[:, None, None, :].astype(jnp.float32)


class BertModel(nn.Module):
    """Encoder trunk: token + position + segment embeddings, N blocks,
    optional tanh pooler over [CLS] (ref FlaxBertModule:557)."""
    config: BertConfig
    add_pooling_layer: bool = True

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        cfg = self.config
        gcfg = cfg.gpt()
        b, s = input_ids.shape
        pos = jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        tok_emb = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                           name="word_embeddings")
        x = tok_emb(input_ids)
        x = x + nn.Embed(cfg.seq_len, cfg.hidden_size, dtype=cfg.dtype,
                         name="position_embeddings")(pos)
        x = x + nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                         dtype=cfg.dtype,
                         name="token_type_embeddings")(token_type_ids)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="embeddings_ln")(x).astype(cfg.dtype)
        bias = (attention_mask_to_bias(attention_mask)
                if attention_mask is not None else None)
        for i in range(cfg.num_layers):
            x, _ = TransformerBlock(gcfg, name=f"layer_{i}")(
                x, None, True, bias)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="final_ln")(x).astype(cfg.dtype)
        pooled = None
        if self.add_pooling_layer:
            pooled = nn.tanh(
                nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                         name="pooler")(x[:, 0]))
        return x, pooled, tok_emb


class BertLMPredictionHead(nn.Module):
    """transform -> gelu -> LN -> decoder(+bias); decoder weights tied to
    the word-embedding table when configured
    (ref FlaxBertLMPredictionHead:486)."""
    config: BertConfig

    @nn.compact
    def __call__(self, hidden, tok_emb):
        cfg = self.config
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="transform")(
            hidden)
        x = nn.gelu(x, approximate=True)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="transform_ln")(x).astype(cfg.dtype)
        if cfg.tie_word_embeddings and tok_emb is not None:
            logits = tok_emb.attend(x)
        else:
            logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype,
                              use_bias=False, name="decoder")(x)
        bias = self.param("decoder_bias", nn.initializers.zeros,
                          (cfg.vocab_size,), cfg.dtype)
        return logits + bias


class BertForPreTraining(nn.Module):
    """MLM + NSP pretraining heads over one trunk
    (ref FlaxBertForPreTrainingModule:609)."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        cfg = self.config
        x, pooled, tok_emb = BertModel(cfg, add_pooling_layer=True,
                                       name="bert")(input_ids,
                                                    attention_mask,
                                                    token_type_ids)
        mlm_logits = BertLMPredictionHead(cfg, name="mlm_head")(x, tok_emb)
        nsp_logits = nn.Dense(2, dtype=cfg.dtype,
                              name="nsp_head")(pooled)
        return mlm_logits, nsp_logits


class BertForMaskedLM(nn.Module):
    """MLM head over the trunk (ref FlaxBertForMaskedLMModule:665)."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        cfg = self.config
        x, _, tok_emb = BertModel(cfg, add_pooling_layer=False,
                                  name="bert")(input_ids, attention_mask,
                                               token_type_ids)
        return BertLMPredictionHead(cfg, name="mlm_head")(x, tok_emb)


class BertForSequenceClassification(nn.Module):
    """(ref FlaxBertForSequenceClassificationModule:718)"""
    config: BertConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        _, pooled, _ = BertModel(self.config, name="bert")(
            input_ids, attention_mask, token_type_ids)
        return nn.Dense(self.num_labels, dtype=self.config.dtype,
                        name="classifier")(pooled)


def bert_pretraining_loss(mlm_logits, nsp_logits, mlm_labels,
                          mlm_weights, nsp_labels):
    """Masked-LM (weighted over masked positions) + NSP cross-entropy,
    fp32 accumulation (the loss the reference's pretraining benchmark
    computes around FlaxBertForPreTrainingModule)."""
    logp = jax.nn.log_softmax(mlm_logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, mlm_labels[..., None], axis=-1)[..., 0]
    w = mlm_weights.astype(jnp.float32)
    mlm_loss = -(ll * w).sum() / jnp.maximum(w.sum(), 1.0)
    nsp_logp = jax.nn.log_softmax(nsp_logits.astype(jnp.float32), axis=-1)
    nsp_ll = jnp.take_along_axis(nsp_logp, nsp_labels[:, None],
                                 axis=-1)[:, 0]
    return mlm_loss - nsp_ll.mean()
