"""BERT-style bidirectional encoder with MLM / classification heads.

Analog of ref ``alpa/model/bert_model.py`` (884 LoC flax BERT).  Reuses the
shared transformer blocks (gpt_model) with ``causal=False`` — the reference
inverts this relationship (its GPT wraps BERT with a causal mask,
ref gpt_model.py:151); either way one block implementation serves both.
"""
import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from alpa_tpu.model.gpt_model import GPTConfig, TransformerBlock


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    seq_len: int = 512
    type_vocab_size: int = 2
    mlp_ratio: int = 4
    dtype: Any = jnp.float32

    def gpt(self) -> GPTConfig:
        return GPTConfig(vocab_size=self.vocab_size,
                         hidden_size=self.hidden_size,
                         num_layers=self.num_layers,
                         num_heads=self.num_heads,
                         seq_len=self.seq_len,
                         mlp_ratio=self.mlp_ratio,
                         dtype=self.dtype,
                         causal=False)


class BertModel(nn.Module):
    """Encoder trunk: token + position + segment embeddings, N blocks."""
    config: BertConfig
    add_pooling_layer: bool = True

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None):
        cfg = self.config
        gcfg = cfg.gpt()
        b, s = input_ids.shape
        pos = jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     name="word_embeddings")(input_ids)
        x = x + nn.Embed(cfg.seq_len, cfg.hidden_size, dtype=cfg.dtype,
                         name="position_embeddings")(pos)
        x = x + nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                         dtype=cfg.dtype,
                         name="token_type_embeddings")(token_type_ids)
        x = nn.LayerNorm(dtype=jnp.float32, name="embeddings_ln")(x)
        for i in range(cfg.num_layers):
            x, _ = TransformerBlock(gcfg, name=f"layer_{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="final_ln")(x)
        pooled = None
        if self.add_pooling_layer:
            pooled = nn.tanh(
                nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                         name="pooler")(x[:, 0]))
        return x, pooled


class BertForMaskedLM(nn.Module):
    """MLM head over the trunk (ref FlaxBertForMaskedLMModule)."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None):
        cfg = self.config
        x, _ = BertModel(cfg, add_pooling_layer=False,
                         name="bert")(input_ids, token_type_ids)
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="transform")(x)
        x = nn.gelu(x, approximate=True)
        x = nn.LayerNorm(dtype=jnp.float32, name="transform_ln")(x)
        logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype,
                          name="decoder")(x)
        return logits


class BertForSequenceClassification(nn.Module):
    config: BertConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None):
        _, pooled = BertModel(self.config, name="bert")(input_ids,
                                                        token_type_ids)
        return nn.Dense(self.num_labels, dtype=self.config.dtype,
                        name="classifier")(pooled)
