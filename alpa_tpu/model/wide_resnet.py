"""Wide-ResNet for the vision benchmark suite.

Analog of ref ``alpa/model/wide_resnet.py`` (176 LoC): the W-ResNet family
benchmarked in ref ``benchmark/alpa/suite_wresnet.py``.  Convolutions are
the 2D-sharding workload exercising the planner's conv strategies (spatial
vs channel vs batch sharding).
"""
import dataclasses
from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class WResNetConfig:
    num_layers: int = 50
    width_factor: int = 2
    num_classes: int = 1000
    dtype: Any = jnp.float32


_BLOCKS = {
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.GroupNorm, num_groups=32, dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = norm(name="norm1")(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), (self.strides, self.strides),
                 name="conv2")(y)
        y = norm(name="norm2")(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = norm(name="norm3")(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            (self.strides, self.strides),
                            name="proj")(residual)
            residual = norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class WideResNet(nn.Module):
    config: WResNetConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        blocks = _BLOCKS[cfg.num_layers]
        w = cfg.width_factor
        x = nn.Conv(64 * w, (7, 7), (2, 2), use_bias=False,
                    dtype=cfg.dtype, name="conv_init")(x)
        x = nn.GroupNorm(num_groups=32, dtype=jnp.float32,
                         name="norm_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n in enumerate(blocks):
            for j in range(n):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(64 * w * (2**i), strides, cfg.dtype,
                                    name=f"block_{i}_{j}")(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(cfg.num_classes, dtype=cfg.dtype, name="head")(x)
