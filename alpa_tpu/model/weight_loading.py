"""Load HuggingFace transformer weights into alpa_tpu models.

Analog of ref ``examples/llm_serving/model/opt_model.py:865``
(``load_opt_params_worker_func`` — distributed weight loading into sharded
buffers): a HF GPT-2-family state dict converts into our ``GPTModel``
params, optionally placed directly with target shardings so large models
materialize distributed (each host/device writes only its shard via
``jax.device_put``'s addressable-shard semantics).
"""
import logging
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from alpa_tpu.model.gpt_model import GPTConfig

logger = logging.getLogger(__name__)


def _np(t):
    if hasattr(t, "detach"):
        return t.detach().cpu().numpy()
    return np.asarray(t)


def convert_gpt2_state_dict(state_dict: Dict[str, Any],
                            config: GPTConfig) -> Dict:
    """HF GPT-2 state dict -> alpa_tpu GPTModel params.

    HF GPT-2 uses Conv1D layers whose weights are already (in, out), so
    they map directly onto flax Dense kernels.
    """
    sd = {k: _np(v) for k, v in state_dict.items()}

    def get(key):
        out = sd.get(key, sd.get("transformer." + key))
        if out is None:
            raise KeyError(
                f"state dict has neither {key!r} nor "
                f"{'transformer.' + key!r} — not a GPT-2-family checkpoint?")
        return out

    params = {
        "wte": {"embedding": get("wte.weight")},
        "wpe": {"embedding": get("wpe.weight")[:config.seq_len]},
        "ln_f": {"scale": get("ln_f.weight"), "bias": get("ln_f.bias")},
    }
    for i in range(config.num_layers):
        p = f"h.{i}."
        params[f"h{i}"] = {
            "ln1": {"scale": get(p + "ln_1.weight"),
                    "bias": get(p + "ln_1.bias")},
            "ln2": {"scale": get(p + "ln_2.weight"),
                    "bias": get(p + "ln_2.bias")},
            "attn": {
                "qkv": {"kernel": get(p + "attn.c_attn.weight"),
                        "bias": get(p + "attn.c_attn.bias")},
                "out": {"kernel": get(p + "attn.c_proj.weight"),
                        "bias": get(p + "attn.c_proj.bias")},
            },
            "mlp": {
                "fc_in": {"kernel": get(p + "mlp.c_fc.weight"),
                          "bias": get(p + "mlp.c_fc.bias")},
                "fc_out": {"kernel": get(p + "mlp.c_proj.weight"),
                           "bias": get(p + "mlp.c_proj.bias")},
            },
        }
    return {"params": params}


def config_from_hf_gpt2(hf_config) -> GPTConfig:
    return GPTConfig(vocab_size=hf_config.vocab_size,
                     hidden_size=hf_config.n_embd,
                     num_layers=hf_config.n_layer,
                     num_heads=hf_config.n_head,
                     seq_len=hf_config.n_positions,
                     tie_embeddings=True)


def convert_opt_state_dict(state_dict: Dict[str, Any],
                           config: GPTConfig) -> Dict:
    """HF OPTForCausalLM state dict -> GPTModel params (ref
    examples/llm_serving/model/opt_model.py:865 weight mapping).

    OPT uses separate q/k/v nn.Linear layers with (out, in) weights —
    transposed and fused into our (in, 3*out) qkv kernel — a ReLU MLP,
    and a learned positional table whose first ``pos_offset``(=2) rows
    are reserved.
    """
    sd = {k: _np(v) for k, v in state_dict.items()}

    def get(key):
        out = sd.get("model.decoder." + key, sd.get("decoder." + key))
        if out is None:
            raise KeyError(
                f"state dict missing decoder key {key!r} — not an "
                "OPT-family checkpoint?")
        return out

    def lin(prefix):
        return {"kernel": get(prefix + ".weight").T,
                "bias": get(prefix + ".bias")}

    params = {
        "wte": {"embedding": get("embed_tokens.weight")},
        "wpe": {"embedding":
                get("embed_positions.weight")
                [:config.seq_len + config.pos_offset]},
        "ln_f": {"scale": get("final_layer_norm.weight"),
                 "bias": get("final_layer_norm.bias")},
    }
    for i in range(config.num_layers):
        p = f"layers.{i}."
        qkv_kernel = np.concatenate(
            [get(p + f"self_attn.{x}_proj.weight").T for x in "qkv"],
            axis=1)
        qkv_bias = np.concatenate(
            [get(p + f"self_attn.{x}_proj.bias") for x in "qkv"])
        params[f"h{i}"] = {
            "ln1": {"scale": get(p + "self_attn_layer_norm.weight"),
                    "bias": get(p + "self_attn_layer_norm.bias")},
            "ln2": {"scale": get(p + "final_layer_norm.weight"),
                    "bias": get(p + "final_layer_norm.bias")},
            "attn": {
                "qkv": {"kernel": qkv_kernel, "bias": qkv_bias},
                "out": lin(p + "self_attn.out_proj"),
            },
            "mlp": {
                "fc_in": lin(p + "fc1"),
                "fc_out": lin(p + "fc2"),
            },
        }
    return {"params": params}


def config_from_hf_opt(hf_config) -> GPTConfig:
    assert getattr(hf_config, "do_layer_norm_before", True), (
        "OPT-350m's post-norm layout is not supported; use a pre-norm "
        "OPT size (125m, 1.3b, 2.7b, ...)")
    assert hf_config.ffn_dim % hf_config.hidden_size == 0
    return GPTConfig(vocab_size=hf_config.vocab_size,
                     hidden_size=hf_config.hidden_size,
                     num_layers=hf_config.num_hidden_layers,
                     num_heads=hf_config.num_attention_heads,
                     seq_len=hf_config.max_position_embeddings,
                     mlp_ratio=hf_config.ffn_dim // hf_config.hidden_size,
                     activation=hf_config.activation_function,
                     pos_offset=2,
                     tie_embeddings=True)


def load_opt(model_name_or_model,
             dtype=jnp.float32,
             shardings: Optional[Any] = None):
    """Build (GPTModel, params, config) from a HF OPT model or name
    (ref opt_model.py:865,956 — ``shardings`` places each leaf directly
    with its target sharding, the distributed-loading path)."""
    from alpa_tpu.model.gpt_model import GPTModel

    if isinstance(model_name_or_model, str):
        from transformers import OPTForCausalLM
        hf_model = OPTForCausalLM.from_pretrained(model_name_or_model)
    else:
        hf_model = model_name_or_model
    config = config_from_hf_opt(hf_model.config)
    params = convert_opt_state_dict(hf_model.state_dict(), config)
    params = _place(params, dtype, shardings)
    return GPTModel(config), params, config


def _leaf_name(path) -> str:
    """Tree-path -> file name, the single convention shared by save /
    load / synthesize so they can never drift."""
    return jax.tree_util.keystr(path).replace("'", "").replace("[", "") \
        .replace("]", ".").strip(".")


def save_params_dir(params, path: str):
    """Write a params pytree as one .npy file per leaf (ref the
    numpy-per-parameter layout load_opt_params_worker_func consumes,
    opt_model.py:865).  Leaf files are named by their tree path."""
    import os

    os.makedirs(path, exist_ok=True)
    flat = jax.tree_util.tree_leaves_with_path(params)
    index = []
    for p, leaf in flat:
        name = _leaf_name(p)
        np.save(os.path.join(path, name + ".npy"), np.asarray(leaf))
        index.append(name)
    with open(os.path.join(path, "index.txt"), "w",
              encoding="utf-8") as f:
        f.write("\n".join(index))


def synthesize_params_dir(params_aval, path: str, std: float = 0.02):
    """Generate a ``save_params_dir`` checkpoint from ABSTRACT shapes,
    one leaf at a time — the multi-billion-parameter drill path: no two
    leaves ever coexist in memory, so a 10B+ checkpoint synthesizes in
    O(largest leaf) host RAM.  Values are deterministic per leaf name
    (layer-norm scales 1, biases 0, weights N(0, std)) so independent
    readers reproduce the same model."""
    import os
    import zlib

    os.makedirs(path, exist_ok=True)
    flat = jax.tree_util.tree_leaves_with_path(params_aval)
    index = []
    for p, leaf in flat:
        name = _leaf_name(p)
        shape = tuple(leaf.shape)
        fpath = os.path.join(path, name + ".npy")
        index.append(name)
        if os.path.exists(fpath):
            try:  # resumable: a completed leaf (shape verifies) is kept
                if np.load(fpath, mmap_mode="r").shape == shape:
                    continue
            except Exception:  # pylint: disable=broad-except
                pass
        if name.endswith("scale"):
            arr = np.ones(shape, np.float32)
        elif name.endswith("bias"):
            arr = np.zeros(shape, np.float32)
        else:
            rs = np.random.RandomState(zlib.crc32(name.encode())
                                       & 0x7fffffff)
            arr = (rs.standard_normal(size=shape) * std).astype(np.float32)
        np.save(fpath, arr)
        del arr
    with open(os.path.join(path, "index.txt"), "w",
              encoding="utf-8") as f:
        f.write("\n".join(index))


def load_params_dir(path: str, shardings, dtype=None):
    """Load a ``save_params_dir`` layout straight into sharded arrays.

    The 175B-class path (ref load_params_dis_array, opt_model.py:956):
    each leaf file is memory-mapped and ``jax.make_array_from_callback``
    reads ONLY the slices this process's addressable shards need — no
    full parameter (let alone the full model) ever materializes in host
    memory.  ``shardings``: pytree of NamedShardings congruent with the
    saved params (None leaves = fully replicated on the first device set).
    """
    import os

    flat_shardings = jax.tree_util.tree_leaves_with_path(
        shardings, is_leaf=lambda t: t is None)
    leaves = {}
    for p, sh in flat_shardings:
        name = _leaf_name(p)
        mm = np.load(os.path.join(path, name + ".npy"), mmap_mode="r")
        if dtype is not None and mm.dtype != np.dtype(dtype):
            # dtype conversion forfeits slice-laziness for this leaf
            mm = np.asarray(mm, dtype)
        if sh is None:
            leaves[name] = jnp.asarray(mm)
        else:
            leaves[name] = jax.make_array_from_callback(
                mm.shape, sh, lambda idx, mm=mm: np.asarray(mm[idx]))
    # rebuild the tree in the shardings' structure
    treedef = jax.tree_util.tree_structure(
        shardings, is_leaf=lambda t: t is None)
    ordered = [leaves[_leaf_name(p)] for p, _ in flat_shardings]
    return jax.tree_util.tree_unflatten(treedef, ordered)


def _place(params, dtype, shardings):
    if shardings is not None:
        # leaves stay numpy until device_put with the TARGET sharding —
        # no full per-device replica ever materializes.  is_leaf lets
        # None entries in the shardings tree mean "replicate this leaf".
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(np.asarray(x, dtype), s)
            if s is not None else jnp.asarray(x, dtype),
            params, shardings,
            is_leaf=lambda t: t is None)
    return jax.tree_util.tree_map(lambda x: jnp.asarray(x, dtype), params)


def load_gpt2(model_name_or_model,
              dtype=jnp.float32,
              shardings: Optional[Any] = None):
    """Build (GPTModel, params, config) from a HF GPT-2 model or name.

    ``shardings``: optional params-pytree of NamedShardings — each leaf is
    device_put directly with its target sharding (the distributed-loading
    path: no full replica ever materializes per device).
    """
    from alpa_tpu.model.gpt_model import GPTModel

    if isinstance(model_name_or_model, str):
        from transformers import GPT2LMHeadModel
        hf_model = GPT2LMHeadModel.from_pretrained(model_name_or_model)
    else:
        hf_model = model_name_or_model
    config = config_from_hf_gpt2(hf_model.config)
    params = convert_gpt2_state_dict(hf_model.state_dict(), config)
    params = _place(params, dtype, shardings)
    return GPTModel(config), params, config
