"""CodeGen (Salesforce) decoder-only LM (flax), TPU-first.

Clean-room analog of ref ``examples/llm_serving/model/codegen_model.py``
(the reference's HF-port for program-synthesis serving).  Architectural
deltas vs GPT:

* rotary position embeddings (GPT-J style rotate-every-two) on the first
  ``rotary_dim`` dims of every head — no learned position table,
* PARALLEL attention + MLP residual off one shared LayerNorm
  (``x + attn(ln(x)) + mlp(ln(x))``),
* bias-free qkv/out projections; untied lm_head with bias.

The HF checkpoint's mp_num-interleaved qkv layout is normalized to plain
head-major [q;k;v] in ``params_from_hf`` so the model itself stays a
straight einsum pipeline (clean mesh targets for the sharding planner).
KV caches follow the gpt_model cache-as-invars convention (scalar or
per-row vector indices) so the serving stack works unchanged.
"""
import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from alpa_tpu.model.gpt_model import reference_attention, update_kv_cache
from alpa_tpu.pipeline_parallel.primitive_def import mark_pipeline_boundary


@dataclasses.dataclass(frozen=True)
class CodeGenConfig:
    vocab_size: int = 50400
    hidden_size: int = 1024
    num_layers: int = 20
    num_heads: int = 16
    seq_len: int = 2048
    rotary_dim: int = 32
    mlp_ratio: int = 4
    dtype: Any = jnp.float32
    layer_norm_eps: float = 1e-5
    pipeline_boundary_every: int = 0

    def __post_init__(self):
        hd = self.hidden_size // self.num_heads
        if self.rotary_dim > hd:
            raise ValueError(
                f"rotary_dim ({self.rotary_dim}) cannot exceed the head "
                f"dim ({hd} = hidden_size {self.hidden_size} / num_heads "
                f"{self.num_heads})")
        if self.rotary_dim % 2 != 0:
            raise ValueError(
                f"rotary_dim ({self.rotary_dim}) must be even: rotary "
                "rotates (2i, 2i+1) dimension pairs")


# name -> (hidden, layers, heads, rotary_dim); ref Salesforce/codegen-*
codegen_specs = {
    "350m": (1024, 20, 16, 32),
    "2b": (2560, 32, 32, 64),
    "6b": (4096, 33, 16, 64),
    "16b": (6144, 34, 24, 64),
}


def config_from_codegen_spec(name: str, **kwargs) -> CodeGenConfig:
    key = name.lower().replace("codegen-", "").split("-")[0]
    hidden, layers, heads, rot = codegen_specs[key]
    return CodeGenConfig(hidden_size=hidden, num_layers=layers,
                         num_heads=heads, rotary_dim=rot, **kwargs)


def apply_rotary(x, offset, rotary_dim: int):
    """GPT-J-style rotate-every-two rotary embedding on the first
    ``rotary_dim`` dims of each head.  x: (B, S, H, D).  ``offset`` is
    the absolute position of x's FIRST token: a scalar (uniform), (B,)
    per-row offsets, or an explicit (B, S) position matrix — token t in
    row b always rotates at offset[b] + t."""
    b, s = x.shape[0], x.shape[1]
    pos = jnp.asarray(offset, jnp.int32)
    if pos.ndim == 0:
        pos = pos + jnp.broadcast_to(jnp.arange(s), (b, s))
    elif pos.ndim == 1:  # (B,) per-row offsets, S tokens each
        pos = pos[:, None] + jnp.arange(s)[None, :]
    inv_freq = 1.0 / (10000.0 ** (np.arange(0, rotary_dim, 2) / rotary_dim))
    ang = pos[..., None].astype(jnp.float32) * inv_freq[None, None, :]
    sin, cos = jnp.sin(ang), jnp.cos(ang)          # (B, S, rot/2)
    rot, rest = x[..., :rotary_dim], x[..., rotary_dim:]
    x1, x2 = rot[..., 0::2], rot[..., 1::2]        # pairs (2i, 2i+1)
    sin = sin[:, :, None, :]
    cos = cos[:, :, None, :]
    r1 = x1 * cos - x2 * sin
    r2 = x2 * cos + x1 * sin
    rot = jnp.stack([r1, r2], axis=-1).reshape(rot.shape)
    return jnp.concatenate([rot, rest], axis=-1).astype(x.dtype)


class CodeGenAttention(nn.Module):
    config: CodeGenConfig

    @nn.compact
    def __call__(self, x, kv_cache=None):
        cfg = self.config
        h, nh = cfg.hidden_size, cfg.num_heads
        hd = h // nh
        qkv = nn.Dense(3 * h, use_bias=False, dtype=cfg.dtype,
                       name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        b, s = x.shape[0], x.shape[1]
        q = q.reshape(b, s, nh, hd)
        k = k.reshape(b, s, nh, hd)
        v = v.reshape(b, s, nh, hd)

        new_cache = None
        if kv_cache is not None:
            index = jnp.asarray(kv_cache[2], jnp.int32)
            # rotary positions are absolute: offset by the write index
            q = apply_rotary(q, index, cfg.rotary_dim)
            k = apply_rotary(k, index, cfg.rotary_dim)
            k_use, v_use, new_cache = update_kv_cache(kv_cache, k, v)
            out = reference_attention(q, k_use, v_use, causal=True,
                                      offset=index)
        else:
            q = apply_rotary(q, 0, cfg.rotary_dim)
            k = apply_rotary(k, 0, cfg.rotary_dim)
            out = reference_attention(q, k, v, causal=True)
        out = out.reshape(b, s, h)
        return nn.Dense(h, use_bias=False, dtype=cfg.dtype,
                        name="out")(out), new_cache


class CodeGenBlock(nn.Module):
    config: CodeGenConfig

    @nn.compact
    def __call__(self, x, kv_cache=None):
        cfg = self.config
        ln = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                          name="ln1")(x)
        attn_out, new_cache = CodeGenAttention(cfg, name="attn")(ln,
                                                                 kv_cache)
        y = nn.Dense(cfg.mlp_ratio * cfg.hidden_size, dtype=cfg.dtype,
                     name="fc_in")(ln.astype(cfg.dtype))
        y = nn.gelu(y, approximate=True)
        y = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="fc_out")(y)
        # parallel residual: one LN feeds both branches (GPT-J layout)
        return x + attn_out.astype(x.dtype) + y.astype(x.dtype), new_cache


class CodeGenModel(nn.Module):
    """Returns logits (and new KV caches when given)."""
    config: CodeGenConfig

    @nn.compact
    def __call__(self, input_ids, position_ids=None, kv_caches=None):
        # positions come from rotary offsets (cache indices); the argument
        # is accepted for Generator interface compatibility
        del position_ids
        cfg = self.config
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     name="wte")(input_ids)
        new_caches = [] if kv_caches is not None else None
        for i in range(cfg.num_layers):
            if (cfg.pipeline_boundary_every and i > 0 and
                    i % cfg.pipeline_boundary_every == 0):
                mark_pipeline_boundary()
            cache_i = kv_caches[i] if kv_caches is not None else None
            x, c = CodeGenBlock(cfg, name=f"h{i}")(x, cache_i)
            if new_caches is not None:
                new_caches.append(c)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="ln_f")(x)
        logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype, use_bias=True,
                          name="lm_head")(x.astype(cfg.dtype))
        if new_caches is not None:
            return logits, new_caches
        return logits


def init_codegen_kv_caches(config: CodeGenConfig, batch_size: int,
                           dtype=None) -> list:
    from alpa_tpu.model.gpt_model import init_kv_caches
    return init_kv_caches(config, batch_size, dtype)


def _qkv_permutation(hidden: int, mp_num: int = 4) -> np.ndarray:
    """Column permutation taking HF CodeGen's qkv layout to plain
    head-major [q; k; v].

    HF packs the 3h output dim as mp_num groups of [query, value, key]
    blocks of h/mp_num columns each (modeling_codegen qkv reshape with
    mp_num=4); perm[j] = the HF column that lands at our column j.
    """
    local = hidden // mp_num
    perm = np.empty(3 * hidden, np.int64)
    for g in range(mp_num):
        base = g * 3 * local
        cols = np.arange(local)
        perm[g * local:(g + 1) * local] = base + cols                # q
        perm[hidden + g * local:hidden + (g + 1) * local] = \
            base + 2 * local + cols                                  # k
        perm[2 * hidden + g * local:2 * hidden + (g + 1) * local] = \
            base + local + cols                                      # v
    return perm


def params_from_hf(hf_model, config: CodeGenConfig):
    """Map a transformers CodeGenForCausalLM state dict onto
    CodeGenModel params (ref codegen_model.py load path)."""
    sd = {k: np.asarray(v.detach().cpu().numpy(), np.float32)
          for k, v in hf_model.state_dict().items()}
    perm = _qkv_permutation(config.hidden_size)
    p = {"wte": {"embedding": sd["transformer.wte.weight"]},
         "ln_f": {"scale": sd["transformer.ln_f.weight"],
                  "bias": sd["transformer.ln_f.bias"]},
         "lm_head": {"kernel": sd["lm_head.weight"].T,
                     "bias": sd["lm_head.bias"]}}
    for i in range(config.num_layers):
        pre = f"transformer.h.{i}."
        p[f"h{i}"] = {
            "ln1": {"scale": sd[pre + "ln_1.weight"],
                    "bias": sd[pre + "ln_1.bias"]},
            "attn": {
                "qkv": {"kernel": sd[pre + "attn.qkv_proj.weight"].T[:,
                                                                     perm]},
                "out": {"kernel": sd[pre + "attn.out_proj.weight"].T},
            },
            "fc_in": {"kernel": sd[pre + "mlp.fc_in.weight"].T,
                      "bias": sd[pre + "mlp.fc_in.bias"]},
            "fc_out": {"kernel": sd[pre + "mlp.fc_out.weight"].T,
                       "bias": sd[pre + "mlp.fc_out.bias"]},
        }
    return {"params": p}
