"""Mixture-of-Experts transformer (GShard-style top-2 gating).

Analog of ref ``alpa/model/moe.py`` (einsum-formulated top-2 gating,
ref :151-184): the expert dimension is a leading einsum dim, and expert
parallelism (``ep_axis``) dispatches tokens with EXPLICIT all-to-alls in a
``shard_map`` over the expert axis — the GShard exchange pattern the
reference obtains through its ILP ``allow_all_to_all`` strategies
(SURVEY.md §2.7 EP row).  Spelling the exchange manually (rather than a
``with_sharding_constraint`` on the expert dim) matters: GSPMD lowers the
constraint form with all-gathers, roughly n_experts/2 x the bytes of the
all-to-all.
"""
import dataclasses
from typing import Any, Optional

import functools
import logging

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from alpa_tpu.model.gpt_model import GPTConfig, SelfAttention

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 51200
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    seq_len: int = 1024
    num_experts: int = 8
    expert_group_size: int = 512   # tokens per routing group
    capacity_factor: float = 2.0
    mlp_ratio: int = 4
    dtype: Any = jnp.float32
    # every k-th layer uses an MoE MLP (ref benchmark suite uses 2)
    moe_every: int = 2
    # mesh axis to shard the expert dim over (None = let GSPMD decide)
    ep_axis: Optional[str] = None
    layer_norm_eps: float = 1e-5

    def gpt(self) -> GPTConfig:
        return GPTConfig(vocab_size=self.vocab_size,
                         hidden_size=self.hidden_size,
                         num_layers=self.num_layers,
                         num_heads=self.num_heads,
                         seq_len=self.seq_len,
                         mlp_ratio=self.mlp_ratio,
                         dtype=self.dtype,
                         layer_norm_eps=self.layer_norm_eps)


def top2_gating(logits: jnp.ndarray, capacity: int):
    """GShard top-2 gating over (G, S, E) router logits.

    Returns (combine_weights (G,S,E,C), dispatch_mask (G,S,E,C), aux_loss).
    Einsum-formulated so everything is one-hot matmuls (MXU-friendly, no
    scatters) — the same formulation family as ref moe.py:151-184.
    """
    g, s, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    gate1 = jnp.argmax(probs, axis=-1)                       # (G,S)
    mask1 = jax.nn.one_hot(gate1, e, dtype=jnp.float32)
    probs_wo1 = probs * (1 - mask1)
    gate2 = jnp.argmax(probs_wo1, axis=-1)
    mask2 = jax.nn.one_hot(gate2, e, dtype=jnp.float32)

    # aux load-balancing loss (mean gate prob * mean assignment per expert)
    density = mask1.mean(axis=1)                             # (G,E)
    density_proxy = probs.mean(axis=1)
    aux_loss = (density * density_proxy).sum(-1).mean() * e * e

    # positions within expert capacity
    pos1 = (jnp.cumsum(mask1, axis=1) - 1) * mask1           # (G,S,E)
    mask1 = mask1 * (pos1 < capacity)
    pos1 = pos1 * mask1
    count1 = mask1.sum(axis=1, keepdims=True)                # (G,1,E)
    pos2 = (jnp.cumsum(mask2, axis=1) - 1) * mask2 + count1 * mask2
    mask2 = mask2 * (pos2 < capacity)
    pos2 = pos2 * mask2

    w1 = (probs * mask1).sum(-1)                             # (G,S)
    w2 = (probs * mask2).sum(-1)
    denom = jnp.maximum(w1 + w2, 1e-9)
    w1, w2 = w1 / denom, w2 / denom

    cap_range = jax.nn.one_hot(pos1.sum(-1).astype(jnp.int32), capacity)
    disp1 = mask1[..., None] * cap_range[:, :, None, :]      # (G,S,E,C)
    cap_range2 = jax.nn.one_hot(pos2.sum(-1).astype(jnp.int32), capacity)
    disp2 = mask2[..., None] * cap_range2[:, :, None, :]
    combine = w1[:, :, None, None] * disp1 + w2[:, :, None, None] * disp2
    dispatch = (combine > 0).astype(jnp.float32)
    return combine, dispatch, aux_loss


@functools.lru_cache(maxsize=64)
def _dispatch_fn(mesh, ep_axis: str):
    """Jitted GShard dispatch, cached per (mesh, axis) so repeated/eager
    calls (e.g. several MoE layers during flax init) share one
    compilation.  The jit wrapper also works around partial-manual
    shard_map rejecting eager execution over an abstract mesh."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    def inner(tok, disp, comb, wi_l, wo_l):
        # tok: (G/n, S, H); disp/comb: (G/n, S, E, C);
        # wi_l/wo_l: (E/n, ...) local expert slices
        expert_in = jnp.einsum("gsec,gsh->egch", disp, tok)
        # exchange: every device keeps its E/n experts for ALL groups
        expert_in = lax.all_to_all(expert_in, ep_axis, split_axis=0,
                                   concat_axis=1, tiled=True)
        hmid = jnp.einsum("egch,ehm->egcm", expert_in, wi_l)
        hmid = nn.gelu(hmid, approximate=True)
        expert_out = jnp.einsum("egcm,emh->egch", hmid, wo_l)
        expert_out = lax.all_to_all(expert_out, ep_axis, split_axis=1,
                                    concat_axis=0, tiled=True)
        return jnp.einsum("egch,gsec->gsh", expert_out, comb)

    sm = jax.shard_map(inner,
                       mesh=mesh,
                       in_specs=(P(ep_axis), P(ep_axis), P(ep_axis),
                                 P(ep_axis), P(ep_axis)),
                       out_specs=P(ep_axis),
                       axis_names={ep_axis},
                       check_vma=False)
    return jax.jit(sm)


def _shard_map_expert_dispatch(tokens, dispatch, combine, wi, wo,
                               ep_axis: str):
    """The GShard dispatch as explicit all-to-alls over ``ep_axis``
    (ref §2.7 EP: 'expert dim sharded => all-to-all inserted by GSPMD' —
    GSPMD actually lowers the constraint form as all-gathers, so we spell
    the exchange ourselves, the same way ulysses_attention does):

      groups sharded over ep ->(local dispatch einsum)-> (E, G/n, C, H)
      -> all_to_all: split E, concat G -> (E/n, G, C, H)
      -> local expert MLP with the device's expert weight slices
      -> inverse all_to_all -> local combine.
    """
    mesh = jax.sharding.get_abstract_mesh()
    return _dispatch_fn(mesh, ep_axis)(tokens, dispatch, combine, wi, wo)


class MoEMLP(nn.Module):
    """Expert-parallel MLP block."""
    config: MoEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        b, s, h = x.shape
        e = cfg.num_experts
        gs = min(cfg.expert_group_size, b * s)
        tokens = x.reshape(-1, h)
        n_tok = tokens.shape[0]
        g = max(1, n_tok // gs)
        if cfg.ep_axis is not None:
            # groups are sharded over the expert axis: G must be a
            # multiple of the axis size
            n_ep = dict(jax.sharding.get_abstract_mesh().shape)[cfg.ep_axis]
            if e % n_ep != 0:
                raise ValueError(
                    f"num_experts ({e}) must be divisible by the "
                    f"'{cfg.ep_axis}' mesh axis size ({n_ep}) for expert-"
                    "parallel dispatch; pick a divisible expert count or "
                    "set ep_axis=None")
            g_adj = max(n_ep, (g // n_ep) * n_ep)
            if g_adj != g:
                logger.warning(
                    "MoE group count adjusted %d -> %d to divide ep axis "
                    "(size %d); per-group capacity changes vs the "
                    "unsharded configuration", g, g_adj, n_ep)
            g = g_adj
            assert n_tok % g == 0, (
                f"tokens ({n_tok}) not divisible into {g} groups for "
                f"ep axis of size {n_ep}; adjust batch/expert_group_size")
        tokens = tokens.reshape(g, -1, h)                    # (G, S', H)
        sp = tokens.shape[1]
        capacity = max(1, int(cfg.capacity_factor * sp / e))

        router = nn.Dense(e, dtype=jnp.float32, use_bias=False,
                          name="router")(tokens)
        combine, dispatch, aux_loss = top2_gating(router, capacity)
        self.sow("intermediates", "aux_loss", aux_loss)

        # per-expert MLP weights (leading expert dim)
        wi = self.param("wi", nn.initializers.lecun_normal(),
                        (e, h, cfg.mlp_ratio * h), cfg.dtype)
        wo = self.param("wo", nn.initializers.lecun_normal(),
                        (e, cfg.mlp_ratio * h, h), cfg.dtype)

        if cfg.ep_axis is not None:
            out = _shard_map_expert_dispatch(
                tokens, dispatch.astype(x.dtype),
                combine.astype(x.dtype), wi, wo, cfg.ep_axis)
        else:
            # dispatch: (G,S,E,C) x (G,S,H) -> (E, G, C, H)
            expert_in = jnp.einsum("gsec,gsh->egch",
                                   dispatch.astype(x.dtype), tokens)
            hmid = jnp.einsum("egch,ehm->egcm", expert_in, wi)
            hmid = nn.gelu(hmid, approximate=True)
            expert_out = jnp.einsum("egcm,emh->egch", hmid, wo)
            # combine: (E,G,C,H) x (G,S,E,C) -> (G,S,H)
            out = jnp.einsum("egch,gsec->gsh", expert_out,
                             combine.astype(x.dtype))
        return out.reshape(b, s, h), aux_loss


class MoEBlock(nn.Module):
    config: MoEConfig
    use_moe: bool

    @nn.compact
    def __call__(self, x, kv_cache=None):
        cfg = self.config
        gcfg = cfg.gpt()
        ln1 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                           name="ln1")(x)
        attn_out, new_cache = SelfAttention(gcfg, name="attn")(ln1,
                                                               kv_cache)
        x = x + attn_out.astype(x.dtype)
        ln2 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                           name="ln2")(x)
        if self.use_moe:
            mlp_out, aux = MoEMLP(cfg, name="moe")(ln2)
        else:
            h = cfg.hidden_size
            y = nn.Dense(cfg.mlp_ratio * h, dtype=cfg.dtype,
                         name="fc_in")(ln2)
            y = nn.gelu(y, approximate=True)
            mlp_out = nn.Dense(h, dtype=cfg.dtype, name="fc_out")(y)
            aux = jnp.float32(0.0)
        return x + mlp_out.astype(x.dtype), aux, new_cache


class MoELMModel(nn.Module):
    """Decoder LM with alternating dense / MoE blocks
    (ref benchmark/alpa/suite_auto_moe.py model family).

    Training call: ``(logits, aux_loss) = apply(params, ids)``.
    Serving call (Mixtral-style MoE decoding): pass ``kv_caches`` and
    get ``(logits, new_caches)`` back — the gpt_model cache-as-invars
    contract, so the Generator / continuous-batching engine drive MoE
    models unchanged (routing happens per decoded token; the aux loss is
    an optimization-only term and is dropped in inference).

    SERVING CAPACITY CAVEAT: bucket-padded prefill feeds pad tokens into
    top-2 routing, and capacity slots go by token order — with
    ``capacity_factor < num_experts`` pads can steal expert capacity
    from real tokens and change their logits.  Serve with
    ``capacity_factor >= num_experts`` (no-drop regime; the Generator
    warns otherwise).  Training is unaffected (no padding there).
    """
    config: MoEConfig

    @nn.compact
    def __call__(self, input_ids, position_ids=None, kv_caches=None):
        cfg = self.config
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
        emb = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       name="wte")
        x = emb(input_ids) + nn.Embed(cfg.seq_len, cfg.hidden_size,
                                      dtype=cfg.dtype,
                                      name="wpe")(position_ids)
        aux_total = jnp.float32(0.0)
        new_caches = [] if kv_caches is not None else None
        for i in range(cfg.num_layers):
            use_moe = (cfg.moe_every > 0 and
                       (i + 1) % cfg.moe_every == 0)
            cache_i = kv_caches[i] if kv_caches is not None else None
            x, aux, c = MoEBlock(cfg, use_moe, name=f"h{i}")(x, cache_i)
            aux_total = aux_total + aux
            if new_caches is not None:
                new_caches.append(c)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="ln_f")(x)
        logits = emb.attend(x.astype(cfg.dtype))
        if new_caches is not None:
            return logits, new_caches
        return logits, aux_total


def init_moe_kv_caches(config: MoEConfig, batch_size: int,
                       dtype=None) -> list:
    """EXACTLY what the serving Generator builds for this config — one
    init path, so tests and serving cannot drift apart."""
    from alpa_tpu.model.gpt_model import init_kv_caches
    return init_kv_caches(config, batch_size, dtype)


# Benchmark ladder (ref benchmark/alpa/suite_auto_moe.py)
moe_specs = {
    "380M": (768, 8, 16, 8),
    "690M": (768, 8, 16, 16),
    "1.3B": (768, 16, 16, 16),
    "2.4B": (1024, 16, 16, 16),
    "10B": (1536, 16, 16, 32),
    "27B": (2048, 16, 16, 48),
}
