"""2D UNets (diffusion-style) for the vision benchmark suite.

Analog of ref ``alpa/model/unet_2d.py`` (1207 LoC diffusers-style
``FlaxUNet2DConditionModel`` used by ``benchmark/alpa/suite_unet.py``).

Two models live here:

* ``UNet2D`` — compact unconditioned UNet (kept for the CPU-runnable
  benchmark suites and conv-planner tests).
* ``UNet2DConditionModel`` — the reference-scale conditioned UNet:
  sinusoidal timestep embeddings + MLP, ResNet blocks with time-embedding
  injection, spatial transformers with cross-attention on encoder hidden
  states (GEGLU feed-forward), cross-attn down/mid/up blocks with skip
  connections and learned down/upsampling (ref unet_2d.py:81-1139).

TPU-first choices: channels-last (NHWC) convs so XLA tiles them onto the
MXU directly, fp32 GroupNorm/softmax with activations in ``dtype``
(bfloat16-ready), static shapes throughout, and attention written as
einsums over (B, HW, C) so the auto-sharding planner sees clean batch /
space / channel mesh targets.
"""
import dataclasses
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 3
    out_channels: int = 3
    block_channels: Tuple[int, ...] = (64, 128, 256)
    layers_per_block: int = 2
    attention_resolutions: Tuple[int, ...] = (2,)  # block indices w/ attn
    num_heads: int = 4
    time_embed_dim: int = 256
    dtype: Any = jnp.float32


@dataclasses.dataclass(frozen=True)
class UNetConditionConfig:
    """Reference-scale conditioned UNet (ref FlaxUNet2DConditionModel,
    unet_2d.py:900; defaults shrunk from the SD-class (320,640,1280,1280)
    so tests stay fast — benchmark suites pass the full widths)."""
    sample_size: int = 32
    in_channels: int = 4
    out_channels: int = 4
    # "CrossAttnDownBlock2D" | "DownBlock2D" per stage (mirrored for up)
    down_block_types: Tuple[str, ...] = ("CrossAttnDownBlock2D",
                                         "CrossAttnDownBlock2D",
                                         "DownBlock2D")
    block_out_channels: Tuple[int, ...] = (64, 128, 256)
    layers_per_block: int = 2
    attention_head_dim: int = 8
    cross_attention_dim: int = 128
    freq_shift: float = 0.0
    dtype: Any = jnp.float32


def _num_groups(channels: int, max_groups: int = 32) -> int:
    """Largest divisor of ``channels`` not exceeding ``max_groups``."""
    g = min(max_groups, channels)
    while channels % g != 0:
        g -= 1
    return g


def timestep_embedding(t, dim, freq_shift: float = 0.0):
    """Sinusoidal timestep embeddings (ref get_sinusoidal_embeddings:65)."""
    half = dim // 2
    freqs = jnp.exp(-np.log(10000.0) *
                    jnp.arange(half, dtype=jnp.float32) /
                    (half - freq_shift))
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.sin(args), jnp.cos(args)], axis=-1)


class TimestepEmbedding(nn.Module):
    """2-layer MLP over the sinusoid (ref FlaxTimestepEmbedding:81)."""
    dim: int
    dtype: Any

    @nn.compact
    def __call__(self, temb):
        temb = nn.Dense(self.dim, dtype=self.dtype, name="linear_1")(temb)
        temb = nn.swish(temb)
        return nn.Dense(self.dim, dtype=self.dtype, name="linear_2")(temb)


class ResnetBlock2D(nn.Module):
    """GN -> swish -> conv, time-emb injection, GN -> swish -> conv,
    learned shortcut on channel change (ref FlaxResnetBlock2D:165)."""
    channels: int
    dtype: Any

    @nn.compact
    def __call__(self, x, temb):
        h = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]),
                         dtype=jnp.float32, name="norm1")(x)
        h = nn.swish(h).astype(self.dtype)
        h = nn.Conv(self.channels, (3, 3), dtype=self.dtype,
                    name="conv1")(h)
        t = nn.Dense(self.channels, dtype=self.dtype,
                     name="time_emb_proj")(nn.swish(temb))
        h = h + t[:, None, None, :]
        h = nn.GroupNorm(num_groups=_num_groups(self.channels),
                         dtype=jnp.float32, name="norm2")(h)
        h = nn.swish(h).astype(self.dtype)
        h = nn.Conv(self.channels, (3, 3), dtype=self.dtype,
                    name="conv2")(h)
        if x.shape[-1] != self.channels:
            x = nn.Conv(self.channels, (1, 1), dtype=self.dtype,
                        name="conv_shortcut")(x)
        return x + h


class Downsample2D(nn.Module):
    """Strided conv downsampling (ref FlaxDownsample2D:145)."""
    channels: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        return nn.Conv(self.channels, (3, 3), strides=(2, 2),
                       dtype=self.dtype, name="conv")(x)


class Upsample2D(nn.Module):
    """Nearest-resize + conv upsampling (ref FlaxUpsample2D:121)."""
    channels: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        x = jax.image.resize(x, (b, h * 2, w * 2, c), "nearest")
        return nn.Conv(self.channels, (3, 3), dtype=self.dtype,
                       name="conv")(x)


class CrossAttention(nn.Module):
    """Multi-head attention; self- when context is None, cross- otherwise.
    fp32 softmax, einsum-formulated (ref attention inside
    FlaxBasicTransformerBlock:323)."""
    query_dim: int
    heads: int
    head_dim: int
    dtype: Any

    @nn.compact
    def __call__(self, x, context=None):
        context = x if context is None else context
        inner = self.heads * self.head_dim
        q = nn.Dense(inner, use_bias=False, dtype=self.dtype,
                     name="to_q")(x)
        k = nn.Dense(inner, use_bias=False, dtype=self.dtype,
                     name="to_k")(context)
        v = nn.Dense(inner, use_bias=False, dtype=self.dtype,
                     name="to_v")(context)
        b, sq, _ = q.shape
        sk = k.shape[1]
        q = q.reshape(b, sq, self.heads, self.head_dim)
        k = k.reshape(b, sk, self.heads, self.head_dim)
        v = v.reshape(b, sk, self.heads, self.head_dim)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        scores = scores / np.sqrt(self.head_dim)
        probs = jax.nn.softmax(scores, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, sq, inner)
        return nn.Dense(self.query_dim, dtype=self.dtype, name="to_out")(out)


class GEGLUFeedForward(nn.Module):
    """GEGLU-gated feed-forward (ref FlaxGluFeedForward:463 / FlaxGEGLU:491)."""
    dim: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.dim * 8, dtype=self.dtype, name="proj_in")(x)
        h, gate = jnp.split(h, 2, axis=-1)
        h = h * nn.gelu(gate, approximate=True)
        return nn.Dense(self.dim, dtype=self.dtype, name="proj_out")(h)


class BasicTransformerBlock(nn.Module):
    """Self-attn -> cross-attn(context) -> GEGLU FF, pre-LN residuals
    (ref FlaxBasicTransformerBlock:323)."""
    dim: int
    heads: int
    head_dim: int
    dtype: Any

    @nn.compact
    def __call__(self, x, context):
        h = nn.LayerNorm(dtype=jnp.float32, name="norm1")(x)
        x = x + CrossAttention(self.dim, self.heads, self.head_dim,
                               self.dtype, name="attn1")(
                                   h.astype(self.dtype))
        h = nn.LayerNorm(dtype=jnp.float32, name="norm2")(x)
        x = x + CrossAttention(self.dim, self.heads, self.head_dim,
                               self.dtype, name="attn2")(
                                   h.astype(self.dtype), context)
        h = nn.LayerNorm(dtype=jnp.float32, name="norm3")(x)
        return x + GEGLUFeedForward(self.dim, self.dtype,
                                    name="ff")(h.astype(self.dtype))


class SpatialTransformer(nn.Module):
    """Flatten (H, W) -> tokens, run transformer blocks with cross-attention
    on the conditioning sequence, project back (ref FlaxSpatialTransformer:388)."""
    channels: int
    heads: int
    head_dim: int
    depth: int
    dtype: Any

    @nn.compact
    def __call__(self, x, context):
        b, h, w, c = x.shape
        residual = x
        y = nn.GroupNorm(num_groups=_num_groups(c), dtype=jnp.float32,
                         name="norm")(x)
        y = nn.Dense(self.channels, dtype=self.dtype,
                     name="proj_in")(y.astype(self.dtype))
        y = y.reshape(b, h * w, self.channels)
        for i in range(self.depth):
            y = BasicTransformerBlock(self.channels, self.heads,
                                      self.head_dim, self.dtype,
                                      name=f"block_{i}")(y, context)
        y = y.reshape(b, h, w, self.channels)
        y = nn.Dense(c, dtype=self.dtype, name="proj_out")(y)
        return y + residual


class CrossAttnDownBlock2D(nn.Module):
    """N x (resnet + spatial transformer) + downsample
    (ref FlaxCrossAttnDownBlock2D:518)."""
    channels: int
    num_layers: int
    heads: int
    head_dim: int
    add_downsample: bool
    dtype: Any

    @nn.compact
    def __call__(self, x, temb, context):
        skips = []
        for i in range(self.num_layers):
            x = ResnetBlock2D(self.channels, self.dtype,
                              name=f"resnet_{i}")(x, temb)
            x = SpatialTransformer(self.channels, self.heads, self.head_dim,
                                   1, self.dtype,
                                   name=f"attn_{i}")(x, context)
            skips.append(x)
        if self.add_downsample:
            x = Downsample2D(self.channels, self.dtype,
                             name="downsample")(x)
            skips.append(x)
        return x, skips


class DownBlock2D(nn.Module):
    """N x resnet + downsample (ref FlaxDownBlock2D:604)."""
    channels: int
    num_layers: int
    add_downsample: bool
    dtype: Any

    @nn.compact
    def __call__(self, x, temb):
        skips = []
        for i in range(self.num_layers):
            x = ResnetBlock2D(self.channels, self.dtype,
                              name=f"resnet_{i}")(x, temb)
            skips.append(x)
        if self.add_downsample:
            x = Downsample2D(self.channels, self.dtype,
                             name="downsample")(x)
            skips.append(x)
        return x, skips


class CrossAttnUpBlock2D(nn.Module):
    """N x (concat-skip + resnet + spatial transformer) + upsample
    (ref FlaxCrossAttnUpBlock2D:667)."""
    channels: int
    num_layers: int
    heads: int
    head_dim: int
    add_upsample: bool
    dtype: Any

    @nn.compact
    def __call__(self, x, skips, temb, context):
        for i in range(self.num_layers):
            x = jnp.concatenate([x, skips.pop()], axis=-1)
            x = ResnetBlock2D(self.channels, self.dtype,
                              name=f"resnet_{i}")(x, temb)
            x = SpatialTransformer(self.channels, self.heads, self.head_dim,
                                   1, self.dtype,
                                   name=f"attn_{i}")(x, context)
        if self.add_upsample:
            x = Upsample2D(self.channels, self.dtype, name="upsample")(x)
        return x


class UpBlock2D(nn.Module):
    """N x (concat-skip + resnet) + upsample (ref FlaxUpBlock2D:755)."""
    channels: int
    num_layers: int
    add_upsample: bool
    dtype: Any

    @nn.compact
    def __call__(self, x, skips, temb):
        for i in range(self.num_layers):
            x = jnp.concatenate([x, skips.pop()], axis=-1)
            x = ResnetBlock2D(self.channels, self.dtype,
                              name=f"resnet_{i}")(x, temb)
        if self.add_upsample:
            x = Upsample2D(self.channels, self.dtype, name="upsample")(x)
        return x


class UNetMidBlock2DCrossAttn(nn.Module):
    """resnet -> spatial transformer -> resnet
    (ref FlaxUNetMidBlock2DCrossAttn:826)."""
    channels: int
    heads: int
    head_dim: int
    dtype: Any

    @nn.compact
    def __call__(self, x, temb, context):
        x = ResnetBlock2D(self.channels, self.dtype,
                          name="resnet_0")(x, temb)
        x = SpatialTransformer(self.channels, self.heads, self.head_dim, 1,
                               self.dtype, name="attn")(x, context)
        return ResnetBlock2D(self.channels, self.dtype,
                             name="resnet_1")(x, temb)


class UNet2DConditionModel(nn.Module):
    """Conditioned UNet: (sample NHWC, timesteps, encoder_hidden_states)
    -> predicted noise (ref FlaxUNet2DConditionModel:900)."""
    config: UNetConditionConfig

    @nn.compact
    def __call__(self, sample, timesteps, encoder_hidden_states):
        cfg = self.config
        chans = cfg.block_out_channels
        heads = [max(1, c // cfg.attention_head_dim) for c in chans]
        temb_dim = chans[0] * 4
        temb = timestep_embedding(timesteps, chans[0], cfg.freq_shift)
        temb = TimestepEmbedding(temb_dim, cfg.dtype,
                                 name="time_embedding")(temb)
        context = encoder_hidden_states.astype(cfg.dtype)

        x = nn.Conv(chans[0], (3, 3), dtype=cfg.dtype,
                    name="conv_in")(sample.astype(cfg.dtype))
        skips = [x]
        for bi, (btype, ch) in enumerate(zip(cfg.down_block_types, chans)):
            last = bi == len(chans) - 1
            if btype == "CrossAttnDownBlock2D":
                x, s = CrossAttnDownBlock2D(
                    ch, cfg.layers_per_block, heads[bi],
                    cfg.attention_head_dim, not last, cfg.dtype,
                    name=f"down_{bi}")(x, temb, context)
            else:
                x, s = DownBlock2D(ch, cfg.layers_per_block, not last,
                                   cfg.dtype, name=f"down_{bi}")(x, temb)
            skips.extend(s)

        x = UNetMidBlock2DCrossAttn(chans[-1], heads[-1],
                                    cfg.attention_head_dim, cfg.dtype,
                                    name="mid")(x, temb, context)

        up_types = tuple(reversed(cfg.down_block_types))
        up_chans = tuple(reversed(chans))
        for bi, (btype, ch) in enumerate(zip(up_types, up_chans)):
            last = bi == len(chans) - 1
            blk_skips = [skips.pop() for _ in range(cfg.layers_per_block + 1)]
            blk_skips.reverse()
            if btype == "CrossAttnDownBlock2D":
                x = CrossAttnUpBlock2D(
                    ch, cfg.layers_per_block + 1, heads[len(chans) - 1 - bi],
                    cfg.attention_head_dim, not last, cfg.dtype,
                    name=f"up_{bi}")(x, blk_skips, temb, context)
            else:
                x = UpBlock2D(ch, cfg.layers_per_block + 1, not last,
                              cfg.dtype, name=f"up_{bi}")(x, blk_skips, temb)

        x = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]),
                         dtype=jnp.float32, name="norm_out")(x)
        x = nn.swish(x).astype(cfg.dtype)
        return nn.Conv(cfg.out_channels, (3, 3), dtype=cfg.dtype,
                       name="conv_out")(x)


class ResBlock(nn.Module):
    channels: int
    dtype: Any

    @nn.compact
    def __call__(self, x, temb):
        h = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]),
                         dtype=jnp.float32)(x)
        h = nn.swish(h)
        h = nn.Conv(self.channels, (3, 3), dtype=self.dtype)(h)
        h = h + nn.Dense(self.channels, dtype=self.dtype)(
            nn.swish(temb))[:, None, None, :]
        h = nn.GroupNorm(num_groups=_num_groups(self.channels),
                         dtype=jnp.float32)(h)
        h = nn.swish(h)
        h = nn.Conv(self.channels, (3, 3), dtype=self.dtype)(h)
        if x.shape[-1] != self.channels:
            x = nn.Conv(self.channels, (1, 1), dtype=self.dtype)(x)
        return x + h


class AttnBlock2D(nn.Module):
    num_heads: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        y = nn.GroupNorm(num_groups=_num_groups(c), dtype=jnp.float32)(x)
        y = y.reshape(b, h * w, c)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, dtype=self.dtype)(y, y)
        return x + y.reshape(b, h, w, c)


class UNet2D(nn.Module):
    """Compact unconditioned UNet (benchmark suites, conv-planner tests)."""
    config: UNetConfig

    @nn.compact
    def __call__(self, x, timesteps):
        cfg = self.config
        temb = timestep_embedding(timesteps, cfg.time_embed_dim)
        temb = nn.Dense(cfg.time_embed_dim, dtype=cfg.dtype)(temb)
        temb = nn.Dense(cfg.time_embed_dim, dtype=cfg.dtype)(
            nn.swish(temb))

        h = nn.Conv(cfg.block_channels[0], (3, 3), dtype=cfg.dtype,
                    name="conv_in")(x)
        skips = [h]
        # down
        for bi, ch in enumerate(cfg.block_channels):
            for _ in range(cfg.layers_per_block):
                h = ResBlock(ch, cfg.dtype)(h, temb)
                if bi in cfg.attention_resolutions:
                    h = AttnBlock2D(cfg.num_heads, cfg.dtype)(h)
                skips.append(h)
            if bi < len(cfg.block_channels) - 1:
                h = nn.Conv(ch, (3, 3), (2, 2), dtype=cfg.dtype)(h)
                skips.append(h)
        # mid
        mid_ch = cfg.block_channels[-1]
        h = ResBlock(mid_ch, cfg.dtype)(h, temb)
        h = AttnBlock2D(cfg.num_heads, cfg.dtype)(h)
        h = ResBlock(mid_ch, cfg.dtype)(h, temb)
        # up
        for bi, ch in reversed(list(enumerate(cfg.block_channels))):
            for _ in range(cfg.layers_per_block + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = ResBlock(ch, cfg.dtype)(h, temb)
                if bi in cfg.attention_resolutions:
                    h = AttnBlock2D(cfg.num_heads, cfg.dtype)(h)
            if bi > 0:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), "nearest")
                h = nn.Conv(c, (3, 3), dtype=cfg.dtype)(h)
        h = nn.GroupNorm(num_groups=_num_groups(h.shape[-1]),
                         dtype=jnp.float32)(h)
        h = nn.swish(h)
        return nn.Conv(cfg.out_channels, (3, 3), dtype=cfg.dtype,
                       name="conv_out")(h)
