"""2D UNet (diffusion-style) for the vision benchmark suite.

Analog of ref ``alpa/model/unet_2d.py`` (1207 LoC diffusers-style UNet used
by ``benchmark/alpa/suite_unet.py``): timestep-conditioned down/mid/up
blocks with attention at low resolutions and skip connections.  Written
compactly and TPU-first (GroupNorm in fp32, channels-last convs).
"""
import dataclasses
from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 3
    out_channels: int = 3
    block_channels: Tuple[int, ...] = (64, 128, 256)
    layers_per_block: int = 2
    attention_resolutions: Tuple[int, ...] = (2,)  # block indices w/ attn
    num_heads: int = 4
    time_embed_dim: int = 256
    dtype: Any = jnp.float32


def _num_groups(channels: int, max_groups: int = 32) -> int:
    """Largest divisor of ``channels`` not exceeding ``max_groups``."""
    g = min(max_groups, channels)
    while channels % g != 0:
        g -= 1
    return g


def timestep_embedding(t, dim):
    half = dim // 2
    freqs = jnp.exp(-np.log(10000.0) *
                    jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.sin(args), jnp.cos(args)], axis=-1)


class ResBlock(nn.Module):
    channels: int
    dtype: Any

    @nn.compact
    def __call__(self, x, temb):
        h = nn.GroupNorm(num_groups=_num_groups(x.shape[-1]),
                         dtype=jnp.float32)(x)
        h = nn.swish(h)
        h = nn.Conv(self.channels, (3, 3), dtype=self.dtype)(h)
        h = h + nn.Dense(self.channels, dtype=self.dtype)(
            nn.swish(temb))[:, None, None, :]
        h = nn.GroupNorm(num_groups=_num_groups(self.channels),
                         dtype=jnp.float32)(h)
        h = nn.swish(h)
        h = nn.Conv(self.channels, (3, 3), dtype=self.dtype)(h)
        if x.shape[-1] != self.channels:
            x = nn.Conv(self.channels, (1, 1), dtype=self.dtype)(x)
        return x + h


class AttnBlock2D(nn.Module):
    num_heads: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        y = nn.GroupNorm(num_groups=_num_groups(c), dtype=jnp.float32)(x)
        y = y.reshape(b, h * w, c)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, dtype=self.dtype)(y, y)
        return x + y.reshape(b, h, w, c)


class UNet2D(nn.Module):
    config: UNetConfig

    @nn.compact
    def __call__(self, x, timesteps):
        cfg = self.config
        temb = timestep_embedding(timesteps, cfg.time_embed_dim)
        temb = nn.Dense(cfg.time_embed_dim, dtype=cfg.dtype)(temb)
        temb = nn.Dense(cfg.time_embed_dim, dtype=cfg.dtype)(
            nn.swish(temb))

        h = nn.Conv(cfg.block_channels[0], (3, 3), dtype=cfg.dtype,
                    name="conv_in")(x)
        skips = [h]
        # down
        for bi, ch in enumerate(cfg.block_channels):
            for _ in range(cfg.layers_per_block):
                h = ResBlock(ch, cfg.dtype)(h, temb)
                if bi in cfg.attention_resolutions:
                    h = AttnBlock2D(cfg.num_heads, cfg.dtype)(h)
                skips.append(h)
            if bi < len(cfg.block_channels) - 1:
                h = nn.Conv(ch, (3, 3), (2, 2), dtype=cfg.dtype)(h)
                skips.append(h)
        # mid
        mid_ch = cfg.block_channels[-1]
        h = ResBlock(mid_ch, cfg.dtype)(h, temb)
        h = AttnBlock2D(cfg.num_heads, cfg.dtype)(h)
        h = ResBlock(mid_ch, cfg.dtype)(h, temb)
        # up
        for bi, ch in reversed(list(enumerate(cfg.block_channels))):
            for _ in range(cfg.layers_per_block + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = ResBlock(ch, cfg.dtype)(h, temb)
                if bi in cfg.attention_resolutions:
                    h = AttnBlock2D(cfg.num_heads, cfg.dtype)(h)
            if bi > 0:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), "nearest")
                h = nn.Conv(c, (3, 3), dtype=cfg.dtype)(h)
        h = nn.GroupNorm(num_groups=_num_groups(h.shape[-1]),
                         dtype=jnp.float32)(h)
        h = nn.swish(h)
        return nn.Conv(cfg.out_channels, (3, 3), dtype=cfg.dtype,
                       name="conv_out")(h)
