"""BLOOM decoder-only LM (flax), TPU-first.

Clean-room analog of ref ``examples/llm_serving/model/bloom_model.py``
(the reference's HF-port for serving).  Architectural deltas vs GPT:

* ALiBi attention biases instead of positional embeddings
  (per-head slopes, linear in key-query distance) — no learned position
  table, so any sequence length the cache allows is admissible,
* LayerNorm directly after the word embedding
  (``word_embeddings_layernorm``),
* fused-style QKV whose per-head layout is (head, 3, head_dim) — the HF
  checkpoint convention, honored by ``params_from_hf``.

KV caches follow the gpt_model convention (cache-as-invars, scalar or
per-row vector write indices) so ``serve.generation.Generator`` and the
continuous-batching engine work unchanged.
"""
import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from alpa_tpu.model.gpt_model import reference_attention, update_kv_cache
from alpa_tpu.pipeline_parallel.primitive_def import mark_pipeline_boundary


@dataclasses.dataclass(frozen=True)
class BloomConfig:
    vocab_size: int = 250880
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    seq_len: int = 2048          # cache capacity; ALiBi has no hard limit
    mlp_ratio: int = 4
    dtype: Any = jnp.float32
    layer_norm_eps: float = 1e-5
    tie_embeddings: bool = True
    pipeline_boundary_every: int = 0


# name -> (hidden, layers, heads); ref bloom family (HF bigscience/bloom-*)
bloom_specs = {
    "560m": (1024, 24, 16),
    "1b1": (1536, 24, 16),
    "1b7": (2048, 24, 16),
    "3b": (2560, 30, 32),
    "7b1": (4096, 30, 32),
    "176b": (14336, 70, 112),
}


def config_from_bloom_spec(name: str, **kwargs) -> BloomConfig:
    hidden, layers, heads = bloom_specs[name.lower().replace("bloom-", "")]
    return BloomConfig(hidden_size=hidden, num_layers=layers,
                       num_heads=heads, **kwargs)


def alibi_slopes(num_heads: int) -> np.ndarray:
    """Per-head ALiBi slopes (Press et al.; matches HF build_alibi_tensor):
    geometric sequence starting at 2^(-8/n) for the nearest power of two,
    interleaved extras for non-power-of-two head counts."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return start * (start ** np.arange(n))

    if np.log2(num_heads).is_integer():
        return pow2_slopes(num_heads)
    closest = 2 ** int(np.floor(np.log2(num_heads)))
    base = pow2_slopes(closest)
    extra = pow2_slopes(2 * closest)[0::2][:num_heads - closest]
    return np.concatenate([base, extra])


def alibi_bias(num_heads: int, q_pos, k_pos) -> jnp.ndarray:
    """(H, Sq, Sk) additive score bias: slope_h * -(q - k) for k <= q.
    HF computes slope * k (key position) which is equivalent under the
    softmax's row-wise shift invariance; the distance form is kept here
    because it is also exact for the cached-decode path."""
    slopes = jnp.asarray(alibi_slopes(num_heads), jnp.float32)
    dist = (k_pos[None, :] - q_pos[:, None]).astype(jnp.float32)  # <= 0 kept
    return slopes[:, None, None] * dist[None, :, :]


class BloomAttention(nn.Module):
    config: BloomConfig

    @nn.compact
    def __call__(self, x, kv_cache=None):
        cfg = self.config
        h, nh = cfg.hidden_size, cfg.num_heads
        hd = h // nh
        qkv = nn.Dense(3 * h, dtype=cfg.dtype, name="qkv")(x)
        b, s = x.shape[0], x.shape[1]
        # HF bloom packs qkv per head: (nh, 3, hd)
        qkv = qkv.reshape(b, s, nh, 3, hd)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]

        new_cache = None
        if kv_cache is not None:
            index = jnp.asarray(kv_cache[2], jnp.int32)
            cache_len = kv_cache[0].shape[1]
            k_use, v_use, new_cache = update_kv_cache(kv_cache, k, v)
            if index.ndim == 0:
                q_pos = index + jnp.arange(s)
            else:
                q_pos = index[:, None] + jnp.arange(s)[None, :]  # (B, S)
            k_pos = jnp.arange(cache_len)
            if q_pos.ndim == 1:
                bias = alibi_bias(nh, q_pos, k_pos)[None]      # (1,H,S,L)
            else:
                bias = jax.vmap(lambda qp: alibi_bias(nh, qp, k_pos))(q_pos)
            out = reference_attention(q, k_use, v_use, causal=True,
                                      offset=index, bias=bias)
        else:
            pos = jnp.arange(s)
            bias = alibi_bias(nh, pos, pos)[None]              # (1,H,S,S)
            out = reference_attention(q, k, v, causal=True, bias=bias)
        out = out.reshape(b, s, h)
        return nn.Dense(h, dtype=cfg.dtype, name="out")(out), new_cache


class BloomBlock(nn.Module):
    config: BloomConfig

    @nn.compact
    def __call__(self, x, kv_cache=None):
        cfg = self.config
        ln1 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                           name="ln1")(x)
        attn_out, new_cache = BloomAttention(cfg, name="attn")(ln1, kv_cache)
        x = x + attn_out.astype(x.dtype)
        ln2 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                           name="ln2")(x)
        y = nn.Dense(cfg.mlp_ratio * cfg.hidden_size, dtype=cfg.dtype,
                     name="fc_in")(ln2)
        y = nn.gelu(y, approximate=True)
        y = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="fc_out")(y)
        return x + y.astype(x.dtype), new_cache


class BloomModel(nn.Module):
    """Returns logits (and new KV caches when given)."""
    config: BloomConfig

    @nn.compact
    def __call__(self, input_ids, position_ids=None, kv_caches=None):
        # position_ids accepted for Generator interface compatibility;
        # ALiBi needs no position table (positions come from cache indices)
        del position_ids
        cfg = self.config
        tok_emb = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                           dtype=cfg.dtype, name="wte")
        x = tok_emb(input_ids)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="ln_emb")(x).astype(cfg.dtype)
        new_caches = [] if kv_caches is not None else None
        for i in range(cfg.num_layers):
            if (cfg.pipeline_boundary_every and i > 0 and
                    i % cfg.pipeline_boundary_every == 0):
                mark_pipeline_boundary()
            cache_i = kv_caches[i] if kv_caches is not None else None
            x, c = BloomBlock(cfg, name=f"h{i}")(x, cache_i)
            if new_caches is not None:
                new_caches.append(c)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="ln_f")(x)
        if cfg.tie_embeddings:
            logits = tok_emb.attend(x.astype(cfg.dtype))
        else:
            logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype,
                              use_bias=False, name="lm_head")(x)
        if new_caches is not None:
            return logits, new_caches
        return logits


def init_bloom_kv_caches(config: BloomConfig, batch_size: int,
                         dtype=None) -> list:
    from alpa_tpu.model.gpt_model import init_kv_caches
    return init_kv_caches(config, batch_size, dtype)


def params_from_hf(hf_model, config: BloomConfig):
    """Map a transformers BloomForCausalLM state dict onto BloomModel
    params (ref bloom_model.py load path; layout notes: HF QKV rows are
    (nh, 3, hd) per head — same as this model's packed projection)."""
    sd = {k: np.asarray(v.detach().cpu().numpy(), np.float32)
          for k, v in hf_model.state_dict().items()}
    p = {"wte": {"embedding": sd["transformer.word_embeddings.weight"]},
         "ln_emb": {
             "scale": sd["transformer.word_embeddings_layernorm.weight"],
             "bias": sd["transformer.word_embeddings_layernorm.bias"]},
         "ln_f": {"scale": sd["transformer.ln_f.weight"],
                  "bias": sd["transformer.ln_f.bias"]}}
    for i in range(config.num_layers):
        pre = f"transformer.h.{i}."
        p[f"h{i}"] = {
            "ln1": {"scale": sd[pre + "input_layernorm.weight"],
                    "bias": sd[pre + "input_layernorm.bias"]},
            "ln2": {"scale": sd[pre + "post_attention_layernorm.weight"],
                    "bias": sd[pre + "post_attention_layernorm.bias"]},
            "attn": {
                "qkv": {
                    "kernel": sd[
                        pre + "self_attention.query_key_value.weight"].T,
                    "bias": sd[pre + "self_attention.query_key_value.bias"],
                },
                "out": {"kernel": sd[pre + "self_attention.dense.weight"].T,
                        "bias": sd[pre + "self_attention.dense.bias"]},
            },
            "fc_in": {"kernel": sd[pre + "mlp.dense_h_to_4h.weight"].T,
                      "bias": sd[pre + "mlp.dense_h_to_4h.bias"]},
            "fc_out": {"kernel": sd[pre + "mlp.dense_4h_to_h.weight"].T,
                       "bias": sd[pre + "mlp.dense_4h_to_h.bias"]},
        }
    return {"params": p}
