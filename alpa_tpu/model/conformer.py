"""Conformer encoder for speech (ASR) in the model zoo.

Analog of ref ``alpa/model/conformer.py`` (314 LoC): conv subsampling of
the feature sequence, then conformer blocks = half-step FFN, multi-head
self-attention with additive sinusoidal positional encoding and padding
mask, depthwise conv module, half-step FFN, post-norm
(ref ConformerLayer:245, MultiHeadSelfAttentionModule:158,
ConvModule:123, FFNModule:100, ConvSubSample:72,
ConformerForASRModule:277).

TPU-first choices: fp32 LayerNorm/softmax over ``dtype`` activations;
GroupNorm(1) instead of BatchNorm in the conv module (no cross-batch
running stats to sync across data-parallel shards — per-timestep norm is
the streaming-friendly, mesh-neutral choice); masks built with
``broadcasted_iota`` so everything stays statically shaped under jit.
"""
import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class ConformerConfig:
    num_mel_bins: int = 80
    hidden_size: int = 256
    num_layers: int = 4
    num_heads: int = 4
    conv_kernel_size: int = 15
    subsample_channels: int = 64
    ffn_ratio: int = 4
    vocab_size: int = 1024          # ASR output vocabulary (CTC logits)
    max_len: int = 2048             # positional-encoding table length
    dtype: Any = jnp.float32
    dropout_rate: float = 0.0
    layer_norm_eps: float = 1e-5


def sinusoidal_position_encoding(length: int, dim: int) -> jnp.ndarray:
    """(length, dim) fixed sinusoid added pre-attention (ref :190)."""
    pos = np.arange(length, dtype=np.float32)[:, None]
    div = np.exp(np.arange(0, dim, 2, dtype=np.float32) *
                 (-np.log(10000.0) / dim))
    enc = np.zeros((length, dim), dtype=np.float32)
    enc[:, 0::2] = np.sin(pos * div)
    enc[:, 1::2] = np.cos(pos * div)
    return jnp.asarray(enc)


def lengths_to_mask(lengths, max_len: int) -> jnp.ndarray:
    """(B,) valid lengths -> (B, max_len) bool mask, static shapes."""
    pos = jax.lax.broadcasted_iota(jnp.int32, (max_len,), 0)
    return pos[None, :] < lengths[:, None]


class ConvSubSample(nn.Module):
    """Two stride-2 2D convs over (time, mel) then linear projection:
    (B, T, F) -> (B, T//4, H) with lengths scaled to match
    (ref ConvSubSample:72)."""
    config: ConformerConfig

    @nn.compact
    def __call__(self, x, lengths=None):
        cfg = self.config
        if lengths is not None:
            # zero pad frames BEFORE the convs: the stride-2 windows at
            # the valid/pad boundary would otherwise mix pad garbage into
            # the last valid subsampled frame
            frame_mask = lengths_to_mask(lengths, x.shape[1])
            x = jnp.where(frame_mask[:, :, None], x, jnp.zeros_like(x))
        h = x[..., None].astype(cfg.dtype)           # (B, T, F, 1)
        h = nn.Conv(cfg.subsample_channels, (3, 3), strides=(2, 2),
                    dtype=cfg.dtype, name="conv1")(h)
        h = nn.relu(h)
        h = nn.Conv(cfg.subsample_channels, (3, 3), strides=(2, 2),
                    dtype=cfg.dtype, name="conv2")(h)
        h = nn.relu(h)
        b, t, f, c = h.shape
        h = h.reshape(b, t, f * c)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="proj")(h)
        if lengths is not None:
            # ceil-div twice: each stride-2 conv (SAME padding) halves T
            lengths = (lengths + 1) // 2
            lengths = (lengths + 1) // 2
        return h, lengths


class FeedForwardModule(nn.Module):
    """Pre-norm swish FFN, used at half weight twice per block
    (ref FFNModule:100)."""
    config: ConformerConfig

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32)(x)
        h = nn.Dense(cfg.ffn_ratio * cfg.hidden_size,
                     dtype=cfg.dtype)(h.astype(cfg.dtype))
        h = nn.swish(h)
        h = nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)(h)
        return nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)


class ConvModule(nn.Module):
    """LN -> pointwise(2H) -> GLU -> depthwise conv -> LN -> swish ->
    pointwise (ref ConvModule:123).  Padding positions are zeroed before
    the depthwise conv so pad frames cannot leak into valid ones through
    the kernel window.  The post-conv norm is a per-position LayerNorm
    (the reference's BatchNorm carries cross-batch running stats that
    would need syncing across data-parallel shards, and a time-reducing
    GroupNorm would make valid frames depend on the batch's pad width —
    per-position LN is the mesh-neutral, padding-invariant choice)."""
    config: ConformerConfig

    @nn.compact
    def __call__(self, x, mask=None, deterministic=True):
        cfg = self.config
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32)(x)
        h = nn.Dense(2 * cfg.hidden_size,
                     dtype=cfg.dtype)(h.astype(cfg.dtype))
        h = nn.glu(h, axis=-1)
        if mask is not None:
            h = jnp.where(mask[:, :, None], h, jnp.zeros_like(h))
        h = nn.Conv(cfg.hidden_size, (cfg.conv_kernel_size,),
                    feature_group_count=cfg.hidden_size,
                    dtype=cfg.dtype)(h)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32)(h)
        h = nn.swish(h).astype(cfg.dtype)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)(h)
        return nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)


class MHSAModule(nn.Module):
    """Pre-norm MHSA with additive sinusoidal positions and padding mask,
    fp32 softmax (ref MultiHeadSelfAttentionModule:158)."""
    config: ConformerConfig

    @nn.compact
    def __call__(self, x, pos_encoding, mask=None, deterministic=True):
        cfg = self.config
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32)(x)
        h = h.astype(cfg.dtype) + pos_encoding.astype(cfg.dtype)
        qkv = nn.Dense(3 * cfg.hidden_size, dtype=cfg.dtype,
                       name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        b, s = h.shape[0], h.shape[1]
        q = q.reshape(b, s, nh, hd)
        k = k.reshape(b, s, nh, hd)
        v = v.reshape(b, s, nh, hd)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        scores = scores / np.sqrt(hd)
        if mask is not None:
            scores = jnp.where(mask[:, None, None, :], scores,
                               jnp.float32(-1e9))
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(
            b, s, cfg.hidden_size)
        out = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="out")(out)
        return nn.Dropout(cfg.dropout_rate)(out,
                                            deterministic=deterministic)


class ConformerBlock(nn.Module):
    """ffn/2 + mhsa + conv + ffn/2 + final LN (ref ConformerLayer:245)."""
    config: ConformerConfig

    @nn.compact
    def __call__(self, x, pos_encoding, mask=None, deterministic=True):
        cfg = self.config
        x = x + 0.5 * FeedForwardModule(cfg, name="ffn1")(
            x, deterministic)
        x = x + MHSAModule(cfg, name="mhsa")(x, pos_encoding, mask,
                                             deterministic)
        x = x + ConvModule(cfg, name="conv")(x, mask, deterministic)
        x = x + 0.5 * FeedForwardModule(cfg, name="ffn2")(
            x, deterministic)
        return nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                            dtype=jnp.float32)(x).astype(cfg.dtype)


class Conformer(nn.Module):
    """Encoder over projected features: (B, T, H) -> (B, T, H).

    Accepts pre-subsampled inputs; ``ConformerForASR`` wires the conv
    subsampling in front for raw (B, T, F) mel features."""
    config: ConformerConfig

    @nn.compact
    def __call__(self, x, lengths=None, deterministic=True):
        cfg = self.config
        # always project: a shape-conditional layer would make the param
        # tree depend on the input width (incompatible checkpoints)
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="proj_in")(x)
        s = x.shape[1]
        assert s <= cfg.max_len, (
            f"sequence length {s} exceeds ConformerConfig.max_len "
            f"{cfg.max_len}")
        pos = sinusoidal_position_encoding(cfg.max_len,
                                           cfg.hidden_size)[None, :s]
        mask = None
        if lengths is not None:
            mask = lengths_to_mask(lengths, s)
        for i in range(cfg.num_layers):
            x = ConformerBlock(cfg, name=f"block_{i}")(x, pos, mask,
                                                       deterministic)
        return x


class ConformerForASR(nn.Module):
    """Subsample + encoder + CTC logits head: (B, T, F) mel features ->
    ((B, T//4, vocab) log-probs, subsampled lengths)
    (ref ConformerForASRModule:277)."""
    config: ConformerConfig

    @nn.compact
    def __call__(self, features, lengths=None, deterministic=True):
        cfg = self.config
        x, lengths = ConvSubSample(cfg, name="subsample")(features, lengths)
        x = Conformer(cfg, name="encoder")(x, lengths, deterministic)
        logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype, name="head")(x)
        log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return log_probs, lengths
