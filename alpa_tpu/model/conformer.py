"""Conformer encoder (speech) for the model zoo.

Analog of ref ``alpa/model/conformer.py`` (314 LoC): conformer blocks =
half-step FFN, multi-head self-attention with relative-ish positions,
depthwise conv module, half-step FFN, all pre-norm with residuals.
"""
import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ConformerConfig:
    hidden_size: int = 256
    num_layers: int = 4
    num_heads: int = 4
    conv_kernel_size: int = 15
    ffn_ratio: int = 4
    dtype: Any = jnp.float32
    dropout_rate: float = 0.0


class FeedForwardModule(nn.Module):
    config: ConformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        h = nn.Dense(cfg.ffn_ratio * cfg.hidden_size, dtype=cfg.dtype)(h)
        h = nn.swish(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)(h)
        return h


class ConvModule(nn.Module):
    config: ConformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        h = nn.Dense(2 * cfg.hidden_size, dtype=cfg.dtype)(h)
        h = nn.glu(h, axis=-1)
        # depthwise conv over time
        h = nn.Conv(cfg.hidden_size, (cfg.conv_kernel_size,),
                    feature_group_count=cfg.hidden_size,
                    dtype=cfg.dtype)(h)
        h = nn.GroupNorm(num_groups=1, dtype=jnp.float32)(h)
        h = nn.swish(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)(h)
        return h


class ConformerBlock(nn.Module):
    config: ConformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        x = x + 0.5 * FeedForwardModule(cfg, name="ffn1")(x)
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        h = nn.MultiHeadDotProductAttention(num_heads=cfg.num_heads,
                                            dtype=cfg.dtype)(h, h)
        x = x + h
        x = x + ConvModule(cfg, name="conv")(x)
        x = x + 0.5 * FeedForwardModule(cfg, name="ffn2")(x)
        return nn.LayerNorm(dtype=jnp.float32)(x)


class Conformer(nn.Module):
    """Encoder: (B, T, F) features -> (B, T, H) representations."""
    config: ConformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="proj_in")(x)
        for i in range(cfg.num_layers):
            x = ConformerBlock(cfg, name=f"block_{i}")(x)
        return x
