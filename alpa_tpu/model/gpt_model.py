"""GPT-style decoder-only transformer (flax), TPU-first.

Clean-room analog of ref ``alpa/model/gpt_model.py`` (which wraps
``bert_model.py``'s encoder with a causal mask).  Design choices for TPU:

* bfloat16 activations/params option; fp32 layernorm + softmax accumulation,
* einsum-formulated attention so batch/head/seq dims are clean mesh targets
  for the auto-sharding planner,
* pluggable attention implementation (``attention_impl``):
  "reference" (jnp, XLA-fused) | "flash" (pallas kernel, ops/flash_attention)
  | "ring" (sequence-parallel ring attention over a mesh axis),
* optional ``mark_pipeline_boundary()`` between blocks for manual pipeline
  layer construction (ref ManualLayerOption),
* KV-cache threading for autoregressive serving (cache as explicit
  function inputs/outputs, mirroring ref examples/llm_serving/model/
  opt_model.py:605 init_cache_aval design).

The GPT ladder (125M..76B, ref benchmark/alpa/suite_manual_gpt.py:18-26) is
reproduced in ``gpt_specs``.
"""
import dataclasses
from functools import partial
from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from alpa_tpu.pipeline_parallel.primitive_def import mark_pipeline_boundary


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 51200
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    seq_len: int = 1024
    mlp_ratio: int = 4
    dtype: Any = jnp.float32
    dropout_rate: float = 0.0
    # "reference" | "flash" | "ring"
    attention_impl: str = "reference"
    # insert pipeline boundary markers every k blocks (0 = never)
    pipeline_boundary_every: int = 0
    # mesh axis name for ring attention (sequence parallel)
    sp_axis: Optional[str] = None
    tie_embeddings: bool = True
    # HF GPT-2 uses 1e-5 (transformers layer_norm_epsilon); flax default
    # 1e-6 makes HF-loaded weights diverge slightly
    layer_norm_eps: float = 1e-5
    # rematerialize each transformer block (training memory <-> flops)
    remat_blocks: bool = False
    # remat policy: None = save nothing (max memory savings, full
    # recompute); "dots" = save matmul outputs (bounded memory, skips
    # recomputing the MXU-heavy ops — usually the best throughput point)
    remat_policy: Optional[str] = None
    # decoder (causal) vs encoder (bidirectional, BERT-style)
    causal: bool = True
    # MLP activation: "gelu" (GPT-2) | "relu" (OPT)
    activation: str = "gelu"
    # learned-positional-table offset (OPT reserves the first 2 rows,
    # ref examples/llm_serving/model/opt_model.py position handling)
    pos_offset: int = 0


# The reference benchmark ladder: name -> (hidden, layers, heads)
# (ref benchmark/alpa/suite_manual_gpt.py:18-26; seq 1024, vocab 51200)
gpt_specs = {
    "125M": (768, 12, 12),
    "350M": (1024, 24, 16),
    "760M": (1536, 24, 16),
    "1.3B": (2048, 24, 32),
    "2.6B": (2560, 32, 32),
    "6.7B": (4096, 32, 32),
    "15B": (5120, 48, 40),
    "39B": (8192, 48, 64),
    "76B": (10240, 60, 80),
}


def config_from_spec(name: str, **kwargs) -> GPTConfig:
    hidden, layers, heads = gpt_specs[name]
    return GPTConfig(hidden_size=hidden, num_layers=layers, num_heads=heads,
                     **kwargs)


# OPT ladder: name -> (hidden, layers, heads); seq 2048, vocab 50272,
# relu MLP, +2 positional offset (ref examples/llm_serving/model/
# opt_model.py get_opt_config; 350m omitted — post-norm layout)
opt_specs = {
    "125m": (768, 12, 12),
    "1.3b": (2048, 24, 32),
    "2.7b": (2560, 32, 32),
    "6.7b": (4096, 32, 32),
    "13b": (5120, 40, 40),
    "30b": (7168, 48, 56),
    "66b": (9216, 64, 72),
    "175b": (12288, 96, 96),
}


def config_from_opt_spec(name: str, **kwargs) -> GPTConfig:
    """OPT-family GPTConfig (ref opt_model.py model table)."""
    hidden, layers, heads = opt_specs[name.lower().replace("opt-", "")]
    defaults = dict(vocab_size=50272, seq_len=2048, activation="relu",
                    pos_offset=2, tie_embeddings=True)
    defaults.update(kwargs)
    return GPTConfig(hidden_size=hidden, num_layers=layers,
                     num_heads=heads, **defaults)


def reference_attention(q, k, v, *, causal: bool, offset=0, bias=None):
    """Plain einsum attention; XLA fuses this well on TPU for short seqs.

    q: (B, Sq, H, D); k/v: (B, Sk, H, D).  fp32 softmax accumulation.
    ``offset`` shifts query positions for decode-with-cache; a scalar
    applies to every row, a (B,) vector gives per-row offsets (mixed
    prompt lengths in one continuously-batched decode).  ``bias`` is an
    fp32 additive score bias broadcastable to (B, H, Sq, Sk) — e.g. a
    padding mask for encoder models (BERT).
    """
    dim = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores / np.sqrt(dim)
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        offset = jnp.asarray(offset, jnp.int32)
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        if offset.ndim == 0:
            mask = (q_pos + offset >= k_pos)[None, None]     # (1,1,Sq,Sk)
        else:
            mask = (q_pos[None] + offset[:, None, None]
                    >= k_pos[None])[:, None]                 # (B,1,Sq,Sk)
        scores = jnp.where(mask, scores, jnp.float32(-1e9))
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def get_attention_fn(config: GPTConfig) -> Callable:
    if config.attention_impl == "flash":
        from alpa_tpu.ops.flash_attention import flash_attention
        return flash_attention
    if config.attention_impl == "ring":
        from alpa_tpu.ops.ring_attention import ring_attention
        return partial(ring_attention, axis_name=config.sp_axis)
    if config.attention_impl == "ulysses":
        from alpa_tpu.ops.ulysses_attention import ulysses_attention
        return partial(ulysses_attention, axis_name=config.sp_axis)
    return reference_attention


def update_kv_cache(kv_cache, k, v):
    """Write step K/V into a resident cache and return the attendable
    views — the mechanics shared by every decoder family (GPT/OPT,
    Bloom, CodeGen).

    ``kv_cache`` is (k_cache, v_cache, index) with a scalar index
    (uniform write position) or a (B,) vector (per-row positions for
    mixed-length continuous batching).  Returns
    ``(k_use, v_use, new_cache)`` where k_use/v_use are the full-length
    caches with unwritten positions zeroed (masked from attention by the
    caller's causal offset) and ``new_cache`` carries index + s.
    """
    k_cache, v_cache, index = kv_cache
    b, s = k.shape[0], k.shape[1]
    index = jnp.asarray(index, jnp.int32)
    if index.ndim == 0:
        k_full = jax.lax.dynamic_update_slice_in_dim(
            k_cache, k.astype(k_cache.dtype), index, axis=1)
        v_full = jax.lax.dynamic_update_slice_in_dim(
            v_cache, v.astype(v_cache.dtype), index, axis=1)
        keep_len = index + s
    else:
        rows = jnp.arange(b)[:, None]
        cols = index[:, None] + jnp.arange(s)[None, :]
        k_full = k_cache.at[rows, cols].set(k.astype(k_cache.dtype))
        v_full = v_cache.at[rows, cols].set(v.astype(v_cache.dtype))
        keep_len = (index + s)[:, None]
    pos = jax.lax.broadcasted_iota(jnp.int32, (k_full.shape[1],), 0)
    keep = pos < keep_len
    if keep.ndim == 1:
        keep = keep[None]
    k_use = jnp.where(keep[:, :, None, None], k_full,
                      jnp.zeros_like(k_full))
    v_use = jnp.where(keep[:, :, None, None], v_full,
                      jnp.zeros_like(v_full))
    return k_use, v_use, (k_full, v_full, index + s)


class SelfAttention(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x, kv_cache=None, deterministic=True,
                 attn_bias=None):
        cfg = self.config
        h, nh = cfg.hidden_size, cfg.num_heads
        hd = h // nh
        qkv = nn.Dense(3 * h, dtype=cfg.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        b, s = x.shape[0], x.shape[1]
        q = q.reshape(b, s, nh, hd)
        k = k.reshape(b, s, nh, hd)
        v = v.reshape(b, s, nh, hd)

        new_cache = None
        if kv_cache is not None:
            index = jnp.asarray(kv_cache[2], jnp.int32)
            k_use, v_use, new_cache = update_kv_cache(kv_cache, k, v)
            # scores to future positions masked by causal offset;
            # attn_bias (e.g. the packed-prefill segment mask) rides on
            # top of the causal mask over the full cache length
            out = reference_attention(q, k_use, v_use, causal=True,
                                      offset=index, bias=attn_bias)
        else:
            if attn_bias is not None:
                # additive padding/score bias: encoder path only (the
                # flash/ring kernels take no bias operand)
                out = reference_attention(q, k, v, causal=cfg.causal,
                                          bias=attn_bias)
            else:
                attn_fn = get_attention_fn(cfg)
                out = attn_fn(q, k, v, causal=cfg.causal)
        out = out.reshape(b, s, h)
        out = nn.Dense(h, dtype=cfg.dtype, name="out")(out)
        return out, new_cache


class MLPBlock(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        h = cfg.hidden_size
        x = nn.Dense(cfg.mlp_ratio * h, dtype=cfg.dtype, name="fc_in")(x)
        x = (nn.relu(x) if cfg.activation == "relu" else
             nn.gelu(x, approximate=True))
        x = nn.Dense(h, dtype=cfg.dtype, name="fc_out")(x)
        return x


class TransformerBlock(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x, kv_cache=None, deterministic=True,
                 attn_bias=None):
        cfg = self.config
        ln1 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                           name="ln1")(x)
        attn_out, new_cache = SelfAttention(cfg, name="attn")(
            ln1, kv_cache, deterministic, attn_bias)
        x = x + attn_out.astype(x.dtype)
        ln2 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                           name="ln2")(x)
        x = x + MLPBlock(cfg, name="mlp")(ln2).astype(x.dtype)
        return x, new_cache


class GPTModel(nn.Module):
    """Decoder-only LM.  Returns logits (and new kv caches if given)."""
    config: GPTConfig

    @nn.compact
    def __call__(self, input_ids, position_ids=None, kv_caches=None,
                 deterministic=True, return_hidden=False,
                 segment_ids=None):
        """``return_hidden=True`` returns the final (B, S, H) hidden states
        instead of logits, for a fused/chunked lm-head + loss (see
        model_util.chunked_cross_entropy_loss).

        ``segment_ids`` (B, S) int32 enables PACKED sequences: tokens only
        attend within their own segment (block-diagonal mask on top of
        causal); ids < 0 mark padding that attends to nothing.  This is
        the TPU-native analog of the reference's 1-D packed batching
        (ref opt_model_1d.py fused-MHA prompt packing): one row carries
        many prompts, masked by segments instead of a custom kernel.
        Pass per-segment ``position_ids`` so positional embeddings
        restart at each segment start.
        """
        cfg = self.config
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
        seg_bias = None
        if segment_ids is not None:
            if kv_caches is not None:
                # The packed chunk is written at the caches' current
                # (scalar) index — 0 for a fresh packed prefill, or the
                # prefix length when packing over a cached system prompt.
                # Keys before that offset are the shared prefix: visible
                # to EVERY real segment; keys past the chunk stay -2.
                cache_len = kv_caches[0][0].shape[1]
                start = jnp.asarray(kv_caches[0][2], jnp.int32)
                seg_k = jnp.full((b, cache_len), -2, jnp.int32)
                seg_k = jax.lax.dynamic_update_slice(
                    seg_k, segment_ids, (0, start))
                kpos = jax.lax.broadcasted_iota(
                    jnp.int32, (1, cache_len), 1)
                prefix_k = kpos < start                      # (1, L)
                same = ((segment_ids[:, :, None] == seg_k[:, None, :]) |
                        prefix_k[:, None, :]) & \
                    (segment_ids[:, :, None] >= 0)
            else:
                same = (segment_ids[:, :, None] ==
                        segment_ids[:, None, :]) & \
                    (segment_ids[:, :, None] >= 0)
            seg_bias = jnp.where(same, 0.0, -1e9)[:, None]  # (B,1,S,L)
        tok_emb = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                           dtype=cfg.dtype, name="wte")
        x = tok_emb(input_ids)
        x = x + nn.Embed(cfg.seq_len + cfg.pos_offset, cfg.hidden_size,
                         dtype=cfg.dtype,
                         name="wpe")(position_ids + cfg.pos_offset)
        block_cls = TransformerBlock
        if cfg.remat_blocks and kv_caches is None:
            policy = None
            if cfg.remat_policy == "dots":
                policy = jax.checkpoint_policies. \
                    dots_with_no_batch_dims_saveable
            elif cfg.remat_policy is not None:
                raise ValueError(
                    f"unknown remat_policy {cfg.remat_policy!r}")
            # Under nn.remat the module instance is arg 0, so the call
            # (x, cache_i, deterministic, seg_bias) puts kv_cache at 2
            # and deterministic at 3 — mark BOTH static; attn_bias (4)
            # stays a traced pytree (None or the packed segment mask)
            block_cls = nn.remat(TransformerBlock,
                                 static_argnums=(2, 3),
                                 policy=policy)
        new_caches = [] if kv_caches is not None else None
        for i in range(cfg.num_layers):
            if (cfg.pipeline_boundary_every and i > 0 and
                    i % cfg.pipeline_boundary_every == 0):
                mark_pipeline_boundary()
            cache_i = kv_caches[i] if kv_caches is not None else None
            x, new_cache = block_cls(cfg, name=f"h{i}")(
                x, cache_i, deterministic, seg_bias)
            if new_caches is not None:
                new_caches.append(new_cache)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         name="ln_f")(x)
        if return_hidden:
            return x
        if cfg.tie_embeddings:
            logits = tok_emb.attend(x.astype(cfg.dtype))
        else:
            logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype,
                              use_bias=False, name="lm_head")(x)
        if new_caches is not None:
            return logits, new_caches
        return logits


def init_kv_caches(config: GPTConfig, batch_size: int,
                   dtype=None) -> list:
    """KV caches as explicit arrays (ref opt_model.py:605 init_cache_aval)."""
    dtype = dtype or config.dtype
    hd = config.hidden_size // config.num_heads
    shape = (batch_size, config.seq_len, config.num_heads, hd)
    return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
             jnp.int32(0)) for _ in range(config.num_layers)]


def init_gpt(config: GPTConfig, batch_size: int, rngkey=None):
    """Initialize model + params on host."""
    rngkey = rngkey if rngkey is not None else jax.random.PRNGKey(0)
    model = GPTModel(config)
    dummy = jnp.ones((batch_size, config.seq_len), jnp.int32)
    params = jax.eval_shape(model.init, rngkey, dummy)
    params = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), params)
    return model, params


def init_gpt_real(config: GPTConfig, batch_size: int, rngkey=None):
    rngkey = rngkey if rngkey is not None else jax.random.PRNGKey(0)
    model = GPTModel(config)
    dummy = jnp.ones((batch_size, config.seq_len), jnp.int32)
    params = model.init(rngkey, dummy)
    return model, params
