"""Persistent compile cache: content-addressed, two-tier (memory + disk).

Every process restart used to re-run layer clustering, stage DP, and the
per-stage ILP from scratch (ISSUE 2): the solver output is a pure function
of (jaxpr, mesh shape, AutoShardingOption, jax version), so it is safe to
persist and replay.  This module provides the shared cache those compile
phases write through:

* ``ilp`` namespace — auto-sharding solutions from
  ``shard_parallel/solver.py::plan_auto_sharding`` (chosen logical mesh
  shape + the one-hot strategy vector).
* ``stage_dp`` namespace — stage-construction decisions from
  ``stage_construction.py::cluster_layers_and_slice_mesh`` (layer->stage
  clustering + submesh shapes + per-stage autosharding dicts).
* ``parallel_plan`` namespace — replayable ``ParallelPlan`` artifacts
  saved by ``api.parallelize`` after each compile.
* ``superopt`` namespace — accepted certified-superoptimization rewrite
  layouts (``analysis/superopt.py``), keyed by baseline program
  fingerprint + calibration-store fingerprint + search knobs, so warm
  restarts replay the winning rewrite with zero search.

Keying: sha256 over a canonical fingerprint of every input that shapes the
answer, ALWAYS including ``jax.__version__`` and a format version — a jax
upgrade or a cache-layout change invalidates everything, never corrupts.

Tiers: an in-memory LRU (process lifetime) in front of an on-disk pickle
store under ``global_config.compile_cache_dir`` (env ``ALPA_TPU_CACHE_DIR``).
With no directory configured the cache is memory-only: warm *in-process*
recompiles still hit, nothing touches the filesystem, and tests stay
hermetic.  Disk writes are atomic (tempfile + rename) so concurrent
processes sharing a cache dir can only ever read complete entries.

Counters (hits / misses / puts / solve seconds spent vs saved) are
per-namespace and surfaced through ``monitoring.get_compile_cache_stats``.
"""
import collections
import dataclasses
import hashlib
import logging
import os
import pickle
import re
import tempfile
import threading
from typing import Any, Dict, List, Optional, Sequence

from alpa_tpu.telemetry import metrics as _tmetrics
from alpa_tpu.telemetry import trace as _ttrace

logger = logging.getLogger(__name__)

# ``str(jaxpr)`` embeds live function addresses (e.g. custom_jvp's
# ``jvp_jaxpr_thunk=<function ... at 0x7f...>``); mask them so the same
# program fingerprints identically across traces and processes.
_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")

# Bump to invalidate every persisted entry on cache-format changes.
# v2 (ISSUE 4): register-file lowering became dataflow-graph aware
# (overlap dispatch), so plans cached against the v1 instruction
# semantics must never hit; disk payloads are now wrapped in a
# ``{"__cache_format__": N, "payload": ...}`` envelope so tooling can
# report which on-disk entries carry the current format.
CACHE_FORMAT_VERSION = 2


def _jax_version() -> str:
    import jax
    return jax.__version__


def fingerprint_parts(parts: Sequence[Any]) -> str:
    """Canonical content fingerprint of heterogeneous key parts.

    Strings pass through; dataclasses expand to sorted field reprs (stable
    across processes, unlike default ``repr`` which may embed addresses);
    everything else uses ``repr``.  Each part is length-prefixed so
    adjacent parts cannot collide by concatenation.
    """
    h = hashlib.sha256()
    h.update(f"v{CACHE_FORMAT_VERSION}:jax={_jax_version()}".encode())
    for p in parts:
        if dataclasses.is_dataclass(p) and not isinstance(p, type):
            s = "{}({})".format(
                type(p).__name__,
                ",".join(f"{k}={v!r}" for k, v in
                         sorted(dataclasses.asdict(p).items())))
        elif isinstance(p, str):
            s = p
        else:
            s = repr(p)
        b = _ADDR_RE.sub("0x0", s).encode()
        h.update(f"|{len(b)}|".encode())
        h.update(b)
    return h.hexdigest()


def read_entry_format(path: str) -> Optional[int]:
    """The cache-format version a disk entry was written with: the
    envelope's ``__cache_format__`` for v2+ entries, 1 for bare legacy
    payloads (pre-dataflow-graph lowering), None if unreadable."""
    try:
        with open(path, "rb") as f:
            value = pickle.load(f)
    except Exception:  # pylint: disable=broad-except
        return None
    if isinstance(value, dict) and "__cache_format__" in value:
        try:
            return int(value["__cache_format__"])
        except (TypeError, ValueError):
            return None
    return 1


@dataclasses.dataclass
class NamespaceStats:
    """Hit/miss accounting for one cache namespace."""
    hits: int = 0
    misses: int = 0
    puts: int = 0
    disk_hits: int = 0
    # seconds spent producing entries that were then stored (the cost a
    # future hit avoids) and seconds a hit demonstrably skipped
    solve_seconds: float = 0.0
    saved_seconds: float = 0.0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "puts": self.puts,
            "disk_hits": self.disk_hits,
            "solve_seconds": round(self.solve_seconds, 4),
            "saved_seconds": round(self.saved_seconds, 4),
        }


class CompileCache:
    """Two-tier (LRU memory + optional disk) content-addressed cache."""

    def __init__(self, cache_dir: Optional[str] = None,
                 memory_entries: int = 128):
        self.cache_dir = cache_dir or None
        self.memory_entries = memory_entries
        self._mem: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self._stats: Dict[str, NamespaceStats] = {}

    # -- keying --------------------------------------------------------

    def make_key(self, namespace: str, parts: Sequence[Any]) -> str:
        return f"{namespace}-{fingerprint_parts(parts)}"

    # -- stats ---------------------------------------------------------

    def _ns_stats(self, namespace: str) -> NamespaceStats:
        return self._stats.setdefault(namespace, NamespaceStats())

    def record_solve_seconds(self, namespace: str, seconds: float):
        with self._lock:
            self._ns_stats(namespace).solve_seconds += seconds

    def record_saved_seconds(self, namespace: str, seconds: float):
        with self._lock:
            self._ns_stats(namespace).saved_seconds += seconds

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "cache_dir": self.cache_dir,
                "memory_entries": len(self._mem),
                "namespaces": {ns: s.as_dict()
                               for ns, s in sorted(self._stats.items())},
            }

    # -- storage -------------------------------------------------------

    def _path_of(self, key: str) -> Optional[str]:
        if not self.cache_dir:
            return None
        return os.path.join(self.cache_dir, key + ".pkl")

    def get(self, namespace: str, key: str) -> Optional[Any]:
        """The cached value, or None.  Memory tier first, then disk;
        a disk hit is promoted into the memory tier."""
        with self._lock:
            if key in self._mem:
                self._mem.move_to_end(key)
                self._ns_stats(namespace).hits += 1
                _ttrace.instant("compile-cache.hit", "compile",
                                {"namespace": namespace}
                                if _ttrace.enabled() else None)
                return self._mem[key]
        path = self._path_of(key)
        if path and os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    value = pickle.load(f)
                # v2 envelope; tolerate bare legacy payloads (their keys
                # embed the old format version so they can only be read
                # by explicit tooling, never hit by lookups)
                if isinstance(value, dict) and "__cache_format__" in value:
                    value = value["payload"]
            except Exception as e:  # pylint: disable=broad-except
                # a truncated/stale entry is a miss, never an error
                logger.warning("compile cache entry %s unreadable (%s); "
                               "dropping", path, e)
                try:
                    os.remove(path)
                except OSError:
                    pass
                with self._lock:
                    self._ns_stats(namespace).misses += 1
                return None
            with self._lock:
                st = self._ns_stats(namespace)
                st.hits += 1
                st.disk_hits += 1
                self._insert_mem(key, value)
            _ttrace.instant("compile-cache.disk-hit", "compile",
                            {"namespace": namespace}
                            if _ttrace.enabled() else None)
            return value
        with self._lock:
            self._ns_stats(namespace).misses += 1
        _ttrace.instant("compile-cache.miss", "compile",
                        {"namespace": namespace}
                        if _ttrace.enabled() else None)
        return None

    def put(self, namespace: str, key: str, value: Any):
        with self._lock:
            self._insert_mem(key, value)
            self._ns_stats(namespace).puts += 1
        path = self._path_of(key)
        if not path:
            return
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.cache_dir,
                                       prefix=".tmp-" + namespace)
            try:
                with os.fdopen(fd, "wb") as f:
                    pickle.dump({"__cache_format__": CACHE_FORMAT_VERSION,
                                 "payload": value}, f)
                os.replace(tmp, path)  # atomic publish
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
        except Exception as e:  # pylint: disable=broad-except
            # the disk tier is an optimization; a read-only or full disk
            # must never fail compilation
            logger.warning("compile cache write %s failed: %s", path, e)

    def _insert_mem(self, key: str, value: Any):
        self._mem[key] = value
        self._mem.move_to_end(key)
        while len(self._mem) > self.memory_entries:
            self._mem.popitem(last=False)

    # -- maintenance ---------------------------------------------------

    def entries(self) -> List[Dict[str, Any]]:
        """Disk-tier inventory (for scripts/cache_tool.py)."""
        out = []
        if not self.cache_dir or not os.path.isdir(self.cache_dir):
            return out
        for name in sorted(os.listdir(self.cache_dir)):
            if not name.endswith(".pkl"):
                continue
            path = os.path.join(self.cache_dir, name)
            ns, _, rest = name.rpartition(".pkl")[0].partition("-")
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append({
                "namespace": ns,
                "key": rest,
                "bytes": st.st_size,
                "mtime": st.st_mtime,
                "path": path,
            })
        return out

    def clear(self, namespace: Optional[str] = None,
              memory_only: bool = False) -> int:
        """Drop entries (all, or one namespace).  Returns the number of
        disk entries removed."""
        with self._lock:
            if namespace is None:
                self._mem.clear()
            else:
                for k in [k for k in self._mem
                          if k.startswith(namespace + "-")]:
                    del self._mem[k]
        removed = 0
        if memory_only:
            return removed
        for e in self.entries():
            if namespace is None or e["namespace"] == namespace:
                try:
                    os.remove(e["path"])
                    removed += 1
                except OSError:
                    pass
        return removed


# ---------------------------------------------------------------------
# process-global instance
# ---------------------------------------------------------------------

_global_cache: Optional[CompileCache] = None
_global_lock = threading.Lock()


def get_compile_cache() -> CompileCache:
    """The process-global cache, built from ``global_config`` on first
    use.  ``reset_compile_cache()`` rebuilds it (tests; dir changes)."""
    global _global_cache
    with _global_lock:
        if _global_cache is None:
            from alpa_tpu.global_env import global_config
            _global_cache = CompileCache(
                cache_dir=global_config.compile_cache_dir,
                memory_entries=global_config.compile_cache_memory_entries)
        return _global_cache


def reset_compile_cache(cache: Optional[CompileCache] = None):
    """Install ``cache`` (or lazily rebuild from global_config).  Used by
    the pytest fixture to isolate the cache dir per test, and by callers
    after changing ``global_config.compile_cache_dir``."""
    global _global_cache
    with _global_lock:
        _global_cache = cache


def cache_enabled() -> bool:
    from alpa_tpu.global_env import global_config
    return bool(global_config.compile_cache_enabled)


# ---------------------------------------------------------------------
# metrics registry export (ISSUE 5)
# ---------------------------------------------------------------------
# The cache object is swapped per-test (reset_compile_cache), so the
# registry cannot hold counters directly — a collector pulls the LIVE
# instance's per-namespace stats into gauges at collect time, keeping
# GET /metrics truthful without breaking per-test isolation.

_REG = _tmetrics.get_registry()
_CC_MEMORY = _REG.gauge(
    "alpa_compile_cache_memory_entries",
    "Entries resident in the compile cache memory tier")
_CC_NS_GAUGES = {
    k: _REG.gauge(f"alpa_compile_cache_{k}", d, labelnames=("namespace",))
    for k, d in (
        ("hits", "Compile cache hits (memory + disk)"),
        ("disk_hits", "Compile cache hits served from the disk tier"),
        ("misses", "Compile cache misses"),
        ("puts", "Compile cache stores"),
        ("solve_seconds", "Seconds spent on solves whose results were "
                          "cached"),
        ("saved_seconds", "Solve seconds demonstrably skipped by hits"),
    )
}


def _collect_compile_cache(_registry):
    cache = _global_cache
    if cache is None:
        _CC_MEMORY.set(0)
        return
    st = cache.stats()
    _CC_MEMORY.set(st["memory_entries"])
    for fam in _CC_NS_GAUGES.values():
        fam.reset()   # drop namespaces from a previously-installed cache
    for ns, d in st["namespaces"].items():
        for k, fam in _CC_NS_GAUGES.items():
            fam.labels(ns).set(d[k])


_REG.register_collector(_collect_compile_cache)
