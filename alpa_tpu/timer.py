"""Named timers and an event tracer.

TPU-native analog of the reference ``alpa/timer.py:7-94``.  ``sync_func`` on
TPU blocks on outstanding device work via ``jax.block_until_ready`` /
``jax.effects_barrier`` rather than cudaDeviceSynchronize.
"""
import time
from collections import namedtuple

TracerEvent = namedtuple("TracerEvent", ("tstamp", "name", "info"))


class _Timer:
    """A named timer with start/stop/elapsed, mirroring ref timer semantics."""

    def __init__(self, name: str):
        self.name = name
        self.started = False
        self.start_time = None
        # Each (start, stop) pair adds one entry.
        self.costs = []

    def start(self, sync_func=None):
        assert not self.started, f"timer {self.name} already started"
        if sync_func:
            sync_func()
        self.start_time = time.perf_counter()
        self.started = True

    def stop(self, sync_func=None):
        assert self.started, f"timer {self.name} not started"
        if sync_func:
            sync_func()
        self.costs.append(time.perf_counter() - self.start_time)
        self.started = False

    def reset(self):
        self.started = False
        self.start_time = None
        self.costs = []

    def elapsed(self, mode: str = "average"):
        if not self.costs:
            return 0.0
        if mode == "average":
            return sum(self.costs) / len(self.costs)
        if mode == "sum":
            return sum(self.costs)
        if mode == "last":
            return self.costs[-1]
        raise ValueError(f"unknown mode {mode}")

    def log(self, mode: str = "average", normalizer: float = 1.0):
        print(f"timer {self.name}: {self.elapsed(mode) / normalizer:.6f} s")


class Timers:
    """A registry of named timers (ref: alpa/timer.py Timers)."""

    def __init__(self):
        self.timers = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def __contains__(self, name: str):
        return name in self.timers

    def reset_all(self):
        for t in self.timers.values():
            t.reset()

    def log(self, names=None, mode="average", normalizer=1.0):
        for name in (names or self.timers):
            self.timers[name].log(mode, normalizer)


class Tracer:
    """Timestamped event log, dumpable as a Chrome trace
    (ref: alpa/timer.py:81-94 + pipeshard_executable.py:592).

    .. deprecated::
        Kept as a compatibility shim over the unified telemetry layer
        (``alpa_tpu.telemetry``): when tracing is enabled, every
        ``log()`` is mirrored into the process ``TraceRecorder`` as a
        ``legacy``-category instant, so old call sites land in the same
        merged Perfetto trace as span-instrumented code.  New code
        should use ``telemetry.trace`` directly.
    """

    def __init__(self):
        self.events = []

    def log(self, name: str, info: str = ""):
        self.events.append(TracerEvent(time.time(), name, info))
        # bridge into the unified trace (no-op when tracing is off);
        # imported lazily so ``alpa_tpu.timer`` stays importable alone
        from alpa_tpu.telemetry import trace as _ttrace
        if _ttrace.enabled():
            _ttrace.instant(name, "legacy",
                            {"info": info} if info else None)

    def clear(self):
        self.events = []

    def to_chrome_trace(self, pid: int = 0):
        """Render events as Chrome trace 'instant' records.

        .. deprecated:: prefer ``telemetry.trace.TraceRecorder.
           to_chrome_trace()``, which carries spans and counters too.
        """
        return [{
            "name": ev.name,
            "ph": "i",
            "ts": ev.tstamp * 1e6,
            "pid": pid,
            "tid": 0,
            "args": {"info": ev.info},
        } for ev in self.events]


timers = Timers()
tracer = Tracer()
