"""Deprecation bridge over the unified telemetry layer.

.. deprecated::
    ``alpa_tpu.timer`` predates ``alpa_tpu.telemetry`` (the reference's
    ``alpa/timer.py:7-94``).  The runtime no longer uses it — dispatch
    latency lives in the ``alpa_pipeshard_dispatch_seconds`` /
    ``alpa_mesh_dispatch_seconds`` histograms and step timelines in
    ``telemetry.trace`` — but the module stays importable for
    third-party call sites:

    * ``timers(name).start()/.stop()`` keeps working and additionally
      mirrors each measured interval into the central metrics registry
      as the ``alpa_legacy_timer_seconds{name}`` histogram, so legacy
      timings show up on GET /metrics next to everything else.
    * ``tracer.log(...)`` keeps its local event list and mirrors into
      the process ``TraceRecorder`` as a ``legacy``-category instant
      when tracing is enabled (same merged Perfetto trace as
      span-instrumented code).

    New code should use ``alpa_tpu.telemetry.metrics`` / ``.trace``.
"""
import time
from collections import namedtuple

TracerEvent = namedtuple("TracerEvent", ("tstamp", "name", "info"))


def _legacy_histogram():
    # lazy so ``alpa_tpu.timer`` stays importable alone
    from alpa_tpu.telemetry import metrics as _tmetrics
    return _tmetrics.get_registry().histogram(
        "alpa_legacy_timer_seconds",
        "Intervals measured through the deprecated alpa_tpu.timer bridge",
        labelnames=("name",))


class _Timer:
    """A named timer with start/stop/elapsed (deprecated; kept for API
    compatibility — each stop also feeds the telemetry histogram)."""

    def __init__(self, name: str):
        self.name = name
        self.started = False
        self.start_time = None
        # Each (start, stop) pair adds one entry.
        self.costs = []

    def start(self, sync_func=None):
        assert not self.started, f"timer {self.name} already started"
        if sync_func:
            sync_func()
        self.start_time = time.perf_counter()
        self.started = True

    def stop(self, sync_func=None):
        assert self.started, f"timer {self.name} not started"
        if sync_func:
            sync_func()
        dt = time.perf_counter() - self.start_time
        self.costs.append(dt)
        self.started = False
        try:
            _legacy_histogram().labels(self.name).observe(dt)
        except Exception:  # pylint: disable=broad-except
            pass

    def reset(self):
        self.started = False
        self.start_time = None
        self.costs = []

    def elapsed(self, mode: str = "average"):
        if not self.costs:
            return 0.0
        if mode == "average":
            return sum(self.costs) / len(self.costs)
        if mode == "sum":
            return sum(self.costs)
        if mode == "last":
            return self.costs[-1]
        raise ValueError(f"unknown mode {mode}")

    def log(self, mode: str = "average", normalizer: float = 1.0):
        print(f"timer {self.name}: {self.elapsed(mode) / normalizer:.6f} s")


class Timers:
    """A registry of named timers (deprecated shim)."""

    def __init__(self):
        self.timers = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def __contains__(self, name: str):
        return name in self.timers

    def reset_all(self):
        for t in self.timers.values():
            t.reset()

    def log(self, names=None, mode="average", normalizer=1.0):
        for name in (names or self.timers):
            self.timers[name].log(mode, normalizer)


class Tracer:
    """Timestamped event log, dumpable as a Chrome trace (deprecated
    shim: when tracing is enabled every ``log()`` is mirrored into the
    process ``TraceRecorder`` as a ``legacy``-category instant)."""

    def __init__(self):
        self.events = []

    def log(self, name: str, info: str = ""):
        self.events.append(TracerEvent(time.time(), name, info))
        from alpa_tpu.telemetry import trace as _ttrace
        if _ttrace.enabled():
            _ttrace.instant(name, "legacy",
                            {"info": info} if info else None)

    def clear(self):
        self.events = []

    def to_chrome_trace(self, pid: int = 0):
        """Render events as Chrome trace 'instant' records (deprecated:
        prefer ``telemetry.trace.TraceRecorder.to_chrome_trace()``)."""
        return [{
            "name": ev.name,
            "ph": "i",
            "ts": ev.tstamp * 1e6,
            "pid": pid,
            "tid": 0,
            "args": {"info": ev.info},
        } for ev in self.events]


timers = Timers()
tracer = Tracer()
