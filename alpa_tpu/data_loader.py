"""Data loaders that place batches directly with the right sharding.

Analog of ref ``alpa/data_loader.py`` (SURVEY.md §2.8): ``DataLoader``
shards host batches onto the mesh with background prefetch;
``MeshDriverDataLoader`` takes the placement from a compiled executable so
batches land exactly where the train step expects them (ref
MeshDriverDataLoader:97 — the per-host-iterator pull model collapses into
the single-controller device_put, which on TPU pods already writes only
each host's addressable shards).
"""
import collections
import itertools
import logging
import queue
import threading
from typing import Any, Callable, Iterator, Optional, Sequence

import jax
import numpy as np

logger = logging.getLogger(__name__)


class DataLoader:
    """Wrap a host-side iterator; device_put each batch with a sharding,
    prefetching ``prefetch_size`` batches ahead (ref DataLoader:15)."""

    def __init__(self,
                 input_iter_func: Callable[[], Iterator],
                 shardings: Any,
                 prefetch_size: int = 2):
        self.input_iter_func = input_iter_func
        self.shardings = shardings
        self.prefetch_size = prefetch_size

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_size)
        stop = object()

        def worker():
            try:
                for batch in self.input_iter_func():
                    placed = jax.tree_util.tree_map(
                        lambda x, s: jax.device_put(x, s), batch,
                        self.shardings,
                        is_leaf=lambda x: isinstance(x, np.ndarray))
                    q.put(placed)
            finally:
                q.put(stop)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is stop:
                break
            yield item


class MeshDriverDataLoader(DataLoader):
    """DataLoader whose shardings come from a compiled executable's batch
    argument placement (ref MeshDriverDataLoader:97)."""

    def __init__(self,
                 batch_size: int,
                 num_samples: int,
                 input_iter_func: Callable,
                 placement_specs: Any,
                 prefetch_size: int = 2):
        self.batch_size = batch_size
        self.num_samples = num_samples

        def iter_func():
            return input_iter_func(0, num_samples, batch_size)

        super().__init__(iter_func, placement_specs, prefetch_size)


def get_batch_shardings(executable, batch_argnums: Sequence[int] = (1,)):
    """Extract the shardings of an executable's batch args, as a flat list
    in argument order (pair with the batch pytree on the user side)."""
    out = []
    for i, (aval, s) in enumerate(zip(executable.in_avals,
                                      executable.in_shardings)):
        out.append(s)
    return out
