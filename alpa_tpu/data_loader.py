"""Data loaders that place batches directly with the right sharding.

Analog of ref ``alpa/data_loader.py`` (SURVEY.md §2.8): ``DataLoader``
shards host batches onto the mesh with background prefetch;
``MeshDriverDataLoader`` takes the placement from a compiled executable so
batches land exactly where the train step expects them (ref
MeshDriverDataLoader:97 — the per-host-iterator pull model collapses into
the single-controller device_put, which on TPU pods already writes only
each host's addressable shards).
"""
import collections
import itertools
import logging
import queue
import threading
from typing import Any, Callable, Iterator, Optional, Sequence

import jax
import numpy as np

logger = logging.getLogger(__name__)


def _prefetch_iter(producer: Callable[[], Iterator], prefetch_size: int):
    """Drain ``producer()`` through a bounded queue on a daemon thread.
    Worker exceptions are re-raised in the consumer — a failing loader
    must not look like a (short) completed epoch."""
    q: "queue.Queue" = queue.Queue(maxsize=prefetch_size)
    stop = object()

    def worker():
        try:
            for item in producer():
                q.put((None, item))
        except BaseException as e:  # pylint: disable=broad-except
            q.put((e, None))
        q.put((None, stop))

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    while True:
        err, item = q.get()
        if err is not None:
            raise err
        if item is stop:
            break
        yield item


class DataLoader:
    """Wrap a host-side iterator; device_put each batch with a sharding,
    prefetching ``prefetch_size`` batches ahead (ref DataLoader:15)."""

    def __init__(self,
                 input_iter_func: Callable[[], Iterator],
                 shardings: Any,
                 prefetch_size: int = 2):
        self.input_iter_func = input_iter_func
        self.shardings = shardings
        self.prefetch_size = prefetch_size

    def __iter__(self):

        def produce():
            for batch in self.input_iter_func():
                yield jax.tree_util.tree_map(
                    lambda x, s: jax.device_put(x, s), batch,
                    self.shardings,
                    is_leaf=lambda x: isinstance(x, np.ndarray))

        yield from _prefetch_iter(produce, self.prefetch_size)


class MeshDriverDataLoader(DataLoader):
    """DataLoader whose shardings come from a compiled executable's batch
    argument placement (ref MeshDriverDataLoader:97)."""

    def __init__(self,
                 batch_size: int,
                 num_samples: int,
                 input_iter_func: Callable,
                 placement_specs: Any,
                 prefetch_size: int = 2):
        self.batch_size = batch_size
        self.num_samples = num_samples

        def iter_func():
            return input_iter_func(0, num_samples, batch_size)

        super().__init__(iter_func, placement_specs, prefetch_size)


class DistributedDataLoader:
    """Per-host shard loading (ref MeshWorkerDataLoader:229): every process
    materializes ONLY the batch rows its addressable devices hold, via
    ``jax.make_array_from_callback`` — no host ever sees the global batch.

    ``row_loader(start, stop) -> np.ndarray`` returns rows [start, stop) of
    the current batch; it is called once per addressable shard with that
    shard's global row range.  Iterating the loader advances the epoch:
    step k calls ``next_batch_fn(k) -> row_loader``.
    """

    def __init__(self,
                 global_batch_shape: Sequence[int],
                 sharding: Any,
                 next_batch_fn: Callable[[int], Callable],
                 num_batches: int,
                 dtype=np.float32,
                 prefetch_size: int = 2):
        self.global_batch_shape = tuple(global_batch_shape)
        self.sharding = sharding
        self.next_batch_fn = next_batch_fn
        self.num_batches = num_batches
        self.dtype = dtype
        self.prefetch_size = prefetch_size
        self.rows_loaded = 0  # this process's loaded row count (telemetry)

    def _make(self, step: int):
        row_loader = self.next_batch_fn(step)

        def cb(index):
            # index: global ndarray index of one addressable shard
            rows = index[0]
            start = rows.start or 0
            stop = (rows.stop if rows.stop is not None else
                    self.global_batch_shape[0])
            data = np.asarray(row_loader(start, stop), self.dtype)
            self.rows_loaded += stop - start
            rest = index[1:]
            return data[(slice(None),) + tuple(rest)] if rest else data

        return jax.make_array_from_callback(self.global_batch_shape,
                                            self.sharding, cb)

    def __iter__(self):

        def produce():
            for step in range(self.num_batches):
                yield self._make(step)

        yield from _prefetch_iter(produce, self.prefetch_size)


def get_batch_shardings(executable, batch_argnums: Sequence[int] = (1,)):
    """Extract the shardings of an executable's batch args, as a flat list
    in argument order (pair with the batch pytree on the user side)."""
    out = []
    for i, (aval, s) in enumerate(zip(executable.in_avals,
                                      executable.in_shardings)):
        out.append(s)
    return out
