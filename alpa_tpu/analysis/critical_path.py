"""Critical-path analysis over measured instruction timings (ISSUE 9).

Pure data layer under ``telemetry/perf.py``: no jax, no recorder — the
inputs are :class:`TimedOp` samples (one per replayed op, timestamps on
the shared trace epoch) plus optional causal predecessor sets derived
from the lowering-time :class:`~alpa_tpu.pipeline_parallel.
runtime_emitter.InstructionDataflowGraph`.  Two complementary models:

* **Measured walk** (:func:`measured_critical_path`) — backward walk
  over the *observed* timeline: from the op that retires last, repeatedly
  step to the op whose completion gated the current op's start.  Causal
  edges (dataflow preds, same-track order) win when they bind; otherwise
  the latest earlier finisher anywhere binds (the driver serializes op
  dispatch, which is a real resource edge even though the dataflow graph
  does not carry it).  The resulting chain spans the step envelope —
  op time on the chain plus attributed gaps equals the envelope — so
  per-op *share* answers "where did the step go".

* **DAG re-simulation** (:func:`simulate_dag` / :func:`whatif`) — replay
  the dependency DAG with per-op durations under an idealized
  infinitely-parallel driver (causal edges only).  This is the what-if
  engine: zero a chosen op class and compare makespans ("if this RESHARD
  were free, step time −X%").  Zeroing never increases the makespan, and
  zeroing an op off the simulated critical path helps at most as much as
  zeroing the path's binding ops.
"""
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "TimedOp", "PathStep", "CriticalPathReport", "MemSpec",
    "measured_critical_path", "simulate_dag", "longest_path", "whatif",
]

# clock-jitter tolerance when deciding whether a candidate predecessor's
# completion "touches" the current op's start (microseconds)
_EPS_US = 2.0


@dataclasses.dataclass(frozen=True)
class TimedOp:
    """One measured op: a span joined back to its replayed instruction."""
    idx: int                 # position in the analyzed op list
    name: str                # span label ("RUN stage_0", "WAIT ...", ...)
    kind: str                # "exec" | "launch" | "wait"
    track: str               # "mesh 0", "mesh 1", ...
    t0_us: float
    t1_us: float

    @property
    def dur_us(self) -> float:
        return self.t1_us - self.t0_us


@dataclasses.dataclass
class PathStep:
    """One link of the critical path, earliest first."""
    op: TimedOp
    gap_us: float = 0.0      # idle between the previous link's finish
                             # and this op's start
    via: str = "start"       # "start" | "dep" | "track" | "issue"
    share: float = 0.0       # op duration / path op-time total


@dataclasses.dataclass
class CriticalPathReport:
    envelope_us: float       # measured step envelope
    total_us: float          # op time on the path
    gap_us: float            # attributed idle on the path
    steps: List[PathStep]

    @property
    def coverage(self) -> float:
        """(path op time + gaps) / envelope — ~1.0 when the walk spans
        the whole step (the perf_tool acceptance check)."""
        if self.envelope_us <= 0:
            return 0.0
        return (self.total_us + self.gap_us) / self.envelope_us

    def top(self, k: int) -> List[PathStep]:
        return sorted(self.steps, key=lambda s: -s.op.dur_us)[:k]

    def by_kind(self) -> Dict[str, float]:
        """Path op time per op kind (exec/launch/wait), microseconds."""
        acc: Dict[str, float] = {}
        for s in self.steps:
            acc[s.op.kind] = acc.get(s.op.kind, 0.0) + s.op.dur_us
        return acc

    def format_table(self, top: int = 10) -> str:
        lines = [
            f"critical path: {self.total_us:.1f} us op time + "
            f"{self.gap_us:.1f} us gaps over a {self.envelope_us:.1f} us "
            f"envelope ({100.0 * self.coverage:.1f}% coverage, "
            f"{len(self.steps)} ops)",
            f"{'share':>7}  {'dur_us':>10}  {'via':>5}  "
            f"{'track':<8} name",
        ]
        for s in self.top(top):
            lines.append(
                f"{100.0 * s.share:6.2f}%  {s.op.dur_us:10.1f}  "
                f"{s.via:>5}  {s.op.track:<8} {s.op.name}")
        return "\n".join(lines)


def _finalize(steps: List[PathStep],
              envelope_us: float) -> CriticalPathReport:
    total = sum(s.op.dur_us for s in steps)
    gaps = sum(s.gap_us for s in steps)
    if total > 0:
        for s in steps:
            s.share = s.op.dur_us / total
    return CriticalPathReport(envelope_us=envelope_us, total_us=total,
                              gap_us=gaps, steps=steps)


def measured_critical_path(
        ops: Sequence[TimedOp],
        preds_of: Optional[Dict[int, Iterable[int]]] = None,
        envelope_us: Optional[float] = None,
        eps_us: float = _EPS_US) -> CriticalPathReport:
    """Backward walk over the measured timeline (module docstring).

    ``preds_of`` maps op idx -> causal predecessor op idxs (dataflow
    edges mapped into op space).  Same-track order and driver issue
    order are always candidate edges; causal edges win ties so the path
    reads as dependencies, not dispatch accidents.
    """
    if not ops:
        return CriticalPathReport(envelope_us or 0.0, 0.0, 0.0, [])
    preds_of = preds_of or {}
    by_idx = {o.idx: o for o in ops}
    # issue order: strictly increasing position guarantees the walk
    # terminates even with zero-duration or clock-jittered spans
    order = sorted(ops, key=lambda o: (o.t0_us, o.t1_us, o.idx))
    pos = {o.idx: i for i, o in enumerate(order)}
    if envelope_us is None:
        envelope_us = (max(o.t1_us for o in ops) -
                       min(o.t0_us for o in ops))
    # prefix max of t1 over issue order, for the O(1) "latest earlier
    # finisher" fallback
    best_prefix: List[TimedOp] = []
    best = None
    for o in order:
        if best is None or o.t1_us > best.t1_us:
            best = o
        best_prefix.append(best)
    last_on_track: Dict[str, List[TimedOp]] = {}
    for o in order:
        last_on_track.setdefault(o.track, []).append(o)

    cur = max(ops, key=lambda o: (o.t1_us, o.idx))
    steps: List[PathStep] = [PathStep(op=cur)]
    while pos[cur.idx] > 0:
        limit = cur.t0_us + eps_us
        fallback = best_prefix[pos[cur.idx] - 1]
        # causal candidates: dataflow preds + previous op on this track
        causal: List[Tuple[TimedOp, str]] = []
        for p in preds_of.get(cur.idx, ()):
            o = by_idx.get(p)
            if o is not None and pos[o.idx] < pos[cur.idx] and \
                    o.t1_us <= limit:
                causal.append((o, "dep"))
        seq = last_on_track.get(cur.track, ())
        for o in reversed(seq):
            if pos[o.idx] < pos[cur.idx]:
                if o.t1_us <= limit:
                    causal.append((o, "track"))
                break
        chosen, via = None, "issue"
        if causal:
            chosen, via = max(causal, key=lambda c: (c[0].t1_us,
                                                     pos[c[0].idx]))
        if chosen is None or (fallback.t1_us > chosen.t1_us + eps_us and
                              fallback.t1_us <= limit):
            # nothing causal binds: the latest earlier finisher does
            # (driver/issue-order serialization)
            if fallback.t1_us <= limit:
                chosen, via = fallback, "issue"
        if chosen is None:
            # cur started while every earlier op was still running —
            # concurrent tracks; fall back to issue order to keep the
            # walk spanning the envelope
            chosen, via = order[pos[cur.idx] - 1], "issue"
        # via/gap describe the edge INTO the current head; the walk's
        # first op keeps the "start" placeholder
        steps[0].gap_us = max(0.0, cur.t0_us - chosen.t1_us)
        steps[0].via = via
        steps.insert(0, PathStep(op=chosen))
        cur = chosen
    return _finalize(steps, envelope_us)


@dataclasses.dataclass(frozen=True)
class MemSpec:
    """Per-op memory effects for the DAG re-simulation (ISSUE 17): the
    slot-level footprint the plan verifier's liveness pass walks
    statically, here replayed on the *simulated* timeline so schedule
    rewrites can be scored on peak-live-bytes before they are lowered.

    ``writes[i]`` / ``kills[i]`` are the slot ids op ``i`` defines /
    frees; ``nbytes``/``mesh_of`` map slot id -> size / owning mesh;
    ``preplaced`` slots are live from t=0 (launch placement).  The state
    machine matches ``plan_verifier.check_liveness`` exactly — a write
    allocates only when the slot is not already live, a kill releases
    only a live slot — so a serial replay in program order reproduces
    the static ``alpa_plan_peak_bytes`` figure bit for bit."""
    writes: Sequence[Sequence[int]]
    kills: Sequence[Sequence[int]]
    nbytes: Dict[int, float]
    mesh_of: Dict[int, int]
    num_meshes: int = 1
    preplaced: frozenset = frozenset()


def _simulate_peaks(finish: Sequence[float],
                    mem: MemSpec) -> List[float]:
    """Peak live bytes per mesh over the simulated timeline: each op's
    memory effects (writes then kills, mirroring the static walk's
    per-op order) land at its simulated finish time; ties resolve in op
    order so a serial chain replays program order."""
    n_meshes = max(1, mem.num_meshes)

    def _mesh(s):
        m = mem.mesh_of.get(s, 0)
        return m if 0 <= m < n_meshes else 0

    live_bytes = [0.0] * n_meshes
    _UNDEF, _LIVE, _DEAD = 0, 1, 2
    state: Dict[int, int] = {}
    for s in mem.preplaced:
        state[s] = _LIVE
        live_bytes[_mesh(s)] += mem.nbytes.get(s, 0)
    peaks = list(live_bytes)
    order = sorted(range(len(finish)), key=lambda i: (finish[i], i))
    for i in order:
        for s in mem.kills[i]:
            if state.get(s, _UNDEF) == _LIVE:
                live_bytes[_mesh(s)] -= mem.nbytes.get(s, 0)
            state[s] = _DEAD
        for s in mem.writes[i]:
            if state.get(s, _UNDEF) != _LIVE:
                m = _mesh(s)
                live_bytes[m] += mem.nbytes.get(s, 0)
                if live_bytes[m] > peaks[m]:
                    peaks[m] = live_bytes[m]
            state[s] = _LIVE
    return peaks


def simulate_dag(durs_us: Sequence[float],
                 preds: Sequence[Iterable[int]],
                 mem: Optional[MemSpec] = None):
    """Earliest-finish replay of the dependency DAG (causal edges only,
    idealized parallel driver).  ``preds[i]`` must reference earlier
    indices; later/self references are ignored.  Returns
    ``(makespan_us, finish_us)`` — or, with a :class:`MemSpec`,
    ``(makespan_us, finish_us, peak_bytes_per_mesh)`` tracking the
    simulated peak-live-bytes each mesh reaches (ISSUE 17's FREE-motion
    objective)."""
    n = len(durs_us)
    finish = [0.0] * n
    for i in range(n):
        start = 0.0
        for p in preds[i]:
            if 0 <= p < i and finish[p] > start:
                start = finish[p]
        finish[i] = start + durs_us[i]
    makespan = max(finish) if finish else 0.0
    if mem is None:
        return makespan, finish
    return makespan, finish, _simulate_peaks(finish, mem)


def longest_path(durs_us: Sequence[float],
                 preds: Sequence[Iterable[int]]
                 ) -> Tuple[float, List[int]]:
    """Longest-duration chain through the DAG: the simulated critical
    path.  Returns ``(length_us, op_idx_list)`` ordered start→end."""
    n = len(durs_us)
    finish = [0.0] * n
    best_pred = [-1] * n
    for i in range(n):
        start, bp = 0.0, -1
        for p in preds[i]:
            if 0 <= p < i and finish[p] > start:
                start, bp = finish[p], p
        finish[i] = start + durs_us[i]
        best_pred[i] = bp
    if not finish:
        return 0.0, []
    i = max(range(n), key=lambda j: finish[j])
    path: List[int] = []
    while i >= 0:
        path.append(i)
        i = best_pred[i]
    path.reverse()
    return max(finish), path


def whatif(durs_us: Sequence[float],
           preds: Sequence[Iterable[int]],
           zeroed: Set[int],
           mem: Optional[MemSpec] = None):
    """Makespan with the chosen ops made free — the "if this RESHARD
    cost nothing" re-simulation.  Monotone: never exceeds the baseline
    :func:`simulate_dag` makespan.  With a :class:`MemSpec`, returns
    ``(makespan_us, peak_bytes_per_mesh)`` so memory-motion what-ifs
    ("if this FREE ran right after the last use") are scored on the
    same timeline."""
    durs = [0.0 if i in zeroed else d for i, d in enumerate(durs_us)]
    if mem is None:
        makespan, _ = simulate_dag(durs, preds)
        return makespan
    makespan, _, peaks = simulate_dag(durs, preds, mem)
    return makespan, peaks
