"""Static plan verifier: typed abstract interpretation over lowered
register-file programs (ISSUE 8 tentpole).

The pipeshard compiler's output is a *static* instruction program
(RUN/RESHARD/FREE per mesh), which makes it exactly the artifact that
can be verified before it ever touches hardware.  This module runs
seven analyses over the lowering's dataflow graph on EVERY
``lower_to_register_file`` compile (gated by
``global_config.verify_plans`` = ``"error" | "warn" | "off"``,
default ``"warn"``):

1. **Slot typing** — propagate (shape, dtype) through every RUN and
   RESHARD and reject producer/consumer mismatches, including the
   quantized-edge safety proof: the lossy transfer codec
   (``reshard_codec``) must never be attached to a weight edge
   (microbatch-invariant value) — previously only a runtime convention
   in ``make_transfer``.
2. **Cross-mesh deadlock freedom** — build the happens-before graph
   over the per-mesh instruction streams (in-stream order plus the
   stream partitioner's cross-stream dependency edges), prove it
   acyclic, prove every cross-mesh RESHARD's source was produced before
   the transfer consumes it (a RECV with no earlier SEND is a
   multi-host hang), check per-channel FIFO pairing, and check the two
   endpoints of every transfer agree on byte size.
3. **Liveness & leaks** — every slot FREEd at most once and only after
   definition, no use-after-free, no FREE of an in-flight transfer
   destination, plus a static peak-live-bytes-per-mesh estimate
   (exported as the ``alpa_plan_peak_bytes{mesh}`` gauge and checked
   against device memory when the backend reports a limit) and leak
   detection: slots produced but never freed and not program outputs
   (``alpa_plan_leaked_slots_total``; the flight recorder annotates
   step dumps with the leaked var names).
4. **Structural invariants** — every compiled :class:`OpHook`'s slot
   footprint equals the union of its member instructions' footprints,
   and batched transfer groups contain only groupable (``direct_p2p``)
   members — collective-strategy and quantized RESHARDs must never be
   folded into a multi-member group.
5. **Model checking** (ISSUE 13, :mod:`alpa_tpu.analysis.model_check`,
   gated by ``global_config.verify_plans_model_check``) — an
   explicit-state exploration of every stream interleaving under real
   SEND/RECV FIFO channel semantics (rendezvous and buffered), with
   hazard re-checking per schedule, in-flight-window verification, and
   a static fault/retry-safety classification installed into
   ``fault.call_with_retry``.
6. **Numerics certification** (ISSUE 14,
   :mod:`alpa_tpu.analysis.numerics`, gated by
   ``global_config.verify_plans_numerics``) — a precision-flow
   abstract interpretation composing the lossy codec's documented
   error bounds end to end: proves weights and optimizer state never
   cross a lossy hop anywhere along their flow, checks every value's
   composed worst-case bound against ``numerics_error_budget``, flags
   below-fp32 accumulation, and enumerates which collectives are
   quantized vs full-precision.
7. **Translation validation** (ISSUE 15,
   :mod:`alpa_tpu.analysis.equivalence`, gated by
   ``global_config.verify_plans_equiv``) — symbolic execution of the
   lowered program over a hash-consed opaque term algebra, proving
   every protected output's term graph equal to the reference term
   obtained by serially composing the same stage decomposition over
   the source jaxpr, modulo two documented rewrite axioms
   (accumulation reassociation/commutation, resharding identity) —
   the value-level check the first six analyses cannot make.

The result is a :class:`PlanVerdict` (errors / warnings / stats),
cached in the compile cache (namespace ``plan_verdict``, keyed by the
program fingerprint) so warm restarts replay the identical verdict,
surfaced in ``monitoring.dump_debug_info`` as ``plan_verdict.txt``, and
printable offline via ``scripts/verify_tool.py verify plan``.

Everything here runs once at lowering time over in-memory lists — the
dispatch replay hot path is untouched (zero per-step cost).
"""
import dataclasses
import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from alpa_tpu.telemetry import metrics as _tmetrics

logger = logging.getLogger(__name__)

__all__ = [
    "ANALYSES", "ANALYSES_VERSION", "Finding", "OpModel", "SlotModel",
    "PlanModel", "PlanVerdict", "PlanVerificationError", "build_model",
    "verify_model", "verify_program", "verify_edge",
]

#: the seven analyses, in report order
ANALYSES = ("typing", "deadlock", "liveness", "structure",
            "model_check", "numerics", "equiv")

#: bump when an analysis changes meaning — invalidates cached verdicts
#: (v2: launch-placed slots are accounted at per-device bytes derived
#: from their static sharding, so ZeRO-sharded optimizer state shows
#: the ~dp× reduction in ``peak_bytes``; v3: the ISSUE-13 model checker
#: joins as the fifth analysis and verdicts grow a ``notes`` severity;
#: v4: the ISSUE-14 numerics certification joins as the sixth analysis
#: and slots/ops grow provenance/codec/precision facts; v5: the
#: ISSUE-15 translation validation joins as the seventh analysis and
#: RUN ops grow stage-decomposition ``equiv`` facts, so cached
#: verdicts re-derive under the new proof obligations; v6: the
#: ISSUE-19 quantized gradient collectives — RUN ops carry
#: ``grad_quant`` facts, the numerics analysis composes the gradient
#: codec's stochastic-rounding bounds under the error-feedback
#: amortization rule, and the equivalence prover admits quantized
#: gradient hops only with a clean numerics certificate)
ANALYSES_VERSION = 6

_REG = _tmetrics.get_registry()
_PEAK_BYTES = _REG.gauge(
    "alpa_plan_peak_bytes",
    "Static peak live register-file bytes per mesh (plan verifier)",
    labelnames=("mesh",))
_OPT_STATE_BYTES = _REG.gauge(
    "alpa_opt_state_bytes",
    "Static per-device optimizer-state bytes resident per mesh "
    "(plan verifier; shrinks ~dp_size x under ZeRO weight-update "
    "sharding)",
    labelnames=("mesh",))
_ZERO_SAVED = _REG.gauge(
    "alpa_zero_bytes_saved_total",
    "Bytes the verified plan's sharded weight-update layout saves per "
    "device versus replicated leaves, summed over meshes")
_LEAKED_SLOTS = _REG.counter(
    "alpa_plan_leaked_slots_total",
    "Slots the plan verifier found produced but never freed")
_VERDICTS = _REG.counter(
    "alpa_plan_verdicts_total",
    "Plan verifier verdicts by result",
    labelnames=("result",))


class PlanVerificationError(RuntimeError):
    """A lowered plan failed static verification under
    ``global_config.verify_plans == "error"``.  Carries the verdict."""

    def __init__(self, message: str, verdict: "PlanVerdict"):
        super().__init__(message)
        self.verdict = verdict


@dataclasses.dataclass(frozen=True)
class Finding:
    """One named, actionable analysis result."""
    analysis: str           # "typing" | "deadlock" | "liveness" | ...
    code: str               # e.g. "typing.run-input-mismatch"
    message: str
    op: int = -1            # flat instruction index (-1 = program level)

    def to_dict(self) -> Dict[str, Any]:
        return {"analysis": self.analysis, "code": self.code,
                "message": self.message, "op": self.op}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Finding":
        return cls(analysis=d["analysis"], code=d["code"],
                   message=d["message"], op=d.get("op", -1))


@dataclasses.dataclass
class SlotModel:
    """Static facts about one register slot: which value lives there."""
    slot: int
    var: str                # var name (diagnostics)
    instance: int           # microbatch instance; -1 = invariant (weight)
    mesh: int
    shape: Tuple[int, ...] = ()
    dtype: str = ""
    nbytes: int = 0             # per-device bytes (sharding-aware when
                                # the driver placed the slot at launch)
    full_nbytes: int = 0        # unsharded (global) bytes of the value
    preplaced: bool = False     # placed by the driver at launch
    protected: bool = False     # program output — never freed by design
    opt_state: bool = False     # optimizer-state leaf (ZeRO target)
    provenance: str = ""        # param|opt_state|gradient|activation
                                # (numerics seed, from invar_paths)


@dataclasses.dataclass
class OpModel:
    """One instruction's verifier-relevant footprint (aligned 1:1 with
    the lowering's phase-1 records and the dataflow graph nodes)."""
    idx: int
    kind: str                               # "RUN" | "RESHARD" | "FREE"
    mesh: int
    reads: Tuple[int, ...] = ()
    writes: Tuple[int, ...] = ()
    kills: Tuple[int, ...] = ()
    edge: Optional[Tuple[int, int]] = None  # RESHARD (src, dst) mesh
    cross: bool = False
    strategy: Optional[str] = None          # RESHARD lowering strategy
    weight: bool = False                    # microbatch-invariant payload
    groupable: bool = True                  # may join a batched group
    nbytes: int = 0                         # RESHARD payload bytes
    # RUN typing: ((shape, dtype) | None) per read / write position
    in_avals: Tuple[Any, ...] = ()
    out_avals: Tuple[Any, ...] = ()
    label: str = ""
    codec: Optional[str] = None             # quantized RESHARD wire mode
    # RUN eqn-classification facts (eqn_classify; numerics analysis)
    precision: Optional[Dict[str, Any]] = None
    # RUN stage-decomposition facts for the translation validation:
    # {"stage": sig, "mb": int, "donate": [pos...], "acc": {out: in}}
    equiv: Optional[Dict[str, Any]] = None
    # RUN quantized-gradient facts (ISSUE 19), present only when
    # global_config.grad_quantize != "off" at lowering time:
    # {"mode": "int8"|"fp8", "ef": bool, "hops": int, "rs": bool} — the
    # numerics analysis composes ERROR_BOUND[f"grad_{mode}"] onto
    # gradient-provenance accumulations, amortized to one hop under
    # error feedback
    grad_quant: Optional[Dict[str, Any]] = None


@dataclasses.dataclass
class PlanModel:
    """The verifier's program model: ops in flat emission order, slot
    facts, and the per-mesh stream partition (happens-before input)."""
    ops: List[OpModel]
    slots: Dict[int, SlotModel]
    num_meshes: int
    streams: List[List[int]]                # per-mesh op idx lists
    deps: Dict[int, Set[int]]               # op -> cross-stream waits
    mode: str = "registers"
    device_memory_bytes: Optional[float] = None
    # (src_mesh, dst_mesh) -> cross-mesh RESHARD op indices in emission
    # (== send) order; the model checker's channel FIFO programs.
    channels: Dict[Tuple[int, int], List[int]] = \
        dataclasses.field(default_factory=dict)
    # the driver's pre-lowering stage decomposition over (var,
    # microbatch) value keys (alpa-equiv-reference/v1) — the
    # translation validation's reference semantics
    reference: Optional[Dict[str, Any]] = None


@dataclasses.dataclass
class PlanVerdict:
    """Errors / warnings / notes / stats from one verification run.
    Picklable and JSON-able: cached in the compile cache and replayed
    verbatim on warm restarts.  ``notes`` (ISSUE 13) carry descriptive
    findings — retry-safety classifications, partial model-check
    coverage — that neither fail the plan nor count as warnings."""
    errors: List[Finding] = dataclasses.field(default_factory=list)
    warnings: List[Finding] = dataclasses.field(default_factory=list)
    stats: Dict[str, Any] = dataclasses.field(default_factory=dict)
    notes: List[Finding] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.errors

    def findings(self) -> List[Finding]:
        return list(self.errors) + list(self.warnings) + \
            list(self.notes)

    def to_dict(self) -> Dict[str, Any]:
        return {"version": ANALYSES_VERSION,
                "errors": [f.to_dict() for f in self.errors],
                "warnings": [f.to_dict() for f in self.warnings],
                "notes": [f.to_dict() for f in self.notes],
                "stats": dict(self.stats)}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PlanVerdict":
        return cls(
            errors=[Finding.from_dict(x) for x in d.get("errors", ())],
            warnings=[Finding.from_dict(x)
                      for x in d.get("warnings", ())],
            notes=[Finding.from_dict(x) for x in d.get("notes", ())],
            stats=dict(d.get("stats", {})))

    def format_table(self) -> str:
        """Human-readable verdict report (``plan_verdict.txt``,
        ``scripts/verify_tool.py verify plan``)."""
        st = self.stats
        lines = [
            "plan verdict: "
            + ("PASS" if self.ok else "FAIL")
            + f" ({len(self.errors)} errors, "
              f"{len(self.warnings)} warnings)"]
        counts = {a: 0 for a in ANALYSES}
        for f in self.findings():
            counts[f.analysis] = counts.get(f.analysis, 0) + 1
        lines.append("analyses: " + "  ".join(
            f"{a}={'ok' if counts.get(a, 0) == 0 else counts[a]}"
            for a in ANALYSES))
        if st:
            by = st.get("by_opcode", {})
            lines.append(
                f"program: ops={st.get('n_ops', '?')} ("
                + " ".join(f"{k}={v}" for k, v in sorted(by.items()))
                + f")  slots={st.get('n_slots', '?')}"
                  f"  cross_mesh={st.get('n_cross_mesh', '?')}"
                  f"  channels={st.get('n_channels', '?')}"
                  f"  mode={st.get('mode', '?')}")
            peaks = st.get("peak_bytes", {})
            if peaks:
                lines.append("peak live bytes: " + "  ".join(
                    f"mesh {m}: {b / 2 ** 20:.2f} MiB"
                    for m, b in sorted(peaks.items(),
                                       key=lambda kv: str(kv[0]))))
            opt = st.get("opt_state_bytes", {})
            if any(opt.values()):
                lines.append("opt-state bytes/device: " + "  ".join(
                    f"mesh {m}: {b / 2 ** 20:.2f} MiB"
                    for m, b in sorted(opt.items(),
                                       key=lambda kv: str(kv[0]))))
                saved = st.get("zero_bytes_saved", 0.0)
                if saved:
                    lines.append(
                        f"zero sharding saves "
                        f"{saved / 2 ** 20:.2f} MiB/device vs "
                        f"replicated")
            leaked = st.get("leaked_vars", ())
            if leaked:
                lines.append(
                    f"leaked slots ({len(leaked)}): "
                    + ", ".join(str(v) for v in leaked[:8])
                    + (" ..." if len(leaked) > 8 else ""))
        mc = st.get("model_check") if st else None
        if mc:
            sem = mc.get("semantics", {})
            lines.append(
                "model check: "
                + "  ".join(f"{k}={v}" for k, v in sorted(sem.items()))
                + f"  states={mc.get('states', 0)}"
                  f"  reduction_ratio={mc.get('reduction_ratio', 0.0)}")
        num = st.get("numerics") if st else None
        if num:
            lossy = num.get("lossy_edges", {})
            lines.append(
                "numerics: "
                + ("no lossy hops" if not lossy else
                   "  ".join(f"{k}={v}"
                             for k, v in sorted(lossy.items())))
                + f"  max_error_bound="
                  f"{num.get('max_error_bound', 0.0):.6g}"
                  f"  budget={num.get('budget', 0.0):.6g}")
        eq = st.get("equiv") if st else None
        if eq:
            lines.append(
                "equiv: "
                + (f"{eq.get('n_proved', 0)}/{eq.get('n_outputs', 0)} "
                   f"outputs proved"
                   if not eq.get("partial") else "PARTIAL")
                + f"  terms={eq.get('n_terms', 0)}"
                  f"  apps={eq.get('n_apps', 0)}"
                  f"  axioms="
                + (",".join(eq.get("axioms_used", ())) or "-"))
        for title, items in (("errors", self.errors),
                             ("warnings", self.warnings),
                             ("notes", self.notes)):
            if items:
                lines.append(f"{title}:")
                for f in items:
                    at = f" (op {f.op})" if f.op >= 0 else ""
                    lines.append(f"  [{f.code}]{at} {f.message}")
        return "\n".join(lines)


def _aval_of(var) -> Tuple[Tuple[int, ...], str, int]:
    """(shape, dtype, nbytes) of a jaxpr var's aval; tolerant of
    abstract tokens and synthetic test vars."""
    aval = getattr(var, "aval", None)
    shape = tuple(getattr(aval, "shape", ()) or ())
    dtype = str(getattr(aval, "dtype", "") or "")
    try:
        import numpy as np
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = n * int(np.dtype(dtype).itemsize) if dtype else 0
    except Exception:  # pylint: disable=broad-except
        nbytes = 0
    return shape, dtype, nbytes


def _strategy_of(transfer) -> str:
    """The lowering strategy a built transfer executor encodes."""
    if transfer is None:
        return "direct_p2p"
    name = type(transfer).__name__
    if name == "QuantizedTransfer":
        return "quantized"
    return getattr(transfer, "strategy", None) or "direct_p2p"


def _per_device_nbytes(sharding, shape: Tuple[int, ...],
                       dtype: str, nbytes: int) -> int:
    """Per-device bytes of a value under ``sharding`` (falls back to
    the global size for replicated / unknown layouts)."""
    try:
        import numpy as np
        shard = sharding.shard_shape(tuple(shape))
        n = int(np.prod(shard, dtype=np.int64)) if shard else 1
        return n * int(np.dtype(dtype).itemsize)
    except Exception:  # pylint: disable=broad-except
        return nbytes


def build_model(instructions: Sequence[Any],
                slot_of: Dict[Tuple[Any, int, int], int],
                preplaced_shardings: Dict[Tuple[Any, int, int], Any],
                recs: Sequence[Dict[str, Any]],
                protected_keys=frozenset(),
                mode: str = "registers",
                opt_state_keys=frozenset(),
                provenance_keys=None,
                reference=None) -> PlanModel:
    """Assemble a :class:`PlanModel` from the lowering's inputs: the
    emitted instruction list, the slot table, the launch-placed keys,
    and the phase-1 per-instruction records (kind / footprint / edge /
    transfer)."""
    from alpa_tpu.pipeline_parallel.runtime_emitter import (
        PipelineInstType, partition_streams)

    slots: Dict[int, SlotModel] = {}
    for (var, inst_id, mesh), s in slot_of.items():
        shape, dtype, nbytes = _aval_of(var)
        key = (var, inst_id, mesh)
        preplaced = key in preplaced_shardings
        per_dev = nbytes
        if preplaced and shape and dtype:
            # launch-placed slots carry a static sharding — account
            # them at per-device bytes so ZeRO-sharded optimizer state
            # proves its ~dp_size x reduction in peak_bytes
            per_dev = _per_device_nbytes(
                preplaced_shardings[key], shape, dtype, nbytes)
        slots[s] = SlotModel(
            slot=s, var=str(var), instance=inst_id, mesh=mesh,
            shape=shape, dtype=dtype, nbytes=per_dev,
            full_nbytes=nbytes,
            preplaced=preplaced,
            protected=key in protected_keys,
            opt_state=key in opt_state_keys,
            provenance=(provenance_keys or {}).get(key, ""))

    num_meshes = 1
    for inst in instructions:
        for m in (getattr(inst, "src_mesh", None),
                  getattr(inst, "dst_mesh", None)):
            if m is not None:
                num_meshes = max(num_meshes, m + 1)
        for k in getattr(inst, "free_keys", None) or ():
            num_meshes = max(num_meshes, k[2] + 1)

    ops: List[OpModel] = []
    for i, (inst, r) in enumerate(zip(instructions, recs)):
        kind = r["kind"]
        op = OpModel(idx=i, kind=kind, mesh=r["mesh"],
                     reads=tuple(r["reads"]), writes=tuple(r["writes"]),
                     kills=tuple(r["kills"]),
                     label=r.get("name", kind))
        if kind == "RUN":
            ex = inst.executable
            op.in_avals = tuple(
                _aval_of(v)[:2] for v in getattr(ex, "invars", ()))
            op.out_avals = tuple(
                _aval_of(v)[:2] for v in getattr(ex, "outvars", ()))
            op.precision = r.get("precision")
            op.equiv = r.get("equiv")
            op.grad_quant = r.get("grad_quant")
        elif kind == "RESHARD":
            op.edge = r.get("edge")
            op.cross = bool(r.get("cross", False))
            t = r.get("transfer")
            op.strategy = _strategy_of(t)
            op.weight = inst.var_key[1] < 0
            if op.strategy == "quantized":
                op.codec = r.get("codec") or getattr(t, "mode", None)
            op.groupable = bool(r.get("groupable", True))
            op.nbytes = int(getattr(t, "nbytes", 0) or
                            _aval_of(inst.var_key[0])[2])
        ops.append(op)
        assert inst.opcode == PipelineInstType[kind], (
            "instruction/record lists misaligned at index %d" % i)

    st = partition_streams(list(instructions), num_meshes)
    return PlanModel(ops=ops, slots=slots, num_meshes=num_meshes,
                     streams=st.streams,
                     deps={k: set(v) for k, v in st.deps.items()},
                     mode=mode,
                     device_memory_bytes=_device_memory_bytes(),
                     channels={k: list(v)
                               for k, v in st.channels.items()},
                     reference=reference)


def _device_memory_bytes() -> Optional[float]:
    """Per-device memory limit when the backend reports one (TPU/GPU);
    None on the CPU test backend."""
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            limit = stats.get("bytes_limit")
            if limit:
                return float(limit)
    except Exception:  # pylint: disable=broad-except
        pass
    return None


########################################
# analysis 1: slot typing
########################################


def check_typing(model: PlanModel) -> List[Finding]:
    out: List[Finding] = []
    # abstract state: the (shape, dtype) each slot currently holds
    cur: Dict[int, Tuple[Tuple[int, ...], str]] = {}
    for s, sm in model.slots.items():
        if sm.preplaced and sm.dtype:
            cur[s] = (sm.shape, sm.dtype)
    for op in model.ops:
        if op.kind == "RUN":
            for pos, s in enumerate(op.reads):
                declared = (op.in_avals[pos]
                            if pos < len(op.in_avals) else None)
                have = cur.get(s)
                if declared and have and declared != have:
                    out.append(Finding(
                        "typing", "typing.run-input-mismatch",
                        f"{op.label}: arg {pos} (slot {s}, "
                        f"{model.slots[s].var}) holds "
                        f"{have[0]}/{have[1]} but the stage expects "
                        f"{declared[0]}/{declared[1]}", op.idx))
            for pos, s in enumerate(op.writes):
                declared = (op.out_avals[pos]
                            if pos < len(op.out_avals) else None)
                sm = model.slots.get(s)
                if declared and sm is not None and sm.dtype and \
                        declared != (sm.shape, sm.dtype):
                    out.append(Finding(
                        "typing", "typing.run-output-mismatch",
                        f"{op.label}: output {pos} (slot {s}, {sm.var}) "
                        f"declared {sm.shape}/{sm.dtype} but the stage "
                        f"produces {declared[0]}/{declared[1]}",
                        op.idx))
                if declared:
                    cur[s] = declared
                elif sm is not None and sm.dtype:
                    cur[s] = (sm.shape, sm.dtype)
        elif op.kind == "RESHARD":
            src = op.reads[0] if op.reads else None
            dst = op.writes[0] if op.writes else None
            have = cur.get(src) if src is not None else None
            dsm = model.slots.get(dst) if dst is not None else None
            if have and dsm is not None and dsm.dtype and \
                    have != (dsm.shape, dsm.dtype):
                out.append(Finding(
                    "typing", "typing.reshard-mismatch",
                    f"{op.label}: transfers {have[0]}/{have[1]} from "
                    f"slot {src} into slot {dst} declared "
                    f"{dsm.shape}/{dsm.dtype}", op.idx))
            if op.strategy == "quantized":
                if op.weight:
                    out.append(Finding(
                        "typing", "typing.quantized-weight-edge",
                        f"{op.label}: lossy quantized codec attached to "
                        f"a weight edge (microbatch-invariant value "
                        f"{model.slots[src].var if src in model.slots else src}"
                        f") — weights must cross losslessly; force "
                        f"reshard_quantize=off for this edge", op.idx))
                dt = have[1] if have else (
                    dsm.dtype if dsm is not None else "")
                if dt and dt not in ("float32", "bfloat16", "float16"):
                    out.append(Finding(
                        "typing", "typing.quantized-dtype",
                        f"{op.label}: quantized codec on non-float "
                        f"payload dtype {dt}", op.idx))
            if dst is not None:
                if have:
                    cur[dst] = have
                elif dsm is not None and dsm.dtype:
                    cur[dst] = (dsm.shape, dsm.dtype)
    return out


########################################
# analysis 2: cross-mesh deadlock freedom
########################################


def check_deadlock(model: PlanModel) -> List[Finding]:
    out: List[Finding] = []
    n = len(model.ops)

    # happens-before: in-stream program order + cross-stream dep edges
    hb_succs: List[List[int]] = [[] for _ in range(n)]
    indeg = [0] * n
    for stream in model.streams:
        for a, b in zip(stream, stream[1:]):
            hb_succs[a].append(b)
            indeg[b] += 1
    for i, waits in model.deps.items():
        for j in waits:
            if 0 <= j < n and j != i:
                hb_succs[j].append(i)
                indeg[i] += 1

    # Kahn's algorithm: every op must be schedulable
    queue = [i for i in range(n) if indeg[i] == 0]
    seen = 0
    while queue:
        i = queue.pop()
        seen += 1
        for s in hb_succs[i]:
            indeg[s] -= 1
            if indeg[s] == 0:
                queue.append(s)
    if seen != n:
        stuck = sorted(i for i in range(n) if indeg[i] > 0)
        labels = ", ".join(
            f"{i}:{model.ops[i].label}" for i in stuck[:6])
        out.append(Finding(
            "deadlock", "deadlock.cycle",
            f"happens-before graph has a cycle over {n - seen} ops "
            f"({labels}) — per-mesh streams would wait on each other "
            f"forever on a multi-host pod", stuck[0] if stuck else -1))

    # SEND-before-RECV: a cross-mesh transfer's source slot must be
    # produced before the transfer consumes it in program order
    defined: Set[int] = {s for s, sm in model.slots.items()
                         if sm.preplaced}
    producer: Dict[int, int] = {}
    for op in model.ops:
        if op.kind == "RESHARD" and op.cross:
            src = op.reads[0] if op.reads else None
            if src is not None and src not in defined:
                sm = model.slots.get(src)
                out.append(Finding(
                    "deadlock", "deadlock.recv-before-send",
                    f"{op.label}: cross-mesh transfer of slot {src} "
                    f"({sm.var if sm else '?'}) is ordered before its "
                    f"producer — the RECV side would block forever "
                    f"waiting for a SEND that has not been issued",
                    op.idx))
        for s in op.writes:
            defined.add(s)
            producer[s] = op.idx

    # byte-matched endpoints: both sides of a transfer agree on size
    for op in model.ops:
        if op.kind != "RESHARD" or not op.cross:
            continue
        src = model.slots.get(op.reads[0]) if op.reads else None
        dst = model.slots.get(op.writes[0]) if op.writes else None
        if src is None or dst is None:
            continue
        if src.nbytes and dst.nbytes and src.nbytes != dst.nbytes:
            out.append(Finding(
                "deadlock", "deadlock.byte-mismatch",
                f"{op.label}: SEND side carries {src.nbytes} bytes "
                f"({src.var}) but the RECV side expects {dst.nbytes} "
                f"bytes ({dst.var}) — a multi-host send/recv pair "
                f"would corrupt or hang", op.idx))

    # per-channel FIFO pairing: on one (src, dst) mesh channel, the
    # receiver must consume values in production order — reordered
    # pairs hang a FIFO DCN channel even though single-controller
    # device_put tolerates them
    channels: Dict[Tuple[int, int], List[Any]] = {}
    for m, stream in enumerate(model.streams):
        for i in stream:
            op = model.ops[i]
            if op.kind == "RESHARD" and op.cross and op.edge:
                channels.setdefault(tuple(op.edge), []).append(op)
    for edge, chan_ops in channels.items():
        prods = [producer.get(op.reads[0], -1)
                 for op in chan_ops if op.reads]
        known = [p for p in prods if p >= 0]
        if known != sorted(known):
            first = next(op for op, p in zip(chan_ops, prods)
                         if p >= 0 and p != min(known))
            out.append(Finding(
                "deadlock", "deadlock.channel-reorder",
                f"channel {edge[0]}->{edge[1]}: receives are ordered "
                f"against production order (producer indices {prods})"
                f" — FIFO send/recv pairing would mismatch payloads",
                first.idx))
    return out


########################################
# analysis 3: liveness, leaks, peak memory
########################################

_UNDEF, _LIVE, _DEAD = 0, 1, 2


def check_liveness(model: PlanModel
                   ) -> Tuple[List[Finding], Dict[str, Any]]:
    out: List[Finding] = []
    state: Dict[int, int] = {}
    last_writer: Dict[int, int] = {}
    last_read: Dict[int, int] = {}
    live_bytes = [0.0] * model.num_meshes
    peak_bytes = [0.0] * model.num_meshes
    stream_of: Dict[int, int] = {}
    for m, stream in enumerate(model.streams):
        for i in stream:
            stream_of[i] = m

    def _mesh(s: int) -> int:
        sm = model.slots.get(s)
        m = sm.mesh if sm is not None else 0
        return m if 0 <= m < model.num_meshes else 0

    def _nbytes(s: int) -> int:
        sm = model.slots.get(s)
        return sm.nbytes if sm is not None else 0

    for s, sm in model.slots.items():
        if sm.preplaced:
            state[s] = _LIVE
            live_bytes[_mesh(s)] += sm.nbytes
    for m in range(model.num_meshes):
        peak_bytes[m] = live_bytes[m]

    def _var(s: int) -> str:
        sm = model.slots.get(s)
        return sm.var if sm is not None else f"slot{s}"

    for op in model.ops:
        for s in op.reads:
            st = state.get(s, _UNDEF)
            if st == _DEAD:
                out.append(Finding(
                    "liveness", "liveness.use-after-free",
                    f"{op.label}: reads slot {s} ({_var(s)}) already "
                    f"freed by op {last_writer.get(s, '?')}", op.idx))
            elif st == _UNDEF and not (op.kind == "RESHARD" and
                                       op.cross):
                # cross-mesh use-before-def is the deadlock pass's
                # recv-before-send finding; report the local flavor here
                out.append(Finding(
                    "liveness", "liveness.use-undefined",
                    f"{op.label}: reads slot {s} ({_var(s)}) that no "
                    f"earlier op or launch placement defines", op.idx))
            last_read[s] = op.idx
        for s in op.kills:
            st = state.get(s, _UNDEF)
            if st == _DEAD:
                out.append(Finding(
                    "liveness", "liveness.double-free",
                    f"{op.label}: frees slot {s} ({_var(s)}) twice",
                    op.idx))
            elif st == _UNDEF:
                out.append(Finding(
                    "liveness", "liveness.free-undefined",
                    f"{op.label}: frees slot {s} ({_var(s)}) that was "
                    f"never defined", op.idx))
            else:
                w = last_writer.get(s)
                if w is not None and model.ops[w].cross and \
                        stream_of.get(w) != stream_of.get(op.idx) and \
                        last_read.get(s, -1) < w and \
                        w not in model.deps.get(op.idx, ()):
                    out.append(Finding(
                        "liveness", "liveness.free-in-flight",
                        f"{op.label}: frees slot {s} ({_var(s)}), the "
                        f"destination of cross-mesh transfer op {w} on "
                        f"another stream, with no dependency edge — "
                        f"the FREE can race the in-flight transfer",
                        op.idx))
                live_bytes[_mesh(s)] -= _nbytes(s)
            state[s] = _DEAD
        for s in op.writes:
            prev = state.get(s, _UNDEF)
            if prev == _LIVE and last_read.get(s, -1) < \
                    last_writer.get(s, -1):
                out.append(Finding(
                    "liveness", "liveness.dead-store",
                    f"{op.label}: overwrites slot {s} ({_var(s)}) "
                    f"whose previous value (op "
                    f"{last_writer.get(s)}) was never read", op.idx))
            if prev != _LIVE:
                m = _mesh(s)
                live_bytes[m] += _nbytes(s)
                if live_bytes[m] > peak_bytes[m]:
                    peak_bytes[m] = live_bytes[m]
            state[s] = _LIVE
            last_writer[s] = op.idx

    written = set(last_writer)
    leaked = sorted(
        s for s, st in state.items()
        if st == _LIVE and s in written
        and not model.slots.get(s, SlotModel(s, "", 0, 0)).protected
        and not model.slots.get(s, SlotModel(s, "", 0, 0)).preplaced)
    leaked_vars = [_var(s) for s in leaked]
    if leaked:
        out.append(Finding(
            "liveness", "liveness.leak",
            f"{len(leaked)} slot(s) produced but never freed (vanish "
            f"silently at step end): "
            + ", ".join(f"{s}={v}" for s, v in
                        list(zip(leaked, leaked_vars))[:8])
            + (" ..." if len(leaked) > 8 else "")))

    if model.device_memory_bytes:
        for m, peak in enumerate(peak_bytes):
            if peak > model.device_memory_bytes:
                out.append(Finding(
                    "liveness", "liveness.peak-exceeds-memory",
                    f"mesh {m}: static peak live bytes "
                    f"{peak:.0f} exceed the device memory limit "
                    f"{model.device_memory_bytes:.0f}"))

    # per-mesh resident optimizer-state bytes (launch-placed slots live
    # for the whole step) and the per-device bytes the plan's sharded
    # weight-update layout saves versus replicated leaves
    opt_bytes = [0.0] * model.num_meshes
    zero_saved = 0.0
    for s, sm in model.slots.items():
        if not sm.opt_state:
            continue
        opt_bytes[_mesh(s)] += sm.nbytes
        if sm.full_nbytes > sm.nbytes:
            zero_saved += sm.full_nbytes - sm.nbytes

    stats = {
        "peak_bytes": {str(m): peak_bytes[m]
                       for m in range(model.num_meshes)},
        "opt_state_bytes": {str(m): opt_bytes[m]
                            for m in range(model.num_meshes)},
        "zero_bytes_saved": zero_saved,
        "leaked_slots": len(leaked),
        "leaked_vars": leaked_vars,
    }
    return out, stats


########################################
# analysis 4: structural invariants (hooks, groups)
########################################


def check_structure(model: PlanModel,
                    hooks: Optional[Sequence[Any]] = None
                    ) -> List[Finding]:
    out: List[Finding] = []
    for op in model.ops:
        if op.kind == "RESHARD":
            if op.edge is None:
                out.append(Finding(
                    "structure", "structure.reshard-no-edge",
                    f"{op.label}: RESHARD op carries no mesh edge",
                    op.idx))
            elif op.cross != (op.edge[0] != op.edge[1]):
                out.append(Finding(
                    "structure", "structure.cross-flag",
                    f"{op.label}: cross_mesh={op.cross} disagrees with "
                    f"edge {op.edge}", op.idx))
            if len(op.reads) != 1 or len(op.writes) != 1:
                out.append(Finding(
                    "structure", "structure.reshard-footprint",
                    f"{op.label}: RESHARD must read exactly one slot "
                    f"and write exactly one slot, has reads={op.reads} "
                    f"writes={op.writes}", op.idx))
            if op.strategy not in (None, "direct_p2p") and \
                    op.groupable:
                out.append(Finding(
                    "structure", "structure.groupable-strategy",
                    f"{op.label}: {op.strategy} transfer marked "
                    f"groupable — only direct_p2p edges may join "
                    f"batched groups", op.idx))
    if hooks is None:
        return out
    n = len(model.ops)
    for hook in hooks:
        members = tuple(getattr(hook, "members", ()) or ())
        if not members:
            continue
        if any(m < 0 or m >= n for m in members):
            out.append(Finding(
                "structure", "structure.hook-member-range",
                f"hook {hook.name}: member indices {members} out of "
                f"range (program has {n} instructions)", hook.node))
            continue
        if hook.node != members[0]:
            out.append(Finding(
                "structure", "structure.hook-node",
                f"hook {hook.name}: node {hook.node} is not its first "
                f"member {members[0]}", hook.node))
        mem_ops = [model.ops[m] for m in members]
        want_reads = {s for o in mem_ops for s in o.reads}
        want_writes = {s for o in mem_ops for s in o.writes}
        want_kills = {s for o in mem_ops for s in o.kills}
        got = (set(hook.reads), set(hook.writes), set(hook.kills))
        if got != (want_reads, want_writes, want_kills):
            out.append(Finding(
                "structure", "structure.hook-footprint",
                f"hook {hook.name}: footprint reads={sorted(got[0])} "
                f"writes={sorted(got[1])} kills={sorted(got[2])} does "
                f"not match its members' union "
                f"reads={sorted(want_reads)} "
                f"writes={sorted(want_writes)} "
                f"kills={sorted(want_kills)}", hook.node))
        if len(members) > 1:
            bad = [o for o in mem_ops
                   if o.kind != "RESHARD" or not o.groupable or
                   o.strategy not in (None, "direct_p2p")]
            if bad:
                out.append(Finding(
                    "structure", "structure.group-nongroupable",
                    f"hook {hook.name}: batched group contains "
                    f"non-groupable member(s) "
                    f"{[(o.idx, o.kind, o.strategy) for o in bad]} — "
                    f"collective/quantized transfers must stay "
                    f"un-coalesced", hook.node))
            edges = {o.edge for o in mem_ops if o.kind == "RESHARD"}
            if len(edges) > 1:
                out.append(Finding(
                    "structure", "structure.group-mixed-edge",
                    f"hook {hook.name}: batched group spans multiple "
                    f"mesh edges {sorted(edges)}", hook.node))
    return out


########################################
# driver
########################################


def verify_model(model: PlanModel,
                 hooks: Optional[Sequence[Any]] = None,
                 model_check: bool = False,
                 overlap_window: int = 0,
                 model_check_budget: Optional[int] = None,
                 numerics: bool = False,
                 numerics_budget: Optional[float] = None,
                 equiv: bool = False,
                 equiv_budget: Optional[int] = None
                 ) -> PlanVerdict:
    """Run the analyses over a plan model; pure function of its
    inputs (no metrics, no cache — see :func:`verify_program` for the
    compile-time wrapper).  The fifth analysis (the ISSUE-13 explicit
    state model checker) is opt-in via ``model_check=True`` — it
    explores every stream interleaving, so the caller decides whether
    this plan is worth the state-space walk.  The sixth (the ISSUE-14
    numerics certification) is opt-in via ``numerics=True`` with a
    per-tensor relative-error ``numerics_budget``.  The seventh (the
    ISSUE-15 translation validation) is opt-in via ``equiv=True`` with
    a hash-consed term budget ``equiv_budget``; it proves the plan
    against ``model.reference`` and consumes the numerics verdict to
    decide whether the quantized-within-bound axiom is admissible."""
    t0 = time.perf_counter()
    findings: List[Finding] = []
    findings += check_typing(model)
    findings += check_deadlock(model)
    live_findings, live_stats = check_liveness(model)
    findings += live_findings
    findings += check_structure(model, hooks)

    mc_stats = None
    mc_severity: Dict[str, str] = {}
    if model_check:
        from alpa_tpu.analysis import model_check as _mc
        mc = _mc.check_model(
            model, hooks=hooks, overlap_window=overlap_window,
            budget=model_check_budget or _mc.DEFAULT_STATE_BUDGET)
        findings += mc.findings
        mc_severity = {f.code: _mc.severity_of(f.code)
                       for f in mc.findings}
        mc_stats = mc.stats

    num_stats = None
    numerics_ok: Optional[bool] = None
    num_severity: Dict[str, str] = {}
    if numerics:
        from alpa_tpu.analysis import numerics as _num
        nr = _num.check_numerics(model, hooks=hooks,
                                 budget=numerics_budget)
        findings += nr.findings
        num_severity = {f.code: _num.severity_of(f.code)
                        for f in nr.findings}
        num_stats = nr.stats
        numerics_ok = nr.ok

    eq_stats = None
    eq_severity: Dict[str, str] = {}
    if equiv:
        from alpa_tpu.analysis import equivalence as _eq
        er = _eq.check_equiv(model, hooks=hooks, budget=equiv_budget,
                             numerics_ok=numerics_ok)
        findings += er.findings
        eq_severity = {f.code: _eq.severity_of(f.code)
                       for f in er.findings}
        eq_stats = er.stats

    warning_codes = ("liveness.leak", "liveness.dead-store",
                     "liveness.peak-exceeds-memory",
                     "deadlock.channel-reorder")
    verdict = PlanVerdict()
    for f in findings:
        sev = mc_severity.get(f.code) or num_severity.get(f.code) or \
            eq_severity.get(f.code) or (
                "warning" if f.code in warning_codes else "error")
        {"error": verdict.errors, "warning": verdict.warnings,
         "note": verdict.notes}[sev].append(f)
    by_opcode: Dict[str, int] = {}
    for op in model.ops:
        by_opcode[op.kind] = by_opcode.get(op.kind, 0) + 1
    verdict.stats = {
        "n_ops": len(model.ops),
        "by_opcode": by_opcode,
        "n_slots": len(model.slots),
        "n_cross_mesh": sum(1 for o in model.ops if o.cross),
        "n_channels": len({tuple(o.edge) for o in model.ops
                           if o.cross and o.edge}),
        "num_meshes": model.num_meshes,
        "mode": model.mode,
        "verify_seconds": round(time.perf_counter() - t0, 6),
        **live_stats,
    }
    if mc_stats is not None:
        verdict.stats["model_check"] = mc_stats
    if num_stats is not None:
        verdict.stats["numerics"] = num_stats
    if eq_stats is not None:
        verdict.stats["equiv"] = eq_stats
    return verdict


def _cache_key(cache, fingerprint: str, mode: str,
               model_checked: bool = False,
               numerics: bool = False,
               numerics_budget: Optional[float] = None,
               equiv: bool = False,
               equiv_budget: Optional[int] = None,
               ref_digest: str = "none") -> str:
    # the budget participates in findings (budget-exceeded), so it must
    # key the cache alongside the on/off bit; the reference digest must
    # key it too — the program fingerprint only covers the lowering, so
    # a changed source decomposition must re-derive the proof rather
    # than replay a stale verdict
    num = f"num1b{numerics_budget!r}" if numerics else "num0"
    eq = f"eq1b{equiv_budget!r}r{ref_digest}" if equiv else "eq0"
    return cache.make_key(
        "plan_verdict", [f"analyses_v{ANALYSES_VERSION}", mode,
                         f"mc{int(model_checked)}", num, eq,
                         fingerprint])


def _model_check_enabled(n_ops: int) -> bool:
    """Whether the knob asks for the fifth analysis on a plan of
    ``n_ops`` instructions: ``"all"`` always, ``"fixture"`` (default)
    only for plans small enough to finish in well under a second,
    ``"off"`` never."""
    from alpa_tpu.global_env import global_config
    from alpa_tpu.analysis import model_check as _mc
    mode = getattr(global_config, "verify_plans_model_check", "fixture")
    if mode == "all":
        return True
    if mode == "fixture":
        return n_ops <= _mc.FIXTURE_MAX_OPS
    return False


def verify_program(instructions: Sequence[Any],
                   prog,
                   preplaced_shardings: Dict[Any, Any],
                   recs: Sequence[Dict[str, Any]],
                   protected_keys=frozenset(),
                   opt_state_keys=frozenset(),
                   provenance_keys=None,
                   reference=None) -> PlanVerdict:
    """Compile-time entry point, called by ``lower_to_register_file``
    for every lowered program when ``global_config.verify_plans`` is
    not ``"off"``.

    Builds the model, replays a cached verdict when the program
    fingerprint was verified before (warm restarts see the identical
    verdict), otherwise runs the analyses and caches the result;
    exports the ``alpa_plan_*`` metrics, annotates the flight recorder
    with leaked slots, and applies the verify policy (raise under
    ``"error"``, log under ``"warn"``).
    """
    from alpa_tpu import compile_cache as _cc
    from alpa_tpu.global_env import global_config

    from alpa_tpu.analysis import equivalence as _eq

    fingerprint = prog.fingerprint()
    do_mc = _model_check_enabled(len(instructions))
    do_num = getattr(global_config, "verify_plans_numerics",
                     "warn") != "off"
    num_budget = float(getattr(global_config, "numerics_error_budget",
                               0.05))
    do_eq = getattr(global_config, "verify_plans_equiv",
                    "warn") != "off" and reference is not None
    eq_budget = int(getattr(global_config, "equiv_term_budget",
                            _eq.DEFAULT_TERM_BUDGET))
    cache = _cc.get_compile_cache() if _cc.cache_enabled() else None
    verdict = None
    if cache is not None:
        key = _cache_key(cache, fingerprint, prog.mode, do_mc,
                         numerics=do_num, numerics_budget=num_budget,
                         equiv=do_eq, equiv_budget=eq_budget,
                         ref_digest=_eq.reference_digest(
                             reference if do_eq else None))
        hit = cache.get("plan_verdict", key)
        if isinstance(hit, dict) and \
                hit.get("version") == ANALYSES_VERSION:
            verdict = PlanVerdict.from_dict(hit)
    if verdict is None:
        model = build_model(instructions, prog.slot_of,
                            preplaced_shardings, recs,
                            protected_keys=protected_keys,
                            mode=prog.mode,
                            opt_state_keys=opt_state_keys,
                            provenance_keys=provenance_keys,
                            reference=reference if do_eq else None)
        verdict = verify_model(
            model, hooks=prog.hooks, model_check=do_mc,
            overlap_window=getattr(prog, "overlap_window", 0) or 0,
            model_check_budget=getattr(
                global_config, "model_check_state_budget", None),
            numerics=do_num, numerics_budget=num_budget,
            equiv=do_eq, equiv_budget=eq_budget)
        if cache is not None:
            cache.put("plan_verdict", key, verdict.to_dict())

    # metrics + flight annotation (process-global observability)
    for m, b in verdict.stats.get("peak_bytes", {}).items():
        _PEAK_BYTES.labels(str(m)).set(b)
    for m, b in verdict.stats.get("opt_state_bytes", {}).items():
        _OPT_STATE_BYTES.labels(str(m)).set(b)
    _ZERO_SAVED.set(float(verdict.stats.get("zero_bytes_saved", 0.0)))
    leaked = verdict.stats.get("leaked_vars", ())
    if leaked:
        _LEAKED_SLOTS.inc(verdict.stats.get("leaked_slots",
                                            len(leaked)))
        from alpa_tpu.telemetry import flight as _flight
        _flight.annotate("leaked_slots", list(leaked))
    _VERDICTS.labels(
        "error" if verdict.errors
        else ("warning" if verdict.warnings else "ok")).inc()

    # model-check observability + the fault layer's static retry
    # classification (replayed on cache hits too, so a warm restart's
    # call_with_retry sees the same refusals as the cold compile)
    from alpa_tpu.analysis import model_check as _mc
    from alpa_tpu import fault as _fault
    mc_stats = verdict.stats.get("model_check")
    if mc_stats:
        mc_codes = {f.code for f in verdict.findings()
                    if f.analysis == "model_check"}
        result = ("error" if any(_mc.severity_of(c) == "error"
                                 for c in mc_codes)
                  else "warning" if any(_mc.severity_of(c) == "warning"
                                        for c in mc_codes)
                  else "ok")
        _mc.export_metrics(mc_stats, result)
        _fault.install_retry_classification(
            mc_stats.get("retry_sites", {}))
    else:
        _mc.export_metrics({}, "skipped")

    # numerics gauges replay from the deterministic stats on cache
    # hits too, so warm restarts export the cold compile's values
    num_stats = verdict.stats.get("numerics")
    if num_stats:
        from alpa_tpu.analysis import numerics as _num
        _num.export_metrics(num_stats)

    # translation-validation metrics replay from the deterministic
    # stats on cache hits too (same warm-restart contract)
    eq_stats = verdict.stats.get("equiv")
    if eq_stats is not None:
        eq_codes = {f.code for f in verdict.findings()
                    if f.analysis == "equiv"}
        result = ("error" if any(_eq.severity_of(c) == "error"
                                 for c in eq_codes)
                  else "warning" if any(_eq.severity_of(c) == "warning"
                                        for c in eq_codes)
                  else "ok")
        _eq.export_metrics(eq_stats, result)
    else:
        _eq.export_metrics(None, "skipped")

    _apply_policy(verdict, fingerprint)
    return verdict


def _apply_policy(verdict: PlanVerdict, fingerprint: str) -> None:
    from alpa_tpu.global_env import global_config
    policy = getattr(global_config, "verify_plans", "warn")
    # numerics-error policy is independent of verify_plans: a lossy
    # weight path / blown budget blocks launch even when the general
    # verifier is only warning
    if getattr(global_config, "verify_plans_numerics", "warn") == \
            "error":
        num_errors = [f for f in verdict.errors
                      if f.analysis == "numerics"]
        if num_errors:
            raise PlanVerificationError(
                "numerics certification failed "
                f"(plan {fingerprint[:12]}):\n"
                + "\n".join(f"  [{f.code}] {f.message}"
                            for f in num_errors[:10]),
                verdict)
    # same independence for the translation validation: an output-level
    # semantic mismatch blocks launch under verify_plans_equiv=error
    # even when the general verifier is only warning
    if getattr(global_config, "verify_plans_equiv", "warn") == "error":
        eq_errors = [f for f in verdict.errors
                     if f.analysis == "equiv"]
        if eq_errors:
            raise PlanVerificationError(
                "translation validation failed "
                f"(plan {fingerprint[:12]}):\n"
                + "\n".join(f"  [{f.code}] {f.message}"
                            for f in eq_errors[:10]),
                verdict)
    if verdict.errors and policy == "error":
        raise PlanVerificationError(
            "static plan verification failed "
            f"(plan {fingerprint[:12]}):\n"
            + "\n".join(f"  [{f.code}] {f.message}"
                        for f in verdict.errors[:10]),
            verdict)
    if verdict.errors:
        logger.warning(
            "plan verifier: %d error(s) in plan %s (verify_plans="
            "'warn'; set ALPA_TPU_VERIFY_PLANS=error to block "
            "compilation):\n%s", len(verdict.errors), fingerprint[:12],
            "\n".join(f"  [{f.code}] {f.message}"
                      for f in verdict.errors[:10]))
    elif verdict.warnings:
        logger.warning(
            "plan verifier: %d warning(s) in plan %s:\n%s",
            len(verdict.warnings), fingerprint[:12],
            "\n".join(f"  [{f.code}] {f.message}"
                      for f in verdict.warnings[:10]))


def load_cached_verdicts(cache=None) -> List[Dict[str, Any]]:
    """Cached verdicts from the compile cache's disk tier, newest
    first, WITHOUT recompiling anything:
    ``[{"key", "mtime", "verdict"}, ...]`` (verify_tool's data
    source)."""
    from alpa_tpu import compile_cache as _cc
    cache = cache or _cc.get_compile_cache()
    out = []
    for e in cache.entries():
        if e["namespace"] != "plan_verdict":
            continue
        try:
            import pickle
            with open(e["path"], "rb") as f:
                value = pickle.load(f)
            if isinstance(value, dict) and "__cache_format__" in value:
                value = value["payload"]
        except Exception:  # pylint: disable=broad-except
            continue
        if isinstance(value, dict) and "errors" in value:
            out.append({"key": e["key"], "mtime": e["mtime"],
                        "verdict": PlanVerdict.from_dict(value)})
    out.sort(key=lambda d: d["mtime"], reverse=True)
    return out


########################################
# per-edge typing verdict (reshard_tool --verify)
########################################


def verify_edge(shape: Tuple[int, ...], dtype: str, src_sharding,
                dst_sharding, weight: bool = False) -> List[str]:
    """Typing + numerics verdict for one cross-mesh edge, independent
    of a full program: endpoint byte match, sharding coverage,
    quantized codec legality, and the codec's documented error bound
    (block size, per-hop bound, and the composed single-hop plan
    bound).  Returns human-readable verdict lines appended to
    ``reshard_tool.py plan --verify``'s candidate table."""
    import numpy as np
    lines: List[str] = []
    try:
        itemsize = int(np.dtype(dtype).itemsize)
        nbytes = int(np.prod(shape, dtype=np.int64)) * itemsize
        lines.append(f"typing: payload {shape} {dtype} = {nbytes} B "
                     "on both endpoints (byte-matched)")
    except Exception as e:  # pylint: disable=broad-except
        return [f"typing: INVALID dtype {dtype!r}: {e}"]
    for name, sh in (("src", src_sharding), ("dst", dst_sharding)):
        try:
            n_shards = len(sh.devices_indices_map(tuple(shape)))
            lines.append(f"typing: {name} sharding covers the array "
                         f"({n_shards} shards)")
        except Exception as e:  # pylint: disable=broad-except
            lines.append(f"typing: {name} sharding INVALID for shape "
                         f"{shape}: {e}")
    if weight:
        lines.append("typing: weight edge — quantized codec "
                     "statically rejected (must cross losslessly)")
    elif dtype in ("float32", "bfloat16", "float16"):
        lines.append("typing: activation edge — quantized codec "
                     "eligible when enabled")
        # numerics: the codec's machine-readable error contract per
        # candidate mode, composed over this single hop (ISSUE 14)
        from alpa_tpu.pipeline_parallel import reshard_codec as _codec
        for mode in sorted(_codec.ERROR_BOUND):
            bound = _codec.ERROR_BOUND[mode]
            lines.append(
                f"numerics: codec {mode} block={_codec.BLOCK} "
                f"documented bound {bound:.6g} of blockmax; composed "
                f"plan bound after this hop {bound:.6g}")
    else:
        lines.append(f"typing: non-float dtype {dtype} — quantized "
                     "codec ineligible")
    return lines
