"""AST repo lint: machine-checked repo invariants (ISSUE 8).

The plan verifier (plan_verifier.py) checks what the *compiler emits*;
this module checks what the *repo contains*.  Both run in tier-1, so an
invariant violation fails CI instead of surviving as a convention that
only holds until the next contributor misses it.

Checked invariants (one code per rule):

``config-env``
    Every knob assigned in ``GlobalConfig.__init__`` must be seeded from
    an ``ALPA_TPU_*`` environment variable (the expression must contain
    an ``ALPA_TPU_*`` string literal).  Keeps every flag settable
    without code edits on a multi-host deployment, where env vars are
    the only config channel that reaches every process.

``config-doc``
    Every knob name must appear somewhere under ``docs/*.md`` (the knob
    reference table in docs/architecture.md satisfies this for all of
    them).

``metric-name``
    Metric families registered through the telemetry registry
    (``.counter(\"name\", \"desc\", ...)`` / ``.gauge`` / ``.histogram``
    with a literal name AND a literal description) must match
    ``alpa_[a-z0-9_]*`` so the /metrics namespace stays coherent.
    Trace-counter calls (``ttrace.counter(name, value)``) have no
    literal description and are not metric families.

``metric-doc``
    Every metric family registered through the telemetry registry must
    appear in ``docs/observability.md`` (mirrors ``config-doc`` for
    knobs).  An undocumented family is invisible to operators reading
    the metric reference — it may as well not exist.

``timer-import``
    No new imports of the deprecated ``alpa_tpu.timer`` bridge outside
    the two grandfathered call sites (the package re-export and the
    pipeshard deprecation shim).  New code uses ``alpa_tpu.telemetry``.

``fault-site``
    String-literal site names handed to ``fault.fire(...)`` or passed
    as ``site=`` / ``fault_site=`` keywords must be registered in
    ``fault.KNOWN_SITES`` — a typo'd site would otherwise never fire
    under any fault plan and never be caught.

``finding-code-doc``
    Every finding code string literal emitted by the static analyses
    under ``alpa_tpu/analysis/*`` (``typing.*``, ``deadlock.*``,
    ``liveness.*``, ``structure.*``, ``model.*``, ``retry.*``,
    ``numerics.*``, ``equiv.*``, …) must appear — backticked — in the
    docs/static_analysis.md taxonomy.  An undocumented finding code is
    a diagnostic an operator cannot look up.

``codec-bound``
    Any module defining a lossy codec (a module-level ``encode`` /
    ``decode`` function pair) must declare a machine-readable
    ``ERROR_BOUND`` dict (codec mode -> worst-case relative error) at
    module level, with non-empty string keys.  The numerics
    certification (``alpa_tpu.analysis.numerics``) composes exactly
    these constants per lossy hop — a codec without a declared bound
    (or with the bound hardcoded elsewhere) would silently escape the
    end-to-end error accounting.

Usage::

    from alpa_tpu.analysis import lint
    violations = lint.run_lint()        # [] when the repo is clean

Run standalone via ``python scripts/verify_tool.py verify lint``; run
in CI via ``tests/util/test_repo_lint.py``.
"""
import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["Violation", "run_lint", "repo_root", "TIMER_IMPORT_ALLOWLIST"]

_METRIC_NAME_RE = re.compile(r"^alpa_[a-z0-9_]+$")
_ENV_VAR_RE = re.compile(r"^ALPA_TPU_[A-Z0-9_]+$")

#: Grandfathered importers of the deprecated timer bridge (repo-relative
#: posix paths).  Do not grow this list — new code uses
#: alpa_tpu.telemetry.
TIMER_IMPORT_ALLOWLIST = frozenset({
    "alpa_tpu/__init__.py",
    "alpa_tpu/pipeline_parallel/pipeshard_executable.py",
})


@dataclasses.dataclass(frozen=True)
class Violation:
    """One lint finding: ``code`` names the rule (see module docstring),
    ``path``/``line`` point at the offending source."""
    code: str
    path: str
    line: int
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.code}] {self.message}"


def repo_root() -> str:
    """The repository root (parent of the ``alpa_tpu`` package dir)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _iter_py_files(root: str) -> Iterable[str]:
    """Library + scripts .py files (tests excluded: they legitimately
    use fake fault sites and scratch metric registries)."""
    for sub in ("alpa_tpu", "scripts"):
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _parse(path: str) -> Optional[ast.AST]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        return ast.parse(src, filename=path)
    except SyntaxError:
        return None


def _str_constants(node: ast.AST) -> Iterable[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


def _rel(root: str, path: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


# ---- rule: config-env / config-doc -----------------------------------


def _docs_text(root: str) -> str:
    chunks = []
    docs = os.path.join(root, "docs")
    if os.path.isdir(docs):
        for fn in sorted(os.listdir(docs)):
            if fn.endswith(".md"):
                with open(os.path.join(docs, fn), encoding="utf-8") as f:
                    chunks.append(f.read())
    return "\n".join(chunks)


def _check_global_config(root: str) -> List[Violation]:
    path = os.path.join(root, "alpa_tpu", "global_env.py")
    tree = _parse(path)
    if tree is None:
        return [Violation("config-env", _rel(root, path), 1,
                          "global_env.py failed to parse")]
    init = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "GlobalConfig":
            for item in node.body:
                if (isinstance(item, ast.FunctionDef)
                        and item.name == "__init__"):
                    init = item
                    break
            break
    if init is None:
        return [Violation("config-env", _rel(root, path), 1,
                          "GlobalConfig.__init__ not found")]
    docs = _docs_text(root)
    out: List[Violation] = []
    for stmt in ast.walk(init):
        if not isinstance(stmt, ast.Assign):
            continue
        targets = [t for t in stmt.targets
                   if isinstance(t, ast.Attribute)
                   and isinstance(t.value, ast.Name)
                   and t.value.id == "self"]
        for t in targets:
            knob = t.attr
            has_env = any(_ENV_VAR_RE.match(s)
                          for s in _str_constants(stmt.value))
            if not has_env:
                out.append(Violation(
                    "config-env", _rel(root, path), stmt.lineno,
                    f"global_config.{knob} is not seeded from an "
                    f"ALPA_TPU_* environment variable"))
            if knob not in docs:
                out.append(Violation(
                    "config-doc", _rel(root, path), stmt.lineno,
                    f"global_config.{knob} is not documented in any "
                    f"docs/*.md (add it to the knob table in "
                    f"docs/architecture.md)"))
    return out


# ---- rule: metric-name ------------------------------------------------


def _metric_families(tree: ast.AST) -> Iterable[Tuple[str, int]]:
    """Yield ``(name, lineno)`` for every metric *family* registration
    in the tree.  A family registration carries (name, description):
    two leading string literals.  Trace counters (name, value) and
    dynamic names (f-strings) are out of scope."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("counter", "gauge", "histogram")):
            continue
        if len(node.args) < 2:
            continue
        name_arg, desc_arg = node.args[0], node.args[1]
        if not (isinstance(name_arg, ast.Constant)
                and isinstance(name_arg.value, str)
                and isinstance(desc_arg, ast.Constant)
                and isinstance(desc_arg.value, str)):
            continue
        yield name_arg.value, node.lineno


def _check_metric_names(root: str, rel: str,
                        tree: ast.AST) -> List[Violation]:
    out: List[Violation] = []
    for name, lineno in _metric_families(tree):
        if not _METRIC_NAME_RE.match(name):
            out.append(Violation(
                "metric-name", rel, lineno,
                f"metric family {name!r} does not match "
                f"alpa_[a-z0-9_]* (keep the /metrics namespace "
                f"coherent)"))
    return out


# ---- rule: metric-doc -------------------------------------------------


def _observability_text(root: str) -> str:
    path = os.path.join(root, "docs", "observability.md")
    if not os.path.isfile(path):
        return ""
    with open(path, encoding="utf-8") as f:
        return f.read()


def _check_metric_docs(rel: str, tree: ast.AST,
                       obs_text: str) -> List[Violation]:
    out: List[Violation] = []
    for name, lineno in _metric_families(tree):
        # Malformed names are already flagged by metric-name; only
        # well-formed families get the documentation requirement.
        if _METRIC_NAME_RE.match(name) and name not in obs_text:
            out.append(Violation(
                "metric-doc", rel, lineno,
                f"metric family {name!r} is not documented in "
                f"docs/observability.md (add a row to the metric "
                f"reference)"))
    return out


# ---- rule: timer-import ----------------------------------------------


def _check_timer_imports(root: str, rel: str,
                         tree: ast.AST) -> List[Violation]:
    if rel in TIMER_IMPORT_ALLOWLIST or rel == "alpa_tpu/timer.py":
        return []
    out: List[Violation] = []
    for node in ast.walk(tree):
        hit = False
        if isinstance(node, ast.Import):
            hit = any(a.name == "alpa_tpu.timer" or
                      a.name.startswith("alpa_tpu.timer.")
                      for a in node.names)
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            hit = (mod == "alpa_tpu.timer"
                   or mod.startswith("alpa_tpu.timer.")
                   or (mod == "alpa_tpu"
                       and any(a.name == "timer" for a in node.names)))
        if hit:
            out.append(Violation(
                "timer-import", rel, node.lineno,
                "new import of the deprecated alpa_tpu.timer bridge "
                "(use alpa_tpu.telemetry; allowlist in "
                "analysis/lint.py if truly unavoidable)"))
    return out


# ---- rule: fault-site -------------------------------------------------


def _known_sites() -> Set[str]:
    from alpa_tpu.fault import KNOWN_SITES
    return set(KNOWN_SITES)


def _check_fault_sites(root: str, rel: str, tree: ast.AST,
                       known: Set[str]) -> List[Violation]:
    if rel == "alpa_tpu/fault.py":
        return []  # the registry itself (docstring examples etc.)
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        site_literals: List[Tuple[str, int]] = []
        # fault.fire("<site>", ...) / _fault.fire("<site>", ...)
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "fire" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            site_literals.append((node.args[0].value, node.lineno))
        for kw in node.keywords:
            if (kw.arg in ("site", "fault_site")
                    and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)):
                site_literals.append((kw.value.value, kw.value.lineno))
        for site, line in site_literals:
            if site not in known:
                out.append(Violation(
                    "fault-site", rel, line,
                    f"fault site {site!r} is not registered in "
                    f"fault.KNOWN_SITES (typo, or add the site to the "
                    f"registry + docstring table)"))
    return out


# ---- rule: finding-code-doc -------------------------------------------

#: a finding code literal: "<analysis>.<kebab-name>" for one of the
#: known analysis families (anchored so prose never matches; must end
#: on an alphanumeric so "model.hazard-"-style prefix literals used to
#: build codes dynamically are out of scope)
_FINDING_CODE_RE = re.compile(
    r"^(typing|deadlock|liveness|structure|model|retry|numerics|equiv)"
    r"\.[a-z][a-z0-9-]*[a-z0-9]$")


def _static_analysis_text(root: str) -> str:
    path = os.path.join(root, "docs", "static_analysis.md")
    if not os.path.isfile(path):
        return ""
    with open(path, encoding="utf-8") as f:
        return f.read()


def _check_finding_codes(rel: str, tree: ast.AST,
                         sa_text: str) -> List[Violation]:
    """Every finding-code string literal in an analysis module must be
    documented (backticked) in docs/static_analysis.md — the taxonomy
    tables are the operator's only decoder ring for verdict output."""
    if not rel.startswith("alpa_tpu/analysis/"):
        return []
    out: List[Violation] = []
    seen: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Constant)
                and isinstance(node.value, str)):
            continue
        code = node.value
        if code in seen or not _FINDING_CODE_RE.match(code):
            continue
        seen.add(code)
        if f"`{code}`" not in sa_text:
            out.append(Violation(
                "finding-code-doc", rel, node.lineno,
                f"finding code {code!r} is not documented in "
                f"docs/static_analysis.md (add it to the analysis's "
                f"taxonomy table)"))
    return out


# ---- rule: codec-bound ------------------------------------------------


def _check_codec_bounds(rel: str, tree: ast.AST) -> List[Violation]:
    """A module-level encode/decode pair marks a lossy codec module; it
    must declare a module-level ``ERROR_BOUND`` dict literal with
    non-empty string keys (values may be computed expressions like
    ``1.0 / 254.0``).  Only ``tree.body`` is inspected — nested helper
    defs (e.g. a local ``decode`` closure) are not codecs."""
    top = {n.name for n in tree.body
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    if not {"encode", "decode"} <= top:
        return []
    for n in tree.body:
        if not isinstance(n, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "ERROR_BOUND"
                   for t in n.targets):
            continue
        if (isinstance(n.value, ast.Dict) and n.value.keys
                and all(isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        for k in n.value.keys)):
            return []
        return [Violation(
            "codec-bound", rel, n.lineno,
            "ERROR_BOUND must be a non-empty dict literal with string "
            "codec-mode keys (the numerics analysis consumes it "
            "machine-readably)")]
    return [Violation(
        "codec-bound", rel, 1,
        "module defines a lossy encode/decode codec pair but declares "
        "no module-level ERROR_BOUND dict — the numerics certification "
        "cannot compose its round-trip error (see reshard_codec.py)")]


# ---- driver -----------------------------------------------------------


def run_lint(root: Optional[str] = None) -> List[Violation]:
    """Run every lint rule over the repo; returns all violations
    (empty list = clean), ordered by path then line."""
    root = root or repo_root()
    known = _known_sites()
    obs_text = _observability_text(root)
    sa_text = _static_analysis_text(root)
    out: List[Violation] = list(_check_global_config(root))
    for path in _iter_py_files(root):
        tree = _parse(path)
        rel = _rel(root, path)
        if tree is None:
            out.append(Violation("parse", rel, 1, "file failed to parse"))
            continue
        out.extend(_check_metric_names(root, rel, tree))
        out.extend(_check_metric_docs(rel, tree, obs_text))
        out.extend(_check_timer_imports(root, rel, tree))
        out.extend(_check_fault_sites(root, rel, tree, known))
        out.extend(_check_finding_codes(rel, tree, sa_text))
        out.extend(_check_codec_bounds(rel, tree))
    out.sort(key=lambda v: (v.path, v.line, v.code))
    return out


def format_report(violations: List[Violation]) -> str:
    if not violations:
        return "repo lint: clean (0 violations)"
    lines = [f"repo lint: {len(violations)} violation(s)"]
    lines.extend(f"  {v}" for v in violations)
    return "\n".join(lines)


if __name__ == "__main__":  # pragma: no cover - debugging convenience
    import sys
    vs = run_lint()
    print(format_report(vs))
    sys.exit(1 if vs else 0)
