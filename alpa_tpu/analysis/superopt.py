"""Certified plan superoptimization (ISSUE 17 tentpole).

PRs 13-15 built a seven-analysis verifier — typing, deadlock, liveness,
structure, model checking, numerics certification, and translation
validation — exactly so lowered plans could be rewritten *boldly* and
checked for free.  This module cashes in that license: a search-based
rewrite engine that runs after ``lower_to_register_file`` and transforms
the pipeline instruction list under four rewrite families, re-lowers the
winner, and accepts it **only if the full verdict on the rewritten
program introduces no ``(analysis, code)`` finding absent from the
baseline verdict**.  Any new finding rejects the rewrite, so the engine
is sound by construction — an unsound search heuristic costs a rejected
candidate, never a wrong answer.

Rewrite families (searched greedily with a bounded beam and a
rewrite-step budget, scored by :func:`~alpa_tpu.analysis.critical_path.
simulate_dag` over CalibrationStore-calibrated costs with an analytic
fallback below ``calibration_min_samples``):

1. **Re-scheduling** — reorder instructions within the
   ``partition_streams`` dependency order (hazard edges + per-channel
   FIFO order preserved) by critical-path list scheduling, shrinking
   the simulated makespan.
2. **FREE sinking/hoisting** — the same scheduler with a memory-aware
   priority (FREEs eagerly, allocations lazily) cuts the simulated
   peak-live-bytes each mesh reaches (``alpa_plan_peak_bytes`` is the
   static analogue the verifier exports).
3. **Transfer fusion/fission** — relocate same-edge groupable RESHARDs
   adjacent (past intervening *independent* instructions, beyond the
   coalescer's adjacent/interleaved-FREE reach) so lowering batches
   them; fission caps oversized groups via ``superopt_max_group``
   (threaded into the shared legality oracle, see
   :func:`reshard_group_extent`).
4. **Recompute-vs-keep flips** — clone a cheap, idempotent activation
   producer in front of a late consumer and free the original value
   after its early consumers, trading one cheap RUN for a shorter live
   range.

A candidate is *admissible* only if it regresses neither the simulated
critical path nor the simulated total peak bytes; the best admissible
candidate is then lowered for real and gated on the verdict diff.
Accepted decisions are cached in the ``superopt`` compile-cache
namespace keyed by baseline program fingerprint + calibration-store
fingerprint + knobs, so warm restarts replay the winning rewrite with
zero search and an identical plan fingerprint.

Shared legality oracle: :func:`reshard_group_extent` is the single
same-edge RESHARD grouping legality check — the registers-mode
coalescer in ``runtime_emitter`` (phase 2a) and the fusion family here
are its two callers (ISSUE 17 satellite 2).

Knobs: ``superopt_mode`` off|suggest|auto (+ ``superopt_beam_width``,
``superopt_step_budget``, ``superopt_verify_budget``,
``superopt_max_group``; all under ``ALPA_TPU_SUPEROPT*``).  Metrics:
``alpa_superopt_*``.  Debug dump: ``superopt.txt``
(``monitoring.dump_debug_info``).  Tooling: ``scripts/perf_tool.py
superopt``; bench: ``benchmark/superopt_bench.py``.
"""
import copy
import dataclasses
import logging
from typing import (Any, Callable, Dict, FrozenSet, List, Optional,
                    Sequence, Set, Tuple)

from alpa_tpu.analysis.critical_path import MemSpec, simulate_dag
from alpa_tpu.global_env import global_config
from alpa_tpu.telemetry import metrics as _tmetrics

logger = logging.getLogger(__name__)

__all__ = [
    "PlanScore", "SuperoptOutcome", "reshard_group_extent",
    "apply_layout", "check_layout", "score_instructions",
    "superopt_search", "run_superopt", "verdict_new_findings",
    "verdict_diff", "format_superopt_report", "SUPEROPT_VERSION",
]

#: Bump to invalidate cached superopt decisions on engine changes.
SUPEROPT_VERSION = 1

# analytic fallbacks (µs) when the calibration store has no measured
# override — only relative magnitudes matter to the search, and every
# candidate and its baseline are priced by the same model
_DEFAULT_RUN_US = 100.0
_DEFAULT_WIRE_BYTES_PER_S = 1e9
_FREE_US = 1.0

_REG = _tmetrics.get_registry()
_M_ATTEMPTED = _REG.counter(
    "alpa_superopt_rewrites_attempted_total",
    "Superopt rewrite candidates scored, by rewrite family",
    labelnames=("family",))
_M_ACCEPTED = _REG.counter(
    "alpa_superopt_rewrites_accepted_total",
    "Superopt rewrites accepted by the seven-analysis verdict gate")
_M_REJECTED = _REG.counter(
    "alpa_superopt_rewrites_rejected_total",
    "Superopt rewrites rejected, by reason (verifier = the verdict "
    "gate found a new (analysis, code) finding; score = no admissible "
    "improvement; fingerprint = warm-restart replay mismatch)",
    labelnames=("reason",))
_M_CP_DELTA = _REG.gauge(
    "alpa_superopt_critical_path_delta_us",
    "Simulated critical-path change of the last accepted rewrite "
    "(negative = faster)")
_M_PEAK_DELTA = _REG.gauge(
    "alpa_superopt_peak_bytes_delta",
    "Simulated total peak-live-bytes change of the last accepted "
    "rewrite (negative = smaller)")
_M_CACHE = _REG.counter(
    "alpa_superopt_cache_total",
    "Superopt compile-cache lookups, by result (hit = zero-search "
    "warm replay)",
    labelnames=("result",))


########################################
# shared fusion legality oracle (satellite 2)
########################################


def reshard_group_extent(recs: Sequence[Dict[str, Any]], i: int,
                         max_members: int = 0
                         ) -> Tuple[List[int], List[int], int, int]:
    """The maximal legal same-edge RESHARD group starting at rec ``i``.

    ONE legality oracle, two callers: the registers-mode coalescer in
    ``runtime_emitter.lower_to_register_file`` (phase 2a) and the
    superopt fusion family.  Group membership may hop intervening FREEs
    — safe because ``emit_free_instructions`` places every FREE after
    its slots' last use, so the batched group runs first and the FREE is
    re-emitted right after it — but a same-edge RESHARD touching a
    hopped slot ends the group instead of joining (it would reorder past
    a FREE of its own slots).  Only ``groupable`` (direct_p2p) members
    may join a multi-member group; ``max_members > 0`` caps the group
    size (the fission knob ``superopt_max_group``: oversized groups
    serialize behind the overlap in-flight window, so splitting them is
    a legal de-optimization the search may prefer).

    Returns ``(members, hopped, n_free_hops, next_i)``: rec indices in
    the group, hopped FREE rec indices to re-emit after it, the number
    of FREE hops that actually enabled a later member, and the index the
    caller resumes scanning at.
    """
    r = recs[i]
    n = len(recs)
    edge = r["edge"]
    members: List[int] = []
    hopped: List[int] = []
    blocked: Set[int] = set()
    n_free_hops = 0
    counted = 0
    j = i
    while j < n:
        q = recs[j]
        if (q["kind"] == "RESHARD" and q["edge"] == edge and
                (j == i or (r.get("groupable", True) and
                            q.get("groupable", True)))):
            if q["ss"] in blocked or q["ds"] in blocked:
                break   # would reorder past a FREE of its slots
            if max_members > 0 and len(members) >= max_members:
                break   # fission: cap the batched group size
            if len(hopped) > counted:
                n_free_hops += len(hopped) - counted
                counted = len(hopped)
            members.append(j)
            j += 1
            continue
        if q["kind"] == "FREE":
            hopped.append(j)
            blocked.update(q["slots"])
            j += 1
            continue
        break
    return members, hopped, n_free_hops, j


########################################
# layouts: serializable rewrite decisions
########################################
#
# A layout describes a rewritten instruction list purely in terms of the
# baseline list, so accepted decisions are cacheable and replayable with
# zero search:
#
#   i                  -> baseline instruction i, verbatim
#   ["clone", i]       -> a copy of baseline RUN i (recompute flips)
#   ["free", i, [p..]] -> a FREE of the given key positions of baseline
#                         FREE i (free splitting / motion)
#
# Every baseline non-FREE instruction appears exactly once; the key
# positions of each baseline FREE appear at most once across the layout.


def _entry_kind(e) -> str:
    if isinstance(e, int):
        return "orig"
    return str(e[0])


def identity_layout(n: int) -> List[Any]:
    return list(range(n))


def check_layout(instructions: Sequence[Any], layout: Sequence[Any]):
    """Validate a layout against the baseline list; raises ValueError
    on malformed entries (the cache-replay safety check)."""
    from alpa_tpu.pipeline_parallel.runtime_emitter import PipelineInstType
    n = len(instructions)
    seen: Set[int] = set()
    free_positions: Dict[int, Set[int]] = {}
    for e in layout:
        if isinstance(e, int):
            if not 0 <= e < n:
                raise ValueError(f"layout index {e} out of range")
            if instructions[e].opcode != PipelineInstType.FREE:
                if e in seen:
                    raise ValueError(f"instruction {e} appears twice")
                seen.add(e)
            else:
                pos = set(range(len(instructions[e].free_keys)))
                if free_positions.setdefault(e, set()) & pos:
                    raise ValueError(f"FREE {e} keys emitted twice")
                free_positions[e] |= pos
            continue
        kind = _entry_kind(e)
        if kind == "clone":
            i = int(e[1])
            if not 0 <= i < n or \
                    instructions[i].opcode != PipelineInstType.RUN:
                raise ValueError(f"clone of non-RUN instruction {i}")
        elif kind == "free":
            i, pos = int(e[1]), set(int(p) for p in e[2])
            if not 0 <= i < n or \
                    instructions[i].opcode != PipelineInstType.FREE:
                raise ValueError(f"free-split of non-FREE {i}")
            if not pos or max(pos) >= len(instructions[i].free_keys):
                raise ValueError(f"free-split positions {sorted(pos)} "
                                 f"out of range for FREE {i}")
            if free_positions.setdefault(i, set()) & pos:
                raise ValueError(f"FREE {i} keys emitted twice")
            free_positions[i] |= pos
        else:
            raise ValueError(f"unknown layout entry {e!r}")
    missing = [i for i, inst in enumerate(instructions)
               if inst.opcode != PipelineInstType.FREE and i not in seen]
    if missing:
        raise ValueError(f"layout drops instruction(s) {missing[:8]}")


def apply_layout(instructions: Sequence[Any],
                 layout: Sequence[Any]) -> List[Any]:
    """Materialize the rewritten instruction list a layout describes."""
    from alpa_tpu.pipeline_parallel.runtime_emitter import (
        PipelineInstType, PipelineInstruction)
    out: List[Any] = []
    for e in layout:
        if isinstance(e, int):
            out.append(instructions[e])
        elif _entry_kind(e) == "clone":
            out.append(copy.copy(instructions[int(e[1])]))
        else:  # free
            src = instructions[int(e[1])]
            keys = [src.free_keys[int(p)] for p in e[2]]
            out.append(PipelineInstruction(
                PipelineInstType.FREE, free_keys=keys, info=src.info))
    return out


def _compose(base_layout: Sequence[Any],
             edits: Sequence[Any]) -> List[Any]:
    """Compose a layout-over-the-current-list with the current layout,
    yielding a layout over the baseline list."""
    out: List[Any] = []
    for e in edits:
        if isinstance(e, int):
            out.append(base_layout[e])
            continue
        kind = _entry_kind(e)
        cur = base_layout[int(e[1])]
        if kind == "clone":
            out.append(["clone", cur if isinstance(cur, int)
                        else int(cur[1])])
        else:  # free over a possibly-already-split FREE
            if isinstance(cur, int):
                out.append(["free", cur, [int(p) for p in e[2]]])
            else:
                out.append(["free", int(cur[1]),
                            [int(cur[2][int(p)]) for p in e[2]]])
    return out


########################################
# plan-level cost model + simulation
########################################


def _key_nbytes(var) -> float:
    aval = getattr(var, "aval", None)
    if aval is None:
        return 0.0
    shape = getattr(aval, "shape", ())
    size = 1
    for d in shape:
        size *= int(d)
    itemsize = getattr(getattr(aval, "dtype", None), "itemsize", 4)
    return float(size * itemsize)


class _CostModel:
    """Per-instruction durations: calibrated medians when the store has
    enough samples (``calibration_min_samples``), analytic fallback
    otherwise.  Group-marginal pricing: a cross-mesh RESHARD directly
    following a same-edge RESHARD pays only the byte leg (no per-message
    latency) — the lowering will coalesce the pair into one batched
    group, which is exactly what makes the fusion family profitable."""

    def __init__(self, store=None, min_samples: Optional[int] = None):
        self.store = store
        self.min_samples = min_samples
        self._cache: Dict[int, Tuple[str, float, float]] = {}
        latency_s = float(getattr(
            global_config, "resharding_transfer_latency_s", 0.0) or 0.0)
        self.latency_us = latency_s * 1e6
        bw = float(getattr(
            global_config, "resharding_wire_bandwidth", 0.0) or 0.0)
        self.bytes_per_us = (bw or _DEFAULT_WIRE_BYTES_PER_S) / 1e6

    def _measured(self, kind: str, signature: str) -> Optional[float]:
        if self.store is None:
            return None
        return self.store.measured_us(kind, signature, self.min_samples)

    def _base(self, inst) -> Tuple[str, float, float]:
        """(kind, full_cost_us, marginal_cost_us) for one instruction."""
        key = id(inst)
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        from alpa_tpu.pipeline_parallel.runtime_emitter import (
            PipelineInstType)
        from alpa_tpu.telemetry import calibration as _cal
        if inst.opcode == PipelineInstType.RUN:
            c = self._measured(
                "stage_run", _cal.stage_signature(str(inst.info)))
            c = c if c is not None else _DEFAULT_RUN_US
            out = ("RUN", c, c)
        elif inst.opcode == PipelineInstType.RESHARD:
            nbytes = _key_nbytes(inst.var_key[0])
            wire = nbytes / self.bytes_per_us if self.bytes_per_us else 0.0
            cross = inst.src_mesh != inst.dst_mesh
            c = self._measured("reshard_wire", _cal.edge_signature(
                str(inst.src_mesh), str(inst.dst_mesh)))
            if c is None:
                c = (self.latency_us + wire) if cross else \
                    max(1.0, 0.5 * wire)
            out = ("RESHARD", c, max(1.0, c - self.latency_us)
                   if cross else c)
        else:
            out = ("FREE", _FREE_US, _FREE_US)
        self._cache[key] = out
        return out

    def durations(self, instructions: Sequence[Any]) -> List[float]:
        from alpa_tpu.pipeline_parallel.runtime_emitter import (
            PipelineInstType)
        durs: List[float] = []
        prev_edge = None
        for inst in instructions:
            kind, full, marginal = self._base(inst)
            if kind == "RESHARD" and inst.src_mesh != inst.dst_mesh:
                edge = (inst.src_mesh, inst.dst_mesh)
                durs.append(marginal if edge == prev_edge else full)
                prev_edge = edge
            else:
                durs.append(full)
                if inst.opcode != PipelineInstType.FREE:
                    prev_edge = None
        return durs


@dataclasses.dataclass(frozen=True)
class PlanScore:
    """One candidate's simulated figures of merit."""
    makespan_us: float
    peak_bytes: Tuple[float, ...]

    @property
    def total_peak(self) -> float:
        return float(sum(self.peak_bytes))

    def admissible_vs(self, base: "PlanScore",
                      eps: float = 1e-9) -> bool:
        """True when this candidate regresses neither objective."""
        return (self.makespan_us <= base.makespan_us * (1 + eps) + eps
                and self.total_peak <= base.total_peak * (1 + eps) + eps)

    def to_dict(self) -> Dict[str, Any]:
        return {"makespan_us": round(self.makespan_us, 3),
                "peak_bytes": {str(m): b for m, b in
                               enumerate(self.peak_bytes)}}


def _mem_spec(instructions: Sequence[Any],
              num_meshes: int) -> MemSpec:
    """Slot-level memory footprint of an instruction list, mirroring
    phase-1 lowering's value-key slots (launch-placed keys — read or
    killed before any write — count as preplaced)."""
    from alpa_tpu.pipeline_parallel.runtime_emitter import (
        instruction_accesses)
    slot_ids: Dict[Tuple[Any, int, int], int] = {}
    nbytes: Dict[int, float] = {}
    mesh_of: Dict[int, int] = {}
    writes: List[List[int]] = []
    kills: List[List[int]] = []
    written: Set[int] = set()
    preplaced: Set[int] = set()

    def _slot(key):
        s = slot_ids.get(key)
        if s is None:
            s = slot_ids[key] = len(slot_ids)
            nbytes[s] = _key_nbytes(key[0])
            mesh_of[s] = key[2] if 0 <= key[2] < num_meshes else 0
        return s

    for inst in instructions:
        w: List[int] = []
        k: List[int] = []
        for key, kind in instruction_accesses(inst):
            s = _slot(key)
            if kind == "write":
                w.append(s)
                written.add(s)
            elif kind == "kill":
                k.append(s)
                if s not in written:
                    preplaced.add(s)
            elif s not in written:
                preplaced.add(s)
        writes.append(w)
        kills.append(k)
    return MemSpec(writes=writes, kills=kills, nbytes=nbytes,
                   mesh_of=mesh_of, num_meshes=max(1, num_meshes),
                   preplaced=frozenset(preplaced))


def score_instructions(instructions: Sequence[Any], num_meshes: int,
                       cost_model: Optional[_CostModel] = None
                       ) -> PlanScore:
    """Simulate one instruction list: per-mesh streams chained serially,
    cross-stream hazard deps, calibrated durations -> (makespan,
    per-mesh simulated peak live bytes)."""
    from alpa_tpu.pipeline_parallel.runtime_emitter import (
        partition_streams)
    cost_model = cost_model or _CostModel()
    streams = partition_streams(list(instructions), num_meshes)
    preds: List[Set[int]] = [set(streams.deps.get(i, ()))
                             for i in range(len(instructions))]
    for stream in streams.streams:
        for a, b in zip(stream, stream[1:]):
            preds[b].add(a)
    durs = cost_model.durations(instructions)
    mem = _mem_spec(instructions, num_meshes)
    makespan, _, peaks = simulate_dag(durs, preds, mem)
    return PlanScore(makespan_us=makespan, peak_bytes=tuple(peaks))


########################################
# hazard graph + rewrite families
########################################


def _hazard_preds(instructions: Sequence[Any]) -> List[Set[int]]:
    """Full reordering-legality graph: RAW/WAW/WAR/kill edges over value
    keys plus per-(src,dst) channel FIFO order (cross-mesh RESHARDs on
    one edge must keep their send order — the model checker's
    ``deadlock.channel-reorder`` invariant)."""
    from alpa_tpu.pipeline_parallel.runtime_emitter import (
        PipelineInstType, instruction_accesses)
    preds: List[Set[int]] = [set() for _ in instructions]
    history: Dict[Any, List[Tuple[int, str]]] = {}
    last_on_edge: Dict[Tuple[int, int], int] = {}
    prev_producer: Dict[Tuple[int, int], int] = {}
    for i, inst in enumerate(instructions):
        if inst.opcode == PipelineInstType.RESHARD and \
                inst.src_mesh != inst.dst_mesh:
            edge = (inst.src_mesh, inst.dst_mesh)
            prev = last_on_edge.get(edge)
            if prev is not None:
                preds[i].add(prev)
            last_on_edge[edge] = i
            # production order must track the channel's send order
            # (``deadlock.channel-reorder``): chain consecutive
            # payload producers on each edge
            src_key = (inst.var_key[0], inst.var_key[1], inst.src_mesh)
            h = history.get(src_key, ())
            prod = next((j for j, k in reversed(h) if k == "write"),
                        None)
            if prod is not None:
                pp = prev_producer.get(edge)
                if pp is not None and pp != prod:
                    preds[prod].add(pp)
                prev_producer[edge] = prod
        for key, kind in instruction_accesses(inst):
            # j == i happens when one instruction both kills and writes
            # a key (donated grad-accumulation RUNs) — never an edge.
            h = history.setdefault(key, [])
            if kind == "read":
                for j, k in reversed(h):
                    if k != "read":
                        if j != i:
                            preds[i].add(j)
                        break
            else:  # write / kill orders against every earlier access
                for j, _k in h:
                    if j != i:
                        preds[i].add(j)
            h.append((i, kind))
    return preds


def _list_schedule(instructions: Sequence[Any], durs: Sequence[float],
                   preds: Sequence[Set[int]],
                   gamma: float) -> List[int]:
    """Priority-topological reorder of the hazard DAG.  Priority is the
    critical-path bottom level minus ``gamma`` x net allocated bytes
    (gamma = 0 is pure critical-path list scheduling; gamma > 0 defers
    allocators and promotes FREEs, the memory-motion variant).  Returns
    a permutation of instruction indices."""
    n = len(instructions)
    succs: List[List[int]] = [[] for _ in range(n)]
    indeg = [0] * n
    for i, ps in enumerate(preds):
        for p in ps:
            succs[p].append(i)
            indeg[i] += 1
    b_level = [0.0] * n
    for i in range(n - 1, -1, -1):
        tail = max((b_level[s] for s in succs[i]), default=0.0)
        b_level[i] = durs[i] + tail
    net_alloc = [0.0] * n
    if gamma:
        mem = _mem_spec(instructions, 1)
        for i in range(n):
            net_alloc[i] = (sum(mem.nbytes[s] for s in mem.writes[i]) -
                            sum(mem.nbytes[s] for s in mem.kills[i]))
    import heapq
    ready = [(-(b_level[i] - gamma * net_alloc[i]), i)
             for i in range(n) if indeg[i] == 0]
    heapq.heapify(ready)
    order: List[int] = []
    while ready:
        _, i = heapq.heappop(ready)
        order.append(i)
        for s in succs[i]:
            indeg[s] -= 1
            if indeg[s] == 0:
                heapq.heappush(
                    ready, (-(b_level[s] - gamma * net_alloc[s]), s))
    if len(order) != n:     # cycle: keep the original order (never
        return list(range(n))   # happens on emitter output)
    return order


def _resched_candidates(instructions, cost_model,
                        ) -> List[Tuple[str, List[Any]]]:
    """Families 1 + 2: critical-path and memory-aware list schedules."""
    durs = cost_model.durations(instructions)
    preds = _hazard_preds(instructions)
    makespan = max(1.0, sum(durs))
    mem = _mem_spec(instructions, 1)
    peak = max(1.0, *(
        [sum(mem.nbytes[s] for s in mem.writes[i]) for i in
         range(len(instructions))] or [1.0]))
    out = []
    for family, gamma in (("reschedule", 0.0),
                          ("free_motion", makespan / peak),
                          ("free_motion", 10.0 * makespan / peak)):
        order = _list_schedule(instructions, durs, preds, gamma)
        if order != list(range(len(instructions))):
            out.append((family, list(order)))
    return out


def _fusion_candidates(instructions) -> List[Tuple[str, List[Any]]]:
    """Family 3: pull a cross-mesh RESHARD up adjacent to the previous
    same-edge RESHARD when every intervening instruction is independent
    of it — beyond the coalescer's FREE-hopping reach, so lowering can
    batch the pair into one grouped transfer."""
    from alpa_tpu.pipeline_parallel.runtime_emitter import (
        PipelineInstType, instructions_independent)
    out: List[Tuple[str, List[Any]]] = []
    last_at: Dict[Tuple[int, int], int] = {}
    n = len(instructions)
    for j in range(n):
        inst = instructions[j]
        if inst.opcode != PipelineInstType.RESHARD or \
                inst.src_mesh == inst.dst_mesh:
            continue
        edge = (inst.src_mesh, inst.dst_mesh)
        i = last_at.get(edge)
        last_at[edge] = j
        if i is None or j == i + 1:
            continue
        between = instructions[i + 1:j]
        if all(b.opcode == PipelineInstType.FREE or
               instructions_independent(b, inst) for b in between):
            order = (list(range(i + 1)) + [j] +
                     list(range(i + 1, j)) + list(range(j + 1, n)))
            out.append(("transfer_fusion", order))
            if len(out) >= 4:
                break
    return out


def _recompute_candidates(instructions) -> List[Tuple[str, List[Any]]]:
    """Family 4: for a value produced by a cheap idempotent RUN with a
    late extra consumer, free it after its early consumers and clone the
    producer right before the late one — shorter live range for one
    re-executed stage."""
    from alpa_tpu.pipeline_parallel.runtime_emitter import (
        PipelineInstType, instruction_accesses)
    n = len(instructions)
    producers: Dict[Any, int] = {}
    readers: Dict[Any, List[int]] = {}
    killers: Dict[Any, int] = {}
    kills_at: Dict[int, Set[Any]] = {}
    for i, inst in enumerate(instructions):
        for key, kind in instruction_accesses(inst):
            if kind == "write":
                producers.setdefault(key, i)
            elif kind == "read":
                readers.setdefault(key, []).append(i)
            else:
                killers[key] = i
                kills_at.setdefault(i, set()).add(key)
    out: List[Tuple[str, List[Any]]] = []
    for key, reads in readers.items():
        if len(reads) < 2 or key not in producers or key not in killers:
            continue
        prod, late, early = producers[key], reads[-1], reads[-2]
        fi = killers[key]
        if late - early < 4 or fi < late:
            continue
        p_inst = instructions[prod]
        if p_inst.opcode != PipelineInstType.RUN:
            continue
        donated = set(getattr(getattr(p_inst, "executable", None),
                              "donate_idx", ()) or ())
        if donated:
            continue    # not idempotent: re-running consumes its inputs
        # producer inputs must still be live at the clone point
        in_keys = {(k[0], k[1], p_inst.dst_mesh)
                   for k in p_inst.input_keys}
        if any(killers.get(k, n) < late for k in in_keys):
            continue
        f_inst = instructions[fi]
        pos = [p for p, k in enumerate(f_inst.free_keys)
               if tuple(k) == key]
        if not pos:
            continue
        rest = [p for p in range(len(f_inst.free_keys))
                if p not in pos]
        layout: List[Any] = []
        for i in range(n):
            if i == fi:
                if rest:
                    layout.append(["free", fi, rest])
                continue
            if i == late:
                layout.append(["clone", prod])
            layout.append(i)
            if i == early:
                layout.append(["free", fi, pos])
        out.append(("recompute", layout))
        if len(out) >= 2:
            break
    return out


def deoptimize_instructions(instructions: Sequence[Any],
                            cost_model: Optional[_CostModel] = None
                            ) -> List[Any]:
    """A hazard-legal adversarial reorder of an instruction list:
    topological over the full hazard DAG (so RAW/WAR/WAW and per-edge
    channel FIFO order all hold — the program is semantically
    identical), but with inverted list-scheduling priority and every
    FREE deferred as late as legality allows.  Live ranges stretch
    (peak bytes inflate) and streams serialize badly (the simulated
    critical path inflates).  This is the bench's adversarial baseline
    (``benchmark/superopt_bench.py``): the plan a register-file
    emitter *could* legally have produced, which ``superopt_mode=auto``
    must then recover."""
    import heapq
    from alpa_tpu.pipeline_parallel.runtime_emitter import (
        PipelineInstType)
    cost_model = cost_model or _CostModel()
    durs = cost_model.durations(instructions)
    preds = _hazard_preds(instructions)
    n = len(instructions)
    succs: List[List[int]] = [[] for _ in range(n)]
    indeg = [0] * n
    for i, ps in enumerate(preds):
        for p in ps:
            succs[p].append(i)
            indeg[i] += 1
    b_level = [0.0] * n
    for i in range(n - 1, -1, -1):
        tail = max((b_level[s] for s in succs[i]), default=0.0)
        b_level[i] = durs[i] + tail

    def _prio(i):
        # max-heap on (-key): shallow ops first, FREEs dead last
        penalty = 1e18 if \
            instructions[i].opcode == PipelineInstType.FREE else 0.0
        return -(-b_level[i] - penalty)

    ready = [(_prio(i), i) for i in range(n) if indeg[i] == 0]
    heapq.heapify(ready)
    order: List[int] = []
    while ready:
        _, i = heapq.heappop(ready)
        order.append(i)
        for s in succs[i]:
            indeg[s] -= 1
            if indeg[s] == 0:
                heapq.heappush(ready, (_prio(s), s))
    if len(order) != n:
        return list(instructions)
    return [instructions[i] for i in order]


########################################
# beam search
########################################


def superopt_search(instructions: Sequence[Any], num_meshes: int,
                    cost_model: Optional[_CostModel] = None,
                    beam_width: Optional[int] = None,
                    step_budget: Optional[int] = None
                    ) -> Tuple[List[Any], PlanScore, PlanScore,
                               List[Dict[str, Any]],
                               List[Tuple[List[Any], PlanScore]]]:
    """Greedy bounded-beam search over the four rewrite families.

    Returns ``(layout, baseline_score, best_score, log, candidates)``
    where ``layout`` describes the best admissible candidate over the
    baseline list (the identity layout when nothing improves) and
    ``candidates`` is the final admissible pool best-first — the gate's
    fallback order when the winner is rejected by the verifier.  Pure
    search: no lowering, no verification — the caller gates the winner.
    """
    beam_width = beam_width if beam_width is not None else int(
        getattr(global_config, "superopt_beam_width", 4))
    step_budget = step_budget if step_budget is not None else int(
        getattr(global_config, "superopt_step_budget", 32))
    cost_model = cost_model or _CostModel()
    base_score = score_instructions(instructions, num_meshes, cost_model)
    n = len(instructions)
    base = (identity_layout(n), list(instructions), base_score)
    beam = [base]
    best = base
    seen: Set[str] = set()
    log: List[Dict[str, Any]] = []
    steps = 0
    improved = True
    while improved and steps < step_budget:
        improved = False
        frontier = []
        for layout, insts, score in beam:
            cands = (_resched_candidates(insts, cost_model) +
                     _fusion_candidates(insts) +
                     _recompute_candidates(insts))
            for family, edits in cands:
                if steps >= step_budget:
                    break
                steps += 1
                _M_ATTEMPTED.labels(family).inc()
                new_layout = _compose(layout, edits)
                sig = repr(new_layout)
                if sig in seen:
                    continue
                seen.add(sig)
                try:
                    check_layout(instructions, new_layout)
                    new_insts = apply_layout(instructions, new_layout)
                    new_score = score_instructions(
                        new_insts, num_meshes, cost_model)
                except (ValueError, KeyError, IndexError) as e:
                    logger.debug("superopt: %s candidate invalid: %s",
                                 family, e)
                    continue
                if not new_score.admissible_vs(base_score):
                    continue
                log.append({
                    "family": family,
                    "makespan_us": round(new_score.makespan_us, 3),
                    "peak_bytes": round(new_score.total_peak, 1),
                })
                frontier.append((new_layout, new_insts, new_score))
        if frontier:
            frontier.sort(key=lambda t: (
                t[2].makespan_us / max(base_score.makespan_us, 1e-9) +
                t[2].total_peak / max(base_score.total_peak, 1e-9)))
            beam = frontier[:max(1, beam_width)]
            if (beam[0][2].makespan_us, beam[0][2].total_peak) < \
                    (best[2].makespan_us, best[2].total_peak):
                best = beam[0]
                improved = True
    # the gate pool holds only STRICT improvements — an equal-score
    # rewrite is pointless churn (and would dirty the plan fingerprint
    # for nothing), so it never reaches the verifier
    pool: List[Tuple[List[Any], PlanScore]] = []
    pool_seen: Set[str] = set()
    for layout, _insts, score in [best] + beam:
        sig = repr(layout)
        if sig in pool_seen or layout == base[0]:
            continue
        if not (score.makespan_us < base_score.makespan_us - 1e-9 or
                score.total_peak < base_score.total_peak - 1e-9):
            continue
        pool_seen.add(sig)
        pool.append((layout, score))
    # gate order = the search objective (normalized makespan + peak),
    # so the balanced winner is verified before single-axis rewrites
    pool.sort(key=lambda t: (
        t[1].makespan_us / max(base_score.makespan_us, 1e-9) +
        t[1].total_peak / max(base_score.total_peak, 1e-9)))
    return best[0], base_score, best[2], log, pool


########################################
# verdict gate
########################################


def verdict_new_findings(baseline, candidate) -> List[Tuple[str, str]]:
    """The ``(analysis, code)`` pairs present in the candidate verdict
    but absent from the baseline — the acceptance gate: non-empty means
    the rewrite is rejected."""
    base = {(f.analysis, f.code) for f in baseline.findings()}
    return sorted({(f.analysis, f.code) for f in candidate.findings()}
                  - base)


def verdict_diff(baseline, candidate) -> Dict[str, Any]:
    """Machine-readable verdict diff (scripts/perf_tool.py superopt and
    scripts/verify_tool.py share this shape)."""
    base = {(f.analysis, f.code) for f in baseline.findings()}
    cand = {(f.analysis, f.code) for f in candidate.findings()}
    return {
        "baseline_findings": sorted(f"{a}.{c}" if not c.startswith(a)
                                    else c for a, c in base),
        "candidate_findings": sorted(f"{a}.{c}" if not c.startswith(a)
                                     else c for a, c in cand),
        "new": [f"{a}:{c}" for a, c in sorted(cand - base)],
        "resolved": [f"{a}:{c}" for a, c in sorted(base - cand)],
        "ok": not (cand - base),
    }


########################################
# driver: cache + gate + metrics
########################################


@dataclasses.dataclass
class SuperoptOutcome:
    """Everything one superopt run decided, for the executable, the
    ``superopt.txt`` dump, tooling, and the bench."""
    mode: str                           # superopt_mode at decision time
    searched: bool                      # False on a warm cache replay
    cache_hit: bool
    accepted: bool
    layout: List[Any]
    baseline_score: PlanScore
    best_score: PlanScore
    baseline_fingerprint: str
    fingerprint: Optional[str]          # accepted program fingerprint
    rejected: List[Tuple[str, str]]     # gate findings that rejected it
    log: List[Dict[str, Any]]
    program: Any = None                 # accepted RegisterFileProgram
    instructions: Optional[List[Any]] = None

    @property
    def critical_path_delta_us(self) -> float:
        return self.best_score.makespan_us - \
            self.baseline_score.makespan_us

    @property
    def peak_bytes_delta(self) -> float:
        return self.best_score.total_peak - \
            self.baseline_score.total_peak

    def to_dict(self) -> Dict[str, Any]:
        return {
            "mode": self.mode,
            "searched": self.searched,
            "cache_hit": self.cache_hit,
            "accepted": self.accepted,
            "baseline": self.baseline_score.to_dict(),
            "best": self.best_score.to_dict(),
            "critical_path_delta_us": round(
                self.critical_path_delta_us, 3),
            "peak_bytes_delta": round(self.peak_bytes_delta, 1),
            "baseline_fingerprint": self.baseline_fingerprint,
            "fingerprint": self.fingerprint,
            "rejected_by": [f"{a}:{c}" for a, c in self.rejected],
            "n_rewrites": sum(
                1 for i, e in enumerate(self.layout)
                if not isinstance(e, int) or e != i),
            "log": self.log,
        }


def _knob_bits() -> Tuple:
    return (int(getattr(global_config, "superopt_beam_width", 4)),
            int(getattr(global_config, "superopt_step_budget", 32)),
            int(getattr(global_config, "superopt_max_group", 0)))


def run_superopt(instructions: Sequence[Any], num_meshes: int,
                 baseline_prog, lower: Callable[[Sequence[Any]], Any],
                 verify: Callable[[Any, Sequence[Any]], Any],
                 mode: Optional[str] = None) -> SuperoptOutcome:
    """The full certified-superoptimization driver.

    ``lower(insts)`` re-lowers a candidate instruction list into a
    RegisterFileProgram; ``verify(prog, insts)`` returns its
    seven-analysis verdict (reusing ``prog.verdict`` when lowering
    already verified).  Flow: consult the ``superopt`` compile-cache
    namespace (baseline fingerprint + calibration-store fingerprint +
    knobs) — a hit replays the accepted layout with **zero search**;
    otherwise beam-search, lower the best admissible candidate, and gate
    it on :func:`verdict_new_findings`.  ``mode="suggest"`` searches and
    reports but never applies; ``"auto"`` returns the accepted program
    for the executable to swap in.
    """
    from alpa_tpu.compile_cache import get_compile_cache
    from alpa_tpu.telemetry import calibration as _cal
    mode = mode or getattr(global_config, "superopt_mode", "off")
    store = _cal.get_calibration_store()
    cost_model = _CostModel(store=store)
    base_fp = baseline_prog.fingerprint()
    n = len(instructions)

    def _outcome(**kw) -> SuperoptOutcome:
        base_score = kw.pop("baseline_score")
        return SuperoptOutcome(
            mode=mode, baseline_score=base_score,
            baseline_fingerprint=base_fp, **kw)

    cache = get_compile_cache()
    cache_key = cache.make_key("superopt", (
        "superopt", SUPEROPT_VERSION, base_fp, baseline_prog.mode,
        store.fingerprint() if len(store) else "analytic",
        _knob_bits()))
    cached = cache.get("superopt", cache_key)
    base_verdict = verify(baseline_prog, instructions)

    if cached is not None:
        _M_CACHE.labels("hit").inc()
        layout = cached["layout"]
        try:
            check_layout(instructions, layout)
            new_insts = apply_layout(instructions, layout)
            prog = lower(new_insts)
            replay_ok = prog.fingerprint() == cached["fingerprint"]
        except Exception as e:  # pylint: disable=broad-except
            logger.warning("superopt: cached layout replay failed "
                           "(%s); re-searching", e)
            replay_ok = False
        if replay_ok:
            verdict = verify(prog, new_insts)
            new = verdict_new_findings(base_verdict, verdict)
            if not new:
                score = score_instructions(new_insts, num_meshes,
                                           cost_model)
                base_score = PlanScore(
                    makespan_us=cached["baseline_makespan_us"],
                    peak_bytes=tuple(cached["baseline_peak_bytes"]))
                _record_accept(score, base_score)
                return _outcome(
                    searched=False, cache_hit=True, accepted=True,
                    layout=layout, baseline_score=base_score,
                    best_score=score, fingerprint=prog.fingerprint(),
                    rejected=[], log=cached.get("log", []),
                    program=prog, instructions=new_insts)
            _M_REJECTED.labels("verifier").inc()
        else:
            _M_REJECTED.labels("fingerprint").inc()
    else:
        _M_CACHE.labels("miss").inc()

    # cold path: bounded beam search, then gate the winners for real —
    # up to superopt_verify_budget candidate lowerings, best-first
    layout, base_score, best_score, log, candidates = superopt_search(
        instructions, num_meshes, cost_model)
    if not candidates:
        _M_REJECTED.labels("score").inc()
        return _outcome(
            searched=True, cache_hit=False, accepted=False,
            layout=identity_layout(n), baseline_score=base_score,
            best_score=base_score, fingerprint=None, rejected=[],
            log=log)

    verify_budget = max(1, int(getattr(
        global_config, "superopt_verify_budget", 2)))
    rejected: List[Tuple[str, str]] = []
    for layout, score in candidates[:verify_budget]:
        try:
            new_insts = apply_layout(instructions, layout)
            prog = lower(new_insts)
            verdict = verify(prog, new_insts)
        except Exception as e:  # pylint: disable=broad-except
            # under verify_plans=strict an unsound candidate raises at
            # lowering — that is a gate rejection, not a compile error
            _M_REJECTED.labels("verifier").inc()
            logger.info("superopt: candidate lowering rejected: %s", e)
            rejected.append(("lowering", type(e).__name__))
            continue
        new = verdict_new_findings(base_verdict, verdict)
        if new:
            _M_REJECTED.labels("verifier").inc()
            logger.info("superopt: candidate rejected by the verdict "
                        "gate: %s",
                        ", ".join(f"{a}:{c}" for a, c in new))
            rejected.extend(new)
            continue
        _record_accept(score, base_score)
        cache.put("superopt", cache_key, {
            "layout": layout,
            "fingerprint": prog.fingerprint(),
            "baseline_fingerprint": base_fp,
            "baseline_makespan_us": base_score.makespan_us,
            "baseline_peak_bytes": list(base_score.peak_bytes),
            "makespan_us": score.makespan_us,
            "peak_bytes": list(score.peak_bytes),
            "log": log,
        })
        logger.info(
            "superopt: accepted rewrite (%s): critical path "
            "%.1f -> %.1f us, peak bytes %.0f -> %.0f",
            mode, base_score.makespan_us, score.makespan_us,
            base_score.total_peak, score.total_peak)
        return _outcome(
            searched=True, cache_hit=False, accepted=True,
            layout=layout, baseline_score=base_score, best_score=score,
            fingerprint=prog.fingerprint(), rejected=[], log=log,
            program=prog, instructions=new_insts)
    return _outcome(
        searched=True, cache_hit=False, accepted=False,
        layout=identity_layout(n), baseline_score=base_score,
        best_score=base_score, fingerprint=None,
        rejected=sorted(set(rejected)), log=log)


def _record_accept(score: PlanScore, base: PlanScore):
    _M_ACCEPTED.inc()
    _M_CP_DELTA.set(score.makespan_us - base.makespan_us)
    _M_PEAK_DELTA.set(score.total_peak - base.total_peak)


def load_cached_decisions(cache=None) -> List[Dict[str, Any]]:
    """Accepted superopt decisions from the compile cache's disk tier,
    newest first, WITHOUT recompiling anything:
    ``[{"key", "mtime", "decision"}, ...]`` — the data source of
    ``scripts/perf_tool.py superopt`` (mirrors
    ``plan_verifier.load_cached_verdicts``)."""
    import pickle
    from alpa_tpu import compile_cache as _cc
    cache = cache or _cc.get_compile_cache()
    out = []
    for e in cache.entries():
        if e["namespace"] != "superopt":
            continue
        try:
            with open(e["path"], "rb") as f:
                value = pickle.load(f)
            if isinstance(value, dict) and "__cache_format__" in value:
                value = value["payload"]
        except Exception:  # pylint: disable=broad-except
            continue
        if isinstance(value, dict) and "layout" in value:
            out.append({"key": e["key"], "mtime": e["mtime"],
                        "decision": value})
    out.sort(key=lambda d: d["mtime"], reverse=True)
    return out


def format_superopt_report(outcome: Optional[SuperoptOutcome]) -> str:
    """Human-readable ``superopt.txt`` (monitoring.dump_debug_info)."""
    if outcome is None:
        return "superopt: (not run — superopt_mode=off or not lowered)"
    d = outcome.to_dict()
    lines = [
        f"superopt: mode={d['mode']} accepted={d['accepted']} "
        f"cache_hit={d['cache_hit']} searched={d['searched']}",
        f"  simulated critical path: "
        f"{d['baseline']['makespan_us']:.1f} -> "
        f"{d['best']['makespan_us']:.1f} us "
        f"(delta {d['critical_path_delta_us']:+.1f})",
        f"  simulated peak bytes:    "
        f"{sum(float(v) for v in d['baseline']['peak_bytes'].values()):.0f}"
        f" -> "
        f"{sum(float(v) for v in d['best']['peak_bytes'].values()):.0f}"
        f" (delta {d['peak_bytes_delta']:+.0f})",
        f"  baseline fingerprint: {d['baseline_fingerprint'][:16]}",
        f"  rewritten fingerprint: "
        f"{(d['fingerprint'] or '-')[:16]}",
        f"  non-identity layout entries: {d['n_rewrites']}",
    ]
    if d["rejected_by"]:
        lines.append("  rejected by verdict gate: "
                     + ", ".join(d["rejected_by"]))
    if d["log"]:
        lines.append("  accepted-candidate search log "
                     f"({len(d['log'])} admissible candidates):")
        for e in d["log"][-12:]:
            lines.append(f"    {e['family']:<16} makespan "
                         f"{e['makespan_us']:.1f} us, peak "
                         f"{e['peak_bytes']:.0f} B")
    return "\n".join(lines)
